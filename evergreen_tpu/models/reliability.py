"""Task reliability scores.

Reference: model/reliability/query.go — GetTaskReliabilityScores
aggregates precomputed daily task stats by (task, variant, distro, date
bucket) and scores each group with the LOWER bound of the Wilson binomial
confidence interval (query.go:92-108), with the z value derived from a
two-tailed significance level (query.go:145-156 significanceToZ). The
filter surface mirrors reliability/filter.go: project + task names
required, optional requesters/variants/distros, date window, group-by
level, group_num_days bucketing, sort by date, limit.

Here the aggregation runs directly over finished task documents in the
store (the reference's daily_task_stats rollup is a Mongo materialization
of the same tasks collection); the scoring math is identical.
"""
from __future__ import annotations

import dataclasses
import math
from statistics import NormalDist
from typing import Dict, List, Optional

from ..globals import TaskStatus
from ..storage.store import Store
from .task import COLLECTION as TASKS_COLLECTION

DAY_S = 86400.0

#: group-by levels (reference taskstats GroupByDistro/Variant/Task):
#: each level keeps the named column and everything to its left in
#: (task, variant, distro)
GROUP_BY_TASK = "task"
GROUP_BY_VARIANT = "variant"
GROUP_BY_DISTRO = "distro"

SORT_EARLIEST = "earliest"
SORT_LATEST = "latest"

#: reference reliability.go:27 reliabilityAPIMaxNumTasksLimit
MAX_LIMIT = 1000


def significance_to_z(significance: float) -> float:
    """Two-tailed z score (reference query.go:145-156): the normal
    quantile at 1 - significance/2. The default significance of 0.05
    yields z ≈ 1.96."""
    return NormalDist().inv_cdf(1.0 - significance / 2.0)


def wilson_lower_bound(num_success: int, num_total: int, z: float) -> float:
    """Lower Wilson score interval bound, rounded UP to two decimals
    exactly as the reference does (query.go:92-108
    ``math.Ceil(low*100)/100``)."""
    if num_total == 0:
        return 0.0
    total = float(num_total)
    p = num_success / total
    dist = z * math.sqrt((p * (1.0 - p) + z * z / (4.0 * total)) / total)
    denominator = 1.0 + z * z / total
    c1 = p + z * z / (2.0 * total)
    low = max(0.0, (c1 - dist) / denominator)
    return math.ceil(low * 100) / 100


@dataclasses.dataclass
class TaskReliability:
    """One scored group (reference query.go:71-87)."""

    task_name: str
    build_variant: str
    distro: str
    date: float  # bucket start, unix seconds UTC
    num_total: int = 0
    num_success: int = 0
    num_failed: int = 0
    num_timeout: int = 0
    num_test_failed: int = 0
    num_system_failed: int = 0
    num_setup_failed: int = 0
    avg_duration_success: float = 0.0
    success_rate: float = 0.0
    z: float = 0.0

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ReliabilityFilter:
    """reference reliability/filter.go TaskReliabilityFilter."""

    project: str
    tasks: List[str]
    after_date: float
    before_date: float
    group_by: str = GROUP_BY_TASK
    group_num_days: int = 1
    requesters: Optional[List[str]] = None
    variants: Optional[List[str]] = None
    distros: Optional[List[str]] = None
    significance: float = 0.05
    sort: str = SORT_LATEST
    limit: int = MAX_LIMIT

    def validate(self) -> Optional[str]:
        if not self.project:
            return "missing project"
        if not self.tasks:
            return "missing tasks"
        if self.group_by not in (GROUP_BY_TASK, GROUP_BY_VARIANT,
                                 GROUP_BY_DISTRO):
            return f"invalid 'group by' {self.group_by!r}"
        if not 0.0 <= self.significance <= 1.0:
            return "invalid significance"
        if self.group_num_days < 1:
            return "invalid group_num_days"
        if self.sort not in (SORT_EARLIEST, SORT_LATEST):
            return f"invalid sort {self.sort!r}"
        if self.after_date >= self.before_date:
            return "after_date must precede before_date"
        return None


def _classify(doc: dict) -> Dict[str, int]:
    """Status counters for one finished execution (reference taskstats
    aggregation stages: success / failed split into test, system, setup,
    timeout)."""
    out = {"success": 0, "failed": 0, "timeout": 0, "test_failed": 0,
           "system_failed": 0, "setup_failed": 0}
    status = doc.get("status", "")
    if status == TaskStatus.SUCCEEDED.value:
        out["success"] = 1
        return out
    out["failed"] = 1
    if doc.get("details_timed_out"):
        out["timeout"] = 1
    dtype = doc.get("details_type", "")
    if dtype == "system":
        out["system_failed"] = 1
    elif dtype == "setup":
        out["setup_failed"] = 1
    else:
        out["test_failed"] = 1
    return out


def get_task_reliability_scores(
    store: Store, f: ReliabilityFilter
) -> List[TaskReliability]:
    """Aggregate + score (reference query.go:158-174
    GetTaskReliabilityScores)."""
    err = f.validate()
    if err:
        raise ValueError(err)
    z = significance_to_z(f.significance)
    tasks = set(f.tasks)
    requesters = set(f.requesters or [])
    variants = set(f.variants or [])
    distros = set(f.distros or [])
    bucket_s = f.group_num_days * DAY_S

    groups: Dict[tuple, TaskReliability] = {}
    for doc in store.collection(TASKS_COLLECTION).find(
        lambda d: d.get("project") == f.project
        and d.get("display_name") in tasks
        and d.get("status")
        in (TaskStatus.SUCCEEDED.value, TaskStatus.FAILED.value)
        and f.after_date <= d.get("finish_time", 0.0) < f.before_date
    ):
        if requesters and doc.get("requester") not in requesters:
            continue
        if variants and doc.get("build_variant") not in variants:
            continue
        if distros and doc.get("distro_id") not in distros:
            continue
        # day-truncate, then bucket relative to the window start
        # (reference buckets stats days onto group_num_days boundaries)
        day = math.floor(doc.get("finish_time", 0.0) / DAY_S) * DAY_S
        start_day = math.floor(f.after_date / DAY_S) * DAY_S
        bucket = start_day + math.floor((day - start_day) / bucket_s) * bucket_s
        variant = doc.get("build_variant", "")
        distro = doc.get("distro_id", "")
        key = (
            doc.get("display_name", ""),
            variant if f.group_by in (GROUP_BY_VARIANT, GROUP_BY_DISTRO) else "",
            distro if f.group_by == GROUP_BY_DISTRO else "",
            bucket,
        )
        g = groups.get(key)
        if g is None:
            g = groups[key] = TaskReliability(
                task_name=key[0], build_variant=key[1], distro=key[2],
                date=bucket, z=z,
            )
        c = _classify(doc)
        g.num_total += 1
        g.num_success += c["success"]
        g.num_failed += c["failed"]
        g.num_timeout += c["timeout"]
        g.num_test_failed += c["test_failed"]
        g.num_system_failed += c["system_failed"]
        g.num_setup_failed += c["setup_failed"]
        if c["success"]:
            dur = max(
                0.0,
                doc.get("finish_time", 0.0) - doc.get("start_time", 0.0),
            )
            # running mean over successes only (reference
            # AvgDurationSuccess)
            g.avg_duration_success += (
                dur - g.avg_duration_success
            ) / g.num_success

    out = list(groups.values())
    for g in out:
        g.success_rate = wilson_lower_bound(g.num_success, g.num_total, z)
    out.sort(
        key=lambda g: (g.date, g.task_name, g.build_variant, g.distro),
        reverse=f.sort == SORT_LATEST,
    )
    return out[: min(f.limit, MAX_LIMIT)]
