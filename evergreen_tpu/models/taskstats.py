"""Task duration statistics: historical rollups → expected durations.

Reference: model/taskstats/ rollups + units/cache_historical_task_data.go
feeding Task.FetchExpectedDuration (model/task/task.go:3510-3580). Rollups
are keyed (project, build variant, display name) and hold the running
average + stddev of recent successful runtimes; version creation stamps new
tasks with the current rollup so the hot scheduling loop never does a
lookup (SURVEY §7 "duration-stats freshness").
"""
from __future__ import annotations

import dataclasses
import math
import time as _time
from typing import Dict, List, Optional, Tuple

from ..globals import TaskStatus
from ..storage.store import Store
from . import task as task_mod

COLLECTION = "task_stats"

#: rollup window (reference uses recent-days windows for duration stats)
DEFAULT_WINDOW_S = 14 * 24 * 3600.0


def _key(project: str, variant: str, name: str) -> str:
    return f"{project}|{variant}|{name}"


@dataclasses.dataclass
class DurationRollup:
    project: str
    build_variant: str
    display_name: str
    average_s: float = 0.0
    std_dev_s: float = 0.0
    count: int = 0
    updated_at: float = 0.0


def get_rollup(
    store: Store, project: str, variant: str, name: str
) -> Optional[DurationRollup]:
    doc = store.collection(COLLECTION).get(_key(project, variant, name))
    if doc is None:
        return None
    doc = {k: v for k, v in doc.items() if k != "_id"}
    return DurationRollup(**doc)


def cache_historical_task_data(
    store: Store, now: Optional[float] = None, window_s: float = DEFAULT_WINDOW_S
) -> int:
    """Recompute rollups from finished tasks in the window (reference
    units/cache_historical_task_data.go). Returns rollups written."""
    now = _time.time() if now is None else now
    cutoff = now - window_s
    sums: Dict[str, Tuple[float, float, int]] = {}
    for doc in task_mod.coll(store).find(
        lambda d: d["status"] == TaskStatus.SUCCEEDED.value
        and d.get("finish_time", 0.0) >= cutoff
        and d.get("start_time", 0.0) > 0.0
    ):
        dur = max(0.0, doc["finish_time"] - doc["start_time"])
        k = _key(doc["project"], doc["build_variant"], doc["display_name"])
        s, s2, n = sums.get(k, (0.0, 0.0, 0))
        sums[k] = (s + dur, s2 + dur * dur, n + 1)

    coll = store.collection(COLLECTION)
    for k, (s, s2, n) in sums.items():
        avg = s / n
        var = max(0.0, s2 / n - avg * avg)
        project, variant, name = k.split("|", 2)
        coll.upsert(
            {
                "_id": k,
                "project": project,
                "build_variant": variant,
                "display_name": name,
                "average_s": avg,
                "std_dev_s": math.sqrt(var),
                "count": n,
                "updated_at": now,
            }
        )
    return len(sums)


def stamp_expected_durations(store: Store, tasks: List) -> int:
    """Stamp newly created tasks with the current rollups (called from
    version creation so the scheduler snapshot reads a plain field)."""
    n = 0
    coll = store.collection(COLLECTION)
    for t in tasks:
        doc = coll.get(_key(t.project, t.build_variant, t.display_name))
        if doc and doc["count"] > 0:
            task_mod.coll(store).update(
                t.id,
                {
                    "expected_duration_s": doc["average_s"],
                    "duration_std_dev_s": doc["std_dev_s"],
                },
            )
            t.expected_duration_s = doc["average_s"]
            t.duration_std_dev_s = doc["std_dev_s"]
            n += 1
    return n
