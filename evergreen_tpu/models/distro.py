"""Distro document + tunable scheduler settings.

Mirrors the knobs of the reference's ``distro.Distro`` that the scheduling
plane consumes (reference model/distro/distro.go:29,267-300,352-405). The
planner/allocator settings become rows of the device-side settings matrix in
the batched solve (see evergreen_tpu/scheduler/snapshot.py).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..globals import (
    EPHEMERAL_PROVIDERS,
    MAX_DURATION_PER_DISTRO_HOST_S,
    DispatcherVersion,
    FeedbackRule,
    FinderVersion,
    OverallocatedRule,
    PlannerVersion,
    Provider,
    RoundingRule,
)
from ..storage.store import Collection, Store

COLLECTION = "distros"


@dataclasses.dataclass
class PlannerSettings:
    """Reference model/distro/distro.go:286-300. Defaults follow the
    reference's resolved defaults (GetPatchFactor et al. fall back to the
    global scheduler config; we bake the commonly-deployed defaults)."""

    version: str = PlannerVersion.TPU.value
    #: host-capacity allocator: "" = the per-distro utilization
    #: heuristic; "tpu" = the joint capacity program over
    #: (distros × provider pools) — ops/capacity.py via
    #: scheduler/capacity_plane.py, breaker-guarded with the heuristic
    #: as its fallback
    capacity: str = ""
    target_time_s: float = 0.0  # 0 → use MAX_DURATION_PER_DISTRO_HOST_S
    group_versions: bool = False
    patch_factor: int = 0
    patch_time_in_queue_factor: int = 0
    commit_queue_factor: int = 0
    mainline_time_in_queue_factor: int = 0
    expected_runtime_factor: int = 0
    generate_task_factor: int = 0
    num_dependents_factor: float = 0.0
    stepback_task_factor: int = 0

    def max_duration_per_host_s(self) -> float:
        return self.target_time_s if self.target_time_s > 0 else float(
            MAX_DURATION_PER_DISTRO_HOST_S
        )


@dataclasses.dataclass
class HostAllocatorSettings:
    """Reference model/distro/distro.go:267-280."""

    version: str = "utilization"
    minimum_hosts: int = 0
    maximum_hosts: int = 0
    auto_tune_maximum_hosts: bool = False
    rounding_rule: str = RoundingRule.DOWN.value
    feedback_rule: str = FeedbackRule.WAITS_OVER_THRESH.value
    hosts_overallocated_rule: str = OverallocatedRule.DEFAULT.value
    acceptable_host_idle_time_s: float = 0.0
    future_host_fraction: float = 0.5


@dataclasses.dataclass
class BootstrapSettings:
    """How a host of this distro acquires a running agent (reference
    model/distro/distro.go BootstrapSettings: method + communication).

    - ``legacy-ssh``/``ssh``: the server pushes the agent over a host
      transport (agent-deploy job) and re-pushes it when it goes silent.
    - ``user-data``: the host self-provisions from generated user data
      (cloud/userdata.py) and phones home; the agent monitor keeps the
      agent alive locally.
    - ``preconfigured-image``: the image already runs an agent monitor;
      no provisioning step beyond the cloud instance coming up.
    """

    METHOD_LEGACY_SSH = "legacy-ssh"
    METHOD_SSH = "ssh"
    METHOD_USER_DATA = "user-data"
    METHOD_PRECONFIGURED = "preconfigured-image"

    method: str = "legacy-ssh"
    communication: str = "legacy-ssh"
    env: dict = dataclasses.field(default_factory=dict)

    def is_legacy(self) -> bool:
        """Reference distro.LegacyBootstrap()."""
        return self.method in ("", self.METHOD_LEGACY_SSH)

    def self_provisions(self) -> bool:
        return self.method in (self.METHOD_USER_DATA, self.METHOD_PRECONFIGURED)


@dataclasses.dataclass
class DispatcherSettings:
    version: str = DispatcherVersion.REVISED_WITH_DEPENDENCIES.value


@dataclasses.dataclass
class FinderSettings:
    version: str = FinderVersion.PIPELINE.value


@dataclasses.dataclass
class Distro:
    id: str
    provider: str = Provider.MOCK.value
    arch: str = "linux_amd64"
    work_dir: str = "/data/evg"
    user: str = "evg-user"
    disabled: bool = False
    container_pool: str = ""
    aliases: List[str] = dataclasses.field(default_factory=list)
    setup: str = ""
    provider_settings: dict = dataclasses.field(default_factory=dict)
    planner_settings: PlannerSettings = dataclasses.field(
        default_factory=PlannerSettings
    )
    host_allocator_settings: HostAllocatorSettings = dataclasses.field(
        default_factory=HostAllocatorSettings
    )
    dispatcher_settings: DispatcherSettings = dataclasses.field(
        default_factory=DispatcherSettings
    )
    finder_settings: FinderSettings = dataclasses.field(default_factory=FinderSettings)
    bootstrap_settings: BootstrapSettings = dataclasses.field(
        default_factory=BootstrapSettings
    )
    single_task_distro: bool = False

    def is_ephemeral(self) -> bool:
        return self.provider in EPHEMERAL_PROVIDERS

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "Distro":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        for key, sub in (
            ("planner_settings", PlannerSettings),
            ("host_allocator_settings", HostAllocatorSettings),
            ("dispatcher_settings", DispatcherSettings),
            ("finder_settings", FinderSettings),
            ("bootstrap_settings", BootstrapSettings),
        ):
            if isinstance(doc.get(key), dict):
                doc[key] = sub(**doc[key])
        known = _DISTRO_FIELDS  # fields() per doc is hot-loop cost
        return cls(**{k: v for k, v in doc.items() if k in known})


_DISTRO_FIELDS = frozenset(f.name for f in dataclasses.fields(Distro))


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def insert(store: Store, d: Distro) -> None:
    coll(store).insert(d.to_doc())


def upsert(store: Store, d: Distro) -> None:
    coll(store).upsert(d.to_doc())


def get(store: Store, distro_id: str) -> Optional[Distro]:
    doc = coll(store).get(distro_id)
    return Distro.from_doc(doc) if doc else None


def find_all(store: Store) -> List[Distro]:
    return [Distro.from_doc(d) for d in coll(store).find()]


def _pool_parent_ids(store: Store) -> set:
    """Distro ids that serve as container-pool PARENT hosts — these are
    managed by the pool-capacity logic, not the normal scheduler/allocator
    fan-out (reference ByNeedsPlanning's $nin over
    config.ContainerPools.Pools[*].Distro, model/distro/db.go:199-212).
    Container distros themselves ARE planned and allocated."""
    doc = store.collection("config").get("container_pools")
    if not doc:
        return set()
    return {p.get("distro", "") for p in doc.get("pools", [])}


def find_needs_planning(store: Store) -> List[Distro]:
    """Distros whose task queues get planned: non-disabled ones, plus static
    distros even when disabled (reference distro.ByNeedsPlanning,
    model/distro/db.go:198-212)."""
    parents = _pool_parent_ids(store)
    return [
        d
        for d in find_all(store)
        if (not d.disabled or d.provider == Provider.STATIC.value)
        and d.id not in parents
    ]


def find_needs_hosts_planning(store: Store) -> List[Distro]:
    """Distros the host allocator runs for: everything except container-pool
    parent distros, including disabled ones — disabled distros still
    maintain their minimum hosts (reference distro.ByNeedsHostsPlanning,
    model/distro/db.go:214-224, and the disabled branch of
    UtilizationBasedHostAllocator :51-67)."""
    parents = _pool_parent_ids(store)
    return [d for d in find_all(store) if d.id not in parents]
