"""Version document — one revision (or patch) of a project
(reference model/version.go)."""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..globals import VersionStatus
from ..storage.store import Collection, Store

COLLECTION = "versions"


@dataclasses.dataclass
class Version:
    id: str
    project: str = ""
    branch: str = ""
    revision: str = ""
    revision_order_number: int = 0
    requester: str = ""
    author: str = ""
    message: str = ""
    status: str = VersionStatus.CREATED.value
    activated: bool = False
    create_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    build_ids: List[str] = dataclasses.field(default_factory=list)
    build_variants_status: List[dict] = dataclasses.field(default_factory=list)
    config_yaml: str = ""
    errors: List[str] = dataclasses.field(default_factory=list)
    warnings: List[str] = dataclasses.field(default_factory=list)
    ignored: bool = False

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "Version":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        known = _VERSION_FIELDS  # fields() per doc is hot-loop cost
        return cls(**{k: v for k, v in doc.items() if k in known})


_VERSION_FIELDS = frozenset(f.name for f in dataclasses.fields(Version))


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def insert(store: Store, v: Version) -> None:
    coll(store).insert(v.to_doc())


def find(store: Store, pred=None) -> List[Version]:
    return [Version.from_doc(d) for d in coll(store).find(pred)]


def get(store: Store, version_id: str) -> Optional[Version]:
    doc = coll(store).get(version_id)
    return Version.from_doc(doc) if doc else None


def find_by_project_order(
    store: Store, project: str, lo: int, hi: int, requester: str = ""
) -> List[Version]:
    """Versions for a project in a revision-order window (stepback walks
    this; reference model/version.go VersionByMostRecentSystemRequester)."""

    def pred(d: dict) -> bool:
        if d["project"] != project:
            return False
        if requester and d["requester"] != requester:
            return False
        return lo <= d["revision_order_number"] <= hi

    out = [Version.from_doc(d) for d in coll(store).find(pred)]
    out.sort(key=lambda v: v.revision_order_number)
    return out
