"""Task annotations + build baron.

Reference: model/annotations/ (failure annotations with suspected/linked
issues), model/build_baron.go (ticket search/creation hooks for known
failures). Ticket-system integration is a pluggable callback (the
thirdparty/jira.go seam).
"""
from __future__ import annotations

import dataclasses
import time as _time
from typing import Callable, Dict, List, Optional

from ..storage.store import Store

COLLECTION = "task_annotations"


@dataclasses.dataclass
class IssueLink:
    url: str
    issue_key: str = ""
    source: str = ""  # api | build-baron | user
    added_by: str = ""


@dataclasses.dataclass
class Annotation:
    task_id: str
    execution: int = 0
    note: str = ""
    issues: List[IssueLink] = dataclasses.field(default_factory=list)
    suspected_issues: List[IssueLink] = dataclasses.field(default_factory=list)
    webhook_configured: bool = False
    updated_at: float = 0.0


def _doc_id(task_id: str, execution: int) -> str:
    return f"{task_id}:{execution}"


def get_annotation(
    store: Store, task_id: str, execution: int = 0
) -> Optional[Annotation]:
    doc = store.collection(COLLECTION).get(_doc_id(task_id, execution))
    if doc is None:
        return None
    doc = {k: v for k, v in doc.items() if k != "_id"}
    doc["issues"] = [IssueLink(**i) for i in doc.get("issues", [])]
    doc["suspected_issues"] = [
        IssueLink(**i) for i in doc.get("suspected_issues", [])
    ]
    return Annotation(**doc)


def upsert_annotation(store: Store, ann: Annotation) -> None:
    ann.updated_at = _time.time()
    doc = dataclasses.asdict(ann)
    doc["_id"] = _doc_id(ann.task_id, ann.execution)
    store.collection(COLLECTION).upsert(doc)


def add_issue(
    store: Store, task_id: str, execution: int, issue: IssueLink,
    suspected: bool = False,
) -> None:
    ann = get_annotation(store, task_id, execution) or Annotation(
        task_id=task_id, execution=execution
    )
    (ann.suspected_issues if suspected else ann.issues).append(issue)
    upsert_annotation(store, ann)


def remove_issue(
    store: Store, task_id: str, execution: int, issue_key: str,
    suspected: bool = False,
) -> bool:
    """Drop an issue link by key (reference annotations RemoveIssueFromAnnotation)."""
    ann = get_annotation(store, task_id, execution)
    if ann is None:
        return False
    links = ann.suspected_issues if suspected else ann.issues
    kept = [l for l in links if l.issue_key != issue_key]
    if len(kept) == len(links):
        return False
    if suspected:
        ann.suspected_issues = kept
    else:
        ann.issues = kept
    upsert_annotation(store, ann)
    return True


def move_issue_to_suspected(
    store: Store, task_id: str, execution: int, issue_key: str,
    to_suspected: bool = True,
) -> bool:
    """Move a link between issues↔suspected (reference MoveIssueToAnnotation)."""
    ann = get_annotation(store, task_id, execution)
    if ann is None:
        return False
    src = ann.issues if to_suspected else ann.suspected_issues
    dst = ann.suspected_issues if to_suspected else ann.issues
    for link in list(src):
        if link.issue_key == issue_key:
            src.remove(link)
            dst.append(link)
            upsert_annotation(store, ann)
            return True
    return False


def set_note(store: Store, task_id: str, execution: int, note: str) -> None:
    """Replace the annotation note (reference UpdateAnnotationNote)."""
    ann = get_annotation(store, task_id, execution) or Annotation(
        task_id=task_id, execution=execution
    )
    ann.note = note
    upsert_annotation(store, ann)


#: build-baron ticket search: project id + task doc → suspected issues
TicketSearcher = Callable[[str, dict], List[IssueLink]]
_TICKET_SEARCHERS: Dict[str, TicketSearcher] = {}


def register_ticket_searcher(project: str, searcher: TicketSearcher) -> None:
    _TICKET_SEARCHERS[project] = searcher


def build_baron_suggest(store: Store, task_id: str) -> List[IssueLink]:
    """Suggest tickets for a failed task (reference model/build_baron.go)."""
    doc = store.collection("tasks").get(task_id)
    if doc is None:
        return []
    searcher = _TICKET_SEARCHERS.get(doc["project"])
    if searcher is None:
        return []
    suggestions = searcher(doc["project"], doc)
    for link in suggestions:
        add_issue(store, task_id, doc.get("execution", 0), link, suspected=True)
    return suggestions
