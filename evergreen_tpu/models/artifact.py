"""Artifacts + test results + blob storage.

Reference: model/artifact/ (attached artifact records + signed URLs,
rest/route/artifact_sign.go), model/task/test_result_service.go +
model/testresult (per-task test results), and pail (blob storage over S3)
— here a content-addressed local blob store with the same get/put seam.
"""
from __future__ import annotations

import dataclasses
import hashlib
import hmac
import os
import time as _time
from typing import List, Optional

from ..storage.store import Store

ARTIFACTS_COLLECTION = "artifacts"
TEST_RESULTS_COLLECTION = "test_results"

_SIGNING_KEY = b"evergreen-tpu-artifact-signing"


# --------------------------------------------------------------------------- #
# Blob store (the pail seam)
# --------------------------------------------------------------------------- #


class BlobStore:
    """Local filesystem bucket with the get/put/exists surface of pail."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = key.replace("/", "_")
        return os.path.join(self.root, safe)

    def put(self, key: str, data: bytes) -> str:
        path = self._path(key)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))


# --------------------------------------------------------------------------- #
# Artifacts
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class ArtifactFile:
    name: str
    link: str
    visibility: str = "public"  # public | private | signed
    content_type: str = ""


def attach_artifacts(
    store: Store, task_id: str, execution: int, files: List[ArtifactFile]
) -> None:
    """reference agent command attach.artifacts → model/artifact records."""
    coll = store.collection(ARTIFACTS_COLLECTION)
    doc = coll.get(f"{task_id}:{execution}")
    entries = [dataclasses.asdict(f) for f in files]
    if doc is None:
        coll.upsert(
            {
                "_id": f"{task_id}:{execution}",
                "task_id": task_id,
                "execution": execution,
                "files": entries,
            }
        )
    else:
        doc["files"].extend(entries)


def get_artifacts(store: Store, task_id: str, execution: int = 0) -> List[ArtifactFile]:
    doc = store.collection(ARTIFACTS_COLLECTION).get(f"{task_id}:{execution}")
    if doc is None:
        return []
    return [ArtifactFile(**f) for f in doc["files"]]


def sign_url(link: str, expires_at: float) -> str:
    """Signed artifact link (reference rest/route/artifact_sign.go)."""
    payload = f"{link}:{int(expires_at)}".encode()
    sig = hmac.new(_SIGNING_KEY, payload, hashlib.sha256).hexdigest()[:32]
    return f"{link}?expires={int(expires_at)}&sig={sig}"


def verify_signed_url(url: str, now: Optional[float] = None) -> bool:
    now = _time.time() if now is None else now
    try:
        link, qs = url.split("?", 1)
        params = dict(kv.split("=", 1) for kv in qs.split("&"))
        expires = int(params["expires"])
        if expires < now:
            return False
        expect = sign_url(link, expires).split("sig=")[1]
        return hmac.compare_digest(expect, params["sig"])
    except (ValueError, KeyError):
        return False


# --------------------------------------------------------------------------- #
# Test results
# --------------------------------------------------------------------------- #


@dataclasses.dataclass
class TestResult:
    test_name: str
    status: str  # pass | fail | skip
    duration_s: float = 0.0
    log_url: str = ""
    line_num: int = 0


def attach_test_results(
    store: Store, task_id: str, execution: int, results: List[TestResult]
) -> None:
    """reference attach.results / attach.xunit_results →
    model/task/test_result_service.go."""
    coll = store.collection(TEST_RESULTS_COLLECTION)
    doc_id = f"{task_id}:{execution}"
    doc = coll.get(doc_id)
    entries = [dataclasses.asdict(r) for r in results]
    if doc is None:
        coll.upsert(
            {
                "_id": doc_id,
                "task_id": task_id,
                "execution": execution,
                "results": entries,
            }
        )
    else:
        doc["results"].extend(entries)
    # tasks with failing results surface it on the task doc (reference
    # Task.ResultsFailed / HasFailedTests)
    if any(r.status == "fail" for r in results):
        store.collection("tasks").update(task_id, {"results_failed": True})


def get_test_results(
    store: Store, task_id: str, execution: int = 0
) -> List[TestResult]:
    doc = store.collection(TEST_RESULTS_COLLECTION).get(f"{task_id}:{execution}")
    if doc is None:
        return []
    return [TestResult(**r) for r in doc["results"]]
