"""Persisted task queue + per-queue aggregate info.

The queue doc is the durable artifact of a planning tick (reference
model/task_queue.go:48-78 DistroQueueInfo; scheduler/task_queue_persister.go).
It is a pure function of the snapshot, so resume ≡ rerun (SURVEY §5
checkpoint analog).
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..storage.store import Collection, Store

COLLECTION = "task_queues"
SECONDARY_COLLECTION = "task_secondary_queues"


@dataclasses.dataclass
class TaskGroupInfo:
    """Per-task-group aggregates feeding the allocator (reference
    model/task_queue.go TaskGroupInfo)."""

    name: str = ""
    count: int = 0
    max_hosts: int = 0
    expected_duration_s: float = 0.0
    count_free: int = 0
    count_required: int = 0
    count_duration_over_threshold: int = 0
    count_wait_over_threshold: int = 0
    count_dep_filled_merge_queue: int = 0
    duration_over_threshold_s: float = 0.0


@dataclasses.dataclass
class DistroQueueInfo:
    length: int = 0
    length_with_dependencies_met: int = 0
    count_dep_filled_merge_queue: int = 0
    expected_duration_s: float = 0.0
    max_duration_threshold_s: float = 0.0
    plan_created_at: float = 0.0
    count_duration_over_threshold: int = 0
    duration_over_threshold_s: float = 0.0
    count_wait_over_threshold: int = 0
    task_group_infos: List[TaskGroupInfo] = dataclasses.field(default_factory=list)
    secondary_queue: bool = False


class QueueInfoView:
    """Lazy ``DistroQueueInfo`` equivalent over the batched solve's raw
    host-side output columns.

    The solve's unpack used to materialize a TaskGroupInfo dataclass per
    segment per tick (~11k at config-3 scale — ~60ms of pure constructor
    overhead); the persister then immediately flattened them back into
    dicts. This view defers ALL object construction: ``doc()`` builds the
    persisted info document only when a queue doc is actually written,
    "is the info unchanged?" is answered ONCE per tick by comparing the
    shared raw columns wholesale (PersisterState.note_solve_infos), not
    per distro. Field order of ``doc()`` matches the dataclass path
    byte-for-byte so full-rewrite and delta runs persist identical docs.
    """

    __slots__ = (
        "secondary_queue", "plan_created_at", "_di", "_seg_ids", "_c",
        "_doc",
    )

    def __init__(self, di: int, seg_ids, cols: dict) -> None:
        self.secondary_queue = False
        self.plan_created_at = 0.0
        self._di = di
        self._seg_ids = seg_ids
        self._c = cols
        self._doc = None

    # the three aggregates the tick driver reads directly
    @property
    def length(self) -> int:
        return int(self._c["d_length"][self._di])

    @property
    def length_with_dependencies_met(self) -> int:
        return int(self._c["d_deps_met"][self._di])

    @property
    def expected_duration_s(self) -> float:
        return float(self._c["d_expected_dur_s"][self._di])

    def doc(self) -> dict:
        d = self._doc
        if d is None:
            c, di = self._c, self._di
            names = c["seg_names"]
            d = self._doc = {
                "length": int(c["d_length"][di]),
                "length_with_dependencies_met": int(c["d_deps_met"][di]),
                "count_dep_filled_merge_queue": int(c["d_merge"][di]),
                "expected_duration_s": float(c["d_expected_dur_s"][di]),
                "max_duration_threshold_s": float(c["d_thresh_s"][di]),
                "plan_created_at": self.plan_created_at,
                "count_duration_over_threshold": int(c["d_over_count"][di]),
                "duration_over_threshold_s": float(c["d_over_dur_s"][di]),
                "count_wait_over_threshold": int(c["d_wait_over"][di]),
                "secondary_queue": self.secondary_queue,
                "task_group_infos": [
                    {
                        "name": names[gi][1],
                        "count": int(c["g_count"][gi]),
                        "max_hosts": int(c["g_max_hosts"][gi]),
                        "expected_duration_s": float(c["g_expected_dur_s"][gi]),
                        "count_free": int(c["g_count_free"][gi]),
                        "count_required": int(c["g_count_required"][gi]),
                        "count_duration_over_threshold": int(c["g_over_count"][gi]),
                        "count_wait_over_threshold": int(c["g_wait_over"][gi]),
                        "count_dep_filled_merge_queue": int(c["g_merge"][gi]),
                        "duration_over_threshold_s": float(c["g_over_dur_s"][gi]),
                    }
                    for gi in self._seg_ids
                ],
            }
        return d


@dataclasses.dataclass
class TaskQueueItem:
    """One planned queue entry — the fields the DAG dispatcher needs
    (reference model/task_queue.go TaskQueueItem)."""

    id: str
    display_name: str = ""
    build_variant: str = ""
    project: str = ""
    version: str = ""
    requester: str = ""
    revision_order_number: int = 0
    priority: int = 0
    sort_value: float = 0.0
    task_group: str = ""
    task_group_max_hosts: int = 0
    task_group_order: int = 0
    expected_duration_s: float = 0.0
    num_dependents: int = 0
    dependencies: List[str] = dataclasses.field(default_factory=list)
    dependencies_met: bool = True


#: column order of the persisted queue doc (matches TaskQueueItem fields)
_ITEM_FIELDS = tuple(
    f.name for f in dataclasses.fields(TaskQueueItem)
)

#: field order of one row in the row-major persist format — exactly
#: Task.queue_row()'s tuple (models/task.py), which is memoized per task
#: instance so the every-tick persist writes shared tuples instead of
#: transposing 50k rows into columns (the read side transposes instead,
#: TTL-amortized).  sort_value / dependencies_met ride as separate
#: top-level columns because they are the only per-tick-dynamic fields.
ROW_FIELDS = (
    "id", "display_name", "build_variant", "project", "version",
    "requester", "revision_order_number", "priority", "task_group",
    "task_group_max_hosts", "task_group_order", "expected_duration_s",
    "num_dependents", "dependencies",
)
_ROW_INDEX = {n: i for i, n in enumerate(ROW_FIELDS)}


def doc_column(doc: dict, name: str) -> list:
    """One logical column from a queue doc in ANY persisted format
    (row-major 'rows', columnar 'cols', or legacy item-list 'queue'),
    always in PLAN order. Docs carrying an ``order`` permutation keep
    their rows in the id-sorted canonical layout (so churn persists are
    row splices instead of full rewrites, scheduler/persister.py) and
    this accessor applies the permutation."""
    rows = doc.get("rows")
    if rows is not None:
        if name in ("sort_value", "dependencies_met"):
            col = doc.get(name) or []
            order = doc.get("order")
            return [col[i] for i in order] if order is not None else col
        idx = _ROW_INDEX[name]
        order = doc.get("order")
        if order is not None:
            return [rows[i][idx] for i in order]
        return [r[idx] for r in rows]
    cols = doc.get("cols")
    if cols is not None:
        return cols.get(name, [])
    return [i.get(name) for i in doc.get("queue", [])]


@dataclasses.dataclass
class TaskQueue:
    distro_id: str
    queue: List[TaskQueueItem] = dataclasses.field(default_factory=list)
    info: DistroQueueInfo = dataclasses.field(default_factory=DistroQueueInfo)
    generated_at: float = 0.0

    def length(self) -> int:
        return len(self.queue)

    def to_doc(self) -> dict:
        return {
            "_id": self.distro_id,
            "distro_id": self.distro_id,
            "queue": [dataclasses.asdict(i) for i in self.queue],
            "info": dataclasses.asdict(self.info),
            "generated_at": self.generated_at,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "TaskQueue":
        info_doc = dict(doc.get("info", {}))
        info_doc["task_group_infos"] = [
            TaskGroupInfo(**g) for g in info_doc.get("task_group_infos", [])
        ]
        rows = doc.get("rows")
        cols = doc.get("cols")
        if rows is not None:
            # row-major persist format (scheduler/persister.py): each row
            # is Task.queue_row() in ROW_FIELDS order; the two dynamic
            # columns ride separately.  Dependencies are copied — rows are
            # memoized tuples shared across ticks.  An ``order``
            # permutation (canonical id-sorted row layout) maps row
            # storage order back to plan order.
            sv = doc.get("sort_value") or [0.0] * len(rows)
            dm = doc.get("dependencies_met") or [True] * len(rows)
            order = doc.get("order")
            triples = (
                ((rows[i], sv[i], dm[i]) for i in order)
                if order is not None else zip(rows, sv, dm)
            )
            queue = [
                TaskQueueItem(
                    r[0], r[1], r[2], r[3], r[4], r[5], r[6], r[7], s,
                    r[8], r[9], r[10], r[11], r[12], list(r[13]), bool(m),
                )
                for r, s, m in triples
            ]
        elif cols is not None:
            # columnar persist format: one list per field — items are
            # reconstructed here on the read side (TTL-amortized)
            names = list(_ITEM_FIELDS)
            queue = [
                TaskQueueItem(**dict(zip(names, values)))
                for values in zip(*(cols[n] for n in names))
            ]
        else:
            queue = [TaskQueueItem(**i) for i in doc.get("queue", [])]
        return cls(
            distro_id=doc["distro_id"],
            queue=queue,
            info=DistroQueueInfo(**info_doc),
            generated_at=doc.get("generated_at", 0.0),
        )


def coll(store: Store, secondary: bool = False) -> Collection:
    return store.collection(SECONDARY_COLLECTION if secondary else COLLECTION)


def save(store: Store, queue: TaskQueue, secondary: bool = False) -> None:
    coll(store, secondary).upsert(queue.to_doc())


def load(store: Store, distro_id: str, secondary: bool = False) -> Optional[TaskQueue]:
    doc = coll(store, secondary).get(distro_id)
    return TaskQueue.from_doc(doc) if doc else None


def load_info(store: Store, distro_id: str) -> Optional[DistroQueueInfo]:
    q = load(store, distro_id)
    return q.info if q else None
