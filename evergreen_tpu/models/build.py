"""Build document — one buildvariant instantiation within a version
(reference model/build/build.go)."""
from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..globals import BuildStatus
from ..storage.store import Collection, Store

COLLECTION = "builds"


@dataclasses.dataclass
class Build:
    id: str
    version: str = ""
    project: str = ""
    build_variant: str = ""
    display_name: str = ""
    revision: str = ""
    revision_order_number: int = 0
    requester: str = ""
    status: str = BuildStatus.CREATED.value
    activated: bool = False
    activated_time: float = 0.0
    create_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0
    tasks: List[str] = dataclasses.field(default_factory=list)

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "Build":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        known = _BUILD_FIELDS  # fields() per doc is hot-loop cost
        return cls(**{k: v for k, v in doc.items() if k in known})


_BUILD_FIELDS = frozenset(f.name for f in dataclasses.fields(Build))


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def insert(store: Store, b: Build) -> None:
    coll(store).insert(b.to_doc())


def get(store: Store, build_id: str) -> Optional[Build]:
    doc = coll(store).get(build_id)
    return Build.from_doc(doc) if doc else None


def find_by_version(store: Store, version_id: str) -> List[Build]:
    return [Build.from_doc(d) for d in coll(store).find(lambda d: d["version"] == version_id)]
