"""Per-project variables with private redaction and copy semantics.

Reference: model/project_vars.go — ProjectVars{Vars map[string]string,
PrivateVars map[string]bool} stored per project ref; consumed by task
expansions and the project-settings surfaces. Copy semantics mirror
rest/route/project_copy.go copyVariablesHandler.Run: dry_run returns the
redacted preview of what would be copied without writing; a real run
merges into the destination (or replaces it when overwrite is set);
private vars are dropped unless include_private.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

from ..storage.store import Store

COLLECTION = "project_vars"


@dataclasses.dataclass
class ProjectVars:
    project_id: str
    vars: Dict[str, str] = dataclasses.field(default_factory=dict)
    private_vars: Dict[str, bool] = dataclasses.field(default_factory=dict)

    def to_doc(self) -> dict:
        return {
            "_id": self.project_id,
            "vars": dict(self.vars),
            "private_vars": dict(self.private_vars),
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "ProjectVars":
        return cls(
            project_id=doc["_id"],
            vars=dict(doc.get("vars", {})),
            private_vars=dict(doc.get("private_vars", {})),
        )

    def redacted(self) -> Dict[str, str]:
        """Private values blanked (reference RedactPrivateVars)."""
        return {
            k: "" if self.private_vars.get(k) else v
            for k, v in self.vars.items()
        }


def get(store: Store, project_id: str) -> ProjectVars:
    doc = store.collection(COLLECTION).get(project_id)
    return ProjectVars.from_doc(doc) if doc else ProjectVars(project_id)


def upsert(store: Store, pv: ProjectVars) -> None:
    store.collection(COLLECTION).upsert(pv.to_doc())


def copy_vars(
    store: Store,
    copy_from: str,
    copy_to: str,
    dry_run: bool = False,
    include_private: bool = False,
    overwrite: bool = False,
) -> Dict[str, str]:
    """reference rest/route/project_copy.go copyVariablesHandler.Run.
    Returns the (redacted) vars that were — or on dry_run, would be —
    written to the destination."""
    src = get(store, copy_from)
    vars_to_copy = dict(src.vars)
    private = dict(src.private_vars)
    if not include_private:
        for k in list(vars_to_copy):
            if private.get(k):
                del vars_to_copy[k]
                del private[k]
    redacted = {k: "" if private.get(k) else v for k, v in vars_to_copy.items()}
    if dry_run:
        return redacted
    dst = get(store, copy_to)
    if overwrite:
        dst.vars = {}
        dst.private_vars = {}
    dst.vars.update(vars_to_copy)
    dst.private_vars.update({k: True for k in private if private[k]})
    dst.project_id = copy_to
    upsert(store, dst)
    return redacted
