"""Typed event log — appended on every state change, consumed by the
trigger/notification pipeline (reference model/event/ package; acts as a
durable outbox, SURVEY §3.5)."""
from __future__ import annotations

import dataclasses
import itertools
import threading

from ..utils import lockcheck as _lockcheck
import time as _time
from typing import List, Optional

from ..storage.store import Collection, Store

COLLECTION = "events"

_SEQ = itertools.count()
_SEQ_LOCK = _lockcheck.make_lock("events.model_seq")
#: highest seq issued in this process — reseeding (after recovering a
#: store with surviving ids) must never move the shared counter BELOW
#: ids already handed out for another store
_SEQ_HWM = -1


# Resource types (reference model/event/event.go)
RESOURCE_TASK = "TASK"
RESOURCE_HOST = "HOST"
RESOURCE_BUILD = "BUILD"
RESOURCE_VERSION = "VERSION"
RESOURCE_PATCH = "PATCH"
RESOURCE_DISTRO = "DISTRO"
RESOURCE_ADMIN = "ADMIN"
RESOURCE_PROJECT = "PROJECT"


@dataclasses.dataclass
class Event:
    id: str
    resource_type: str
    event_type: str
    resource_id: str
    timestamp: float
    processed_at: float = 0.0
    data: dict = dataclasses.field(default_factory=dict)

    def to_doc(self) -> dict:
        # hand-rolled flat doc: dataclasses.asdict's recursive deepcopy
        # was 40% of the agent dispatch cycle (two events per handout at
        # 10k pulls/s). Event payloads are small flat dicts — a shallow
        # copy keeps the doc detached from the caller's mapping.
        return {
            "_id": self.id,
            "resource_type": self.resource_type,
            "event_type": self.event_type,
            "resource_id": self.resource_id,
            "timestamp": self.timestamp,
            "processed_at": self.processed_at,
            "data": dict(self.data),
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Event":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        return cls(**doc)


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def _reseed_past(c: Collection) -> None:
    """Resume the id sequence past the highest surviving event id — a
    process that recovered a durable store must not re-issue ids its
    predecessor already journaled (the crash harness found the first
    post-restart event colliding with a replayed ``evt-0`` and wedging
    every event-logging caller). The process-wide high-water mark keeps
    a reseed against a low-id store from dragging the shared counter
    back below ids already issued for another store."""
    global _SEQ
    floor = _SEQ_HWM
    for k in c.key_order():
        try:
            floor = max(floor, int(k.rsplit("-", 1)[1]))
        except (IndexError, ValueError):
            continue
    _SEQ = itertools.count(floor + 1)


def log(
    store: Store,
    resource_type: str,
    event_type: str,
    resource_id: str,
    data: Optional[dict] = None,
    timestamp: Optional[float] = None,
) -> Event:
    global _SEQ_HWM
    c = coll(store)
    for attempt in range(3):
        with _SEQ_LOCK:
            seq = next(_SEQ)
            _SEQ_HWM = max(_SEQ_HWM, seq)
        ev = Event(
            id=f"evt-{seq}",
            resource_type=resource_type,
            event_type=event_type,
            resource_id=resource_id,
            timestamp=_time.time() if timestamp is None else timestamp,
            data=data or {},
        )
        try:
            c.insert(ev.to_doc())
            return ev
        except KeyError:
            # recovered store carries ids ahead of this process's
            # counter: jump past them and retry (bounded — concurrent
            # reseeders can only move the counter forward)
            with _SEQ_LOCK:
                _reseed_past(c)
    raise KeyError("could not allocate a fresh event id after reseeding")


def find_unprocessed(store: Store, limit: int = 0) -> List[Event]:
    evs = [Event.from_doc(d) for d in coll(store).find(lambda d: d["processed_at"] == 0.0)]
    evs.sort(key=lambda e: e.timestamp)
    return evs[:limit] if limit else evs


def mark_processed(store: Store, event_id: str, when: Optional[float] = None) -> bool:
    return coll(store).update(
        event_id, {"processed_at": _time.time() if when is None else when}
    )


def find_by_resource(store: Store, resource_id: str) -> List[Event]:
    evs = [Event.from_doc(d) for d in coll(store).find(lambda d: d["resource_id"] == resource_id)]
    evs.sort(key=lambda e: e.timestamp)
    return evs
