"""Host document + state machine.

Mirrors the allocator/dispatch-consumed core of the reference's ``host.Host``
(reference model/host/host.go, 4.4k LoC): status lifecycle, atomic
running-task assignment, task-group stickiness, intent hosts.
"""
from __future__ import annotations

import dataclasses
import time as _time
import uuid
from typing import Dict, List, Optional

from ..globals import (
    HOST_ACTIVE_STATUSES,
    HOST_UP_STATUSES,
    HostStatus,
)
from ..storage.store import Collection, Store

COLLECTION = "hosts"

# Reprovision transitions (reference model/host/host.go:196-209
# ReprovisionType). "restart-agent" is the analog of RestartJasper: same
# bootstrap method, but the host's agent runtime must be bounced.
REPROVISION_NONE = ""
REPROVISION_TO_NEW = "convert-to-new"
REPROVISION_TO_LEGACY = "convert-to-legacy"
REPROVISION_RESTART_AGENT = "restart-agent"


@dataclasses.dataclass(slots=True)
class Host:
    id: str
    distro_id: str = ""
    provider: str = "mock"
    status: str = HostStatus.UNINITIALIZED.value
    started_by: str = "mci"  # "mci" == system-owned; else spawn host user
    user_host: bool = False
    no_expiration: bool = False
    expiration_time: float = 0.0

    creation_time: float = 0.0
    start_time: float = 0.0
    agent_start_time: float = 0.0
    termination_time: float = 0.0
    last_communication_time: float = 0.0

    # Dispatch state (reference host.go RunningTask block)
    running_task: str = ""
    running_task_group: str = ""
    running_task_build_variant: str = ""
    running_task_version: str = ""
    running_task_project: str = ""
    running_task_group_order: int = 0
    last_task: str = ""
    last_group: str = ""
    last_build_variant: str = ""
    last_version: str = ""
    last_project: str = ""
    task_count: int = 0
    task_group_teardown_start_time: float = 0.0

    total_idle_time_s: float = 0.0
    provision_time: float = 0.0
    #: the instance is spot/preemptible capacity (recorded at spawn from
    #: the provider's launch spec): reclamation — the cloud taking it
    #: back mid-task — is expected weather, counted by
    #: ``cloud_spot_reclaimed_total`` when the monitor discovers it
    spot: bool = False
    #: pending bootstrap transition (REPROVISION_* below); consumed by
    #: cloud/provisioning.reprovision_hosts and gates next_task
    needs_reprovision: str = ""
    provision_attempts: int = 0
    #: bootstrap method the host was actually provisioned with — compared
    #: against the distro's current BootstrapSettings.method to detect
    #: needed reprovisioning (reference host.Distro.BootstrapSettings
    #: snapshot vs the live distro, scheduler/wrapper.go:233-266)
    bootstrap_method: str = ""
    #: consecutive failed agent (re)deploys; poisons the host at the cap
    agent_deploy_attempts: int = 0
    #: generated cloud-init payload for self-provisioning hosts; attached
    #: to the provider's launch request (reference ec2 LaunchTemplate
    #: UserData)
    user_data: str = ""

    #: per-host agent credential, generated at creation and handed to the
    #: agent at deploy time; agent routes authenticate with it (reference
    #: host.Secret + rest/route middleware host-ID/secret check)
    secret: str = ""

    # Container-pool topology (reference host.go parent/container fields)
    parent_id: str = ""
    has_containers: bool = False
    container_pool_id: str = ""

    instance_type: str = ""
    zone: str = ""
    ip_address: str = ""
    external_id: str = ""  # cloud-provider instance id

    # Spawn-host user surface (reference model/host/host.go DisplayName /
    # InstanceTags / ProvisionOptions; edited via rest/route/host_spawn.go
    # and the editSpawnHost mutation)
    display_name: str = ""
    instance_tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    provision_options: Dict[str, str] = dataclasses.field(
        default_factory=dict
    )
    #: RDP/admin password was set for a Windows spawn host (write-only;
    #: the password itself is never stored)
    service_password_set: bool = False

    def __post_init__(self) -> None:
        if self.creation_time == 0.0:
            self.creation_time = _time.time()

    # -- predicates (reference model/host/host.go:215 IsFree etc.) ----------- #

    def is_tearing_down(self) -> bool:
        return self.task_group_teardown_start_time > 0.0

    def is_free(self) -> bool:
        return self.running_task == "" and not self.is_tearing_down()

    def is_active(self) -> bool:
        return self.status in HOST_ACTIVE_STATUSES

    def is_up(self) -> bool:
        return self.status in HOST_UP_STATUSES

    def can_run_tasks(self) -> bool:
        return self.status == HostStatus.RUNNING.value and self.started_by == "mci"

    def task_group_string(self) -> str:
        return (
            f"{self.running_task_group}_{self.running_task_build_variant}_"
            f"{self.running_task_project}_{self.running_task_version}"
        )

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    def to_api_doc(self) -> dict:
        """Store doc minus the agent credential — the ONLY shape API
        surfaces may serialize (a leaked secret lets any API user
        impersonate the host's agent). The generated user_data embeds the
        same secret, so it is stripped too."""
        doc = self.to_doc()
        doc.pop("secret", None)
        doc.pop("user_data", None)
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "Host":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        known = _HOST_FIELDS  # fields() per doc is hot-loop cost
        return cls(**{k: v for k, v in doc.items() if k in known})


_HOST_FIELDS = frozenset(f.name for f in dataclasses.fields(Host))


def new_intent(distro_id: str, provider: str) -> Host:
    """Cloud-agnostic placeholder host created by the allocator output
    (reference scheduler/scheduler.go:176-220 CreateIntentHosts +
    host.NewIntent)."""
    return Host(
        id=f"evg-{distro_id}-{uuid.uuid4().hex[:12]}",
        distro_id=distro_id,
        provider=provider,
        status=HostStatus.UNINITIALIZED.value,
        secret=uuid.uuid4().hex,
    )


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def insert(store: Store, h: Host) -> None:
    coll(store).insert(h.to_doc())


def insert_many(store: Store, hosts: List[Host]) -> None:
    coll(store).insert_many([h.to_doc() for h in hosts])


def get(store: Store, host_id: str) -> Optional[Host]:
    doc = coll(store).get(host_id)
    return Host.from_doc(doc) if doc else None


def find(store: Store, pred=None) -> List[Host]:
    return [Host.from_doc(d) for d in coll(store).find(pred)]


def count_intents_in_flight(store: Store) -> int:
    """Intent hosts not yet materialized by the cloud — the ONE
    definition of "in flight" the intent-budget accounting uses, shared
    by the classic per-store path (scheduler/wrapper.py) and the
    sharded driver's fleet split (scheduler/sharded_plane.py) so the
    two deployments can never enforce different fleet caps."""
    return coll(store).count(
        lambda doc: doc["status"] == HostStatus.UNINITIALIZED.value
    )


def is_active_host_doc(doc: dict) -> bool:
    """The allocator's capacity predicate at doc level — the ONE
    definition shared by the cold scan below and the TickCache's warm
    host map (scheduler/cache.py), so warm/cold parity cannot drift."""
    return (
        doc["status"] in HOST_ACTIVE_STATUSES and doc["started_by"] == "mci"
    )


def all_active_hosts(store: Store, distro_id: str = "") -> List[Host]:
    """Capacity view for the allocator (reference host.AllActiveHosts via
    units/host_allocator.go:152): system-owned hosts in an active state."""

    def pred(doc: dict) -> bool:
        if not is_active_host_doc(doc):
            return False
        if distro_id and doc["distro_id"] != distro_id:
            return False
        return True

    return find(store, pred)


def assign_running_task(
    store: Store, host_id: str, task, dispatch_time: float
) -> bool:
    """Atomic compare-and-set of the host's running task — the dispatch
    correctness primitive (reference rest/route/host_agent.go:311-420)."""
    return coll(store).compare_and_set(
        host_id,
        expect={"running_task": "", "status": HostStatus.RUNNING.value},
        update={
            "running_task": task.id,
            "running_task_group": task.task_group,
            "running_task_build_variant": task.build_variant,
            "running_task_version": task.version,
            "running_task_project": task.project,
            "running_task_group_order": task.task_group_order,
            "last_communication_time": dispatch_time,
        },
    )


#: the one definition of "no running task" — shared by task-end clearing
#: below and the recovery pass's half-dispatched-claim release
#: (scheduler/recovery.py), so a new running_task_* field can't be
#: cleared in one place and leak in the other
RUNNING_TASK_CLEAR_FIELDS = {
    "running_task": "",
    "running_task_group": "",
    "running_task_build_variant": "",
    "running_task_version": "",
    "running_task_project": "",
    "running_task_group_order": 0,
}


def clear_running_task(store: Store, host_id: str, task_id: str, now: float) -> bool:
    """Clear assignment at task end, recording last-task affinity state
    (reference host.ClearRunningTask)."""
    c = coll(store)
    doc = c.get(host_id)
    if doc is None or doc.get("running_task") != task_id:
        return False
    return c.compare_and_set(
        host_id,
        expect={"running_task": task_id},
        update={
            **RUNNING_TASK_CLEAR_FIELDS,
            "last_task": task_id,
            "last_group": doc.get("running_task_group", ""),
            "last_build_variant": doc.get("running_task_build_variant", ""),
            "last_version": doc.get("running_task_version", ""),
            "last_project": doc.get("running_task_project", ""),
            "task_count": doc.get("task_count", 0) + 1,
            "last_communication_time": now,
        },
    )


def remove_stale_initializing(store: Store, distro_id: str, now: float,
                              ttl_s: float = 3 * 60.0) -> int:
    """Drop intent hosts that never started building (reference
    host.RemoveStaleInitializing via units/host_allocator.go:127)."""

    def pred(doc: dict) -> bool:
        return (
            doc["status"] == HostStatus.UNINITIALIZED.value
            and (not distro_id or doc["distro_id"] == distro_id)
            and now - doc.get("creation_time", now) > ttl_s
        )

    return coll(store).remove_where(pred)
