"""Cost accounting: per-task cloud cost attribution.

Reference: config_cost.go (financial formulas), model/cost/,
model/ec2instancereferenceprice, and the MarkEnd cost attributes
(model/task_lifecycle.go:754-768). Tasks are billed their runtime × the
host's instance-type rate (on-demand or spot-discounted), plus an EBS
per-hour component.
"""
from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, Optional

from ..settings import ConfigSection, register_section
from ..storage.store import Store

TASK_COSTS_COLLECTION = "task_costs"


@register_section
@dataclasses.dataclass
class CostConfig(ConfigSection):
    """reference config_cost.go."""

    section_id = "cost"

    #: instance type → USD per hour (on-demand)
    on_demand_prices: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: fraction of on-demand paid for spot capacity
    spot_discount: float = 0.35
    #: default rate for unknown instance types
    default_price_per_hour: float = 0.10
    #: EBS/hour attached-storage component
    ebs_price_per_hour: float = 0.01
    financial_formula_percentage: float = 1.0


def hourly_rate(config: CostConfig, instance_type: str, spot: bool) -> float:
    base = config.on_demand_prices.get(
        instance_type, config.default_price_per_hour
    )
    if spot:
        base *= config.spot_discount
    return base + config.ebs_price_per_hour


def attribute_task_cost(
    store: Store, task_id: str, now: Optional[float] = None
) -> Optional[float]:
    """Record the finished task's attributed cost (called from MarkEnd;
    reference model/task_lifecycle.go:754-768)."""
    now = _time.time() if now is None else now
    t = store.collection("tasks").get(task_id)
    if t is None or t.get("start_time", 0.0) <= 0:
        return None
    duration_s = max(0.0, t.get("finish_time", now) - t["start_time"])
    host = store.collection("hosts").get(t.get("host_id", "")) or {}
    config = CostConfig.get(store)
    distro = store.collection("distros").get(t.get("distro_id", "")) or {}
    spot = bool(
        (distro.get("provider_settings") or {}).get("fleet_use_spot", False)
    )
    rate = hourly_rate(config, host.get("instance_type", ""), spot)
    cost = (duration_s / 3600.0) * rate * config.financial_formula_percentage
    store.collection(TASK_COSTS_COLLECTION).upsert(
        {
            "_id": f"{task_id}:{t.get('execution', 0)}",
            "task_id": task_id,
            "project": t.get("project", ""),
            "duration_s": duration_s,
            "instance_type": host.get("instance_type", ""),
            "hourly_rate": rate,
            "cost_usd": cost,
            "at": now,
        }
    )
    return cost


def project_cost(store: Store, project: str, since: float = 0.0) -> float:
    """Aggregate attributed cost per project (the cost-reporting surface)."""
    return sum(
        d["cost_usd"]
        for d in store.collection(TASK_COSTS_COLLECTION).find(
            lambda d: d["project"] == project and d["at"] >= since
        )
    )
