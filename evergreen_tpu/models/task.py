"""Task document + state transitions.

Field set mirrors the scheduler-consumed core of the reference's
``task.Task`` (reference model/task/task.go:100-250): dependency edges,
scheduling signals (priority, requester, activation times), task-group
membership, and duration statistics. Times are epoch seconds (float);
durations are seconds (float) — tensor-friendly by construction.
"""
from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, Iterable, List, Optional

from ..globals import (
    DEFAULT_TASK_DURATION_S,
    MAX_TASK_TIME_IN_QUEUE_S,
    STEPBACK_TASK_ACTIVATOR,
    TASK_COMPLETED_STATUSES,
    TaskStatus,
)
from ..storage.store import Collection, Store

COLLECTION = "tasks"

#: Dependency status wildcard: dependency is met when the parent finishes
#: with any status (reference model/task AllStatuses).
DEP_STATUS_ANY = "*"


@dataclasses.dataclass(slots=True)
class Dependency:
    """One dependency edge (reference model/task/task.go:427-437)."""

    task_id: str
    status: str = TaskStatus.SUCCEEDED.value  # "" in the reference ≡ success
    unattainable: bool = False
    finished: bool = False

    def to_doc(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_doc(cls, doc: dict) -> "Dependency":
        return cls(**doc)


@dataclasses.dataclass
class DurationStats:
    """Historical runtime estimate (reference model/task/task.go:3510-3580,
    ``FetchExpectedDuration`` returning average + stddev)."""

    average_s: float = 0.0
    std_dev_s: float = 0.0


@dataclasses.dataclass(slots=True)
class Task:
    """``slots=True``: the snapshot packer (native/evgpack) reads ~10
    attributes per task per tick at 50k-task scale — slot descriptors cut
    that PyObject_GetAttr cost and halve per-instance memory."""

    id: str
    display_name: str = ""
    project: str = ""
    version: str = ""
    build_id: str = ""
    build_variant: str = ""
    distro_id: str = ""
    secondary_distros: List[str] = dataclasses.field(default_factory=list)
    revision: str = ""
    revision_order_number: int = 0

    status: str = TaskStatus.UNDISPATCHED.value
    activated: bool = False
    activated_by: str = ""
    priority: int = 0
    requester: str = ""
    execution: int = 0

    # Scheduling signals
    create_time: float = 0.0
    ingest_time: float = 0.0
    activated_time: float = 0.0
    scheduled_time: float = 0.0
    dependencies_met_time: float = 0.0
    dispatch_time: float = 0.0
    start_time: float = 0.0
    finish_time: float = 0.0

    depends_on: List[Dependency] = dataclasses.field(default_factory=list)
    num_dependents: int = 0
    override_dependencies: bool = False

    task_group: str = ""
    task_group_max_hosts: int = 0
    task_group_order: int = 0

    generate_task: bool = False
    generated_by: str = ""

    expected_duration_s: float = 0.0
    duration_std_dev_s: float = 0.0

    host_id: str = ""
    execution_platform: str = "host"
    container: str = ""

    aborted: bool = False
    details_type: str = ""  # "system", "setup", "test", "" — failure class
    details_desc: str = ""
    details_timed_out: bool = False
    results_failed: bool = False

    # Stepback bookkeeping (reference model/task/task.go stepback fields)
    last_heartbeat: float = 0.0
    can_reset: bool = False
    reset_when_finished: bool = False
    num_automatic_restarts: int = 0

    #: per-instance queue_row() memo (slot, since there is no __dict__);
    #: excluded from to_doc/compare, never persisted
    _qrow: Optional[tuple] = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def __post_init__(self) -> None:
        if self.ingest_time == 0.0 and self.create_time:
            self.ingest_time = self.create_time

    def fetch_expected_duration(self) -> DurationStats:
        """Expected runtime with the no-history default (reference
        model/task/task.go:3510-3580 FetchExpectedDuration: stats rollup,
        falling back to defaultTaskDuration)."""
        if self.expected_duration_s > 0:
            return DurationStats(
                average_s=self.expected_duration_s,
                std_dev_s=self.duration_std_dev_s,
            )
        return DurationStats(average_s=float(DEFAULT_TASK_DURATION_S))

    # -- identity ----------------------------------------------------------- #

    def queue_row(self) -> tuple:
        """The queue-persist columns that never change for a materialized
        task (scheduler/persister.py), memoized per instance: the
        incremental TickCache replaces changed docs with NEW Task objects,
        so an unchanged task pays the 13-attribute extraction once across
        all its ticks, not once per tick."""
        row = self._qrow
        if row is None:
            row = self._qrow = (
                self.id,
                self.display_name,
                self.build_variant,
                self.project,
                self.version,
                self.requester,
                self.revision_order_number,
                self.priority,
                self.task_group,
                self.task_group_max_hosts,
                self.task_group_order,
                self.expected_duration_s,
                self.num_dependents,
                [d.task_id for d in self.depends_on],
            )
        return row

    def task_group_string(self) -> str:
        """Unit key for task-group members (reference
        model/task/task.go GetTaskGroupString): group _ variant _ project _ version."""
        return f"{self.task_group}_{self.build_variant}_{self.project}_{self.version}"

    # -- predicates ---------------------------------------------------------- #

    def is_finished(self) -> bool:
        return self.status in TASK_COMPLETED_STATUSES

    def is_dispatchable(self) -> bool:
        return (
            self.status == TaskStatus.UNDISPATCHED.value
            and self.activated
            and self.priority >= 0
        )

    def is_stepback_activated(self) -> bool:
        return self.activated_by == STEPBACK_TASK_ACTIVATOR

    def is_in_task_group(self) -> bool:
        return self.task_group != ""

    def is_single_host_task_group(self) -> bool:
        return self.task_group != "" and self.task_group_max_hosts == 1

    def blocked(self) -> bool:
        """A task is blocked when any dependency is marked unattainable
        (reference model/task/task.go Blocked)."""
        if self.override_dependencies:
            return False
        return any(d.unattainable for d in self.depends_on)

    def dependencies_met(self, cache: Dict[str, "Task"]) -> bool:
        """Reference semantics of task.DependenciesMet
        (model/task/task.go:634): every parent must be finished with the
        required status; missing parents count as unmet."""
        if self.override_dependencies or not self.depends_on:
            return True
        for dep in self.depends_on:
            parent = cache.get(dep.task_id)
            if parent is None:
                return False
            if not parent.is_finished():
                return False
            if dep.status == DEP_STATUS_ANY:
                continue
            if parent.status != dep.status:
                return False
        return True

    def time_in_queue(self, now: Optional[float] = None) -> float:
        """Queue-wait signal used by the planner (reference
        scheduler/planner.go:318-322): prefer activated time, fall back to
        ingest time."""
        now = _time.time() if now is None else now
        if self.activated_time > 0.0:
            return min(max(0.0, now - self.activated_time), MAX_TASK_TIME_IN_QUEUE_S)
        if self.ingest_time > 0.0:
            return min(max(0.0, now - self.ingest_time), MAX_TASK_TIME_IN_QUEUE_S)
        return 0.0

    def wait_since_dependencies_met(self, now: Optional[float] = None) -> float:
        """Overdue-wait signal for the allocator feedback rule (reference
        scheduler/scheduler.go:121-133)."""
        now = _time.time() if now is None else now
        start = max(self.scheduled_time, self.dependencies_met_time)
        if start <= 0.0:
            return 0.0
        return max(0.0, now - start)

    # -- serialization ------------------------------------------------------- #

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        doc.pop("_qrow", None)  # instance memo, not document state
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "Task":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        doc["depends_on"] = [
            d if isinstance(d, Dependency) else Dependency.from_doc(d)
            for d in doc.get("depends_on", [])
        ]
        known = _TASK_FIELDS  # dataclasses.fields() per doc is hot-loop cost
        return cls(**{k: v for k, v in doc.items() if k in known})


_TASK_FIELDS = frozenset(f.name for f in dataclasses.fields(Task))


# --------------------------------------------------------------------------- #
# Queries (reference model/task/db.go query builders)
# --------------------------------------------------------------------------- #


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def insert(store: Store, task: Task) -> None:
    coll(store).insert(task.to_doc())


def insert_many(store: Store, tasks: List[Task]) -> None:
    coll(store).insert_many([t.to_doc() for t in tasks])


def get(store: Store, task_id: str) -> Optional[Task]:
    doc = coll(store).get(task_id)
    return Task.from_doc(doc) if doc else None


def by_ids(store: Store, ids: List[str]) -> List[Task]:
    return [Task.from_doc(d) for d in coll(store).find_ids(ids)]


def find(store: Store, pred=None) -> List[Task]:
    return [Task.from_doc(d) for d in coll(store).find(pred)]


def find_host_runnable(store: Store, distro_id: str = "") -> List[Task]:
    """The finder: undispatched + activated + non-disabled host tasks for a
    distro, including not-yet-dep-met tasks (the revised dispatcher handles
    ordering). Reference: task.FindHostRunnable ($graphLookup pipeline,
    scheduler/task_finder.go:34-36) with IncludesDependencies semantics.
    """

    def pred(doc: dict) -> bool:
        if doc["status"] != TaskStatus.UNDISPATCHED.value or not doc["activated"]:
            return False
        if doc["priority"] < 0:
            return False
        if doc.get("execution_platform", "host") != "host":
            return False
        if distro_id and doc["distro_id"] != distro_id and distro_id not in doc.get(
            "secondary_distros", []
        ):
            return False
        return True

    tasks = find(store, pred)
    # Drop blocked tasks (unattainable dependencies): the reference's
    # $graphLookup pipeline filters these out of the runnable set.
    return [t for t in tasks if not t.blocked()]


def mark_scheduled(
    store: Store, task_ids: List[str], when: float, deps_met_ids: Iterable[str] = ()
) -> int:
    """Stamp scheduled_time for newly planned tasks and
    dependencies_met_time the first time a task is seen with its
    dependencies satisfied (reference SetTasksScheduledAndDepsMetTime via
    scheduler/task_queue_persister.go:17-62 + model/task/task.go:1161-1175;
    the latter keeps the allocator's waits-over-threshold feedback from
    counting pre-dependency wait)."""
    c = coll(store)
    deps_met_set = set(deps_met_ids)
    # check-before-mutate (a steady-state tick must not dirty unchanged
    # tasks), then ONE batched update per stamp kind: each bulk_update is
    # a single lock acquisition and a single WAL record instead of a
    # mutate round per task; the only_if predicate re-checks under the
    # lock so a concurrent stamp can't be double-applied
    docs = c.find_ids(task_ids)
    sched_ids = [
        d["_id"] for d in docs if d.get("scheduled_time", 0.0) <= 0.0
    ]
    dmt_ids = [
        d["_id"]
        for d in docs
        if d["_id"] in deps_met_set
        and d.get("dependencies_met_time", 0.0) <= 0.0
    ]
    n = c.bulk_update(
        sched_ids,
        {"scheduled_time": when},
        only_if=lambda d: d.get("scheduled_time", 0.0) <= 0.0,
    )
    c.bulk_update(
        dmt_ids,
        {"dependencies_met_time": when},
        only_if=lambda d: d.get("dependencies_met_time", 0.0) <= 0.0,
    )
    return n


def unschedule_stale_underwater(
    store: Store, distro_id: str, now: float, threshold_s: float
) -> List[str]:
    """Deactivate tasks stale in the queue beyond the underwater threshold
    and zero their priority (reference task.UnscheduleStaleUnderwaterHostTasks
    via scheduler/scheduler.go:223)."""

    def stale(doc: dict) -> bool:
        if doc["status"] != TaskStatus.UNDISPATCHED.value or not doc["activated"]:
            return False
        if distro_id and doc["distro_id"] != distro_id:
            return False
        activated = doc.get("activated_time", 0.0) or doc.get("ingest_time", 0.0)
        return activated > 0.0 and (now - activated) > threshold_s

    c = coll(store)
    doomed = [d["_id"] for d in c.find(stale)]
    for tid in doomed:
        c.update(tid, {"activated": False, "priority": 0})
    return doomed
