"""Users, API keys, roles.

Reference: auth/ package (naive/github/okta/api-only user managers,
auth.go:17 LoadUserManager) + gimlet role-based ACL wired in
environment.go:1249. One pluggable UserManager with the naive (config
users) implementation; role scopes gate admin/project actions.
"""
from __future__ import annotations

import dataclasses
import hashlib
import random
import secrets
import time as _time
from typing import List, Optional

from ..storage.store import Collection, Store

COLLECTION = "users"

# role scopes (the subset of gimlet's role manager the routes consume)
SCOPE_SUPERUSER = "superuser"
SCOPE_PROJECT_ADMIN = "project_admin"  # per-project, stored as project:<id>
SCOPE_TASK_ADMIN = "task_admin"


@dataclasses.dataclass
class User:
    id: str
    display_name: str = ""
    email: str = ""
    api_key: str = ""
    roles: List[str] = dataclasses.field(default_factory=list)
    created_at: float = 0.0
    banned: bool = False

    def has_scope(self, scope: str) -> bool:
        return not self.banned and (
            scope in self.roles or SCOPE_SUPERUSER in self.roles
        )

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "User":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        return cls(**doc)


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def create_user(
    store: Store, user_id: str, display_name: str = "", email: str = "",
    roles: Optional[List[str]] = None,
) -> User:
    u = User(
        id=user_id,
        display_name=display_name or user_id,
        email=email,
        api_key=secrets.token_hex(16),
        roles=roles or [],
        created_at=_time.time(),
    )
    coll(store).insert(u.to_doc())
    return u


def get_user(store: Store, user_id: str) -> Optional[User]:
    doc = coll(store).get(user_id)
    return User.from_doc(doc) if doc else None


def user_by_api_key(store: Store, api_key: str) -> Optional[User]:
    if not api_key:
        return None
    docs = coll(store).find(lambda d: d.get("api_key") == api_key)
    return User.from_doc(docs[0]) if docs else None


def grant_role(store: Store, user_id: str, role: str) -> bool:
    def add(doc: dict) -> None:
        if role not in doc["roles"]:
            doc["roles"].append(role)

    return coll(store).mutate(user_id, add)


class RateLimiter:
    """Sliding-window per-key limiter (reference ratelimit/ NewRateLimiter,
    Redis-backed there; windowed counters here)."""

    def __init__(self, store: Store, limit: int, window_s: float = 60.0) -> None:
        self.store = store
        self.limit = limit
        self.window_s = window_s

    def allow(
        self, key: str, now: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> bool:
        limit = self.limit if limit is None else limit
        now = _time.time() if now is None else now
        bucket = int(now // self.window_s)
        doc_id = f"{key}:{bucket}"
        coll = self.store.collection("rate_limits")

        count = {"n": 0}

        def bump(doc: dict) -> None:
            doc["n"] += 1
            count["n"] = doc["n"]

        if not coll.mutate(doc_id, bump):
            coll.upsert({"_id": doc_id, "n": 1, "at": now})
            count["n"] = 1
        # probabilistic cleanup of old windows — a full-collection scan on
        # every request would let any client buy O(collection) work per call
        if random.random() < 1.0 / 64:
            coll.remove_where(
                lambda d: now - d.get("at", now) > 2 * self.window_s
            )
        return count["n"] <= limit
