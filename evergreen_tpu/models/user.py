"""Users, API keys, roles.

Reference: auth/ package (naive/github/okta/api-only user managers,
auth.go:17 LoadUserManager) + gimlet role-based ACL wired in
environment.go:1249. One pluggable UserManager with the naive (config
users) implementation; role scopes gate admin/project actions.
"""
from __future__ import annotations

import dataclasses
import hashlib
import random
import re
import secrets
import time as _time
from typing import List, Optional

from ..storage.store import Collection, Store

COLLECTION = "users"

# role scopes (the subset of gimlet's role manager the routes consume)
SCOPE_SUPERUSER = "superuser"
SCOPE_PROJECT_ADMIN = "project_admin"  # per-project, stored as project:<id>
SCOPE_TASK_ADMIN = "task_admin"


@dataclasses.dataclass
class User:
    id: str
    display_name: str = ""
    email: str = ""
    api_key: str = ""
    roles: List[str] = dataclasses.field(default_factory=list)
    created_at: float = 0.0
    banned: bool = False
    #: named SSH public keys ([{name, key, created_at}]) — injected into
    #: the user's spawn hosts at provision time (reference
    #: model/user/user.go:35 PubKeys + cloud spawn-host authorized_keys)
    public_keys: List[dict] = dataclasses.field(default_factory=list)

    def has_scope(self, scope: str) -> bool:
        return not self.banned and (
            scope in self.roles or SCOPE_SUPERUSER in self.roles
        )

    def to_doc(self) -> dict:
        doc = dataclasses.asdict(self)
        doc["_id"] = doc.pop("id")
        return doc

    @classmethod
    def from_doc(cls, doc: dict) -> "User":
        doc = dict(doc)
        doc["id"] = doc.pop("_id")
        return cls(**doc)


def coll(store: Store) -> Collection:
    return store.collection(COLLECTION)


def create_user(
    store: Store, user_id: str, display_name: str = "", email: str = "",
    roles: Optional[List[str]] = None,
) -> User:
    u = User(
        id=user_id,
        display_name=display_name or user_id,
        email=email,
        api_key=secrets.token_hex(16),
        roles=roles or [],
        created_at=_time.time(),
    )
    coll(store).insert(u.to_doc())
    return u


def get_user(store: Store, user_id: str) -> Optional[User]:
    doc = coll(store).get(user_id)
    return User.from_doc(doc) if doc else None


def user_by_api_key(store: Store, api_key: str) -> Optional[User]:
    if not api_key:
        return None
    docs = coll(store).find(lambda d: d.get("api_key") == api_key)
    return User.from_doc(docs[0]) if docs else None


def grant_role(store: Store, user_id: str, role: str) -> bool:
    def add(doc: dict) -> None:
        if role not in doc["roles"]:
            doc["roles"].append(role)

    return coll(store).mutate(user_id, add)


def revoke_role(store: Store, user_id: str, role: str) -> bool:
    def drop(doc: dict) -> None:
        if role in doc["roles"]:
            doc["roles"].remove(role)

    return coll(store).mutate(user_id, drop)


def revoke_all_roles(store: Store, user_id: str) -> bool:
    """reference rest/route/permissions.go deleteUserPermissions: strip
    every role from the user in one shot."""

    def clear(doc: dict) -> None:
        doc["roles"] = []

    return coll(store).mutate(user_id, clear)


#: key names must be route- and shell-addressable; key text must be one
#: line of the ssh authorized_keys charset — this is the guard that keeps
#: user-controlled key text from ever being able to escape the user-data
#: script that writes it (cloud/userdata.py)
_KEY_NAME_RE = re.compile(r"^[A-Za-z0-9._\-]{1,64}$")
_KEY_TEXT_RE = re.compile(r"^[A-Za-z0-9+/=@.:_\- ]{1,16384}$")


class PublicKeyError(ValueError):
    pass


def add_public_key(
    store: Store, user_id: str, name: str, key: str,
    now: Optional[float] = None,
) -> bool:
    """Add a named SSH public key (reference user.AddPublicKey); names
    are unique per user — re-adding a name replaces the key."""
    if not _KEY_NAME_RE.match(name):
        raise PublicKeyError(
            "key name must be 1-64 chars of letters, digits, . _ -"
        )
    if not _KEY_TEXT_RE.match(key):
        raise PublicKeyError(
            "key must be a single line of ssh public-key characters"
        )
    now = _time.time() if now is None else now

    def add(doc: dict) -> None:
        keys = [k for k in doc.get("public_keys", []) if k["name"] != name]
        keys.append({"name": name, "key": key, "created_at": now})
        doc["public_keys"] = keys

    return coll(store).mutate(user_id, add)


def delete_public_key(store: Store, user_id: str, name: str) -> bool:
    """reference user.DeletePublicKey; False when no such key name."""
    removed = {"n": 0}

    def drop(doc: dict) -> None:
        keys = doc.get("public_keys", [])
        kept = [k for k in keys if k["name"] != name]
        removed["n"] = len(keys) - len(kept)
        doc["public_keys"] = kept

    if not coll(store).mutate(user_id, drop):
        return False
    return removed["n"] > 0


class RateLimiter:
    """Sliding-window per-key limiter (reference ratelimit/ NewRateLimiter,
    Redis-backed there; windowed counters here)."""

    def __init__(self, store: Store, limit: int, window_s: float = 60.0) -> None:
        self.store = store
        self.limit = limit
        self.window_s = window_s

    def allow(
        self, key: str, now: Optional[float] = None,
        limit: Optional[int] = None,
    ) -> bool:
        limit = self.limit if limit is None else limit
        now = _time.time() if now is None else now
        bucket = int(now // self.window_s)
        doc_id = f"{key}:{bucket}"
        coll = self.store.collection("rate_limits")

        count = {"n": 0}

        def bump(doc: dict) -> None:
            doc["n"] += 1
            count["n"] = doc["n"]

        if not coll.mutate(doc_id, bump):
            coll.upsert({"_id": doc_id, "n": 1, "at": now})
            count["n"] = 1
        # probabilistic cleanup of old windows — a full-collection scan on
        # every request would let any client buy O(collection) work per call
        if random.random() < 1.0 / 64:
            coll.remove_where(
                lambda d: now - d.get("at", now) > 2 * self.window_s
            )
        return count["n"] <= limit

    def retry_after_s(self, now: Optional[float] = None) -> float:
        """Seconds until the current window rolls over — what a limited
        client should put in its backoff (served as Retry-After)."""
        now = _time.time() if now is None else now
        return self.window_s - (now % self.window_s)
