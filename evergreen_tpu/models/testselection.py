"""Test selection service.

The reference delegates to an external test-selection service (TSS) —
config_test_selection.go + the test_selection.get agent command — whose
job is to recommend the subset of a task's tests worth running. This is
the in-process equivalent behind the same command: strategies over the
framework's own historical test results.

Default strategy ``failed-first``: a test is DESELECTED only when recent
history for the same (project, variant, task) shows it consistently
passing; failures anywhere in the window and tests with no history (new
tests) are always selected. That matches the TSS goal — skip the tests
that demonstrably never fail — while never skipping anything the data
cannot vouch for.
"""
from __future__ import annotations

from typing import Dict, List

from ..globals import TASK_COMPLETED_STATUSES
from ..storage.store import Store
from . import artifact as artifact_mod
from . import task as task_mod

#: how many recent finished executions of the same task definition to consult
HISTORY_WINDOW = 5
#: minimum consistently-passing observations before a test may be skipped
MIN_OBSERVATIONS = 2


def select_tests(
    store: Store, task_id: str, tests: List[str], strategies: str = ""
) -> List[str]:
    """Recommend the subset of ``tests`` to run for ``task_id``.

    Unknown strategy names fall back to selecting everything (the
    reference treats the service as advisory — a selection failure must
    never drop coverage).
    """
    if not tests:
        return []
    strategy = (strategies or "failed-first").split(",")[0].strip()
    if strategy not in ("failed-first",):
        return list(tests)
    t = task_mod.get(store, task_id)
    if t is None:
        return list(tests)

    # recent finished runs of the same task definition (any execution)
    history = task_mod.find(
        store,
        lambda d: d["project"] == t.project
        and d["build_variant"] == t.build_variant
        and d["display_name"] == t.display_name
        and d["_id"] != task_id
        and d["status"] in TASK_COMPLETED_STATUSES,
    )
    history.sort(key=lambda h: h.finish_time, reverse=True)
    passes: Dict[str, int] = {}
    failed: set = set()
    for h in history[:HISTORY_WINDOW]:
        for r in artifact_mod.get_test_results(store, h.id, h.execution):
            if r.status == "pass":
                passes[r.test_name] = passes.get(r.test_name, 0) + 1
            else:
                failed.add(r.test_name)

    selected = [
        name
        for name in tests
        if name in failed or passes.get(name, 0) < MIN_OBSERVATIONS
    ]
    return selected
