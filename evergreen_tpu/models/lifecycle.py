"""Task lifecycle state transitions.

The MarkEnd path is the write-heavy heart of the control plane (reference
model/task_lifecycle.go:713-1150): finishing a task propagates to dependent
tasks (finished flags + transitive unattainable marking), frees the host,
feeds the event log, evaluates stepback, and rolls build/version statuses up.
"""
from __future__ import annotations

import time as _time
from typing import Dict, List, Optional, Tuple

from ..globals import (
    CONSECUTIVE_SYSTEM_FAILURE_THRESHOLD,
    STEPBACK_TASK_ACTIVATOR,
    TASK_COMPLETED_STATUSES,
    BuildStatus,
    HostStatus,
    Provider,
    Requester,
    TaskStatus,
    VersionStatus,
)
from ..storage.store import Store
from . import build as build_mod
from . import event as event_mod
from . import host as host_mod
from . import task as task_mod
from . import version as version_mod
from .task import DEP_STATUS_ANY, Task


def mark_task_dispatched(
    store: Store, task_id: str, host_id: str, now: Optional[float] = None
) -> bool:
    """Atomic undispatched→dispatched transition (reference
    task.MarkAsHostDispatched via rest/route/host_agent.go:311-420)."""
    now = _time.time() if now is None else now
    return task_mod.coll(store).compare_and_set(
        task_id,
        expect={"status": TaskStatus.UNDISPATCHED.value},
        update={
            "status": TaskStatus.DISPATCHED.value,
            "dispatch_time": now,
            "host_id": host_id,
            "last_heartbeat": now,
        },
    )


def mark_task_started(
    store: Store, task_id: str, now: Optional[float] = None
) -> bool:
    now = _time.time() if now is None else now
    ok = task_mod.coll(store).compare_and_set(
        task_id,
        expect={"status": TaskStatus.DISPATCHED.value},
        update={
            "status": TaskStatus.STARTED.value,
            "start_time": now,
            "last_heartbeat": now,
        },
    )
    if ok:
        event_mod.log(
            store, event_mod.RESOURCE_TASK, "TASK_STARTED", task_id, timestamp=now
        )
    return ok


def _dep_satisfied(dep_status: str, final_status: str) -> bool:
    if dep_status == DEP_STATUS_ANY:
        return True
    return dep_status == final_status


def update_dependencies_on_finish(
    store: Store, finished: Task, now: float
) -> List[str]:
    """Propagate a finished task's outcome to its dependents: set the edge's
    finished flag; if unsatisfied, mark it unattainable and transitively
    block downstream tasks (reference UpdateBlockedDependencies +
    MarkDependenciesFinished, model/task_lifecycle.go:775-776).

    Dependents whose every edge is now finished-and-satisfied get a
    dependency WAKE: their queue items flip to dependencies-met in place
    and the distro's dispatcher is invalidated, so they dispatch on the
    next poll instead of after the next planning tick + dispatcher TTL
    (a latency improvement over the reference, which waits for both —
    task_queue_service_dependency.go:316-317).

    Returns the ids of tasks that became blocked.
    """
    coll = task_mod.coll(store)
    newly_ready: List[str] = []
    # Wave of (task id, final-or-blocked status, blocked?) to propagate.
    newly_blocked: List[str] = []
    wave = [(finished.id, finished.status, False)]
    seen: set = set()
    while wave:
        parent_id, parent_status, parent_blocked = wave.pop()
        if parent_id in seen:
            continue
        seen.add(parent_id)

        def affects(doc: dict) -> bool:
            return any(d["task_id"] == parent_id for d in doc.get("depends_on", []))

        for doc in coll.find(affects):
            # docs returned by find() alias live store state: mutate a COPY
            # of the edge list and land it via coll.update so concurrent
            # readers never see half-updated edges and the change always
            # fires the dirty-set listener (tick-cache invariant)
            deps = [dict(d) for d in doc["depends_on"]]
            changed = False
            became_blocked = False
            for dep in deps:
                if dep["task_id"] != parent_id:
                    continue
                if parent_blocked:
                    if not dep["unattainable"]:
                        dep["unattainable"] = True
                        changed = became_blocked = True
                else:
                    dep["finished"] = True
                    changed = True
                    if not _dep_satisfied(dep["status"], parent_status):
                        if not dep["unattainable"]:
                            dep["unattainable"] = True
                            became_blocked = True
            if changed:
                coll.update(doc["_id"], {"depends_on": deps})
                if (
                    not became_blocked
                    and doc["status"] == TaskStatus.UNDISPATCHED.value
                    and doc.get("activated")
                    and all(
                        d["finished"] and not d["unattainable"]
                        for d in deps
                    )
                ):
                    newly_ready.append(doc["_id"])
                    if doc.get("dependencies_met_time", 0.0) <= 0.0:
                        coll.update(doc["_id"], {"dependencies_met_time": now})
            if became_blocked and not doc.get("override_dependencies", False):
                newly_blocked.append(doc["_id"])
                wave.append((doc["_id"], "", True))
                event_mod.log(
                    store,
                    event_mod.RESOURCE_TASK,
                    "TASK_BLOCKED",
                    doc["_id"],
                    {"blocked_by": parent_id},
                    timestamp=now,
                )

    if newly_ready:
        from ..dispatch.wake import wake_dependents

        wake_dependents(store, newly_ready, now)
    return newly_blocked


def check_reset_single_host_task_group(
    store: Store, t: Task, now: float
) -> bool:
    """Once every task of a single-host task group is finished (or blocked
    or deactivated), restart the whole group if any member requested it via
    ``reset_when_finished`` (reference model/task_lifecycle.go:2770
    checkResetSingleHostTaskGroup, invoked from MarkEnd). Returns whether a
    reset happened."""
    if not t.is_single_host_task_group():
        return False
    members = task_mod.find(
        store,
        lambda d: d["build_id"] == t.build_id
        and d["task_group"] == t.task_group,
    )
    if not members:
        return False
    should_reset = False
    for m in members:
        if m.reset_when_finished:
            should_reset = True
        if (
            m.status not in TASK_COMPLETED_STATUSES
            and m.activated
            and not m.blocked()
        ):
            return False  # a member still needs to run
    if not should_reset:
        return False
    from ..units.task_jobs import restart_task

    c = task_mod.coll(store)
    reset_ids: List[str] = []
    for m in members:
        c.update(m.id, {"reset_when_finished": False})
        if m.status in TASK_COMPLETED_STATUSES:
            if restart_task(store, m.id, by="single-host-group-reset",
                            now=now):
                reset_ids.append(m.id)
        else:
            # never ran this round (deactivated or blocked): reactivate so
            # the whole group reruns together (reference resetManyTasks
            # resets every member, model/task_lifecycle.go:2798)
            c.update(m.id, {"activated": True,
                            "activated_by": "single-host-group-reset",
                            "activated_time": now})
            reset_ids.append(m.id)
    event_mod.log(
        store,
        event_mod.RESOURCE_TASK,
        "TASK_GROUP_RESET",
        t.id,
        {"task_group": t.task_group, "build_id": t.build_id,
         "members": reset_ids},
        timestamp=now,
    )
    return True


def finish_agent_task(
    store: Store,
    task_id: str,
    status: str,
    details_type: str = "",
    details_desc: str = "",
    timed_out: bool = False,
    now: Optional[float] = None,
) -> Tuple[Optional[Task], bool]:
    """The one agent-facing finish path shared by every transport (HTTP
    route and in-process communicator): MarkEnd plus poisoned-host
    accounting. Returns (finished task or None if not running,
    should_exit)."""
    now = _time.time() if now is None else now
    t = mark_end(
        store,
        task_id,
        status,
        now=now,
        details_type=details_type,
        details_desc=details_desc,
        timed_out=timed_out,
    )
    if t is None:
        return None, False
    return t, note_host_task_outcome(store, t, details_type, now)


def note_host_task_outcome(
    store: Store, t: Task, details_type: str, now: float
) -> bool:
    """Poisoned-host detection (reference rest/route/host_agent.go:32,1454-
    1469): a dynamic host whose last N task finishes were all system
    failures is assumed unhealthy — decommission it and tell the agent to
    exit. Returns should_exit. Static hosts are managed separately and are
    never auto-disabled."""
    if not t.host_id:
        return False
    hcoll = host_mod.coll(store)
    h = hcoll.get(t.host_id)
    if h is None or h["provider"] == Provider.STATIC.value:
        return False
    system_failed = (
        t.status == TaskStatus.FAILED.value and details_type == "system"
    )
    if not system_failed:
        if h.get("consecutive_system_fails", 0):
            hcoll.update(t.host_id, {"consecutive_system_fails": 0})
        return False
    n = h.get("consecutive_system_fails", 0) + 1
    hcoll.update(t.host_id, {"consecutive_system_fails": n})
    if n < CONSECUTIVE_SYSTEM_FAILURE_THRESHOLD:
        return False
    if h["status"] == HostStatus.RUNNING.value:
        # already-down statuses (quarantined for debugging, terminated,
        # decommissioned) are never overwritten — the reference's poison
        # handler no-ops on any non-running host
        hcoll.update(t.host_id, {"status": HostStatus.DECOMMISSIONED.value})
        event_mod.log(
            store,
            event_mod.RESOURCE_HOST,
            "HOST_POISONED",
            t.host_id,
            {"consecutive_system_failures": n, "task_id": t.id},
            timestamp=now,
        )
    return True


def block_single_host_task_group(store: Store, t: Task, now: float) -> List[str]:
    """When a single-host task-group member fails, later members of the
    group must not run: they gain an unattainable dependency on the failed
    task (reference: EndTask-side group blocking,
    model/task_lifecycle.go blockTaskGroupTasks; the dispatcher comment at
    task_queue_service_dependency.go:690 'rely on EndTask to block later
    tasks')."""
    if not t.is_single_host_task_group():
        return []
    if t.status == TaskStatus.SUCCEEDED.value:
        return []
    group_key = t.task_group_string()
    blocked: List[str] = []
    c = task_mod.coll(store)
    for doc in c.find(
        lambda d: d["task_group"] == t.task_group
        and d["build_variant"] == t.build_variant
        and d["project"] == t.project
        and d["version"] == t.version
        and d["task_group_order"] > t.task_group_order
        and d["status"] == TaskStatus.UNDISPATCHED.value
    ):
        deps = doc.get("depends_on", [])
        if any(d["task_id"] == t.id for d in deps):
            for d in deps:
                if d["task_id"] == t.id:
                    d["unattainable"] = True
                    d["finished"] = True
        else:
            deps.append(
                {
                    "task_id": t.id,
                    "status": TaskStatus.SUCCEEDED.value,
                    "unattainable": True,
                    "finished": True,
                }
            )
        c.update(doc["_id"], {"depends_on": deps})
        blocked.append(doc["_id"])
        event_mod.log(
            store,
            event_mod.RESOURCE_TASK,
            "TASK_BLOCKED",
            doc["_id"],
            {"blocked_by": t.id, "reason": "single-host task group failure",
             "group": group_key},
            timestamp=now,
        )
    return blocked


def activate_task_with_dependencies(
    store: Store, task_id: str, by: str, now: Optional[float] = None
) -> List[str]:
    """Activate a task AND its unfinished dependency closure (reference
    model.SetActiveState / task.ActivateDeactivatedDependencies —
    scheduling a task implies scheduling everything it needs).
    Returns every task id activated."""
    now = _time.time() if now is None else now
    c = task_mod.coll(store)
    activated: List[str] = []
    stack = [task_id]
    seen: set = set()
    while stack:
        tid = stack.pop()
        if tid in seen:
            continue
        seen.add(tid)
        doc = c.get(tid)
        if doc is None:
            continue
        if doc["status"] == TaskStatus.UNDISPATCHED.value and not doc["activated"]:
            c.update(
                tid,
                {"activated": True, "activated_by": by, "activated_time": now},
            )
            activated.append(tid)
        stack.extend(d["task_id"] for d in doc.get("depends_on", []))
    if activated:
        event_mod.log(
            store,
            event_mod.RESOURCE_TASK,
            "TASKS_ACTIVATED",
            task_id,
            {"by": by, "count": len(activated)},
            timestamp=now,
        )
    return activated


def evaluate_stepback(store: Store, t: Task, now: float) -> Optional[str]:
    """Stepback: when a mainline task fails, activate the same task at an
    earlier commit to locate the offending revision — the previous commit
    (linear, reference doLinearStepback model/task_lifecycle.go:464) or the
    midpoint between the last pass and this failure (bisect, :496),
    selected per project ref. Returns the activated task id, if any."""
    if t.status != TaskStatus.FAILED.value:
        return None
    if t.requester != Requester.REPOTRACKER.value:
        return None
    if t.details_type == "system":
        return None  # system failures don't step back

    ref_doc = store.collection("project_refs").get(t.project) or {}
    if ref_doc.get("stepback_disabled"):
        return None
    bisect = bool(ref_doc.get("stepback_bisect"))

    candidates = task_mod.find(
        store,
        lambda doc: doc["project"] == t.project
        and doc["build_variant"] == t.build_variant
        and doc["display_name"] == t.display_name
        and doc["requester"] == Requester.REPOTRACKER.value
        and doc["revision_order_number"] < t.revision_order_number,
    )
    if not candidates:
        return None
    candidates.sort(key=lambda x: x.revision_order_number)

    target: Optional[Task] = None
    if bisect:
        # window: (last passing order, current failing order)
        passing = [
            c for c in candidates if c.status == TaskStatus.SUCCEEDED.value
        ]
        lo = passing[-1].revision_order_number if passing else 0
        window = [
            c
            for c in candidates
            if lo < c.revision_order_number < t.revision_order_number
            and c.status == TaskStatus.UNDISPATCHED.value
            and not c.activated
        ]
        if window:
            target = window[len(window) // 2]
    else:
        prev = candidates[-1]
        if prev.status == TaskStatus.UNDISPATCHED.value and not prev.activated:
            target = prev

    if target is None:
        return None
    task_mod.coll(store).update(
        target.id,
        {
            "activated": True,
            "activated_by": STEPBACK_TASK_ACTIVATOR,
            "activated_time": now,
        },
    )
    event_mod.log(
        store,
        event_mod.RESOURCE_TASK,
        "TASK_ACTIVATED_STEPBACK",
        target.id,
        {"failed_task": t.id, "mode": "bisect" if bisect else "linear"},
        timestamp=now,
    )
    return target.id


def update_build_and_version_status(store: Store, t: Task, now: float) -> None:
    """Roll task status up to its build and version (reference
    UpdateBuildAndVersionStatusForTask, model/task_lifecycle.go)."""
    if not t.build_id:
        return
    b = build_mod.get(store, t.build_id)
    if b is None:
        return
    member_tasks = task_mod.find(store, lambda d: d["build_id"] == t.build_id)
    activated = [x for x in member_tasks if x.activated or x.is_finished()]
    all_finished = activated and all(x.is_finished() for x in activated)
    any_failed = any(x.status == TaskStatus.FAILED.value for x in member_tasks)
    any_active = any(
        x.status in (TaskStatus.DISPATCHED.value, TaskStatus.STARTED.value)
        for x in member_tasks
    )
    if all_finished:
        new_status = (
            BuildStatus.FAILED.value if any_failed else BuildStatus.SUCCEEDED.value
        )
    elif any_active or any(x.is_finished() for x in member_tasks):
        new_status = BuildStatus.STARTED.value
    else:
        new_status = b.status
    if new_status != b.status:
        update: Dict = {"status": new_status}
        if new_status == BuildStatus.STARTED.value and b.start_time == 0.0:
            update["start_time"] = now
        if new_status in (BuildStatus.FAILED.value, BuildStatus.SUCCEEDED.value):
            update["finish_time"] = now
        build_mod.coll(store).update(b.id, update)
        event_mod.log(
            store,
            event_mod.RESOURCE_BUILD,
            f"BUILD_{new_status.upper().replace('-', '_')}",
            b.id,
            timestamp=now,
        )

    if not b.version:
        return
    v = version_mod.get(store, b.version)
    if v is None:
        return
    builds = build_mod.find_by_version(store, b.version)
    statuses = [
        new_status if x.id == b.id else x.status for x in builds
    ]
    if statuses and all(
        s in (BuildStatus.FAILED.value, BuildStatus.SUCCEEDED.value)
        for s in statuses
    ):
        v_status = (
            VersionStatus.FAILED.value
            if any(s == BuildStatus.FAILED.value for s in statuses)
            else VersionStatus.SUCCEEDED.value
        )
    elif any(
        s
        in (
            BuildStatus.STARTED.value,
            BuildStatus.FAILED.value,
            BuildStatus.SUCCEEDED.value,
        )
        for s in statuses
    ):
        v_status = VersionStatus.STARTED.value
    else:
        v_status = v.status
    if v_status != v.status:
        update = {"status": v_status}
        if v_status == VersionStatus.STARTED.value and v.start_time == 0.0:
            update["start_time"] = now
        if v_status in (VersionStatus.FAILED.value, VersionStatus.SUCCEEDED.value):
            update["finish_time"] = now
        version_mod.coll(store).update(v.id, update)
        event_mod.log(
            store,
            event_mod.RESOURCE_VERSION,
            f"VERSION_{v_status.upper()}",
            v.id,
            timestamp=now,
        )


def mark_end(
    store: Store,
    task_id: str,
    status: str,
    now: Optional[float] = None,
    details_type: str = "",
    details_desc: str = "",
    timed_out: bool = False,
) -> Optional[Task]:
    """Finish a task: final status + details, host release, dependency
    propagation, event, stepback, status rollup (reference model.MarkEnd,
    model/task_lifecycle.go:713-1150)."""
    now = _time.time() if now is None else now
    c = task_mod.coll(store)
    doc = c.get(task_id)
    if doc is None:
        return None
    if doc["status"] not in (
        TaskStatus.DISPATCHED.value,
        TaskStatus.STARTED.value,
    ):
        return None
    c.update(
        task_id,
        {
            "status": status,
            "finish_time": now,
            "details_type": details_type,
            "details_desc": details_desc,
            "details_timed_out": timed_out,
        },
    )
    t = task_mod.get(store, task_id)

    if t.host_id:
        host_mod.clear_running_task(store, t.host_id, task_id, now)

    event_mod.log(
        store,
        event_mod.RESOURCE_TASK,
        "TASK_FINISHED",
        task_id,
        {"status": status, "details_type": details_type},
        timestamp=now,
    )

    update_dependencies_on_finish(store, t, now)
    block_single_host_task_group(store, t, now)
    check_reset_single_host_task_group(store, t, now)
    if t.reset_when_finished and not t.is_single_host_task_group():
        # reference SetResetWhenFinished semantics for ordinary tasks: a
        # restart requested while the task ran happens now, automatically.
        # Single-host group members defer to the group reset above, which
        # fires only once every member has finished.
        from ..units.task_jobs import restart_task

        task_mod.coll(store).update(t.id, {"reset_when_finished": False})
        restart_task(store, t.id, by="reset-when-finished", now=now)
    evaluate_stepback(store, t, now)
    update_build_and_version_status(store, t, now)

    # cloud cost attribution (reference model/task_lifecycle.go:754-768)
    from .cost import attribute_task_cost

    attribute_task_cost(store, task_id, now)
    return t
