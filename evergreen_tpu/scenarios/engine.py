"""The trace-driven scenario engine: compile a spec, replay it, score it.

One ``ScenarioRun`` owns a full in-process plane — a Store (in-memory or
DurableStore + writer lease in a temp data dir), the scheduler tick
(``run_tick``), the dispatch CAS pair, the cloud manager fakes, the
provisioning pipeline, and the overload ladder — and replays the spec's
event timeline on a **virtual clock**: tick ``t`` happens at
``NOW + (t+1) * tick_s`` regardless of how fast this box runs, so a
week-of-weather trace compresses to minutes and the scorecard is a
function of the seed, not the hardware.

Injection rides the existing seams, never new wiring: faults install a
PR-1 ``FaultPlan`` (scheduler.solve / wal.commit / wal.fence / …), a
region failover is the PR-3 lease steal fired from the ``wal.fence``
seam mid-commit (the engine then fails over to the thief's epoch and
keeps replaying), and spot reclamation terminates instances inside the
cloud fakes so the monitor pass discovers them the way production would.

Per tick the engine runs the service's real loop:

  events due → ``run_tick`` → cloud reconcile (monitor/provision/expire)
  → complete due tasks (the deterministic agent) → dispatch free hosts.

At the end it computes stats, runs the spec's checks and SLOs, asserts
the cross-cutting invariants (scenarios/invariants.py), and returns one
scorecard entry.
"""
from __future__ import annotations

import dataclasses
import tempfile
import time as _time
from typing import Callable, Dict, List, Optional

from ..globals import HostStatus, Provider, Requester, TaskStatus
from ..models import distro as distro_mod
from ..models import host as host_mod
from ..models import task as task_mod
from ..models.distro import (
    BootstrapSettings,
    Distro,
    HostAllocatorSettings,
    PlannerSettings,
)
from ..models.host import Host
from ..models.task import Dependency, Task
from ..storage.store import Store
from ..utils import faults as faults_mod
from ..utils import log as log_mod
from ..utils import overload as overload_mod
from ..utils.benchgen import NOW
from ..utils.faults import Fault, FaultPlan
from .invariants import INVARIANT_CHECKS
from .spec import Ev, ScenarioSpec, scorecard_entry_fingerprint

#: counter-name prefixes the scorecard carries (shed / retry / fallback /
#: recovery / fault accounting — the graceful-degradation ledger)
SCORECARD_COUNTER_PREFIXES = (
    "overload.",
    "faults.",
    "recovery.",
    "cloud.",
    "retry.",
    "scheduler.tick.",
    "lease.",
    "storage.",
)


def _engine_overload_config(spec: ScenarioSpec) -> dict:
    """Base OverloadConfig for scenario runs: every wall-clock-coupled
    signal is disarmed (a slow CI box must not flip a deterministic
    scenario's ladder) and the cadence matches the spec's virtual tick,
    so tick-lag reads 0 on schedule. Specs re-arm exactly the signals
    their trace drives via ``spec.overload``."""
    off = [0.0, 0.0, 0.0]
    base = {
        "tick_cadence_s": spec.tick_s,
        "eval_interval_s": 1e9,  # no monotonic-clock auto-evaluates
        "hysteresis_ticks": 2,
        "tick_lag_levels_s": list(off),
        "store_latency_ms_levels": list(off),
        "api_rps_levels": list(off),
        "wal_backlog_levels": list(off),
        "queue_pending_levels": list(off),
        "outbox_depth_levels": list(off),
    }
    base.update(spec.overload)
    return base


class ScenarioRun:
    """One seeded replay of one spec. Mutable state the event handlers
    and checks read/write; see the module docstring for the loop."""

    def __init__(self, spec: ScenarioSpec, seed: Optional[int] = None,
                 keep_data_dir: bool = False):
        self.spec = spec
        self.seed = spec.seed if seed is None else seed
        #: trace capture reads the WAL after the run: leave the durable
        #: data dir on disk for the caller to harvest (and remove)
        self.keep_data_dir = keep_data_dir
        self.data_dir: Optional[str] = None
        self.lease = None
        self._thief = None  # pending failover lease (region-steal event)
        self.store = self._build_store()
        self.tick = -1
        self.now = NOW
        self.clock_offset = 0.0
        self.tick_results: List = []
        self.epochs: List[int] = []
        self.dwell: Dict[str, int] = {}
        self.degraded: Dict[str, int] = {}
        self.stats: Dict = {}
        self.dispatch_tick: Dict[str, int] = {}
        self.dispatched_total = 0
        self.failovers = 0
        #: completion failure plan: [{"match": prefix, "details_type",
        #: "remaining": n|None}] consumed in sorted task order
        self.fail_plan: List[Dict] = []
        self._counters0 = log_mod.counters_snapshot()
        #: every structured-log record emitted during the replay (the
        #: matrix cases' breadcrumb assertions read this)
        self.logs: List[dict] = []
        self.fault_plan = FaultPlan()
        self._events_by_tick: Dict[int, List] = {}
        for ev in spec.events:
            self._events_by_tick.setdefault(ev.tick, []).append(ev)

    # -- construction ---------------------------------------------------- #

    def _build_store(self):
        from ..cloud import docker as docker_mod
        from ..cloud import ec2_fleet

        ec2_fleet.reset_default_client()
        docker_mod.reset_default_client()
        if self.spec.durable:
            import os

            from ..storage.durable import DurableStore
            from ..storage.lease import FileLease

            self.data_dir = tempfile.mkdtemp(
                prefix=f"scenario-{self.spec.name}-"
            )
            self.lease = FileLease(
                os.path.join(self.data_dir, "writer.lease"), ttl_s=600.0
            )
            assert self.lease.try_acquire()
            store = DurableStore(self.data_dir, lease=self.lease)
        else:
            store = Store()
        from ..settings import OverloadConfig

        OverloadConfig(**_engine_overload_config(self.spec)).set(store)
        for section_name, kwargs in self.spec.config.items():
            import evergreen_tpu.settings as settings_mod

            getattr(settings_mod, section_name)(**kwargs).set(store)
        return store

    def tick_options(self):
        from ..scheduler.wrapper import TickOptions

        base = TickOptions(
            create_intent_hosts=False,
            underwater_unschedule=False,
            use_cache=False,
        )
        return dataclasses.replace(base, **self.spec.tick_options)

    # -- bookkeeping ----------------------------------------------------- #

    def counter_delta(self, name: str) -> int:
        return log_mod.get_counter(name) - self._counters0.get(name, 0)

    def counter_deltas(self) -> Dict[str, int]:
        out = {}
        for name, value in log_mod.counters_snapshot().items():
            if not name.startswith(SCORECARD_COUNTER_PREFIXES):
                continue
            delta = value - self._counters0.get(name, 0)
            if delta:
                out[name] = delta
        return out

    def task_duration_ticks(self, task_id: str) -> int:
        return max(1, int(self.spec.default_task_ticks))

    # -- failover -------------------------------------------------------- #

    def arm_failover(self, thief_lease) -> None:
        """A lease-steal event hands the engine the thief's lease; when
        the deposed holder's tick comes back ``degraded="fenced"``, the
        engine opens the data dir under the thief's (higher) epoch and
        keeps replaying — the in-process region failover."""
        self._thief = thief_lease

    def _maybe_failover(self) -> None:
        if self._thief is None:
            return
        from ..scheduler.recovery import run_recovery_pass
        from ..storage.durable import DurableStore

        thief, self._thief = self._thief, None
        try:
            # the deposed holder's store still owns a WAL handle and the
            # async flusher thread; a fenced close may refuse work, but
            # the handles must not leak across multi-seed soaks
            self.store.close()
        except Exception:  # noqa: BLE001 — fenced stores refuse closes  # evglint: disable=shedcheck -- a deposed holder's close is refused by the fence by design; handles die with the run
            pass
        self.lease = thief
        self.store = DurableStore(self.data_dir, lease=thief)
        run_recovery_pass(self.store, now=self.now)
        from ..settings import OverloadConfig

        OverloadConfig(**_engine_overload_config(self.spec)).set(self.store)
        self.failovers += 1

    # -- the replay loop ------------------------------------------------- #

    def execute(self) -> Dict:
        t0 = _time.perf_counter()
        from ..scheduler.wrapper import run_tick

        faults_mod.install(self.fault_plan)
        log_mod.add_sink(self.logs.append)
        opts = self.tick_options()
        try:
            for t in range(self.spec.ticks):
                self.tick = t
                self.now = NOW + (t + 1) * self.spec.tick_s \
                    + self.clock_offset
                for ev in self._events_by_tick.get(t, ()):
                    EVENT_HANDLERS[ev.kind](self, **ev.args)
                res = run_tick(self.store, opts, now=self.now)
                self.tick_results.append(res)
                self.epochs.append(
                    getattr(self.lease, "epoch", 0) if self.lease else 0
                )
                self.dwell[res.overload] = (
                    self.dwell.get(res.overload, 0) + 1
                )
                if res.degraded:
                    self.degraded[res.degraded] = (
                        self.degraded.get(res.degraded, 0) + 1
                    )
                if res.degraded == "fenced":
                    self._maybe_failover()
                    continue
                if self.spec.service_loop:
                    self._service_pass()
        finally:
            faults_mod.uninstall()
            log_mod.remove_sink(self.logs.append)
        entry = self._score()
        entry["timing"] = {
            "wall_ms": round((_time.perf_counter() - t0) * 1e3, 1)
        }
        entry["fingerprint"] = scorecard_entry_fingerprint(entry)
        self._teardown()
        return entry

    def _service_pass(self) -> None:
        """The between-ticks service work, in the order the crons run it:
        cloud reconcile, provisioning, spawn-host expiry, then the
        deterministic agent (complete due tasks, dispatch free hosts)."""
        from ..cloud.provisioning import (
            create_hosts_from_intents,
            provision_ready_hosts,
        )
        from ..cloud.spawnhost import expire_spawn_hosts
        from ..units.host_jobs import monitor_host_cloud_state

        monitor_host_cloud_state(self.store, now=self.now)
        create_hosts_from_intents(self.store, now=self.now)
        provision_ready_hosts(self.store, now=self.now)
        expire_spawn_hosts(self.store, now=self.now)
        self._complete_due_tasks()
        self._dispatch_free_hosts()

    def _complete_due_tasks(self) -> None:
        from ..models.lifecycle import mark_end, mark_task_started

        c = task_mod.coll(self.store)
        due = sorted(
            d["_id"]
            for d in c.find(
                lambda d: d["status"]
                in (TaskStatus.DISPATCHED.value, TaskStatus.STARTED.value)
            )
            if self.dispatch_tick.get(d["_id"], self.tick)
            + self.task_duration_ticks(d["_id"])
            <= self.tick
        )
        for tid in due:
            mark_task_started(self.store, tid, now=self.now)
            status, details = TaskStatus.SUCCEEDED.value, ""
            for plan in self.fail_plan:
                hit = (
                    tid == plan["match"] if plan.get("exact")
                    else tid.startswith(plan["match"])
                )
                if hit and (
                    plan.get("remaining") is None or plan["remaining"] > 0
                ):
                    status = TaskStatus.FAILED.value
                    details = plan.get("details_type", "test")
                    if plan.get("remaining") is not None:
                        plan["remaining"] -= 1
                    break
            mark_end(
                self.store, tid, status, now=self.now,
                details_type=details,
            )

    def _dispatch_free_hosts(self) -> None:
        from ..dispatch.assign import assign_next_available_task
        from ..dispatch.dag_dispatcher import DispatcherService

        svc = DispatcherService(self.store)  # fresh: no TTL staleness
        hosts = sorted(
            (
                h
                for h in host_mod.find(self.store)
                if h.can_run_tasks() and not h.running_task
            ),
            key=lambda h: h.id,
        )
        for h in hosts:
            t = assign_next_available_task(self.store, svc, h, now=self.now)
            if t is not None:
                self.dispatch_tick[t.id] = self.tick
                self.dispatched_total += 1

    # -- scoring --------------------------------------------------------- #

    def _base_stats(self) -> Dict:
        tasks = self.store.collection("tasks").find()
        finished = [
            d for d in tasks
            if d["status"]
            in (TaskStatus.SUCCEEDED.value, TaskStatus.FAILED.value)
        ]
        stepback_events = self.store.collection("events").count(
            lambda d: d.get("event_type") == "TASK_ACTIVATED_STEPBACK"
        )
        level_rank = {"green": 0, "yellow": 1, "red": 2, "black": 3}
        max_level = max(
            (level_rank.get(k, 0) for k in self.dwell), default=0
        )
        last = self.tick_results[-1] if self.tick_results else None
        return {
            "ticks": len(self.tick_results),
            "tasks_total": len(tasks),
            "tasks_finished": len(finished),
            "tasks_succeeded": sum(
                1 for d in finished
                if d["status"] == TaskStatus.SUCCEEDED.value
            ),
            "tasks_failed": sum(
                1 for d in finished
                if d["status"] == TaskStatus.FAILED.value
            ),
            "tasks_system_failed": sum(
                1 for d in finished
                if d["status"] == TaskStatus.FAILED.value
                and d.get("details_type") == "system"
            ),
            "tasks_unfinished": len(tasks) - len(finished),
            "dispatched_total": self.dispatched_total,
            "restarts_total": sum(
                d.get("num_automatic_restarts", 0) for d in tasks
            ),
            "stepback_activations": stepback_events,
            "max_overload_level": max_level,
            "ended_green": 1 if last and last.overload == "green" else 0,
            "fenced_ticks": self.degraded.get("fenced", 0),
            "failovers": self.failovers,
            "sheds_total": self.counter_delta("overload.shed"),
            "spot_reclaimed": self.counter_delta("cloud.spot_reclaimed"),
        }

    def _score(self) -> Dict:
        self.stats = {**self._base_stats(), **self.stats}
        checks = {}
        for name, fn in self.spec.checks:
            try:
                problem = fn(self)
            except Exception as exc:  # noqa: BLE001 — a raising check is
                # a failing check, never a crashed scorecard
                problem = f"check raised: {exc!r}"
            checks[name] = {"ok": problem is None, "detail": problem or ""}
        slos = {}
        for slo in self.spec.slos:
            slos[slo.name] = slo.evaluate(self.stats)
        invariants = {}
        for name in self.spec.invariants:
            try:
                problem = INVARIANT_CHECKS[name](self)
            except Exception as exc:  # noqa: BLE001
                problem = f"invariant raised: {exc!r}"
            invariants[name] = {
                "ok": problem is None, "detail": problem or "",
            }
        ok = (
            all(v["ok"] for v in invariants.values())
            and all(v["ok"] for v in checks.values())
            and all(v["ok"] for v in slos.values())
        )
        return {
            "name": self.spec.name,
            "ok": ok,
            "seed": self.seed,
            "deterministic": self.spec.deterministic,
            "invariants": invariants,
            "checks": checks,
            "slos": slos,
            "dwell_ticks": dict(sorted(self.dwell.items())),
            "degraded": dict(sorted(self.degraded.items())),
            "counters": dict(sorted(self.counter_deltas().items())),
            "stats": {
                k: self.stats[k] for k in sorted(self.stats)
                if isinstance(self.stats[k], (int, float, bool, str))
            },
        }

    def _teardown(self) -> None:
        import shutil

        try:
            if self.lease is not None:
                self.lease.release()
            if hasattr(self.store, "close"):
                self.store.close()
        except Exception:  # noqa: BLE001 — a fenced/failed-over store may  # evglint: disable=shedcheck -- teardown after the scorecard is computed; nothing reads the store again
            # refuse close work; the scorecard is already computed
            pass
        if self.data_dir is not None and not self.keep_data_dir:
            shutil.rmtree(self.data_dir, ignore_errors=True)  # evglint: disable=fencecheck -- harness-owned temp data dir removed after the plane is closed; no live holder to fence against


def run_scenario(spec: ScenarioSpec, seed: Optional[int] = None) -> Dict:
    """Replay one spec once; returns its scorecard entry."""
    return ScenarioRun(spec, seed=seed).execute()


# --------------------------------------------------------------------------- #
# event vocabulary
# --------------------------------------------------------------------------- #


def _distro_from_spec(dspec: Dict) -> Distro:
    planner = PlannerSettings(
        patch_factor=7,
        patch_time_in_queue_factor=2,
        commit_queue_factor=20,
        mainline_time_in_queue_factor=1,
        expected_runtime_factor=1,
        num_dependents_factor=2.0,
        stepback_task_factor=10,
        **dspec.get("planner", {}),
    )
    alloc = HostAllocatorSettings(
        maximum_hosts=dspec.get("max_hosts", 100),
        minimum_hosts=dspec.get("min_hosts", 0),
        future_host_fraction=0.5,
    )
    boot = BootstrapSettings(
        method=dspec.get("bootstrap", BootstrapSettings.METHOD_PRECONFIGURED)
    )
    return Distro(
        id=dspec["id"],
        provider=dspec.get("provider", Provider.MOCK.value),
        provider_settings=dspec.get("provider_settings", {}),
        container_pool=dspec.get("container_pool", ""),
        planner_settings=planner,
        host_allocator_settings=alloc,
        bootstrap_settings=boot,
    )


def ev_fleet(run: ScenarioRun, distros: List[Dict]) -> None:
    """Create distros and their initial RUNNING hosts (deterministic
    ids). ``distros``: [{"id", "provider", "hosts", "planner": {...},
    "provider_settings": {...}, ...}]."""
    from ..cloud.ec2_fleet import default_client
    from ..cloud.mock import MockCloudManager
    from ..cloud.manager import CloudHostStatus

    for dspec in distros:
        d = _distro_from_spec(dspec)
        distro_mod.insert(run.store, d)
        hosts = [
            Host(
                id=f"{d.id}-h{hi:03d}",
                distro_id=d.id,
                provider=d.provider,
                status=HostStatus.RUNNING.value,
                creation_time=run.now - 7200,
                start_time=run.now - 7200,
                last_communication_time=run.now,
                has_containers=dspec.get("has_containers", False),
            )
            for hi in range(dspec.get("hosts", 0))
        ]
        # register pre-existing hosts with their provider's truth so the
        # cloud-reconcile pass sees live instances, not NONEXISTENT ones
        for h in hosts:
            if d.provider in (
                Provider.MOCK.value, Provider.DOCKER_MOCK.value
            ):
                h.external_id = f"mock-{h.id}"
                MockCloudManager.instances[h.external_id] = (
                    CloudHostStatus.RUNNING
                )
            elif d.provider in (
                Provider.EC2_FLEET.value, Provider.EC2_ONDEMAND.value
            ):
                spot = bool(dspec.get("provider_settings", {}).get(
                    "fleet_use_spot", True
                ))
                client = default_client()
                iid = client.create_fleet({"spot": spot})
                client.describe_instance(iid)  # pending → running
                h.external_id = iid
                # mirror what spawn_host records, or ev_spot_reclaim
                # (which filters on the doc's spot flag) would silently
                # skip every pre-seeded instance
                h.spot = spot
        if hosts:
            host_mod.insert_many(run.store, hosts)


def ev_grow_fleet(
    run: ScenarioRun, distro: str, n: int, prefix: str = ""
) -> None:
    """Add intent hosts with deterministic ids; the service pass spawns
    them through the real provider (FakeEC2 fleet / docker pools) and
    provisions them to RUNNING."""
    prefix = prefix or f"{distro}-g{run.tick}"
    d = distro_mod.get(run.store, distro)
    hosts_coll = run.store.collection("hosts")
    created, i = 0, 0
    while created < n:
        # two grows on one distro in one tick share the default prefix
        # (fuzzer-found, seed 160077) — number past taken ids instead
        # of crashing, keeping ids stable for every existing scenario
        hid = f"{prefix}-{i:03d}"
        i += 1
        if hosts_coll.get(hid) is not None:
            continue
        h = Host(
            id=hid,
            distro_id=distro,
            provider=d.provider if d else Provider.MOCK.value,
            status=HostStatus.UNINITIALIZED.value,
            creation_time=run.now,
        )
        host_mod.insert(run.store, h)
        created += 1


def ev_tasks(
    run: ScenarioRun,
    distro: str,
    n: int,
    prefix: str = "",
    requester: str = Requester.REPOTRACKER.value,
    project: str = "proj",
    build_variant: str = "bv0",
    priority: int = 0,
    dep_chain: bool = False,
    expected_s: float = 600.0,
) -> None:
    """A batch of activated tasks arriving (one commit's build, a patch
    burst slice, interactive load)."""
    prefix = prefix or f"{distro}-t{run.tick}"
    prev_id = ""
    tasks = []
    for i in range(n):
        t = Task(
            id=f"{prefix}-{i:03d}",
            display_name=f"{prefix}-{i:03d}",
            distro_id=distro,
            project=project,
            version=f"{prefix}-v",
            build_variant=build_variant,
            status=TaskStatus.UNDISPATCHED.value,
            activated=True,
            requester=requester,
            priority=priority,
            create_time=run.now - 60,
            activated_time=run.now - 30,
            scheduled_time=run.now,
            expected_duration_s=expected_s,
        )
        if dep_chain and prev_id:
            t.depends_on = [Dependency(task_id=prev_id)]
        prev_id = t.id
        tasks.append(t)
    task_mod.insert_many(run.store, tasks)


def ev_merge_stack(
    run: ScenarioRun,
    distro: str,
    stack: str,
    n: int,
    project: str = "proj",
) -> None:
    """One merge-queue patch stack: ``n`` github-merge tasks chained by
    dependencies (each entry builds on the previous — the conflicting
    overlap with sibling stacks is that they all race one project)."""
    prev_id = ""
    tasks = []
    for i in range(n):
        t = Task(
            id=f"{distro}-{stack}-{i:02d}",
            display_name=f"{stack}-{i:02d}",
            distro_id=distro,
            project=project,
            version=f"{stack}-v{i}",
            build_variant="bv0",
            status=TaskStatus.UNDISPATCHED.value,
            activated=True,
            requester=Requester.GITHUB_MERGE.value,
            create_time=run.now - 120,
            activated_time=run.now - 60,
            scheduled_time=run.now,
            expected_duration_s=300.0,
        )
        if prev_id:
            t.depends_on = [Dependency(task_id=prev_id)]
        prev_id = t.id
        tasks.append(t)
    task_mod.insert_many(run.store, tasks)


def ev_dag(run: ScenarioRun, distro: str, nodes: List[Dict]) -> None:
    """An explicit dependency DAG across revisions: nodes carry
    display_name / revision_order / deps / activation — the stepback
    scenario's mainline history."""
    tasks = []
    for node in nodes:
        t = Task(
            id=node["id"],
            display_name=node.get("display_name", node["id"]),
            distro_id=distro,
            project=node.get("project", "proj"),
            version=node.get("version", f"{node['id']}-v"),
            build_variant=node.get("build_variant", "bv0"),
            status=TaskStatus.UNDISPATCHED.value,
            activated=node.get("activated", True),
            requester=node.get(
                "requester", Requester.REPOTRACKER.value
            ),
            priority=node.get("priority", 0),
            revision_order_number=node.get("revision_order", 0),
            create_time=run.now - 60,
            activated_time=run.now - 30 if node.get("activated", True)
            else 0.0,
            scheduled_time=run.now,
            expected_duration_s=node.get("expected_s", 300.0),
        )
        t.depends_on = [
            Dependency(task_id=dep) for dep in node.get("deps", ())
        ]
        tasks.append(t)
    task_mod.insert_many(run.store, tasks)


def ev_fail_next(
    run: ScenarioRun,
    match: str,
    details_type: str = "test",
    count: Optional[int] = 1,
    exact: bool = False,
) -> None:
    """Arm the completion agent: the next ``count`` completions of tasks
    whose id starts with ``match`` fail with ``details_type``.
    ``exact`` requires a full-id match — trace capture arms one plan per
    originally-failed task, and a prefix would mis-fire on ids that
    happen to extend it (``t-1`` vs ``t-10``)."""
    run.fail_plan.append(
        {"match": match, "details_type": details_type,
         "remaining": count, "exact": exact}
    )


def ev_spot_reclaim(run: ScenarioRun, n: int, distro: str = "") -> None:
    """Reclaim ``n`` spot-backed EC2 instances out from under us —
    terminated inside the provider fake, host docs untouched, so only
    the next cloud-reconcile pass can discover it (exactly the
    production shape). Prefers busy hosts: reclamation mid-task is the
    scenario the recovery path must survive."""
    from ..cloud.ec2_fleet import default_client

    client = default_client()
    candidates = sorted(
        (
            h for h in host_mod.find(
                run.store,
                lambda d: d["status"] == HostStatus.RUNNING.value
                and d.get("spot")
                and d.get("external_id")
                and (not distro or d["distro_id"] == distro),
            )
        ),
        key=lambda h: (not h.running_task, h.id),
    )
    for h in candidates[:n]:
        inst = client.instances.get(h.external_id)
        if inst is not None:
            inst["state"] = "terminated"


def ev_lease_steal(run: ScenarioRun, failover: bool = True) -> None:
    """Arm a mid-commit lease steal at the ``wal.fence`` seam (the PR-3
    failover machinery): the NEXT group commit observes a thief holding
    a higher epoch, the tick is fenced and shed, and — unless
    ``failover=False`` (the migrated matrix case asserts on the deposed
    holder alone) — the engine fails over to the thief for the
    remaining ticks."""
    import os

    from ..storage.lease import FileLease

    assert run.spec.durable, "lease_steal requires a durable scenario"
    lease_path = os.path.join(run.data_dir, "writer.lease")

    def steal():
        thief = FileLease(lease_path, ttl_s=600.0)
        thief.ttl_s = -1.0  # force "stale" so the steal fires now
        assert thief.try_acquire()
        thief.ttl_s = 600.0
        if failover:
            run.arm_failover(thief)

    calls = run.fault_plan._calls.get("wal.fence", 0)
    run.fault_plan.at("wal.fence", calls, Fault("call", fn=steal))


def ev_gauge(
    run: ScenarioRun, name: str, value: float, ewma: float = 0.0
) -> None:
    """Push one load-ladder gauge sample (the declarative analog of a
    job-queue backlog or WAL-flusher lag the trace implies)."""
    overload_mod.monitor_for(run.store).observe(name, value, ewma=ewma)


def ev_outbox(
    run: ScenarioRun, n: int, channel: str = "slack_outbox",
    distinct: bool = True, key: str = "",
) -> None:
    """A notification fan-out burst: ``n`` outbox inserts (distinct
    texts, or repeats of one coalesce key)."""
    from ..events.senders import insert_outbox_row

    for i in range(n):
        text = (
            f"storm-{run.tick}-{i}\nbody" if distinct
            else f"{key or 'storm-dup'}\nbody"
        )
        insert_outbox_row(
            run.store, channel,
            {"channel_type": "slack", "slack_channel": "#ops",
             "text": text},
        )


def ev_drain_outbox(
    run: ScenarioRun, channel: str = "slack_outbox"
) -> None:
    """The notification drain catching up (delivers every undelivered
    row and tells the ladder the backlog cleared)."""
    coll = run.store.collection(channel)
    undelivered = coll.find(
        lambda d: not d.get("delivered") and not d.get("failed")
    )
    for doc in undelivered:
        coll.update(doc["_id"], {"delivered": True})
    overload_mod.monitor_for(run.store).note_outbox_drained(
        channel, len(undelivered)
    )


def ev_spawn_burst(
    run: ScenarioRun, distro: str, users: int, prefix: str = "user"
) -> None:
    """An interactive spawn-host burst: ``users`` workstation requests
    land at once (rest/route host_spawn shape, minus the HTTP)."""
    from ..cloud.spawnhost import create_spawn_host

    for i in range(users):
        create_spawn_host(
            run.store, f"{prefix}{i:03d}", distro, now=run.now
        )


def ev_advance_clock(run: ScenarioRun, s: float) -> None:
    """Jump the virtual clock (expiry sweeps, idle reaping): every
    subsequent tick happens ``s`` seconds later."""
    run.clock_offset += s


def ev_fault(
    run: ScenarioRun,
    seam: str,
    kind: str = "raise",
    at: Optional[int] = None,
    delay_s: float = 0.0,
    always: bool = False,
) -> None:
    """Install one PR-1 fault-plan entry on the live plan. ``at`` is an
    absolute seam call index; None targets the seam's NEXT call."""
    fault = Fault(kind, delay_s=delay_s)
    if always:
        run.fault_plan.always(seam, fault)
    else:
        idx = (
            at if at is not None
            else run.fault_plan._calls.get(seam, 0)
        )
        run.fault_plan.at(seam, idx, fault)


def ev_clear_faults(run: ScenarioRun, seam: str = "") -> None:
    """Remove scheduled/always faults (one seam, or all)."""
    if seam:
        run.fault_plan._at.pop(seam, None)
        run.fault_plan._always.pop(seam, None)
    else:
        run.fault_plan._at.clear()
        run.fault_plan._always.clear()


#: disk_fault targets → the utils/faults.py seam each one arms
_DISK_FAULT_SEAMS = {
    "wal": "wal.commit",
    "snapshot": "snapshot.write",
}


def ev_disk_fault(
    run: ScenarioRun,
    target: str = "wal",
    kind: str = "bitrot",
    at: Optional[int] = None,
) -> None:
    """Arm one storage-integrity fault (enospc/eio/short/bitrot) at a
    disk seam, then schedule the follow-through that makes the run
    CONVERGE despite it: a ``snapshot`` target forces a checkpoint next
    tick so the armed fault actually lands (tolerating the loud
    enospc/eio raise — a failed checkpoint leaves the previous one
    authoritative), and every target schedules a ``scrub()`` the tick
    after, so detection + quarantine + rebuild happen inside the replay
    and resume≡rerun holds at convergence. No-op on non-durable specs —
    there is no disk to fault."""
    from ..storage.durable import DurableStore

    if not isinstance(run.store, DurableStore):
        return
    seam = _DISK_FAULT_SEAMS.get(target)
    if seam is None:
        raise ValueError(f"unknown disk_fault target {target!r}")
    ev_fault(run, seam=seam, kind=kind, at=at)

    def _force_checkpoint(r: ScenarioRun) -> None:
        try:
            r.store.checkpoint()
        except OSError:
            pass  # injected enospc/eio: previous checkpoint stays live

    def _scrub(r: ScenarioRun) -> None:
        if isinstance(r.store, DurableStore):
            r.store.scrub()

    if target == "snapshot":
        run._events_by_tick.setdefault(run.tick + 1, []).append(
            Ev(run.tick + 1, "call", {"fn": _force_checkpoint})
        )
    run._events_by_tick.setdefault(run.tick + 2, []).append(
        Ev(run.tick + 2, "call", {"fn": _scrub})
    )


#: net_fault targets → the utils/faults.py transport seam each one arms
_NET_FAULT_SEAMS = {
    "agent": "agent.request",
    "replica": "replica.tail",
    "ipc": "ipc.send",
    "adopt": "sock.adopt",
}


def _lossy_claim_storm(run: ScenarioRun, agents: int = 0) -> None:
    """Drive every free host's next-task claim THROUGH the
    ``agent.request`` transport seam with an at-least-once retry shim —
    the in-process stand-in for a parked agent fleet re-requesting work
    across a lossy network. Directive semantics mirror
    agent/rest_comm.py:

    - ``drop``/``partition``: the request vanished before the server saw
      it — retry with a fresh attempt.
    - ``half_open``: the server DID the work but the response
      black-holed — the claim lands, then the agent retries anyway.
      That retry is duplicate delivery; the dispatch CAS (and the
      running-task resume path in dispatch/assign.py) must fence it.
    - ``duplicate``: the transport delivered the same request twice.

    Every assignment path funnels through ``assign_next_available_task``
    so the no-duplicate-dispatch invariant audits the result for free.
    """
    from ..dispatch.assign import assign_next_available_task
    from ..dispatch.dag_dispatcher import DispatcherService

    svc = DispatcherService(run.store)  # fresh: no TTL staleness
    hosts = sorted(
        (
            h
            for h in host_mod.find(run.store)
            if h.can_run_tasks() and not h.running_task
        ),
        key=lambda h: h.id,
    )
    if agents:
        hosts = hosts[:agents]

    def _claim(host_id: str) -> Optional[Task]:
        cur = host_mod.get(run.store, host_id)
        if cur is None:
            return None
        t = assign_next_available_task(run.store, svc, cur, now=run.now)
        if t is not None:
            run.dispatch_tick.setdefault(t.id, run.tick)
            run.dispatched_total += 1
        return t

    for h in hosts:
        for _attempt in range(4):  # bounded at-least-once retry budget
            directive = faults_mod.fire("agent.request")
            if directive in ("drop", "partition"):
                continue  # lost before the server saw it: retry
            t = _claim(h.id)
            if directive == "half_open":
                # response lost after processing — the agent's retry
                # re-delivers a claim the server already honored
                _claim(h.id)
            elif directive == "duplicate":
                _claim(h.id)
            if t is not None or directive is None:
                break


def ev_net_fault(
    run: ScenarioRun,
    target: str = "agent",
    kind: str = "drop",
    rate: float = 0.3,
    agents: int = 0,
    at: Optional[int] = None,
    always: bool = False,
) -> None:
    """Arm one network-chaos fault (drop/duplicate/reorder/partition/
    half_open/delay) at a transport seam, then schedule the
    follow-through that makes the run CONVERGE despite it.

    The ``agent`` target seeds a replayable lossy window onto the live
    plan — each upcoming ``agent.request`` call independently faulted
    with probability ``rate`` from a seed-derived stream — fires a
    claim storm through it next tick, and clears the seam the tick
    after, so the partition HEALS inside the replay and resume≡rerun
    holds at convergence. Other targets arm the seam only (``at``-next
    or the given absolute index); the weather/matrix case drives its
    own exercise and clears via ``clear_faults``.
    """
    import random as _random

    seam = _NET_FAULT_SEAMS.get(target)
    if seam is None:
        raise ValueError(f"unknown net_fault target {target!r}")

    if target != "agent":
        ev_fault(run, seam=seam, kind=kind, at=at, always=always)
        return

    # replayable lossy window: derived from (scenario seed, tick), never
    # wall clock, so a resumed run re-arms the identical window
    rng = _random.Random((int(run.seed or 0) ^ 0x4E46) + run.tick * 7919)
    base = run.fault_plan._calls.get(seam, 0)
    window = max(1, agents or 8) * 4  # matches the storm's retry budget
    for i in range(window):
        if rng.random() < max(0.0, min(1.0, rate)):
            run.fault_plan.at(seam, base + i, Fault(kind))

    storm_agents = agents

    def _storm(r: ScenarioRun) -> None:
        _lossy_claim_storm(r, agents=storm_agents)

    def _heal(r: ScenarioRun) -> None:
        ev_clear_faults(r, seam=seam)

    run._events_by_tick.setdefault(run.tick + 1, []).append(
        Ev(run.tick + 1, "call", {"fn": _storm})
    )
    run._events_by_tick.setdefault(run.tick + 2, []).append(
        Ev(run.tick + 2, "call", {"fn": _heal})
    )


def ev_container_pools(run: ScenarioRun, pools: List[Dict]) -> None:
    """Configure docker container pools (parent distro + capacity)."""
    from ..cloud.docker import ContainerPool, set_container_pools

    set_container_pools(
        run.store, [ContainerPool(**p) for p in pools]
    )


def ev_call(run: ScenarioRun, fn: Callable) -> None:
    """Escape hatch for migrated matrix cases: run ``fn(run)`` at this
    tick."""
    fn(run)


EVENT_HANDLERS: Dict[str, Callable] = {
    "fleet": ev_fleet,
    "grow_fleet": ev_grow_fleet,
    "tasks": ev_tasks,
    "merge_stack": ev_merge_stack,
    "dag": ev_dag,
    "fail_next": ev_fail_next,
    "spot_reclaim": ev_spot_reclaim,
    "lease_steal": ev_lease_steal,
    "gauge": ev_gauge,
    "outbox": ev_outbox,
    "drain_outbox": ev_drain_outbox,
    "spawn_burst": ev_spawn_burst,
    "advance_clock": ev_advance_clock,
    "fault": ev_fault,
    "clear_faults": ev_clear_faults,
    "disk_fault": ev_disk_fault,
    "net_fault": ev_net_fault,
    "container_pools": ev_container_pools,
    "call": ev_call,
}
