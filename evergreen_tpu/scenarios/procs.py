"""Child-process replay backend for the scenario engine.

The in-process engine (scenarios/engine.py) replays a spec against one
store in one process; this backend replays a spec against a **supervised
fleet of real worker processes** (runtime/supervisor.py +
runtime/worker.py over a temp data dir) — the deployment shape of
``service --shards N`` — so the crash matrix's process-SIGKILL points
and the supervised-fleet weathers run THROUGH the engine vocabulary
with the same invariants.

Proc specs are ordinary ``ScenarioSpec``s using the proc event kinds:

  ``proc_fleet``    (tick 0) the workload: n_shards + a seeded problem
                    partitioned across the shard stores before spawn
  ``proc_kill``     SIGKILL a named worker — immediately, or AT a named
                    PR-1 fault seam (``arm_fault`` installs a ``crash``
                    kind in the live worker: ``os._exit(86)`` at the
                    seam, the SIGKILL shape — no atexit, no finally)
  ``proc_hang``     SIGSTOP a named worker: heartbeats stop, the
                    supervisor's deadline trips, the worker is killed
                    and restarted — the hang resolves exactly like a
                    crash, fenced at a higher epoch
  ``proc_migrate``  drive one fenced handoff over the control protocol
  ``sup_kill``      crash the SUPERVISOR itself (``at``: "idle", or
                    "mid_round" — kill after the tick fan-out left,
                    before replies — or "mid_handoff" — kill between
                    the release and prime legs of a live migration).
                    Workers observe stdin EOF and go ORPHAN: leases
                    kept, autonomous local ticks, bounded grace
  ``sup_restart``   start a fresh supervisor over the same data dir:
                    it steals the fleet lease at a strictly higher
                    epoch, ADOPTS the orphaned workers via the fleet
                    manifest + control sockets (same pids, same
                    shard-lease epochs, no recovery pass) and runs
                    ``reconcile_handoffs`` first thing

Each virtual tick runs: due events → supervisor round (every live
worker's ``run_tick``) → the deterministic agent step (complete
in-flight, dispatch free hosts — the real CAS pair) → wait for any
killed worker's fenced takeover to land. At the end the fleet drains
and shuts down, the shard stores are reopened cold, and the scorecard
asserts the crash-matrix contracts as engine invariants:

  ``no_duplicate_dispatch`` / ``store_consistent`` — on the merged
  fleet state; ``exactly_one_owner`` — no distro-scoped doc in two
  shard stores; ``monotone_epochs`` — every restart stole its shard's
  lease at a strictly higher fencing epoch; ``resume_equals_rerun`` —
  the crashed-and-recovered fleet converges to the same canonical
  state as an uninterrupted run of the same spec (kills stripped);
  ``converged`` — the workload drained.

``run_crash_point`` runs one classic crash-matrix kill point (seam @
call-index on a 1-shard fleet) through this backend;
``tools/crash_matrix.py`` delegates its 13-point SIGKILL matrix here
the way PR 10's tools delegate the fault/overload matrices.
"""
from __future__ import annotations

import os
import signal
import tempfile
import time as _time
from typing import Dict, List, Optional

from ..utils.benchgen import NOW
from .invariants import (
    canonical_state,
    check_duplicate_dispatch,
    check_store_consistent,
)
from .spec import Ev, SLO, ScenarioSpec, scorecard_entry_fingerprint

#: event kinds the proc backend handles (anything else in a proc spec
#: is a spec error — in-process events cannot reach a child's store)
#: ``leader_kill`` / ``leader_hang`` target the SOLVER LEADER
#: (runtime/solver.py SolverService, living inside the supervisor):
#: a fault armed at a named solver seam in the harness process —
#: ``call``-crash for kill, ``hang`` for a stall — so leader death
#: lands exactly at the publish/solve/return seams of a live round
PROC_EVENT_KINDS = ("proc_fleet", "proc_kill", "proc_hang",
                    "proc_migrate", "sup_kill", "sup_restart",
                    "leader_kill", "leader_hang",
                    "net_fault", "net_heal")

#: the proc analog of spec.DEFAULT_INVARIANTS
DEFAULT_PROC_INVARIANTS = (
    "no_duplicate_dispatch",
    "store_consistent",
    "exactly_one_owner",
    "monotone_epochs",
    "resume_equals_rerun",
    "converged",
)

#: deterministic workload clock (the crash matrix's anchor)
TICK_S = 15.0
LEASE_TTL_S = 1.0


def _seed_fleet(data_dir: str, n_shards: int, workload: dict) -> None:
    """Partition one deterministic problem across the shard stores
    BEFORE any worker spawns (epoch-0 frames — the workers' leased
    writes land after them). Same workload shape as the crash matrix:
    phantom running-task stamps cleared so every dispatch is a real
    CAS pair."""
    from ..models import distro as distro_mod
    from ..models import host as host_mod
    from ..models import task as task_mod
    from ..parallel.topology import ShardTopology
    from ..storage.durable import DurableStore
    from ..utils.benchgen import generate_problem

    distros, tasks_by_distro, hosts_by_distro, _, _ = generate_problem(
        workload.get("distros", 2),
        workload.get("tasks", 24),
        seed=workload.get("seed", 11),
        hosts_per_distro=workload.get("hosts_per_distro", 3),
        dep_fraction=workload.get("dep_fraction", 0.25),
    )
    sabotage = bool(workload.get("sabotage_duplicate_claim"))
    topo = ShardTopology(n_shards)
    stores = [
        DurableStore(data_dir, shard_id=k) for k in range(n_shards)
    ]
    try:
        for d in distros:
            store = stores[topo.shard_for(d.id)]
            store.begin_tick()
            try:
                distro_mod.coll(store).upsert(d.to_doc())
                for t in tasks_by_distro[d.id]:
                    task_mod.coll(store).upsert(t.to_doc())
                for h in hosts_by_distro[d.id]:
                    h.running_task = ""
                    h.running_task_group = ""
                    h.running_task_build_variant = ""
                    h.running_task_version = ""
                    h.running_task_project = ""
                    host_mod.coll(store).upsert(h.to_doc())
            finally:
                store.end_tick()
        if sabotage:
            # fuzz-gate self-test: forge two TASK_DISPATCHED events for
            # one (task, timestamp) — two CAS winners — bypassing the
            # dispatch path entirely. The campaign MUST score this fleet
            # red (no_duplicate_dispatch) or the invariant layer is dead.
            store = stores[0]
            store.begin_tick()
            try:
                ev_coll = store.collection("events")
                for i in range(2):
                    ev_coll.upsert({
                        "_id": f"sabotage-dup-{i}",
                        "event_type": "TASK_DISPATCHED",
                        "resource_id": "sabotage-t0",
                        "timestamp": NOW,
                    })
            finally:
                store.end_tick()
    finally:
        for s in stores:
            s.sync_persist()
            s.close()


def _open_fleet_stores(data_dir: str, n_shards: int) -> list:
    from ..storage.durable import DurableStore

    return [
        DurableStore(data_dir, shard_id=k) for k in range(n_shards)
    ]


class ProcScenarioRun:
    """One replay of one proc spec against a supervised fleet."""

    def __init__(self, spec: ScenarioSpec,
                 with_reference: bool = True,
                 seed: Optional[int] = None,
                 keep_data_dir: bool = False) -> None:
        self.spec = spec
        self.with_reference = with_reference
        self.keep_data_dir = keep_data_dir
        fleet_evs = [e for e in spec.events if e.kind == "proc_fleet"]
        if len(fleet_evs) != 1 or fleet_evs[0].tick != 0:
            raise ValueError(
                "a proc spec needs exactly one proc_fleet event at "
                "tick 0"
            )
        self.workload = dict(fleet_evs[0].args)
        # seed flows END TO END: an explicit seed overrides both the
        # spec's stamp and the workload generator's, so a fuzzer-found
        # timeline replays the same seeded problem in process mode that
        # it replayed in-process (ISSUE 16 satellite bugfix)
        if seed is not None:
            self.workload["seed"] = int(seed)
        # the scorecard reports the EFFECTIVE workload seed (what
        # generate_problem actually consumed), never a dead spec stamp
        self.seed = int(self.workload.get("seed", 11))
        self.n_shards = int(self.workload.get("shards", 2))
        bad = [
            e.kind for e in spec.events
            if e.kind not in PROC_EVENT_KINDS
        ]
        if bad:
            raise ValueError(
                f"proc specs only take {PROC_EVENT_KINDS}; got {bad}"
            )
        late = [
            (e.kind, e.tick) for e in spec.events
            if not (0 <= e.tick < spec.ticks)
        ]
        if late:
            # an event past the timeline would silently never fire —
            # the fault it was meant to inject would score as tested
            raise ValueError(
                f"events outside [0, ticks={spec.ticks}): {late}"
            )
        self.sup = None
        #: previous supervisor incarnations (sup_kill/sup_restart):
        #: scoring aggregates restarts/exits/epochs across ALL of them
        self.sups: List = []
        #: True once a leader_kill/leader_hang installed a fault plan
        #: (process-global — execute()'s finally restores the previous)
        self._armed_faults = False
        self._prev_faults = None
        #: the accumulating net_fault plan (net_fault/net_heal share
        #: one installed plan; a later leader_kill would clobber it —
        #: proc specs never mix the two fault families on one timeline)
        self._net_plan = None
        self.data_dir: Optional[str] = None
        self.rounds: List[Dict[int, dict]] = []
        self.dispatched_total = 0
        self.unfinished = -1
        self.converged_at = -1
        self.fault_exits = 0
        self.stats: Dict = {}
        self.reference_state: Optional[dict] = None

    # -- events ----------------------------------------------------------- #

    def _apply_event(self, ev: Ev, now: float) -> None:
        if ev.kind == "proc_fleet":
            return  # consumed at setup
        if ev.kind == "proc_kill":
            shard = int(ev.args.get("worker", 0))
            seam = ev.args.get("seam", "")
            h = self.sup.handles[shard]
            if seam:
                h.send(op="arm_fault", seam=seam, kind="crash",
                       at=ev.args.get("at"))
                h.wait_reply("armed", 10.0)
            elif h.alive():
                os.kill(h.pid, signal.SIGKILL)
        elif ev.kind == "proc_hang":
            shard = int(ev.args.get("worker", 0))
            seam = ev.args.get("seam", "")
            h = self.sup.handles[shard]
            if seam:
                h.send(op="arm_fault", seam=seam, kind="hang",
                       delay_s=float(ev.args.get("delay_s", 30.0)),
                       always=bool(ev.args.get("always", True)))
                h.wait_reply("armed", 10.0)
            elif h.alive():
                os.kill(h.pid, signal.SIGSTOP)
        elif ev.kind == "proc_migrate":
            distro = ev.args["distro"]
            src = int(ev.args["from"])
            dst = int(ev.args["to"])
            self.sup.migrate(distro, src, dst)
        elif ev.kind == "sup_kill":
            at = ev.args.get("at", "idle")
            if at == "mid_round":
                # fan the tick out, then die before collecting a single
                # reply — the workers execute it into the void
                ready = [
                    h for h in self.sup.handles.values()
                    if h.state == "ready"
                ]
                for h in ready:
                    h.send(op="tick", now=now, req=h.next_req())
                self.sup.simulate_crash()
            elif at == "mid_handoff":
                self._release_then_crash(now)
            else:
                self.sup.simulate_crash()
        elif ev.kind == "sup_restart":
            self._restart_supervisor()
        elif ev.kind == "leader_kill":
            # crash the supervisor AT a solver seam of the NEXT round's
            # serve: the fault plan is process-global (the SolverService
            # runs in this harness process), installed fresh so index 0
            # is the next fire of the seam; execute() restores the
            # previous plan in its finally
            from ..utils import faults

            sup = self.sup
            if sup.solver_service is None:
                # never elected (device-starved host, lease held
                # elsewhere): no solver seam will ever fire — degrade
                # to a plain supervisor kill so the scheduled
                # sup_restart still has a corpse to replace
                sup.simulate_crash()
                return
            seam = ev.args.get("seam", "solver.round")
            plan = faults.FaultPlan().at(
                seam, int(ev.args.get("index", 0)),
                faults.Fault("call", fn=sup.simulate_crash),
            )
            faults.install(plan)
            self._armed_faults = True
        elif ev.kind == "leader_hang":
            from ..utils import faults

            seam = ev.args.get("seam", "solver.solve")
            plan = faults.FaultPlan().at(
                seam, int(ev.args.get("index", 0)),
                faults.Fault(
                    "hang",
                    delay_s=float(ev.args.get("delay_s", 8.0)),
                ),
            )
            faults.install(plan)
            self._armed_faults = True
        elif ev.kind == "net_fault":
            # arm a transport fault at a supervisor-side seam. The
            # seams fire in THIS harness process (the supervisor owns
            # both IPC directions), so shard-scoped aliases like
            # ``ipc.send.0`` partition ONE worker of the fleet while
            # its siblings keep talking — the Jepsen one-way-partition
            # shape. Faults accumulate onto one installed plan until a
            # ``net_heal`` clears the seam (or the run's finally
            # restores the pre-replay plan).
            from ..utils import faults

            seam = ev.args.get("seam", "ipc.send")
            kind = ev.args.get("kind", "partition")
            if self._net_plan is None:
                self._net_plan = faults.FaultPlan()
                faults.install(self._net_plan)
                self._armed_faults = True
            fault = faults.Fault(
                kind, delay_s=float(ev.args.get("delay_s", 0.0))
            )
            if ev.args.get("at") is not None:
                self._net_plan.at(seam, int(ev.args["at"]), fault)
            else:
                self._net_plan.always(seam, fault)
        elif ev.kind == "net_heal":
            # the partition heals: clear one seam (or every armed
            # transport fault) so the degraded side reconnects and the
            # run converges — resume≡rerun compares POST-heal states
            seam = ev.args.get("seam", "")
            plan = self._net_plan
            if plan is not None:
                if seam:
                    plan._at.pop(seam, None)
                    plan._always.pop(seam, None)
                else:
                    plan._at.clear()
                    plan._always.clear()

    def _release_then_crash(self, now: float) -> None:
        """Drive the RELEASE leg of a real migration, then crash the
        supervisor before the prime leg ever leaves: the released
        record is durable on the source, the target knows nothing —
        the successor's post-adoption ``reconcile_handoffs`` must
        converge it to exactly-one-owner."""
        sup = self.sup
        loads = sup.broadcast("load", "load")
        src = dst = None
        distro = None
        for k in sorted(loads):
            reps = loads[k].get("reps") or {}
            if reps:
                src = k
                distro = sorted(reps.values())[0]
                dst = next(
                    j for j in range(self.n_shards) if j != k
                )
                break
        if distro is None:  # nothing to move: degrade to a plain kill
            sup.simulate_crash()
            return
        hs = sup.handles[src]
        sup._seq += 1
        req = hs.next_req()
        hs.send(op="release", distro=distro, target=dst,
                seq=sup._seq, now=now, req=req)
        hs.wait_reply("released", 60.0, req=req)
        sup.simulate_crash()

    def _restart_supervisor(self) -> None:
        """The successor: a fresh supervisor over the same data dir —
        steals the fleet lease at a higher epoch, adopts the orphans,
        reconciles. Adoption quality is scored: zero shard-lease epoch
        bumps, zero pid changes, zero recovery passes."""
        old = self.sup
        pre = {k: (h.pid, h.epoch) for k, h in old.handles.items()}
        self.sups.append(old)
        sup2 = self._build_supervisor()
        sup2.start()
        self.sup = sup2
        adopted = [
            k for k, h in sup2.handles.items() if h.adopted
        ]

        def bump(key: str, by: int) -> None:
            self.stats[key] = self.stats.get(key, 0) + by

        bump("sup_restarts", 1)
        bump("adoptions_total", len(adopted))
        bump("adoption_epoch_bumps", sum(
            1 for k in adopted if sup2.handles[k].epoch != pre[k][1]
        ))
        bump("adoption_pid_changes", sum(
            1 for k in adopted if sup2.handles[k].pid != pre[k][0]
        ))
        # the worker counts every recovery pass it has EVER run; an
        # adopted process must still be at its single boot-time pass
        bump("adoption_recoveries", sum(
            1 for k in adopted
            if sup2.handles[k].adopt_hello.get("recovery_passes", 1)
            > 1
        ))
        bump("orphan_adoptions", sum(
            1 for k in adopted
            if sup2.handles[k].adopt_hello.get("orphaned")
        ))
        # solver-leader re-election: the successor must STEAL
        # solver.lease at a strictly higher epoch than the incarnation
        # it replaced (the dead leader abandoned, never released)
        old_sep = (
            old.solver_service.lease.epoch
            if old.solver_service is not None else 0
        )
        new_sep = (
            sup2.solver_service.lease.epoch
            if sup2.solver_service is not None else 0
        )
        if old_sep or new_sep:
            bump("solver_reelections", 1 if new_sep > old_sep else 0)
            self.stats["solver_epoch_prev"] = max(
                self.stats.get("solver_epoch_prev", 0), old_sep
            )
            self.stats["solver_epoch_last"] = new_sep

    # -- the replay loop -------------------------------------------------- #

    def _build_supervisor(self):
        from ..runtime.supervisor import FleetSupervisor
        from ..utils.retry import RetryPolicy

        return FleetSupervisor(
            self.data_dir,
            self.n_shards,
            ttl_s=self.workload.get("ttl_s", LEASE_TTL_S),
            hb_interval_s=0.25,
            hb_deadline_s=1.5,
            tick_s=self.spec.tick_s,
            # partition weathers shrink this: a black-holed tick
            # command otherwise blocks the round for the full default
            round_timeout_s=float(
                self.workload.get("round_timeout_s", 180.0)
            ),
            harness=True,
            recovery_anchor=NOW,
            restart_policy=RetryPolicy(
                attempts=1_000_000, base_backoff_s=0.25,
                max_backoff_s=2.0, jitter=0.0,
            ),
            worker_stderr="devnull",  # induced crashes would spam CI
            # survivability knobs sized for the harness: workers ride
            # out a supervisor kill for a minute (bounded so a leaked
            # orphan still dies), tick locally every second meanwhile,
            # and the successor steals the fleet lease after ~1s
            orphan_grace_s=float(
                self.workload.get("orphan_grace_s", 60.0)
            ),
            orphan_tick_s=1.0,
            # command-staleness deadline (one-way-partition detection):
            # 0 keeps it off unless the weather opts in — a partitioned
            # worker orphans after this many silent seconds and ticks
            # locally until commands resume
            command_silence_s=float(
                self.workload.get("command_silence_s", 0.0)
            ),
            supervisor_lease_ttl_s=1.0,
            # solver-leader plane: the workload opts in ("auto"); tight
            # TTL/timeout so leader death degrades and re-elects inside
            # the harness's tick cadence
            solver=self.workload.get("solver", "never"),
            solver_lease_ttl_s=1.0,
            solver_timeout_s=float(
                self.workload.get("solver_timeout_s", 6.0)
            ),
        )

    def _events_by_tick(self) -> Dict[int, List[Ev]]:
        out: Dict[int, List[Ev]] = {}
        for ev in self.spec.events:
            if ev.kind != "proc_fleet":
                out.setdefault(ev.tick, []).append(ev)
        return out

    def _wait_fleet_healthy(self, timeout_s: float = 60.0) -> None:
        """Let fenced takeovers land before the next virtual tick: any
        worker that died gets restarted by the watchdog (backoff +
        lease-TTL steal) — the round loop must not outrun it forever."""
        from ..utils.retry import Deadline

        deadline = Deadline.after(timeout_s)
        while not deadline.exceeded():
            if self.sup.crashed or self.sup.deposed:
                return  # nobody is coming until sup_restart fires
            if all(
                h.state == "ready" for h in self.sup.handles.values()
            ):
                return
            _time.sleep(0.05)

    def execute(self) -> Dict:
        from ..utils import faults

        t0 = _time.perf_counter()
        self._prev_faults = faults.active()
        self.data_dir = tempfile.mkdtemp(
            prefix=f"proc-{self.spec.name}-"
        )
        _seed_fleet(self.data_dir, self.n_shards, self.workload)
        self.sup = self._build_supervisor()
        self.sup.start()
        events = self._events_by_tick()
        try:
            max_ticks = self.spec.ticks * 3  # crash retries headroom
            for i in range(max_ticks):
                now = NOW + (i + 1) * self.spec.tick_s
                for ev in events.pop(i, ()):
                    self._apply_event(ev, now)
                self.rounds.append(self.sup.round(now=now))
                done = self.sup.agent_sim(now=now)
                self.dispatched_total += sum(
                    r.get("dispatched", 0) for r in done.values()
                )
                if done and len(done) == self.n_shards:
                    self.unfinished = sum(
                        r.get("unfinished", 0) for r in done.values()
                    )
                    if self.unfinished == 0 and not events:
                        self.converged_at = i
                        break
                self._wait_fleet_healthy()
            self.stats["supervisor_epoch"] = self.sup.sup_epoch
            self.sup.drain()
        finally:
            if self._armed_faults:
                # the leader fault plan is process-global: restore
                # whatever was installed before this replay
                if self._prev_faults is not None:
                    faults.install(self._prev_faults)
                else:
                    faults.uninstall()
            self.sup.stop(graceful=True)
            # crashed incarnations still hold the Popen objects for
            # workers the successor adopted: reap the zombies (the
            # successor's stop() already ended the processes)
            for old in self.sups:
                for h in old.handles.values():
                    if h.proc is None:
                        continue
                    if h.proc.poll() is None:
                        try:
                            h.proc.kill()
                        except OSError:
                            pass
                    try:
                        h.proc.wait(timeout=5.0)
                    except Exception:  # noqa: BLE001 — best effort  # evglint: disable=shedcheck -- child already SIGKILLed; the wait only reaps the zombie
                        pass
        try:
            if (
                self.with_reference
                and self._has_faults()
                and self.reference_state is None
            ):
                self.reference_state = _reference_canonical(
                    self.spec, seed=self.seed
                )
            entry = self._score()
            entry["timing"] = {
                "wall_ms": round((_time.perf_counter() - t0) * 1e3, 1)
            }
            entry["fingerprint"] = scorecard_entry_fingerprint(entry)
        finally:
            # the temp data dir (per-shard WAL segments) must go even
            # when scoring/reference raises — failed gate loops would
            # otherwise accumulate multi-MB orphans
            self._teardown()
        return entry

    def _has_faults(self) -> bool:
        return any(
            e.kind in ("proc_kill", "proc_hang", "sup_kill",
                       "leader_kill", "leader_hang", "net_fault")
            for e in self.spec.events
        )

    # -- scoring ---------------------------------------------------------- #

    def _score(self) -> Dict:
        from ..scheduler.sharded_plane import (
            fleet_owner_violations,
            merge_fleet_state,
        )

        stores = _open_fleet_stores(self.data_dir, self.n_shards)
        self.stores = stores
        try:
            self.owner_violations = fleet_owner_violations(stores)
            try:
                self.merged = merge_fleet_state(stores)
            except ValueError:
                self.merged = None
            #: the run's own converged canonical state — the rerun side
            #: a later crashed run compares against (captured here,
            #: before the data dir is torn down)
            self.reference_canonical = (
                canonical_state(self.merged)
                if self.merged is not None else None
            )
            # aggregate across EVERY supervisor incarnation: a
            # sup_kill/sup_restart weather's restarts, exits and
            # handoffs are spread over self.sups + the final one
            all_sups = [*self.sups, self.sup]
            solver_stacked = 0
            solver_local = 0
            stale_by_shard: Dict[int, int] = {}
            for rnd in self.rounds:
                for shard, reply in rnd.items():
                    sol = reply.get("solve")
                    if sol == "stacked":
                        solver_stacked += 1
                    elif sol == "local":
                        solver_local += 1
                    # cumulative per-worker counter: the per-round max
                    # is the lifetime total, summing rounds would
                    # double-count
                    stale_by_shard[shard] = max(
                        stale_by_shard.get(shard, 0),
                        int(reply.get("solve_stale_accepted", 0)),
                    )
            if solver_stacked or solver_local or stale_by_shard:
                self.stats["solver_stacked_replies"] = solver_stacked
                self.stats["solver_local_replies"] = solver_local
                self.stats["solver_stale_accepted"] = sum(
                    stale_by_shard.values()
                )
                self.stats["shm_leaked"] = self._count_leaked_segments()
            self.stats = {
                "ticks": len(self.rounds),
                "converged_at": self.converged_at,
                "unfinished_final": self.unfinished,
                "dispatched_total": self.dispatched_total,
                "restarts_total": sum(
                    h.restarts for s in all_sups
                    for h in s.handles.values()
                ),
                "crash_exits": sum(
                    1 for s in all_sups for h in s.handles.values()
                    for rc in h.exits if rc == 86
                ),
                "kill_exits": sum(
                    1 for s in all_sups for h in s.handles.values()
                    for rc in h.exits if rc < 0
                ),
                "max_epoch": max(
                    (h.epoch for s in all_sups
                     for h in s.handles.values()), default=0
                ),
                "migrations": sum(
                    len(s.migrations) for s in all_sups
                ),
                "reconciled_handoffs": sum(
                    len(s.reconciled) for s in all_sups
                ),
                **self.stats,
            }
            invariants = {}
            for name in (self.spec.invariants or ()):
                fn = PROC_INVARIANT_CHECKS.get(name)
                if fn is None:
                    invariants[name] = {
                        "ok": False,
                        "detail": f"unknown proc invariant {name!r}",
                    }
                    continue
                try:
                    problem = fn(self)
                except Exception as exc:  # noqa: BLE001 — a raising
                    # check is a failing check, never a crashed scorecard
                    problem = f"invariant raised: {exc!r}"
                invariants[name] = {
                    "ok": problem is None, "detail": problem or "",
                }
            checks = {}
            for name, fn in self.spec.checks:
                try:
                    problem = fn(self)
                except Exception as exc:  # noqa: BLE001
                    problem = f"check raised: {exc!r}"
                checks[name] = {
                    "ok": problem is None, "detail": problem or "",
                }
            slos = {s.name: s.evaluate(self.stats) for s in self.spec.slos}
            ok = (
                all(v["ok"] for v in invariants.values())
                and all(v["ok"] for v in checks.values())
                and all(v["ok"] for v in slos.values())
            )
            return {
                "name": self.spec.name,
                "ok": ok,
                "seed": self.seed,
                "deterministic": False,  # real processes, real clocks
                "backend": "procs",
                "invariants": invariants,
                "checks": checks,
                "slos": slos,
                "stats": {
                    k: self.stats[k] for k in sorted(self.stats)
                    if isinstance(self.stats[k], (int, float, bool, str))
                },
            }
        finally:
            for s in stores:
                try:
                    s.close()
                except Exception:  # noqa: BLE001 — inspection handles  # evglint: disable=shedcheck -- post-run inspection handles on a dead fleet's stores
                    pass

    def _count_leaked_segments(self) -> int:
        """Solver shm segments still attachable after the fleet stopped.

        Clean exits unlink their segment; a leaked one means a worker
        (or a crashed leader's reap pass) skipped hygiene — scenarios
        gate on this being zero."""
        from ..runtime.solver import Segment, segment_name

        leaked = 0
        for shard in range(self.n_shards):
            seg = Segment.attach(segment_name(self.data_dir, shard))
            if seg is not None:
                leaked += 1
                seg.close()
        return leaked

    def _teardown(self) -> None:
        import shutil

        from ..runtime.solver import Segment, segment_name

        # leaked solver segments live in /dev/shm, not the data dir:
        # rmtree won't reach them, so force-unlink before the run's
        # evidence disappears (leak already counted by _score)
        if self.data_dir is not None:
            for shard in range(self.n_shards):
                seg = Segment.attach(
                    segment_name(self.data_dir, shard)
                )
                if seg is not None:
                    seg.unlink()
                    seg.close()
        # trace capture reads the per-shard WAL segments after the run:
        # leave the data dir on disk for the caller to harvest (and
        # remove)
        if self.data_dir is not None and not self.keep_data_dir:
            shutil.rmtree(self.data_dir, ignore_errors=True)  # evglint: disable=fencecheck -- harness-owned temp data dir removed after every worker process exited; no live holder to fence against


# --------------------------------------------------------------------------- #
# proc invariants
# --------------------------------------------------------------------------- #


def _pinv_no_duplicate_dispatch(run: ProcScenarioRun) -> Optional[str]:
    if run.merged is None:
        return "fleet not mergeable (owner violations)"
    problems = check_duplicate_dispatch(run.merged)
    return "; ".join(problems[:3]) if problems else None


def _pinv_store_consistent(run: ProcScenarioRun) -> Optional[str]:
    if run.merged is None:
        return "fleet not mergeable (owner violations)"
    problems = check_store_consistent(run.merged)
    return "; ".join(problems[:3]) if problems else None


def _pinv_exactly_one_owner(run: ProcScenarioRun) -> Optional[str]:
    return (
        "; ".join(run.owner_violations[:3])
        if run.owner_violations else None
    )


def _pinv_monotone_epochs(run: ProcScenarioRun) -> Optional[str]:
    sups = [*run.sups, run.sup]
    for k in range(run.n_shards):
        es = [e for s in sups for e in s.handles[k].epochs]
        # an ADOPTION legitimately re-observes the same epoch (that is
        # the whole point: no bump) — collapse consecutive repeats,
        # then require strictly increasing; a lower epoch appearing
        # later is still caught
        collapsed = [
            e for i, e in enumerate(es) if i == 0 or e != es[i - 1]
        ]
        if collapsed != sorted(set(collapsed)):
            return f"shard {k} epochs not strictly increasing: {es}"
        restarts = sum(s.handles[k].restarts for s in sups)
        if restarts and not es:
            return (
                f"shard {k}: {restarts} restart(s) but no takeover "
                "ever said hello"
            )
        # a crash BEFORE the first hello (e.g. inside the recovery
        # pass) leaves only the successor's epoch observed — strictly-
        # increasing over the observed hellos is the checkable half;
        # the lease's epoch floor guarantees the unobserved half
    return None


def _pinv_resume_equals_rerun(run: ProcScenarioRun) -> Optional[str]:
    """The crashed-and-recovered fleet must converge to the same
    canonical state as an uninterrupted run of the same spec (the
    crash matrix's resume ≡ rerun, fleet-wide)."""
    if run.reference_state is None:
        return None  # no faults in the spec: nothing to compare
    if run.merged is None:
        return "fleet not mergeable (owner violations)"
    live = canonical_state(run.merged)
    if live != run.reference_state:
        diffs = []
        for key in ("tasks", "queues"):
            a = live[key]
            b = run.reference_state[key]
            for k in sorted(set(a) | set(b)):
                if a.get(k) != b.get(k):
                    diffs.append(f"{key}/{k}: {a.get(k)} != {b.get(k)}")
                if len(diffs) >= 3:
                    break
        return "resume != rerun: " + "; ".join(diffs[:3])
    return None


def _pinv_converged(run: ProcScenarioRun) -> Optional[str]:
    if run.unfinished != 0:
        return (
            f"workload did not drain: {run.unfinished} unfinished "
            f"after {len(run.rounds)} rounds"
        )
    return None


PROC_INVARIANT_CHECKS = {
    "no_duplicate_dispatch": _pinv_no_duplicate_dispatch,
    "store_consistent": _pinv_store_consistent,
    "exactly_one_owner": _pinv_exactly_one_owner,
    "monotone_epochs": _pinv_monotone_epochs,
    "resume_equals_rerun": _pinv_resume_equals_rerun,
    "converged": _pinv_converged,
}


def _reference_canonical(spec: ScenarioSpec,
                         seed: Optional[int] = None) -> dict:
    """The rerun side: the same spec with every proc_kill / proc_hang
    stripped, replayed uninterrupted; returns the merged canonical
    state at convergence. ``seed`` pins the reference to the crashed
    run's effective workload seed — resume ≡ rerun compares the SAME
    seeded problem."""
    import dataclasses

    clean = dataclasses.replace(
        spec,
        name=f"{spec.name}-reference",
        events=[
            e for e in spec.events
            if e.kind not in ("proc_kill", "proc_hang",
                              "sup_kill", "sup_restart",
                              "leader_kill", "leader_hang",
                              "net_fault", "net_heal")
        ],
        checks=[],
        slos=[],
        invariants=("converged",),
    )
    run = ProcScenarioRun(clean, with_reference=False, seed=seed)
    entry = run.execute()
    if not entry["ok"]:
        raise RuntimeError(
            f"proc reference run failed: {entry['invariants']}"
        )
    # the data dir is torn down inside execute(); the canonical state
    # was captured from the merged view at scoring time
    return run.reference_canonical


def run_proc_scenario(spec: ScenarioSpec,
                      seed: Optional[int] = None) -> Dict:
    """Replay one proc spec; returns its scorecard entry. ``seed``
    overrides the workload seed end-to-end (same contract as
    ``engine.run_scenario(spec, seed)``), so a fuzzer-found timeline
    replays the identical seeded problem in process mode."""
    return ProcScenarioRun(spec, seed=seed).execute()


# --------------------------------------------------------------------------- #
# the supervised-fleet weathers (gate --fleet-runtime)
# --------------------------------------------------------------------------- #


def _proc_sigkill_spec(seed: int = 0) -> ScenarioSpec:
    """2-shard supervised fleet; worker 0 is killed AT the wal.commit
    seam mid-round (SIGKILL shape) and must come back fenced at a
    strictly higher epoch with zero duplicate dispatch and resume ≡
    rerun state."""

    def restarted(run: ProcScenarioRun) -> Optional[str]:
        if run.stats.get("restarts_total", 0) < 1:
            return "no worker restart happened"
        if run.stats.get("crash_exits", 0) < 1:
            return "no crash-shaped (exit 86) death observed"
        h = run.sup.handles[0]
        if len(h.epochs) < 2 or h.epochs[-1] <= h.epochs[0]:
            return (
                f"shard 0 takeover not at a higher epoch: {h.epochs}"
            )
        return None

    return ScenarioSpec(
        name="proc-fleet-sigkill",
        description="supervised 2-shard fleet: SIGKILL-shaped worker "
                    "death at the wal.commit seam mid-round, fenced "
                    "takeover at a higher epoch, fleet converges",
        ticks=12,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                "hosts_per_distro": 3,
            }),
            Ev(2, "proc_kill", {"worker": 0, "seam": "wal.commit"}),
        ],
        slos=[
            SLO("bounded-restarts", "restarts_total", "<=", 3),
        ],
        checks=[("fenced-restart", restarted)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _proc_hang_spec(seed: int = 0) -> ScenarioSpec:
    """2-shard fleet; worker 1 is SIGSTOPped: its heartbeats stop, the
    supervisor's missed-heartbeat deadline kills and restarts it, and
    the replacement steals the shard lease at a higher epoch."""

    def hang_resolved(run: ProcScenarioRun) -> Optional[str]:
        if run.stats.get("kill_exits", 0) < 1:
            return "the hung worker was never killed"
        if run.stats.get("restarts_total", 0) < 1:
            return "the hung worker was never restarted"
        h = run.sup.handles[1]
        if len(h.epochs) < 2 or h.epochs[-1] <= h.epochs[0]:
            return f"shard 1 takeover not at a higher epoch: {h.epochs}"
        return None

    return ScenarioSpec(
        name="proc-fleet-hang",
        description="supervised 2-shard fleet: a SIGSTOPped worker "
                    "misses its heartbeat deadline, is killed and "
                    "restarted fenced; the fleet converges",
        ticks=12,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                "hosts_per_distro": 3,
            }),
            Ev(2, "proc_hang", {"worker": 1}),
        ],
        slos=[
            SLO("bounded-restarts", "restarts_total", "<=", 3),
        ],
        checks=[("hang-resolved", hang_resolved)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _sup_kill_midround_spec(seed: int = 0) -> ScenarioSpec:
    """The ISSUE-14 acceptance centerpiece: the SUPERVISOR is SIGKILLed
    mid-round fan-out on a 2-shard fleet; both workers go orphan (shard
    leases kept, autonomous local ticks), a restarted supervisor steals
    the fleet lease at a higher epoch and ADOPTS both live workers —
    zero shard-lease epoch bumps, zero recovery passes, same pids
    (resident plane never re-primed) — and rounds resume to
    convergence with zero duplicate dispatch."""

    def adopted_live(run: ProcScenarioRun) -> Optional[str]:
        st = run.stats
        if st.get("sup_restarts", 0) < 1:
            return "the supervisor never restarted"
        if st.get("adoptions_total", 0) < 2:
            return (
                "both live workers must be adopted, got "
                f"{st.get('adoptions_total', 0)}"
            )
        if st.get("orphan_adoptions", 0) < 2:
            return "workers were not adopted FROM orphan mode"
        if st.get("adoption_epoch_bumps", 0):
            return "adoption bumped a shard-lease epoch"
        if st.get("adoption_pid_changes", 0):
            return "adoption changed a worker pid (cold respawn)"
        if st.get("adoption_recoveries", 0):
            return "an adopted worker reported a recovery pass"
        if st.get("restarts_total", 0):
            return "a worker was cold-restarted"
        return None

    return ScenarioSpec(
        name="proc-sup-kill-midround",
        description="2-shard fleet: supervisor killed mid-round "
                    "fan-out; workers orphan, the restarted "
                    "supervisor adopts both live (no epoch bumps, no "
                    "recovery), rounds resume, fleet converges",
        ticks=14,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                "hosts_per_distro": 3,
            }),
            Ev(2, "sup_kill", {"at": "mid_round"}),
            Ev(3, "sup_restart", {}),
        ],
        slos=[
            SLO("no-worker-restarts", "restarts_total", "<=", 0),
        ],
        checks=[("adopted-live", adopted_live)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _sup_kill_midhandoff_spec(seed: int = 0) -> ScenarioSpec:
    """Supervisor killed BETWEEN the release and prime legs of a live
    migration: the released record is durable on the source, the
    target knows nothing — the successor's post-adoption
    ``reconcile_handoffs`` must converge to exactly-one-owner."""

    def handoff_reconciled(run: ProcScenarioRun) -> Optional[str]:
        st = run.stats
        if st.get("sup_restarts", 0) < 1:
            return "the supervisor never restarted"
        if st.get("adoptions_total", 0) < 2:
            return (
                "both live workers must be adopted, got "
                f"{st.get('adoptions_total', 0)}"
            )
        if st.get("reconciled_handoffs", 0) < 1:
            return (
                "the released-but-unprimed handoff was never "
                "reconciled by the successor"
            )
        return None

    return ScenarioSpec(
        name="proc-sup-kill-midhandoff",
        description="2-shard fleet: supervisor killed between the "
                    "release and prime handoff legs; the restarted "
                    "supervisor adopts the workers and reconciles to "
                    "exactly-one-owner",
        ticks=14,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                "hosts_per_distro": 3,
            }),
            Ev(2, "sup_kill", {"at": "mid_handoff"}),
            Ev(3, "sup_restart", {}),
        ],
        slos=[
            SLO("no-worker-restarts", "restarts_total", "<=", 0),
        ],
        checks=[("handoff-reconciled", handoff_reconciled)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


#: the solver-leader fleets need load on BOTH shards (the topology
#: hash-partitions distros; 2 distros can land on one shard, leaving
#: the other with nothing to publish and the leader declining the
#: single publication as partial) — 6 distros spreads reliably
_SOLVER_WORKLOAD = {
    "shards": 2, "distros": 6, "tasks": 36, "seed": 7,
    "hosts_per_distro": 3, "solver": "auto", "solver_timeout_s": 6.0,
}


def _check_solver_survived(run: "ProcScenarioRun") -> Optional[str]:
    """Shared acceptance for every leader-death weather: the fleet
    degraded to local (never corrupted), the successor re-elected at a
    higher epoch, and stacked rounds RESUMED after the restart."""
    st = run.stats
    if st.get("sup_restarts", 0) < 1:
        return "the supervisor never restarted"
    if st.get("solver_stacked_replies", 0) < 2:
        return (
            "fleet never produced a stacked round, got "
            f"{st.get('solver_stacked_replies', 0)} stacked replies"
        )
    if st.get("solver_reelections", 0) < 1:
        return "the successor never re-elected a solver leader"
    if st.get("solver_stale_accepted", 0):
        return (
            "a worker accepted a stale leader's result: "
            f"{st['solver_stale_accepted']}"
        )
    if st.get("shm_leaked", 0):
        return f"{st['shm_leaked']} solver shm segment(s) leaked"
    for i, rnd in enumerate(run.rounds):
        if i <= 3:
            continue  # pre-restart rounds don't prove recovery
        stacked = sum(
            1 for r in rnd.values() if r.get("solve") == "stacked"
        )
        if stacked >= 2:
            return None
    return "no fully stacked round after the supervisor restart"


def _leader_kill_spec(seam: str, slug: str,
                      seed: int = 0) -> ScenarioSpec:
    """Leader SIGKILL-shaped death at one solver seam on a 2-shard
    durable fleet: workers must degrade to local within the round
    (fenced at the shm header, never a torn fleet solve), orphan, get
    adopted by the successor, and return to stacked rounds under the
    successor's strictly-higher solver epoch."""
    return ScenarioSpec(
        name=f"proc-leader-kill-{slug}",
        description=f"2-shard solver fleet: leader dies at {seam}; "
                    "workers degrade to local, successor re-elects "
                    "and stacked rounds resume",
        ticks=14,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", dict(_SOLVER_WORKLOAD)),
            Ev(2, "leader_kill", {"seam": seam}),
            Ev(3, "sup_restart", {}),
        ],
        slos=[
            SLO("no-worker-restarts", "restarts_total", "<=", 0),
        ],
        checks=[("solver-survived", _check_solver_survived)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _leader_kill_publish_spec(seed: int = 0) -> ScenarioSpec:
    return _leader_kill_spec("solver.publish", "publish", seed)


def _leader_kill_solve_spec(seed: int = 0) -> ScenarioSpec:
    return _leader_kill_spec("solver.solve", "solve", seed)


def _leader_kill_return_spec(seed: int = 0) -> ScenarioSpec:
    """The nastiest point: the leader dies AFTER writing the first
    shard's result — one shard got a solved column, the other must
    fence at out_seq and degrade local, and resume ≡ rerun still
    holds (stacked and local solves are bit-identical)."""
    return _leader_kill_spec("solver.return", "return", seed)


def _leader_kill_midround_spec(seed: int = 0) -> ScenarioSpec:
    return _leader_kill_spec("solver.round", "midround", seed)


def _leader_hang_spec(seed: int = 0) -> ScenarioSpec:
    """The leader stalls INSIDE the stacked solve, past the workers'
    solver timeout: both degrade to local that round; when the stalled
    solve finally lands its out_seq is from a finished round, so
    nobody accepts it, and the next round goes stacked again — no
    restart, no re-election, same leader."""

    def hang_degraded(run: ProcScenarioRun) -> Optional[str]:
        st = run.stats
        if st.get("solver_local_replies", 0) < 1:
            return "no round ever degraded to local solve"
        if st.get("solver_stale_accepted", 0):
            return (
                "a worker accepted the stalled leader's late result: "
                f"{st['solver_stale_accepted']}"
            )
        if st.get("shm_leaked", 0):
            return f"{st['shm_leaked']} solver shm segment(s) leaked"
        saw_local = False
        for rnd in run.rounds:
            solves = [r.get("solve") for r in rnd.values()]
            if "local" in solves:
                saw_local = True
            elif saw_local and solves.count("stacked") >= 2:
                return None
        return "no stacked round after the timeout-degraded one"

    return ScenarioSpec(
        name="proc-leader-hang",
        description="2-shard solver fleet: leader stalls inside the "
                    "stacked solve past the worker timeout; that "
                    "round degrades to local, the late result is "
                    "fenced at out_seq, stacked rounds resume",
        ticks=14,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", dict(_SOLVER_WORKLOAD)),
            Ev(2, "leader_hang",
               {"seam": "solver.solve", "delay_s": 8.0}),
        ],
        slos=[
            SLO("no-worker-restarts", "restarts_total", "<=", 0),
        ],
        checks=[("hang-degraded", hang_degraded)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _net_oneway_partition_spec(seed: int = 0) -> ScenarioSpec:
    """The Jepsen one-way partition: supervisor→worker 0 commands are
    black-holed at the ``ipc.send.0`` seam while worker 0's heartbeats
    keep flowing the other way. The heartbeat watchdog must NOT kill a
    worker it can still hear (no split-brain restart); instead the
    worker's command-staleness deadline fires — it orphans, keeps its
    shard lease, and ticks locally — and when the partition heals the
    next delivered command clears orphan mode in place: zero cold
    restarts, zero epoch bumps, zero duplicate dispatch."""

    def partition_ridden_out(run: ProcScenarioRun) -> Optional[str]:
        h = run.sup.handles[0]
        if h.cmd_silences < 1:
            return (
                "worker 0 never tripped its command-staleness "
                "deadline (cmd_silences == 0)"
            )
        if run.stats.get("restarts_total", 0):
            return "the partitioned worker was cold-restarted"
        if len(h.epochs) != 1:
            return (
                f"worker 0 bumped its shard-lease epoch: {h.epochs}"
            )
        return None

    return ScenarioSpec(
        name="proc-net-oneway-partition",
        description="2-shard fleet: supervisor→worker commands "
                    "black-holed one way (heartbeats still flow); the "
                    "command-staleness deadline orphans the worker, "
                    "the heal un-orphans it in place — no restart, no "
                    "epoch bump, no duplicate dispatch",
        ticks=14,
        seed=seed,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                "hosts_per_distro": 3,
                # partitioned rounds must go partial FAST, and the
                # silence deadline must fire inside the blackout window
                "round_timeout_s": 4.0, "command_silence_s": 2.0,
            }),
            Ev(2, "net_fault", {"seam": "ipc.send.0",
                                "kind": "partition"}),
            Ev(5, "net_heal", {"seam": "ipc.send.0"}),
        ],
        slos=[
            SLO("no-worker-restarts", "restarts_total", "<=", 0),
        ],
        checks=[("partition-ridden-out", partition_ridden_out)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


PROC_SCENARIOS: Dict[str, callable] = {
    "proc-fleet-sigkill": _proc_sigkill_spec,
    "proc-fleet-hang": _proc_hang_spec,
    "proc-sup-kill-midround": _sup_kill_midround_spec,
    "proc-sup-kill-midhandoff": _sup_kill_midhandoff_spec,
    "proc-leader-kill-publish": _leader_kill_publish_spec,
    "proc-leader-kill-solve": _leader_kill_solve_spec,
    "proc-leader-kill-return": _leader_kill_return_spec,
    "proc-leader-kill-midround": _leader_kill_midround_spec,
    "proc-leader-hang": _leader_hang_spec,
    "proc-net-oneway-partition": _net_oneway_partition_spec,
}

#: the supervisor-crash subset (tools/crash_matrix.py run_sup_points
#: runs these inside the full matrix; gate --fleet-runtime runs every
#: PROC_SCENARIOS weather including them)
SUP_KILL_SCENARIOS = ("proc-sup-kill-midround",
                      "proc-sup-kill-midhandoff")

#: the solver-leader death subset (tools/crash_matrix.py
#: run_solver_points runs these; gate --fleet-runtime gets them via
#: PROC_SCENARIOS like every other weather)
SOLVER_SCENARIOS = ("proc-leader-kill-publish",
                    "proc-leader-kill-solve",
                    "proc-leader-kill-return",
                    "proc-leader-kill-midround",
                    "proc-leader-hang")


# --------------------------------------------------------------------------- #
# crash-matrix delegation (tools/crash_matrix.py KILL_POINTS)
# --------------------------------------------------------------------------- #


def _crash_point_spec(seam: str, index: int,
                      ticks: int) -> ScenarioSpec:
    return ScenarioSpec(
        name=f"proc-crash-{seam.replace('.', '-')}-{index}",
        description=f"crash-matrix kill point {seam}@{index} through "
                    "the supervised-fleet backend",
        ticks=ticks,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", {
                "shards": 1, "distros": 2, "tasks": 24, "seed": 11,
                "hosts_per_distro": 3,
            }),
        ],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def run_crash_point(
    seam: str,
    index: int,
    ticks: int = 9,
    reference: Optional[dict] = None,
) -> dict:
    """One classic crash-matrix kill point through the fleet runtime:
    a 1-shard supervised fleet whose worker is spawned with
    ``--crash seam@index`` (the deterministic PR-1 kill point — only
    the FIRST spawn carries it; the supervisor's restart comes back
    clean), driven to convergence, then checked against the
    crash-matrix contracts. Returns the legacy point-result shape
    (``point`` / ``ok`` / ``crashed`` / ``epochs`` / ``parity_ok`` /
    ``problems``) tools/crash_matrix.py prints."""
    spec = _crash_point_spec(seam, index, ticks)
    run = ProcScenarioRun(spec, with_reference=False)
    # splice the spawn-time kill point into the supervisor build —
    # only the FIRST spawn carries it; the watchdog's restart is clean
    orig_build = run._build_supervisor

    def build_with_crash():
        sup = orig_build()
        sup.spawn_crash = {0: f"{seam}@{index}"}
        return sup

    run._build_supervisor = build_with_crash
    run.reference_state = reference
    entry = run.execute()
    problems = [
        f"{section}:{name}: {v['detail']}"
        for section in ("invariants", "checks")
        for name, v in entry.get(section, {}).items()
        if not v["ok"]
    ]
    crashed = entry["stats"].get("crash_exits", 0) >= 1
    if not crashed:
        # lease.renew kill points can fire between rounds; a point that
        # never fired at all proves nothing
        problems.append("kill point never fired (no exit-86 death)")
    if reference is not None:
        parity_ok = not any(
            p.startswith("invariants:resume_equals_rerun")
            for p in problems
        )
    else:
        parity_ok = True
    return {
        "point": f"{seam}@{index}",
        "ok": crashed and not problems,
        "crashed": crashed,
        "rc": entry["stats"].get("restarts_total", 0),
        "epochs": [
            h for hd in ([] if run.sup is None else
                         run.sup.handles.values())
            for h in hd.epochs
        ],
        "parity_ok": parity_ok,
        "problems": problems,
        "entry": entry,
    }


def proc_reference_state(ticks: int = 9) -> dict:
    """The uninterrupted 1-shard fleet run of the crash workload — the
    rerun side every kill point compares against."""
    spec = _crash_point_spec("reference", 0, ticks)
    run = ProcScenarioRun(spec, with_reference=False)
    entry = run.execute()
    if not entry["ok"]:
        raise RuntimeError(
            f"proc crash reference failed: {entry['invariants']}"
        )
    return run.reference_canonical
