"""Trace-driven scenario engine: one harness that replays every weather.

Public surface:

  ``ScenarioSpec`` / ``Ev`` / ``SLO``  — the declarative vocabulary
  ``run_scenario(spec)``               — one seeded replay → scorecard entry
  ``SCENARIOS``                        — the shipped six-weather library
  ``SABOTAGE_SCENARIOS``               — deliberately-red self-test specs
  ``FAULT_SCENARIO_CASES`` / ``OVERLOAD_SCENARIO_CASES`` /
  ``run_matrix_case``                  — the fault/overload matrix cases
                                         migrated to run THROUGH the engine
  ``PROC_SCENARIOS`` / ``run_proc_scenario`` / ``run_crash_point``
                                       — the child-process replay backend
                                         (scenarios/procs.py): specs with
                                         proc_kill/proc_hang events against
                                         a supervised worker-process fleet
  ``generate_weather`` / ``campaign`` / ``shrink_spec`` /
  ``sabotage_selftest``                — the property-based weather fuzzer
                                         (scenarios/fuzz.py): seeded random
                                         timelines over this vocabulary,
                                         delta-debugging shrinker, soak
                                         campaign + found-bug self-test
  ``TraceRecorder`` / ``trace_to_spec`` / ``save_regression_spec`` /
  ``load_regression_specs``            — trace capture (scenarios/trace.py):
                                         a live plane's WAL journal, log
                                         stream, and supervisor IPC recorded
                                         and distilled back into a replayable
                                         ``ScenarioSpec``; fuzz-found minimal
                                         timelines are checked in under
                                         ``scenarios/regressions/``

``tools/scenario_engine.py`` is the CLI (SCORECARD.json emission +
determinism check + last-green diff); ``tools/gate.py --scenarios``
wires it into CI, and ``tools/gate.py --fuzz`` (tools/fuzz_matrix.py)
runs the fuzz campaign + sabotage self-test.
"""
from .engine import EVENT_HANDLERS, ScenarioRun, run_scenario
from .fuzz import (
    campaign,
    generate_proc_weather,
    generate_weather,
    sabotage_selftest,
    shrink_spec,
)
from .library import SABOTAGE_SCENARIOS, SCENARIOS
from .matrix import (
    FAULT_SCENARIO_CASES,
    OVERLOAD_SCENARIO_CASES,
    run_matrix_case,
)
from .procs import (
    PROC_SCENARIOS,
    ProcScenarioRun,
    run_crash_point,
    run_proc_scenario,
)
from .spec import DEFAULT_INVARIANTS, Ev, SLO, ScenarioSpec
from .trace import (
    TraceRecorder,
    capture_data_dir,
    load_regression_specs,
    save_regression_spec,
    spec_from_jsonable,
    spec_to_jsonable,
    trace_to_spec,
)

__all__ = [
    "DEFAULT_INVARIANTS",
    "Ev",
    "EVENT_HANDLERS",
    "FAULT_SCENARIO_CASES",
    "OVERLOAD_SCENARIO_CASES",
    "PROC_SCENARIOS",
    "ProcScenarioRun",
    "SABOTAGE_SCENARIOS",
    "SCENARIOS",
    "SLO",
    "ScenarioRun",
    "ScenarioSpec",
    "TraceRecorder",
    "campaign",
    "capture_data_dir",
    "generate_proc_weather",
    "generate_weather",
    "load_regression_specs",
    "run_crash_point",
    "run_matrix_case",
    "run_proc_scenario",
    "run_scenario",
    "sabotage_selftest",
    "save_regression_spec",
    "shrink_spec",
    "spec_from_jsonable",
    "spec_to_jsonable",
    "trace_to_spec",
]
