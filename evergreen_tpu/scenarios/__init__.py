"""Trace-driven scenario engine: one harness that replays every weather.

Public surface:

  ``ScenarioSpec`` / ``Ev`` / ``SLO``  — the declarative vocabulary
  ``run_scenario(spec)``               — one seeded replay → scorecard entry
  ``SCENARIOS``                        — the shipped six-weather library
  ``SABOTAGE_SCENARIOS``               — deliberately-red self-test specs
  ``FAULT_SCENARIO_CASES`` / ``OVERLOAD_SCENARIO_CASES`` /
  ``run_matrix_case``                  — the fault/overload matrix cases
                                         migrated to run THROUGH the engine
  ``PROC_SCENARIOS`` / ``run_proc_scenario`` / ``run_crash_point``
                                       — the child-process replay backend
                                         (scenarios/procs.py): specs with
                                         proc_kill/proc_hang events against
                                         a supervised worker-process fleet

``tools/scenario_engine.py`` is the CLI (SCORECARD.json emission +
determinism check + last-green diff); ``tools/gate.py --scenarios``
wires it into CI.
"""
from .engine import EVENT_HANDLERS, ScenarioRun, run_scenario
from .library import SABOTAGE_SCENARIOS, SCENARIOS
from .matrix import (
    FAULT_SCENARIO_CASES,
    OVERLOAD_SCENARIO_CASES,
    run_matrix_case,
)
from .procs import (
    PROC_SCENARIOS,
    ProcScenarioRun,
    run_crash_point,
    run_proc_scenario,
)
from .spec import DEFAULT_INVARIANTS, Ev, SLO, ScenarioSpec

__all__ = [
    "DEFAULT_INVARIANTS",
    "Ev",
    "EVENT_HANDLERS",
    "FAULT_SCENARIO_CASES",
    "OVERLOAD_SCENARIO_CASES",
    "PROC_SCENARIOS",
    "ProcScenarioRun",
    "SABOTAGE_SCENARIOS",
    "SCENARIOS",
    "SLO",
    "ScenarioRun",
    "ScenarioSpec",
    "run_crash_point",
    "run_matrix_case",
    "run_proc_scenario",
    "run_scenario",
]
