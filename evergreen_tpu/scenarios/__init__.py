"""Trace-driven scenario engine: one harness that replays every weather.

Public surface:

  ``ScenarioSpec`` / ``Ev`` / ``SLO``  — the declarative vocabulary
  ``run_scenario(spec)``               — one seeded replay → scorecard entry
  ``SCENARIOS``                        — the shipped six-weather library
  ``SABOTAGE_SCENARIOS``               — deliberately-red self-test specs
  ``FAULT_SCENARIO_CASES`` / ``OVERLOAD_SCENARIO_CASES`` /
  ``run_matrix_case``                  — the fault/overload matrix cases
                                         migrated to run THROUGH the engine

``tools/scenario_engine.py`` is the CLI (SCORECARD.json emission +
determinism check + last-green diff); ``tools/gate.py --scenarios``
wires it into CI.
"""
from .engine import EVENT_HANDLERS, ScenarioRun, run_scenario
from .library import SABOTAGE_SCENARIOS, SCENARIOS
from .matrix import (
    FAULT_SCENARIO_CASES,
    OVERLOAD_SCENARIO_CASES,
    run_matrix_case,
)
from .spec import DEFAULT_INVARIANTS, Ev, SLO, ScenarioSpec

__all__ = [
    "DEFAULT_INVARIANTS",
    "Ev",
    "EVENT_HANDLERS",
    "FAULT_SCENARIO_CASES",
    "OVERLOAD_SCENARIO_CASES",
    "SABOTAGE_SCENARIOS",
    "SCENARIOS",
    "SLO",
    "ScenarioRun",
    "ScenarioSpec",
    "run_matrix_case",
    "run_scenario",
]
