"""The shipped scenario library: eight realistic weathers, one sabotage.

Each spec is a declarative timeline over the engine's event vocabulary
(scenarios/engine.py) plus named checks for the assertions the SLO
vocabulary cannot express. All six are deterministic: same seed ⇒ same
scorecard fingerprint (tools/scenario_engine.py --check-determinism).

  merge-queue-storm   conflicting patch stacks racing one project, a
                      mid-stack failure blocking its tail
  dag-stepback        deep dependency DAG, mid-build failure, stepback
                      activation of the prior revision's task
  spot-reclamation    mixed EC2-fleet(spot)/docker/static fleet; spot
                      instances reclaimed mid-task
  region-failover     lease stolen between begin_tick and the group
                      flush; the engine fails over to the thief's epoch
  spawn-burst         interactive spawn-host burst beside CI load, then
                      the expiry sweep reaps the fleet
  seasonality         a week compressed to minutes: arrivals + backlog
                      gauges drive GREEN→…→BLACK→…→GREEN with counted
                      shedding and a green landing
  disk-bitrot-snapshot  a published snapshot silently rots on disk: the
                      scrub detects the digest mismatch, quarantines
                      the file and rebuilds a verified checkpoint while
                      serving never stops
  disk-enospc-commit  the disk fills at a WAL group commit: the frame
                      is shed loudly (RED floor) instead of raising
                      mid-commit, and the first accepted frame heals
                      durability back to GREEN
"""
from __future__ import annotations

from typing import Dict, Optional

from ..globals import HostStatus, Provider, Requester, TaskStatus
from .spec import Ev, SLO, ScenarioSpec

# --------------------------------------------------------------------------- #
# checks
# --------------------------------------------------------------------------- #


def _check_merge_before_patch(run) -> Optional[str]:
    """Merge-queue entries must outrank plain patches: the first merge
    dispatch happens no later than the first patch dispatch."""
    merge_ticks = [
        t for tid, t in run.dispatch_tick.items() if "-stack" in tid
    ]
    patch_ticks = [
        t for tid, t in run.dispatch_tick.items() if "-patch" in tid
    ]
    if not merge_ticks or not patch_ticks:
        return "storm never dispatched both classes"
    if min(merge_ticks) > min(patch_ticks):
        return (
            f"first merge dispatch at tick {min(merge_ticks)} after "
            f"first patch dispatch at tick {min(patch_ticks)}"
        )
    return None


def _check_blocked_tail(run) -> Optional[str]:
    """The failed stack entry's dependents must stay blocked, never
    dispatched over an unattainable dependency."""
    for tid in ("dmq-stackB-02", "dmq-stackB-03"):
        doc = run.store.collection("tasks").get(tid)
        if doc is None:
            return f"{tid} missing"
        if doc["status"] != TaskStatus.UNDISPATCHED.value:
            return f"{tid} ran over a failed dependency: {doc['status']}"
    return None


STEPBACK_TARGET = "dsb-int-09"


def _check_stepback_scheduled(run) -> Optional[str]:
    """The stepback-activated task was scheduled AND the packed solve's
    t_stepback provenance column flagged it (PR-6 provenance riding the
    result buffer)."""
    doc = run.store.collection("tasks").get(STEPBACK_TARGET)
    if doc is None or not doc.get("activated"):
        return "stepback target never activated"
    if doc.get("activated_by") != "stepback-activator":
        return f"activated by {doc.get('activated_by')!r}, not stepback"
    if STEPBACK_TARGET not in run.dispatch_tick:
        return "stepback task never dispatched"
    for res in run.tick_results:
        prov = getattr(res, "provenance", None)
        if prov is None:
            continue
        terms = prov.explain("dsb", STEPBACK_TARGET)
        if terms is not None:
            if not terms.get("stepback"):
                return "provenance shows stepback=False for the target"
            if terms.get("rank_term", 0.0) < 10.0:
                return (
                    "stepback rank term missing its factor boost: "
                    f"{terms.get('rank_term')}"
                )
            run.stats["stepback_rank_term"] = terms["rank_term"]
            return None
    return "stepback task never appeared in solve provenance"


def _check_stepback_dedup(run) -> Optional[str]:
    """Re-delivering the failure (a recovery re-run of mark_end's
    stepback evaluation) must not activate a second task."""
    from ..models import task as task_mod
    from ..models.lifecycle import evaluate_stepback

    failed = task_mod.get(run.store, "dsb-int-10")
    if failed is None:
        return "failed task missing"
    evaluate_stepback(run.store, failed, run.now)  # the re-delivery
    n = run.store.collection("events").count(
        lambda d: d.get("event_type") == "TASK_ACTIVATED_STEPBACK"
    )
    if n != 1:
        return f"stepback activated {n} times (dedup broken)"
    return None


def _check_no_stranded_claims(run) -> Optional[str]:
    """A terminated host must never keep a running_task claim (the
    stranded-dispatch-claim gap the reclamation scenario exists to
    catch)."""
    for doc in run.store.collection("hosts").find(
        lambda d: d["status"] == HostStatus.TERMINATED.value
    ):
        if doc.get("running_task"):
            return (
                f"terminated host {doc['_id']} still claims "
                f"{doc['running_task']}"
            )
    return None


def _check_reclaimed_restart_credits(run) -> Optional[str]:
    """Each reclaimed-mid-task execution is archived as a system failure
    and charged exactly one automatic-restart credit."""
    reclaimed = run.counter_delta("cloud.spot_reclaimed")
    reset = run.counter_delta("recovery.stranded_reset")
    if reset != reclaimed:
        return (
            f"{reclaimed} reclamations but {reset} restart-credited "
            "resets"
        )
    return None


def _check_mixed_fleet(run) -> Optional[str]:
    """The fleet really is mixed: ec2-spot, docker containers, and
    static hosts all ran work."""
    for distro in ("dspot", "ddock", "dstatic"):
        if not any(
            tid.startswith(distro) for tid in run.dispatch_tick
        ):
            return f"{distro} never dispatched a task"
    return None


def _check_failover_resumes(run) -> Optional[str]:
    """After the fenced tick, the thief's very next tick must plan
    cleanly at a strictly higher epoch."""
    fenced_at = next(
        (
            i for i, r in enumerate(run.tick_results)
            if r.degraded == "fenced"
        ),
        None,
    )
    if fenced_at is None:
        return "no tick was fenced"
    if fenced_at + 1 >= len(run.tick_results):
        return "run ended at the fenced tick"
    after = run.tick_results[fenced_at + 1]
    if after.degraded:
        return f"post-failover tick degraded: {after.degraded!r}"
    if run.epochs[fenced_at + 1] <= run.epochs[fenced_at]:
        return (
            f"failover did not raise the epoch: "
            f"{run.epochs[fenced_at]} -> {run.epochs[fenced_at + 1]}"
        )
    run.stats["failover_downtime_ticks"] = 1
    return None


def _check_spawn_lifecycle(run) -> Optional[str]:
    """Every spawn host reached RUNNING during the burst and was reaped
    by the expiry sweep after the clock jump."""
    hosts = run.store.collection("hosts").find(
        lambda d: d.get("user_host")
    )
    if len(hosts) != 40:
        return f"expected 40 spawn hosts, found {len(hosts)}"
    ran = sum(1 for d in hosts if d.get("provision_time") or d.get(
        "start_time"
    ))
    run.stats["spawn_hosts_started"] = ran
    not_reaped = [
        d["_id"] for d in hosts
        if d["status"] != HostStatus.TERMINATED.value
    ]
    if not_reaped:
        return (
            f"{len(not_reaped)} spawn hosts survived expiry "
            f"(e.g. {not_reaped[0]})"
        )
    return None


def _check_ladder_cycle(run) -> Optional[str]:
    """The full GREEN→…→BLACK→…→GREEN cycle, in order."""
    levels = [r.overload for r in run.tick_results]
    try:
        i_black = levels.index("black")
    except ValueError:
        return f"never reached BLACK (saw {sorted(set(levels))})"
    if "green" not in levels[:i_black]:
        return "did not start GREEN"
    if "green" not in levels[i_black:]:
        return "never recovered to GREEN after BLACK"
    run.stats["ticks_to_recover_green"] = (
        levels[i_black:].index("green")
    )
    return None


def _check_outbox_cap_held(run) -> Optional[str]:
    undelivered = run.store.collection("slack_outbox").count(
        lambda d: not d.get("delivered") and not d.get("failed")
    )
    if undelivered > 400:
        return f"outbox cap breached: {undelivered} undelivered"
    return None


def _sabotage_duplicate_claim(run) -> None:
    """Deliberately corrupt the dispatch books — duplicate a host's
    running-task claim bypassing the CAS — so the invariant layer must
    catch it (the gate's self-test that a violation fails CI)."""
    hosts = sorted(
        (
            d for d in run.store.collection("hosts").find()
            if d.get("running_task")
        ),
        key=lambda d: d["_id"],
    )
    free = sorted(
        (
            d for d in run.store.collection("hosts").find()
            if not d.get("running_task")
        ),
        key=lambda d: d["_id"],
    )
    if hosts and free:
        run.store.collection("hosts").update(
            free[0]["_id"], {"running_task": hosts[0]["running_task"]}
        )


# --------------------------------------------------------------------------- #
# the six weathers
# --------------------------------------------------------------------------- #


def _merge_queue_storm() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dmq", "provider": Provider.MOCK.value, "hosts": 6},
        ]}),
        Ev(0, "tasks", {
            "distro": "dmq", "n": 12, "prefix": "dmq-patch",
            "requester": Requester.PATCH.value,
        }),
        Ev(0, "merge_stack", {"distro": "dmq", "stack": "stackA", "n": 4}),
        Ev(1, "merge_stack", {"distro": "dmq", "stack": "stackB", "n": 4}),
        Ev(1, "merge_stack", {"distro": "dmq", "stack": "stackC", "n": 4}),
        # the storm's conflict: stackB's second entry breaks mid-merge
        Ev(2, "fail_next", {"match": "dmq-stackB-01", "count": 1}),
    ]
    return ScenarioSpec(
        name="merge-queue-storm",
        description="conflicting merge-queue patch stacks racing one "
                    "project; a mid-stack failure blocks exactly its "
                    "tail while siblings merge through",
        ticks=16,
        events=events,
        slos=[
            SLO("one-conflict-failure", "tasks_failed", "==", 1),
            # everything except the broken stack's 2-entry tail finishes
            SLO("storm-drains", "tasks_unfinished", "==", 2),
            SLO("no-system-failures", "tasks_system_failed", "==", 0),
        ],
        checks=[
            ("merge-prioritized", _check_merge_before_patch),
            ("failed-stack-tail-blocked", _check_blocked_tail),
        ],
    )


def _dag_stepback() -> ScenarioSpec:
    # mainline history: revision 9 (all inactive — already built) and
    # revision 10 (activated), each a 4-deep DAG
    def rev(order: int, activated: bool):
        s = f"{order:02d}"
        return [
            {"id": f"dsb-compile-{s}", "display_name": "compile",
             "revision_order": order, "activated": activated},
            {"id": f"dsb-unit-{s}", "display_name": "unit",
             "revision_order": order, "activated": activated,
             "deps": [f"dsb-compile-{s}"]},
            {"id": f"dsb-int-{s}", "display_name": "integration",
             "revision_order": order, "activated": activated,
             "deps": [] if not activated else [f"dsb-unit-{s}"]},
            {"id": f"dsb-pkg-{s}", "display_name": "package",
             "revision_order": order, "activated": activated,
             "deps": [f"dsb-int-{s}"]},
        ]

    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dsb", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "dag", {"distro": "dsb", "nodes": rev(9, False)}),
        Ev(0, "dag", {"distro": "dsb", "nodes": rev(10, True)}),
        # integration-10 fails mid-build → linear stepback must activate
        # integration-09 (undispatched, inactive, prior revision)
        Ev(0, "fail_next", {"match": "dsb-int-10", "count": 1}),
    ]
    return ScenarioSpec(
        name="dag-stepback",
        description="deep dependency DAG; a mid-build failure triggers "
                    "stepback activation of the prior revision's task, "
                    "prioritized by the packed solve's t_stepback term "
                    "and deduplicated on re-replay",
        ticks=12,
        events=events,
        slos=[
            SLO("one-stepback", "stepback_activations", "==", 1),
            SLO("one-failure", "tasks_failed", "==", 1),
            # pkg-10 blocks on the failed integration; everything else
            # (rev-10 chain + the stepback target) runs
            SLO("dag-progresses", "tasks_unfinished", "<=", 7),
        ],
        checks=[
            ("stepback-scheduled-and-ranked", _check_stepback_scheduled),
            ("stepback-dedup-on-replay", _check_stepback_dedup),
        ],
    )


def _spot_reclamation() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dstatic", "provider": Provider.STATIC.value,
             "hosts": 4},
            # container-pool parents host containers instead of running
            # tasks — their own distro, like production pools
            {"id": "dparent", "provider": Provider.STATIC.value,
             "hosts": 2, "has_containers": True},
            {"id": "dspot", "provider": Provider.EC2_FLEET.value,
             "hosts": 0,
             "provider_settings": {"fleet_use_spot": True,
                                   "instance_type": "m5.large"}},
            {"id": "ddock", "provider": Provider.DOCKER.value,
             "hosts": 0, "container_pool": "pool1"},
        ]}),
        Ev(0, "container_pools", {"pools": [
            {"id": "pool1", "distro": "dparent", "max_containers": 2},
        ]}),
        Ev(0, "grow_fleet", {"distro": "dspot", "n": 6}),
        Ev(0, "grow_fleet", {"distro": "ddock", "n": 3}),
        Ev(1, "tasks", {"distro": "dspot", "n": 18, "prefix": "dspot-t"}),
        Ev(1, "tasks", {"distro": "dstatic", "n": 8,
                        "prefix": "dstatic-t"}),
        Ev(1, "tasks", {"distro": "ddock", "n": 6, "prefix": "ddock-t"}),
        # mid-run, AWS takes three busy spot instances back
        Ev(4, "spot_reclaim", {"n": 3, "distro": "dspot"}),
        # replacement capacity arrives two ticks later
        Ev(6, "grow_fleet", {"distro": "dspot", "n": 3}),
    ]
    return ScenarioSpec(
        name="spot-reclamation",
        description="mixed EC2-fleet(spot)/docker/static fleet; spot "
                    "instances reclaimed mid-task must route through "
                    "reset-or-system-fail with restart credits and no "
                    "stranded dispatch claim",
        ticks=18,
        events=events,
        slos=[
            SLO("reclaimed", "spot_reclaimed", "==", 3),
            SLO("reclaimed-tasks-restarted", "restarts_total", "==", 3),
            SLO("no-credit-exhaustion", "tasks_system_failed", "==", 0),
            SLO("everything-finishes", "tasks_unfinished", "==", 0),
        ],
        checks=[
            ("mixed-fleet-all-ran", _check_mixed_fleet),
            ("no-stranded-claims", _check_no_stranded_claims),
            ("restart-credit-accounting",
             _check_reclaimed_restart_credits),
        ],
    )


def _region_failover() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dreg", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "tasks", {"distro": "dreg", "n": 16, "prefix": "dreg-a"}),
        # the steal lands between begin_tick and the group flush of
        # tick 2's commit (the PR-3 wal.fence machinery)
        Ev(2, "lease_steal", {}),
        Ev(4, "tasks", {"distro": "dreg", "n": 8, "prefix": "dreg-b"}),
    ]
    return ScenarioSpec(
        name="region-failover",
        description="writer lease stolen mid-tick (region failover): "
                    "the fenced holder sheds its tick, the thief "
                    "resumes at a higher epoch, and the WAL replays to "
                    "the same converged state",
        ticks=12,
        durable=True,
        events=events,
        slos=[
            SLO("one-fenced-tick", "fenced_ticks", "==", 1),
            SLO("one-failover", "failovers", "==", 1),
            SLO("work-survives", "tasks_unfinished", "==", 0),
        ],
        checks=[
            ("failover-resumes-next-tick", _check_failover_resumes),
        ],
    )


def _spawn_burst() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dci", "provider": Provider.MOCK.value, "hosts": 5},
            {"id": "dws", "provider": Provider.EC2_FLEET.value,
             "hosts": 0,
             "provider_settings": {"fleet_use_spot": False,
                                   "instance_type": "c5.xlarge"}},
        ]}),
        Ev(0, "tasks", {"distro": "dci", "n": 20, "prefix": "dci-t"}),
        Ev(1, "spawn_burst", {"distro": "dws", "users": 25}),
        Ev(2, "spawn_burst", {"distro": "dws", "users": 15,
                              "prefix": "late"}),
        # day over: jump past the 24h default expiration; the expiry
        # sweep must reap the whole interactive fleet
        Ev(8, "advance_clock", {"s": 25 * 3600.0}),
    ]
    return ScenarioSpec(
        name="spawn-burst",
        description="interactive spawn-host burst (40 workstations in "
                    "two waves) beside CI load: all provision to "
                    "RUNNING, CI planning is untouched, and the expiry "
                    "sweep reaps them after the compressed day",
        ticks=12,
        events=events,
        slos=[
            SLO("ci-unaffected", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
        ],
        checks=[
            ("spawn-lifecycle", _check_spawn_lifecycle),
        ],
    )


def _seasonality() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dsea", "provider": Provider.MOCK.value, "hosts": 6},
        ]}),
    ]
    # a commuter week in 36 ticks: (arrivals, queue-backlog gauge,
    # outbox burst) per phase — the backlog gauge is the declarative
    # stand-in for the job plane's pending depth under that traffic
    phases = [
        (range(0, 6), 2, 10.0, 0),      # overnight
        (range(6, 11), 4, 80.0, 0),     # morning ramp → YELLOW
        (range(11, 16), 6, 160.0, 60),  # storm → RED
        (range(16, 19), 4, 300.0, 150),  # peak → BLACK
        (range(19, 25), 2, 100.0, 0),   # decline
        (range(25, 33), 1, 5.0, 0),     # calm
        (range(33, 36), 0, 5.0, 0),     # idle tail → the week drains
    ]
    for ticks, arrivals, backlog, outbox in phases:
        for t in ticks:
            if arrivals:
                events.append(Ev(t, "tasks", {
                    "distro": "dsea", "n": arrivals,
                    "prefix": f"dsea-w{t:02d}",
                }))
            events.append(Ev(t, "gauge", {
                "name": "queue_pending", "value": backlog,
            }))
            if outbox:
                events.append(Ev(t, "outbox", {"n": outbox}))
            if not outbox and t >= 19:
                events.append(Ev(t, "drain_outbox", {}))
    return ScenarioSpec(
        name="seasonality",
        description="a week compressed to minutes: arrivals and backlog "
                    "gauges drive the ladder GREEN→YELLOW→RED→BLACK and "
                    "back, with stats/events shed (and counted) at the "
                    "peak and a green landing",
        ticks=36,
        events=events,
        overload={
            "queue_pending_levels": [50.0, 120.0, 250.0],
            "outbox_depth_levels": [60.0, 150.0, 280.0],
            "outbox_cap": 400,
            "hysteresis_ticks": 2,
        },
        slos=[
            SLO("reaches-black", "max_overload_level", "==", 3),
            SLO("lands-green", "ended_green", "truthy", 1),
            SLO("sheds-are-counted", "sheds_total", ">=", 1),
            SLO("week-drains", "tasks_unfinished", "==", 0),
        ],
        checks=[
            ("full-ladder-cycle", _check_ladder_cycle),
            ("outbox-cap-held", _check_outbox_cap_held),
        ],
    )


# --------------------------------------------------------------------------- #
# capacity-plane weathers (ISSUE 15 satellite; closes the ROADMAP item-5
# "capacity weather" remainder)
# --------------------------------------------------------------------------- #


def _capacity_recorder(rec):
    """`call` event fn: wrap the (store-cached) CapacityPlane.apply so
    every tick's heuristic-in / decision-out pair lands in ``rec`` —
    including whether the plane fell back by returning the heuristic
    dict ITSELF (the bit-identity the breaker gate pins)."""

    def install(run):
        from ..scheduler.capacity_plane import capacity_plane_for

        plane = capacity_plane_for(run.store)
        orig = plane.apply

        def recording_apply(distros, infos, new_hosts, hosts_by_distro,
                            now, **kw):
            before = dict(new_hosts)
            out = orig(distros, infos, new_hosts, hosts_by_distro, now,
                       **kw)
            rec.append({
                "tick": run.tick,
                "in": before,
                "out": dict(out),
                "identity_fallback": out is new_hosts,
                "existing": {
                    d.id: len(hosts_by_distro.get(d.id, []))
                    for d in distros
                },
            })
            return out

        plane.apply = recording_apply

    return install


def _set_capacity_config(**fields):
    def fn(run):
        from ..settings import CapacityConfig

        cfg = CapacityConfig.get(run.store)
        import dataclasses as _dc

        _dc.replace(cfg, **fields).set(run.store)

    return fn


def _cap_share(entries, distro):
    """``distro``'s share of all capacity intents granted in ``entries``
    (0.0 when no intents were granted at all)."""
    total = sum(sum(e["out"].values()) for e in entries)
    mine = sum(e["out"].get(distro, 0) for e in entries)
    return (mine / total) if total else 0.0


def _capacity_price_spike(spike_tick: int = 6) -> ScenarioSpec:
    rec = []

    def check_solver_ran(run) -> Optional[str]:
        applied = [e for e in rec if not e["identity_fallback"]]
        pre = [e for e in applied if e["tick"] < spike_tick]
        post = [e for e in applied if e["tick"] >= spike_tick]
        if not pre or not post:
            return (f"capacity solve must run on both sides of the "
                    f"spike (pre={len(pre)}, post={len(post)})")
        return None

    def check_retraded(run) -> Optional[str]:
        applied = [e for e in rec if not e["identity_fallback"]]
        pre = _cap_share(
            [e for e in applied if e["tick"] < spike_tick], "dpricey"
        )
        post = _cap_share(
            [e for e in applied if e["tick"] >= spike_tick], "dpricey"
        )
        if pre <= 0.0:
            return "pricey pool got nothing even at par pricing"
        if post >= pre:
            return (f"price spike did not move capacity off the pricey "
                    f"pool (share {pre:.2f} -> {post:.2f})")
        return None

    def check_no_fallbacks(run) -> Optional[str]:
        bad = [e["tick"] for e in rec if e["identity_fallback"]]
        if bad:
            return f"capacity plane fell back on ticks {bad}"
        return None

    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dcheap", "provider": Provider.MOCK.value, "hosts": 2,
             "planner": {"capacity": "tpu"}, "max_hosts": 30},
            {"id": "dpricey", "provider": Provider.EC2_FLEET.value,
             "hosts": 2, "planner": {"capacity": "tpu"}, "max_hosts": 30,
             "provider_settings": {"fleet_use_spot": False}},
        ]}),
        Ev(0, "call", {"fn": _capacity_recorder(rec)}),
        # symmetric steady demand: with pools at par the solver splits
        # the shared intent budget roughly evenly
        *[Ev(t, "tasks", {"distro": d, "n": 8, "expected_s": 1800.0,
                          "prefix": f"{d}-w{t}"})
          for t in (1, 3, 5, 7, 9)
          for d in ("dcheap", "dpricey")],
        # tick `spike_tick`: the pricey pool's $/host-hour jumps 30x —
        # the next solve must re-trade the budget toward the cheap pool
        Ev(spike_tick, "call", {"fn": _set_capacity_config(
            pool_prices={"mock": 1.0, "ec2-fleet": 30.0},
            price_weight=0.2,
        )}),
    ]
    return ScenarioSpec(
        name="capacity-price-spike",
        description="two capacity-opted distros on different provider "
                    "pools under one fleet intent budget; a 30x price "
                    "spike on one pool mid-run must re-trade capacity "
                    "toward the cheap pool with zero solver fallbacks",
        ticks=12,
        events=events,
        slos=[],
        checks=[
            ("solver-ran-both-sides", check_solver_ran),
            ("spike-retrades-pools", check_retraded),
            ("zero-capacity-fallbacks", check_no_fallbacks),
        ],
        tick_options={"create_intent_hosts": True},
        config={"CapacityConfig": {
            "pool_prices": {"mock": 1.0, "ec2-fleet": 1.0},
            "price_weight": 0.2,
            "fleet_intent_budget": 6,
        }},
    )


def _capacity_quota_squeeze(
    squeeze_tick: int = 4, fault_tick: int = 11
) -> ScenarioSpec:
    rec = []
    quota_after = 8
    pool_distros = ("ddeep", "dshallow")

    def _headroom(e):
        return max(
            0, quota_after - sum(e["existing"].get(d, 0)
                                 for d in pool_distros)
        )

    def check_feasible(run) -> Optional[str]:
        # the deliberate capacity.solve fault at `fault_tick` is the ONE
        # allowed fallback; the squeeze itself must never cause one
        bad = [e["tick"] for e in rec
               if e["identity_fallback"] and e["tick"] != fault_tick]
        if bad:
            return f"quota squeeze broke feasibility on ticks {bad}"
        return None

    def check_quota_respected(run) -> Optional[str]:
        # post-squeeze the solver may only grant what the squeezed
        # quota leaves over the EXISTING fleet (hosts the quota change
        # cannot un-spawn drain through drawdown, not through the solve)
        for e in rec:
            if e["tick"] <= squeeze_tick or e["identity_fallback"]:
                continue
            granted = sum(e["out"].get(d, 0) for d in pool_distros)
            if granted > _headroom(e):
                return (f"tick {e['tick']}: granted {granted} new hosts "
                        f"over headroom {_headroom(e)} of the squeezed "
                        f"quota {quota_after}")
        return None

    def check_squeeze_binds(run) -> Optional[str]:
        # the squeeze must be VISIBLE: at least one post-squeeze solve
        # where the heuristic asked for more than the headroom and the
        # solver held the line (otherwise this weather proves nothing)
        for e in rec:
            if e["tick"] <= squeeze_tick or e["identity_fallback"]:
                continue
            asked = sum(e["in"].get(d, 0) for d in pool_distros)
            granted = sum(e["out"].get(d, 0) for d in pool_distros)
            if asked > _headroom(e) and granted <= _headroom(e) < asked:
                return None
        return ("no post-squeeze tick where demand exceeded the "
                "squeezed quota's headroom — the squeeze never bound")

    def check_deep_outbids_shallow(run) -> Optional[str]:
        solved = [e for e in rec if not e["identity_fallback"]]
        deep = sum(e["out"].get("ddeep", 0) for e in solved)
        shallow = sum(e["out"].get("dshallow", 0) for e in solved)
        if deep <= shallow:
            return (f"inside the shared pool the deep backlog must "
                    f"outbid the shallow one (deep={deep}, "
                    f"shallow={shallow})")
        return None

    def check_fallback_bit_identical(run) -> Optional[str]:
        falls = [e for e in rec if e["tick"] == fault_tick]
        if not falls:
            return f"no capacity call recorded on fault tick {fault_tick}"
        e = falls[0]
        if not e["identity_fallback"]:
            return "the injected capacity.solve fault did not fall back"
        if e["out"] != e["in"]:
            return ("fallback altered the heuristic counts — the "
                    "bit-identical contract is broken")
        return None

    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "ddeep", "provider": Provider.MOCK.value, "hosts": 2,
             "planner": {"capacity": "tpu"}, "max_hosts": 40},
            {"id": "dshallow", "provider": Provider.MOCK.value, "hosts": 2,
             "planner": {"capacity": "tpu"}, "max_hosts": 40},
        ]}),
        Ev(0, "call", {"fn": _capacity_recorder(rec)}),
        # asymmetric backlogs inside ONE provider pool: a deep queue of
        # long tasks vs a shallow queue of short ones
        *[Ev(t, "tasks", {"distro": "ddeep", "n": 14,
                          "expected_s": 2400.0, "prefix": f"ddeep-w{t}"})
          for t in (1, 3)],
        *[Ev(t, "tasks", {"distro": "dshallow", "n": 3,
                          "expected_s": 600.0, "prefix": f"dshallow-w{t}"})
          for t in (1, 3)],
        # tick `squeeze_tick`: the shared pool quota collapses 24 -> 8
        # (below the fleet the generous quota already built); the solver
        # must keep every later grant inside the shrunken headroom
        # without ever going infeasible
        Ev(squeeze_tick, "call", {"fn": _set_capacity_config(
            pool_quotas={"mock": quota_after},
        )}),
        # post-squeeze demand storms that the heuristic would chase with
        # new hosts — the squeezed quota must hold the line
        *[Ev(t, "tasks", {"distro": "ddeep", "n": 30,
                          "expected_s": 2400.0, "prefix": f"ddeep-s{t}"})
          for t in (6, 9)],
        # tick `fault_tick`: a raising capacity solve — the plane must
        # hand back the heuristic's counts bit-identically
        Ev(fault_tick, "fault", {"seam": "capacity.solve"}),
    ]
    return ScenarioSpec(
        name="capacity-quota-squeeze",
        description="two capacity-opted distros sharing one provider "
                    "pool; the pool quota collapses mid-run (solver "
                    "keeps trading inside the smaller box, deep backlog "
                    "outbids shallow) and an injected solve fault must "
                    "fall back to bit-identical heuristic counts",
        ticks=12,
        events=events,
        slos=[],
        checks=[
            ("squeeze-stays-feasible", check_feasible),
            ("squeezed-quota-respected", check_quota_respected),
            ("squeeze-binds", check_squeeze_binds),
            ("deep-backlog-outbids-shallow", check_deep_outbids_shallow),
            ("fallback-bit-identical", check_fallback_bit_identical),
        ],
        tick_options={"create_intent_hosts": True},
        config={"CapacityConfig": {
            "pool_quotas": {"mock": 24},
        }},
    )


def _leader_death() -> ScenarioSpec:
    """The shipped solver-leader weather (proc backend, distinct from
    the crash-matrix points): the fleet loses its BRAIN and then a
    HAND in one run. The supervisor (= elected solver-leader) dies at
    the stacked-solve seam; both workers degrade to local and orphan;
    the successor adopts them and re-elects the solver lease at a
    strictly higher epoch; then a worker is SIGKILLed at a WAL seam —
    its fenced replacement must rejoin the shared-memory plane and the
    fleet must return to fully stacked rounds with zero stale results
    and zero leaked segments."""
    from .procs import (
        _SOLVER_WORKLOAD,
        _check_solver_survived,
        DEFAULT_PROC_INVARIANTS,
        ProcScenarioRun,
    )

    def worker_rejoined(run: "ProcScenarioRun") -> Optional[str]:
        # the victim is an ADOPTED process: the successor holds no
        # Popen for it, so its exit code (86) is unobservable — the
        # restart plus its OWN stacked reply after the kill tick are
        # the proof it died and the replacement rejoined the shm plane
        if run.stats.get("restarts_total", 0) < 1:
            return "the killed worker was never restarted"
        if not any(
            rnd.get(1, {}).get("solve") == "stacked"
            for i, rnd in enumerate(run.rounds) if i > 5
        ):
            return (
                "the replacement worker never published into a "
                "stacked round after the kill tick"
            )
        return _check_solver_survived(run)

    return ScenarioSpec(
        name="leader-death",
        description="2-shard solver fleet: the leader dies at the "
                    "stacked solve, the successor adopts and "
                    "re-elects; then a worker is SIGKILLed — its "
                    "fenced replacement rejoins the shm plane and "
                    "stacked rounds resume",
        ticks=16,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", dict(_SOLVER_WORKLOAD)),
            Ev(2, "leader_kill", {"seam": "solver.solve"}),
            Ev(3, "sup_restart", {}),
            Ev(5, "proc_kill", {"worker": 1, "seam": "wal.commit"}),
        ],
        slos=[
            SLO("bounded-restarts", "restarts_total", "<=", 3),
        ],
        checks=[("worker-rejoined-after-leader-death",
                 worker_rejoined)],
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


#: proc-backed weathers shipped with the library: these run real
#: worker processes, so the fleet-runtime smoke (tools/fleet_runtime.py
#: run_weathers) replays them alongside PROC_SCENARIOS — the engine
#: suite above cannot host them
PROC_WEATHERS: Dict[str, callable] = {
    "leader-death": _leader_death,
}


def _check_snapshot_healed(run) -> Optional[str]:
    """The rotted snapshot was quarantined (forensics kept), a verified
    checkpoint replaced it, and the published pair passes its digest."""
    import os

    from ..storage import integrity as integrity_mod
    from ..storage.durable import SNAPSHOT_FILE

    if run.counter_delta("storage.snapshot_quarantined") != 1:
        return (
            f"{run.counter_delta('storage.snapshot_quarantined')} "
            "snapshots quarantined (want exactly the injected one)"
        )
    if run.counter_delta("storage.rebuilds") < 1:
        return "no self-heal rebuild was counted"
    if not any(
        name.startswith(SNAPSHOT_FILE + ".corrupt-")
        for name in os.listdir(run.data_dir)
    ):
        return "no .corrupt-<ts> forensic file kept beside the store"
    snap = os.path.join(run.data_dir, SNAPSHOT_FILE)
    meta = _read_json(snap + ".meta")
    if meta is None:
        return "healed snapshot has no .meta sidecar"
    if meta.get("crc") != integrity_mod.file_crc32(snap):
        return "healed snapshot does not match its recorded digest"
    return None


def _read_json(path) -> Optional[dict]:
    import json

    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def _check_enospc_shed_healed(run) -> Optional[str]:
    """The full disk shed exactly one group loudly (breadcrumbs both
    ways), and durability healed — shed writes re-covered by checkpoint,
    floor released."""
    if run.counter_delta("storage.enospc_sheds") != 1:
        return (
            f"{run.counter_delta('storage.enospc_sheds')} ENOSPC sheds "
            "(want exactly the injected one)"
        )
    shed = [r for r in run.logs if r.get("message") == "wal-enospc-shed"]
    healed = [
        r for r in run.logs if r.get("message") == "wal-enospc-healed"
    ]
    if not shed:
        return "no wal-enospc-shed breadcrumb"
    if not healed:
        return "durability never healed after the shed"
    return None


def _disk_bitrot_snapshot() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "drot", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "tasks", {"distro": "drot", "n": 8, "prefix": "drot-t"}),
        # arms snapshot.write:bitrot, forces a checkpoint next tick (the
        # rot lands on the PUBLISHED file), and scrubs the tick after
        Ev(2, "disk_fault", {"target": "snapshot", "kind": "bitrot"}),
        Ev(6, "tasks", {"distro": "drot", "n": 4, "prefix": "drot-b"}),
    ]
    return ScenarioSpec(
        name="disk-bitrot-snapshot",
        description="a published snapshot rots on disk after its "
                    "rename: the scrub catches the digest mismatch, "
                    "quarantines the file as .corrupt-<ts> and rebuilds "
                    "a verified checkpoint — serving and scheduling "
                    "never notice",
        ticks=12,
        durable=True,
        events=events,
        slos=[
            SLO("work-survives", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
            SLO("ends-green", "ended_green", "==", 1),
        ],
        checks=[
            ("snapshot-quarantined-and-healed", _check_snapshot_healed),
        ],
    )


def _disk_enospc_commit() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dfull", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "tasks", {"distro": "dfull", "n": 8, "prefix": "dfull-t"}),
        # the next WAL group commit hits a full disk: SHED + RED floor,
        # never a raise mid-commit; the scrub two ticks later verifies
        # the surviving log still passes its stamps
        Ev(2, "disk_fault", {"target": "wal", "kind": "enospc"}),
        Ev(6, "tasks", {"distro": "dfull", "n": 4, "prefix": "dfull-b"}),
    ]
    return ScenarioSpec(
        name="disk-enospc-commit",
        description="the disk fills at a WAL group commit: the frame is "
                    "shed loudly with the overload floor forced RED, "
                    "in-memory truth keeps every write, and the first "
                    "accepted frame re-covers them durably and heals "
                    "back to GREEN",
        ticks=12,
        durable=True,
        events=events,
        slos=[
            SLO("work-survives", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
            SLO("ends-green", "ended_green", "==", 1),
        ],
        checks=[
            ("enospc-shed-then-healed", _check_enospc_shed_healed),
        ],
    )


def _check_loss_window_fired(run) -> Optional[str]:
    """The seeded lossy window actually dropped requests (otherwise the
    weather silently tested a perfect network)."""
    if run.counter_delta("faults.fired") < 1:
        return "no transport fault ever fired during the storm"
    return None


def _net_agent_storm(kind: str, slug: str, desc: str) -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dnet", "provider": Provider.MOCK.value, "hosts": 8},
        ]}),
        Ev(0, "tasks", {"distro": "dnet", "n": 16, "prefix": "dnet-t"}),
        # seeds the lossy window on agent.request, fires the claim
        # storm next tick, heals the tick after (engine ev_net_fault)
        Ev(2, "net_fault", {"target": "agent", "kind": kind,
                            "rate": 0.3, "agents": 8}),
    ]
    return ScenarioSpec(
        name=f"net-agent-storm-{slug}",
        description=desc,
        ticks=12,
        events=events,
        slos=[
            SLO("work-survives", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
        ],
        checks=[("loss-window-fired", _check_loss_window_fired)],
    )


def _net_agent_storm_loss() -> ScenarioSpec:
    """An agent claim storm under 30% request loss: every drop is
    retried at-least-once, and the no-duplicate-dispatch +
    resume≡rerun invariants prove the retries never double-claim."""
    return _net_agent_storm(
        "drop", "loss",
        "8 agents storm the dispatch claim path under 30% request "
        "loss; at-least-once retries converge the backlog with zero "
        "duplicate dispatch and resume ≡ rerun",
    )


def _net_agent_storm_halfopen() -> ScenarioSpec:
    """The nastier shape: the server processes the claim but the
    RESPONSE black-holes, so the agent's retry is duplicate delivery —
    the dispatch CAS (and the running-task resume path) must fence
    every copy."""
    return _net_agent_storm(
        "half_open", "halfopen",
        "8 agents claim under 30% half-open responses (request "
        "processed, reply lost): each retry is a duplicate delivery "
        "the dispatch CAS must fence — zero duplicate dispatch",
    )


def _replica_halfopen_probe(run) -> None:
    """Tick-2 call: attach a read replica to the run's data dir and
    record the healthy baseline (caught up, usable within a tight
    staleness bound)."""
    from ..storage.durable import DurableStore
    from ..storage.replica import ReplicaStore

    if not isinstance(run.store, DurableStore):
        return
    run.store.checkpoint()
    rep = ReplicaStore(
        run.data_dir, poll_interval_s=3600.0,
        replica_id="net-weather",  # pinned: scorecards must replay
    )
    run._net_replica = rep
    run._net_replica_obs = {
        "baseline_applied": rep.applied_seq,
        "baseline_staleness_ms": rep.staleness_ms(),
    }


def _replica_halfopen_observe(run) -> None:
    """Tick-5 call (seam armed half_open since tick 3): polls return
    nothing and never refresh the caught-up stamp, so the staleness
    bound GROWS past any serving threshold — the read router's
    readiness flip — while the primary keeps committing."""
    import time as _time

    rep = getattr(run, "_net_replica", None)
    if rep is None:
        return
    obs = run._net_replica_obs
    polled = rep.poll()
    _time.sleep(0.06)  # let wall-clock staleness clear the 50ms bound
    obs["faulted_polled"] = polled
    obs["faulted_applied"] = rep.applied_seq
    obs["faulted_staleness_ms"] = rep.staleness_ms()
    obs["primary_seq"] = run.store.wal_seq


def _replica_halfopen_heal(run) -> None:
    """Tick-7 call (seam cleared at tick 6): the reconnected tail
    catches back up to the primary's watermark and readiness returns."""
    rep = getattr(run, "_net_replica", None)
    if rep is None:
        return
    obs = run._net_replica_obs
    rep.poll()
    obs["healed_applied"] = rep.applied_seq
    obs["healed_staleness_ms"] = rep.staleness_ms()
    obs["healed_primary_seq"] = run.store.wal_seq
    rep.close()


def _check_replica_halfopen(run) -> Optional[str]:
    obs = getattr(run, "_net_replica_obs", None)
    if not obs or "healed_applied" not in obs:
        return "the replica probe never ran to completion"
    if obs["baseline_staleness_ms"] == float("inf"):
        return "the replica never caught up before the fault"
    if obs["faulted_polled"] != 0:
        return (
            "the half-open tail still applied "
            f"{obs['faulted_polled']} records"
        )
    if obs["faulted_applied"] != obs["baseline_applied"]:
        return "applied_seq moved while the tail was black-holed"
    if obs["faulted_staleness_ms"] <= 50.0:
        return (
            "staleness did not grow past the 50ms serving bound: "
            f"{obs['faulted_staleness_ms']:.1f}ms (readiness never "
            "flipped)"
        )
    if obs["healed_applied"] < obs["healed_primary_seq"]:
        return (
            "the healed tail never caught up: applied "
            f"{obs['healed_applied']} < primary "
            f"{obs['healed_primary_seq']}"
        )
    # NOTE: staleness_ms right after the heal still carries the worst
    # commit→apply gap of the blackout's backlog (by design — those
    # reads really were that stale), so the heal is proven by the
    # watermark above, not by an instant staleness drop
    return None


def _net_replica_halfopen() -> ScenarioSpec:
    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "drep", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "tasks", {"distro": "drep", "n": 8, "prefix": "drep-t"}),
        Ev(2, "call", {"fn": _replica_halfopen_probe}),
        Ev(3, "net_fault", {"target": "replica", "kind": "half_open",
                            "always": True}),
        Ev(5, "call", {"fn": _replica_halfopen_observe}),
        Ev(6, "clear_faults", {"seam": "replica.tail"}),
        Ev(7, "call", {"fn": _replica_halfopen_heal}),
    ]
    return ScenarioSpec(
        name="net-replica-halfopen",
        description="a read replica's WAL tail goes half-open: polls "
                    "return nothing, the staleness bound grows past "
                    "the serving threshold (readiness flips to the "
                    "primary), and the healed tail catches back up",
        ticks=12,
        durable=True,
        events=events,
        slos=[
            SLO("work-survives", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
        ],
        checks=[
            ("replica-staleness-bounded", _check_replica_halfopen),
        ],
    )


def _sabotage() -> ScenarioSpec:
    return ScenarioSpec(
        name="sabotage-duplicate-claim",
        description="deliberate invariant violation (a forged duplicate "
                    "running-task claim): the engine must score this "
                    "RED — the gate's self-test",
        ticks=6,
        events=[
            # 4 hosts, 2 tasks: at tick 1 two hosts are mid-task and two
            # are free — the forged duplicate claim has both sides live
            Ev(0, "fleet", {"distros": [
                {"id": "dsab", "provider": Provider.MOCK.value,
                 "hosts": 4},
            ]}),
            Ev(0, "tasks", {"distro": "dsab", "n": 2,
                            "prefix": "dsab-t"}),
            Ev(1, "call", {"fn": _sabotage_duplicate_claim}),
        ],
        slos=[],
        checks=[],
        tier1=False,
    )


#: name → spec factory for the default suite (factories, not instances:
#: specs carry mutable event args and every run deserves a fresh one)
SCENARIOS: Dict[str, callable] = {
    "merge-queue-storm": _merge_queue_storm,
    "dag-stepback": _dag_stepback,
    "spot-reclamation": _spot_reclamation,
    "region-failover": _region_failover,
    "spawn-burst": _spawn_burst,
    "seasonality": _seasonality,
    "capacity-price-spike": _capacity_price_spike,
    "capacity-quota-squeeze": _capacity_quota_squeeze,
    "disk-bitrot-snapshot": _disk_bitrot_snapshot,
    "disk-enospc-commit": _disk_enospc_commit,
    "net-agent-storm-loss": _net_agent_storm_loss,
    "net-agent-storm-halfopen": _net_agent_storm_halfopen,
    "net-replica-halfopen": _net_replica_halfopen,
}

#: deliberately-broken specs the gate's self-test runs EXPECTING failure
SABOTAGE_SCENARIOS: Dict[str, callable] = {
    "sabotage-duplicate-claim": _sabotage,
}
