"""Property-based weather fuzzing over the scenario-engine vocabulary.

The shipped weathers (library.py) and the fault/crash matrices check
the six cross-cutting invariants where a human thought to look; this
module checks them everywhere a seeded generator can reach. A **weather**
is drawn from the existing ``Ev`` vocabulary — task bursts, merge
stacks, dependency DAGs, fleet growth, spot reclamation, notification
storms, clock jumps, fault seams (utils/faults.py), writer lease steals,
disk faults at the storage seams (ENOSPC/EIO/short-write/bitrot against
the WAL or a published snapshot, with the self-heal scrub scheduled
behind them) — as a pure function of one integer seed, replayed
deterministically
under ``DEFAULT_INVARIANTS``. A proc variant composes the child-process
vocabulary (worker SIGKILLs at WAL seams, hangs, supervisor kills) for
the supervised-fleet backend.

Failures shrink automatically: ``shrink_spec`` is a delta-debugging
loop (chunked event removal → single events → numeric arg shrinking →
timeline trim) that re-runs the failure predicate after every candidate
reduction, so any red schedule collapses to a minimal timeline — which
``campaign`` emits as a ready-to-check-in regression ``ScenarioSpec``
(scenarios/trace.py serialization, scenarios/regressions/ corpus).

``campaign`` is the soak arm ``tools/fuzz_matrix.py`` time-boxes: seeds
are enumerated from a pinned start so a CI window is reproducible, and
the sabotage self-test (a deliberately corrupted dispatch book, in both
in-process and child-process modes) proves the invariant layer still
bites before any green result is trusted.

When ``hypothesis`` is installed, ``weather_strategy()`` exposes the
same generator as a strategy; absent the dep, the stdlib-seeded
fallback (utils/proptest.py) keeps every property test running.
"""
from __future__ import annotations

import dataclasses
import random
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from ..globals import Provider
from .procs import DEFAULT_PROC_INVARIANTS
from .spec import (
    DEFAULT_INVARIANTS,
    Ev,
    ScenarioSpec,
    scorecard_entry_fingerprint,
)

#: the pinned campaign anchor (gate runs are reproducible by default;
#: soak runs pass --start-seed to explore)
DEFAULT_CAMPAIGN_SEED = 16_0001

#: fault seams the in-process tick pipeline survives by contract (the
#: fault matrix's migrated cases): solve raises trip the breaker and
#: fall back, WAL group-commit errors shed the tick. Seams whose raise
#: crashes the harness itself (agent transport, dispatch CAS) belong to
#: the proc arm, where the blast radius is a worker process.
SAFE_FAULT_SEAMS = ("scheduler.solve",)
DURABLE_FAULT_SEAMS = ("wal.commit",)

#: the disk-fault vocabulary the generator draws for durable weathers
#: (scenarios/engine.py ev_disk_fault schedules the forced checkpoint
#: and the self-heal scrub behind each one)
DISK_FAULT_TARGETS = ("wal", "snapshot")
DISK_FAULT_KINDS = ("enospc", "bitrot", "short", "eio")

#: seams the proc arm SIGKILLs workers at (crash-matrix vocabulary)
PROC_KILL_SEAMS = ("wal.commit", "wal.append", "lease.renew")

#: the network-chaos vocabulary the generator draws for agent-side
#: lossy windows (scenarios/engine.py ev_net_fault seeds the window,
#: fires the claim storm, and heals it so the weather converges)
NET_FAULT_KINDS = ("drop", "half_open", "duplicate", "partition")


# --------------------------------------------------------------------------- #
# the generator: seed → weather
# --------------------------------------------------------------------------- #


def generate_weather(seed: int, sabotage: bool = False) -> ScenarioSpec:
    """One randomized in-process weather, a pure function of ``seed``.

    With ``sabotage=True`` a deliberate invariant violation (a forged
    duplicate running-task claim, bypassing the dispatch CAS) is
    spliced mid-run — the campaign's self-test weather. Sabotage specs
    carry a live callable, so they serialize only lossily."""
    rng = random.Random(int(seed))
    durable = rng.random() < 0.3
    n_distros = rng.randint(1, 3)
    spot_distro = ""
    spot_hosts = 0
    fleet: List[Dict] = []
    for k in range(n_distros):
        did = f"fz{k}"
        hosts = rng.randint(2, 5)
        if k == n_distros - 1 and n_distros > 1 and rng.random() < 0.4:
            spot_distro, spot_hosts = did, hosts
            fleet.append({
                "id": did, "provider": Provider.EC2_FLEET.value,
                "hosts": hosts,
                "provider_settings": {"fleet_use_spot": True,
                                      "instance_type": "m5.large"},
            })
        else:
            fleet.append({
                "id": did, "provider": Provider.MOCK.value,
                "hosts": hosts,
            })
    events: List[Ev] = [Ev(0, "fleet", {"distros": fleet})]
    n_hosts = sum(f["hosts"] for f in fleet)

    span = rng.randint(4, 10)
    n_tasks = 0
    depth = 0
    task_prefixes: List[str] = []
    lease_stolen = False
    dag_serial = 0
    for t in range(1, span + 1):
        for _ in range(rng.randint(0, 2)):
            d = fleet[rng.randrange(len(fleet))]["id"]
            kind = rng.choices(
                ("tasks", "merge_stack", "dag", "fail_next",
                 "grow_fleet", "spot_reclaim", "outbox", "drain_outbox",
                 "advance_clock", "fault", "lease_steal"),
                weights=(30, 8, 8, 12, 8, 6, 6, 4, 4, 8, 4),
            )[0]
            if kind == "tasks":
                n = rng.randint(2, 10)
                prefix = f"fzt{t}x{len(task_prefixes)}"
                chain = rng.random() < 0.25
                events.append(Ev(t, "tasks", {
                    "distro": d, "n": n, "prefix": prefix,
                    "priority": rng.choice((0, 0, 0, 50)),
                    "dep_chain": chain,
                }))
                task_prefixes.append(prefix)
                n_tasks += n
                if chain:
                    depth = max(depth, n)
            elif kind == "merge_stack":
                n = rng.randint(2, 5)
                dag_serial += 1
                events.append(Ev(t, "merge_stack", {
                    "distro": d, "stack": f"fzm{dag_serial}", "n": n,
                }))
                n_tasks += n
                depth = max(depth, n)
            elif kind == "dag":
                n = rng.randint(2, 4)
                dag_serial += 1
                stem = f"fzd{dag_serial}"
                nodes = []
                for i in range(n):
                    nodes.append({
                        "id": f"{stem}-{i}",
                        "revision_order": i + 1,
                        "deps": [f"{stem}-{i - 1}"] if i else [],
                        "activated": True,
                    })
                events.append(Ev(t, "dag", {"distro": d, "nodes": nodes}))
                n_tasks += n
                depth = max(depth, n)
            elif kind == "fail_next" and task_prefixes:
                events.append(Ev(t, "fail_next", {
                    "match": rng.choice(task_prefixes),
                    "details_type": rng.choice(("test", "system",
                                                "setup")),
                    "count": rng.randint(1, 3),
                }))
            elif kind == "grow_fleet":
                events.append(Ev(t, "grow_fleet", {
                    "distro": d, "n": rng.randint(1, 3),
                }))
            elif kind == "spot_reclaim" and spot_distro and spot_hosts:
                n = rng.randint(1, min(2, spot_hosts))
                spot_hosts -= n
                events.append(Ev(t, "spot_reclaim", {
                    "n": n, "distro": spot_distro,
                }))
            elif kind == "outbox":
                events.append(Ev(t, "outbox", {
                    "n": rng.randint(2, 12),
                    "distinct": rng.random() < 0.7,
                }))
            elif kind == "drain_outbox":
                events.append(Ev(t, "drain_outbox", {}))
            elif kind == "advance_clock":
                events.append(Ev(t, "advance_clock", {
                    "s": float(rng.choice((300, 1800, 3600))),
                }))
            elif kind == "fault":
                seams = SAFE_FAULT_SEAMS + (
                    DURABLE_FAULT_SEAMS if durable else ()
                )
                events.append(Ev(t, "fault", {
                    "seam": rng.choice(seams),
                    "at": rng.randint(0, 2),
                }))
            elif kind == "lease_steal" and durable and not lease_stolen \
                    and t >= 2:
                lease_stolen = True
                events.append(Ev(t, "lease_steal", {}))

    if durable:
        # disk weather rides on its OWN rng stream so its addition left
        # every pre-existing seed's event sequence untouched (the pinned
        # campaign anchor and the checked-in regression corpus replay
        # byte-identically)
        drng = random.Random(int(seed) ^ 0xD15C0)
        if drng.random() < 0.5:
            events.append(Ev(drng.randint(1, span), "disk_fault", {
                "target": drng.choice(DISK_FAULT_TARGETS),
                "kind": drng.choice(DISK_FAULT_KINDS),
            }))

    # network chaos rides its OWN rng stream for the same reason as the
    # disk stream above: every pre-existing seed replays byte-identically
    nrng = random.Random(int(seed) ^ 0x4E4654)
    if nrng.random() < 0.4:
        events.append(Ev(nrng.randint(1, span), "net_fault", {
            "target": "agent",
            "kind": nrng.choice(NET_FAULT_KINDS),
            "rate": round(nrng.uniform(0.15, 0.45), 2),
            "agents": nrng.randint(3, 8),
        }))

    if sabotage:
        from .library import _sabotage_duplicate_claim

        events.append(Ev(
            max(2, span // 2), "call",
            {"fn": _sabotage_duplicate_claim},
        ))

    # converge: arrival span + dependency depth + drain at capacity,
    # then slack — an underestimate would score honest weathers red on
    # starvation, so lean generous (the replay clock is virtual)
    drain = -(-max(1, n_tasks) // max(1, n_hosts))
    ticks = span + 2 * (depth + drain) + 6
    name = f"fuzz-sabotage-{seed}" if sabotage else f"fuzz-w{seed}"
    return ScenarioSpec(
        name=name,
        description=(
            f"generated weather (seed {seed}): {n_tasks} tasks over "
            f"{len(fleet)} distros, {len(events) - 1} events"
            + (", sabotaged dispatch books" if sabotage else "")
        ),
        ticks=ticks,
        events=events,
        seed=int(seed),
        durable=durable,
        invariants=DEFAULT_INVARIANTS,
        tier1=False,
    )


def generate_proc_weather(seed: int,
                          sabotage: bool = False) -> ScenarioSpec:
    """One randomized supervised-fleet weather (child-process backend):
    a seeded workload partitioned across 1–2 real worker processes with
    SIGKILLs at WAL seams, SIGSTOP hangs, or a supervisor kill+restart
    drawn from the proc vocabulary. Sabotage forges a duplicate
    dispatch CAS win directly into the seeded shard stores."""
    rng = random.Random(int(seed) ^ 0x9E3779B9)
    shards = rng.choice((1, 2))
    workload = {
        "shards": shards,
        "distros": rng.choice((2, 4)),
        "tasks": rng.choice((16, 24, 32)),
        "seed": rng.randint(1, 10_000),
        "hosts_per_distro": rng.randint(2, 3),
    }
    if sabotage:
        workload["sabotage_duplicate_claim"] = True
    events: List[Ev] = [Ev(0, "proc_fleet", workload)]
    ticks = 12
    if not sabotage:
        storms = ["kill", "hang", "sup", "none"]
        if shards == 2:
            # solver-leader storms need a real 2-shard fleet: a
            # 1-shard round never elects (stacking one shard is local)
            storms.append("leader")
        storm = rng.choice(tuple(storms))
        if storm == "kill":
            events.append(Ev(rng.randint(1, 3), "proc_kill", {
                "worker": rng.randrange(shards),
                "seam": rng.choice(PROC_KILL_SEAMS),
            }))
        elif storm == "hang":
            events.append(Ev(rng.randint(1, 3), "proc_hang", {
                "worker": rng.randrange(shards),
            }))
        elif storm == "sup":
            at = rng.choice(("idle", "mid_round"))
            t = rng.randint(1, 3)
            events.append(Ev(t, "sup_kill", {"at": at}))
            events.append(Ev(t + 1, "sup_restart", {}))
            ticks = 14
        elif storm == "leader":
            # events[0] holds THIS dict: the solver plane opt-in and
            # the both-shards load floor (the hash topology can land 2
            # distros on one shard, starving the stack) ride along
            workload["distros"] = 6
            workload["tasks"] = 36
            workload["solver"] = "auto"
            workload["solver_timeout_s"] = 6.0
            t = rng.randint(1, 3)
            if rng.random() < 0.5:
                events.append(Ev(t, "leader_kill", {
                    "seam": rng.choice((
                        "solver.round", "solver.publish",
                        "solver.solve", "solver.return",
                    )),
                }))
                events.append(Ev(t + 1, "sup_restart", {}))
            else:
                events.append(Ev(t, "leader_hang", {
                    "seam": "solver.solve", "delay_s": 8.0,
                }))
            ticks = 14
    return ScenarioSpec(
        name=(f"fuzz-proc-sabotage-{seed}" if sabotage
              else f"fuzz-proc-w{seed}"),
        description=(
            f"generated proc weather (seed {seed}): {shards}-shard "
            f"supervised fleet"
            + (", sabotaged dispatch books" if sabotage else "")
        ),
        ticks=ticks,
        seed=int(seed),
        durable=True,
        deterministic=False,
        events=events,
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def weather_strategy(max_seed: int = 2**32 - 1):
    """The generator as a property-testing strategy — hypothesis when
    installed, the seeded stdlib fallback otherwise (never a skip)."""
    try:
        from hypothesis import strategies as st
    except ImportError:
        from ..utils import proptest as st
    return st.builds(
        generate_weather, st.integers(min_value=0, max_value=max_seed)
    )


# --------------------------------------------------------------------------- #
# running + the failure predicate
# --------------------------------------------------------------------------- #


def run_case(spec: ScenarioSpec,
             seed: Optional[int] = None) -> Dict:
    """Replay one generated weather on the backend its events name; a
    raising replay is a RED entry (the fuzzer treats a crash as a
    failing schedule, never as a skipped one)."""
    is_proc = any(e.kind == "proc_fleet" for e in spec.events)
    try:
        if is_proc:
            from .procs import run_proc_scenario

            return run_proc_scenario(spec, seed=seed)
        from .engine import run_scenario

        return run_scenario(spec, seed=seed)
    except Exception as exc:  # noqa: BLE001 — the schedule crashed the
        # harness: that IS a finding, and it must shrink like one
        return {
            "name": spec.name,
            "ok": False,
            "seed": spec.seed if seed is None else seed,
            "deterministic": False,
            "error": repr(exc)[:500],
            "invariants": {}, "checks": {}, "slos": {}, "stats": {},
            "fingerprint": "crashed",
        }


def case_fails(spec: ScenarioSpec) -> bool:
    return not run_case(spec)["ok"]


def red_keys(entry: Dict) -> List[str]:
    """The failing invariant/check/SLO names of one entry (plus
    "crashed" for a raising replay)."""
    keys = sorted(
        k for sec in ("invariants", "checks", "slos")
        for k, v in entry.get(sec, {}).items() if not v.get("ok")
    )
    if entry.get("error"):
        keys.append("crashed")
    return keys


def fails_matching(keys) -> Callable[[ScenarioSpec], bool]:
    """A shrink predicate that only accepts reductions reproducing one
    of the ORIGINAL failures — a trimmed timeline that merely starves
    the workload must not replace the finding it was shrunk from."""
    wanted = set(keys)

    def fails(spec: ScenarioSpec) -> bool:
        return bool(wanted & set(red_keys(run_case(spec))))

    return fails


def proc_fuzz_fingerprint(entry: Dict) -> str:
    """Determinism surface for child-process replays: verdicts and
    converged workload state, not wall-clock shape (round counts and
    dispatch interleavings vary with real scheduling; the contracts the
    fuzzer enforces must not)."""
    return scorecard_entry_fingerprint({
        "name": entry.get("name"),
        "seed": entry.get("seed"),
        "ok": entry.get("ok"),
        "invariants": entry.get("invariants", {}),
        "checks": entry.get("checks", {}),
        "slos": entry.get("slos", {}),
        "unfinished_final": entry.get("stats", {}).get(
            "unfinished_final"
        ),
    })


# --------------------------------------------------------------------------- #
# shrinking
# --------------------------------------------------------------------------- #


def _pinned(ev: Ev) -> bool:
    # the tick-0 fleet/workload IS the world; removing it only proves
    # "no fleet fails differently", never a smaller schedule
    return ev.tick == 0 and ev.kind in ("fleet", "proc_fleet")


class _Budget:
    def __init__(self, max_runs: int) -> None:
        self.left = max_runs

    def spend(self) -> bool:
        if self.left <= 0:
            return False
        self.left -= 1
        return True


def _rebuild(spec: ScenarioSpec, events: List[Ev],
             ticks: Optional[int] = None) -> ScenarioSpec:
    return dataclasses.replace(
        spec, events=list(events),
        ticks=spec.ticks if ticks is None else ticks,
    )


def _ddmin_events(
    spec: ScenarioSpec,
    fails: Callable[[ScenarioSpec], bool],
    budget: _Budget,
) -> List[Ev]:
    """Classic delta debugging over the removable events: drop chunks
    while the failure reproduces, halving granularity until single
    events are irreducible."""
    pinned = [e for e in spec.events if _pinned(e)]
    items = [e for e in spec.events if not _pinned(e)]
    n = 2
    while len(items) >= 1 and n <= len(items) * 2:
        chunk = max(1, len(items) // n)
        reduced = False
        i = 0
        while i < len(items):
            candidate = items[:i] + items[i + chunk:]
            if not budget.spend():
                return pinned + items
            if fails(_rebuild(spec, pinned + candidate)):
                items = candidate
                n = max(2, n - 1)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if n >= len(items):
                break
            n = min(len(items), n * 2)
    return pinned + items


_SHRINKABLE_INTS = ("n", "count", "users")


def _shrink_args(
    spec: ScenarioSpec,
    fails: Callable[[ScenarioSpec], bool],
    budget: _Budget,
) -> ScenarioSpec:
    """Lower numeric event args toward 1 (binary descent) while the
    failure keeps reproducing — a 40-task burst that still fails with 2
    tasks reads like a bug report, not like weather."""
    events = list(spec.events)
    for idx, ev in enumerate(events):
        for key in _SHRINKABLE_INTS:
            val = ev.args.get(key)
            if not isinstance(val, int) or val <= 1:
                continue
            lo, cur = 1, val
            while lo < cur:
                mid = (lo + cur) // 2
                trial = dataclasses.replace(
                    ev, args={**ev.args, key: mid}
                )
                candidate = events[:idx] + [trial] + events[idx + 1:]
                if not budget.spend():
                    return _rebuild(spec, events)
                if fails(_rebuild(spec, candidate)):
                    cur = mid
                    events = candidate
                    ev = trial
                else:
                    lo = mid + 1
    return _rebuild(spec, events)


def _trim_ticks(
    spec: ScenarioSpec,
    fails: Callable[[ScenarioSpec], bool],
    budget: _Budget,
) -> ScenarioSpec:
    last = max((e.tick for e in spec.events), default=0)
    for slack in (2, 4, 8):
        ticks = last + 1 + slack
        if ticks >= spec.ticks:
            break
        if not budget.spend():
            break
        if fails(_rebuild(spec, list(spec.events), ticks=ticks)):
            return _rebuild(spec, list(spec.events), ticks=ticks)
    return spec


def shrink_spec(
    spec: ScenarioSpec,
    fails: Optional[Callable[[ScenarioSpec], bool]] = None,
    max_runs: int = 120,
) -> ScenarioSpec:
    """Reduce a failing weather to a minimal timeline that still fails.

    Runs chunked event removal (ddmin), then numeric arg descent, then
    timeline trimming, re-verifying the failure after every accepted
    step; bounded by ``max_runs`` replays. The result is renamed
    ``<name>-min`` and ready for ``trace.save_regression_spec``."""
    fails = fails or case_fails
    budget = _Budget(max_runs)
    events = _ddmin_events(spec, fails, budget)
    cur = _rebuild(spec, events)
    cur = _shrink_args(cur, fails, budget)
    cur = _trim_ticks(cur, fails, budget)
    return dataclasses.replace(
        cur,
        name=f"{spec.name}-min",
        description=(
            f"shrunk from {len(spec.events)} events / "
            f"{spec.ticks} ticks to {len(cur.events)} events / "
            f"{cur.ticks} ticks; original: {spec.description}"
        ),
    )


# --------------------------------------------------------------------------- #
# the campaign (tools/fuzz_matrix.py's engine)
# --------------------------------------------------------------------------- #


def campaign(
    time_budget_s: float = 60.0,
    start_seed: int = DEFAULT_CAMPAIGN_SEED,
    max_cases: Optional[int] = None,
    proc: bool = False,
    shrink: bool = True,
    emit_dir: Optional[str] = None,
    progress: Optional[Callable[[Dict], None]] = None,
) -> Dict:
    """Time-boxed randomized-weather soak: enumerate seeds from
    ``start_seed``, replay each weather, shrink any failure and emit it
    as a regression spec. Returns the campaign report; ``ok`` means
    zero invariant violations found (sabotage runs EXPECT failures and
    invert this — see tools/fuzz_matrix.py)."""
    from . import trace

    t0 = _time.monotonic()
    gen = generate_proc_weather if proc else generate_weather
    failures: List[Dict] = []
    cases = 0
    while _time.monotonic() - t0 < time_budget_s:
        if max_cases is not None and cases >= max_cases:
            break
        seed = start_seed + cases
        spec = gen(seed)
        entry = run_case(spec)
        cases += 1
        if progress is not None:
            progress({"seed": seed, "name": spec.name,
                      "ok": entry["ok"]})
        if entry["ok"]:
            continue
        finding: Dict = {
            "seed": seed,
            "name": spec.name,
            "events": len(spec.events),
            "error": entry.get("error", ""),
            "red": red_keys(entry),
        }
        if shrink:
            minimal = shrink_spec(
                spec, fails=fails_matching(finding["red"])
            )
            finding["shrunk_events"] = len(minimal.events)
            finding["shrunk_ticks"] = minimal.ticks
            if emit_dir is not None:
                finding["regression_spec"] = trace.save_regression_spec(
                    minimal, out_dir=emit_dir, lossy=True,
                )
        failures.append(finding)
    return {
        "backend": "procs" if proc else "engine",
        "start_seed": start_seed,
        "cases": cases,
        "elapsed_s": round(_time.monotonic() - t0, 2),
        "failures": failures,
        "ok": not failures,
    }


def sabotage_selftest(proc: bool = False,
                      seed: int = DEFAULT_CAMPAIGN_SEED) -> Dict:
    """The self-test the gate trusts before any green campaign: a
    deliberately seeded invariant violation must be FOUND, shrink to a
    minimal timeline, and replay deterministically (same seed ⇒
    fingerprint-identical scorecard) on its backend."""
    gen = generate_proc_weather if proc else generate_weather
    spec = gen(seed, sabotage=True)
    entry = run_case(spec)
    caught = not entry["ok"]
    result: Dict = {
        "backend": "procs" if proc else "engine",
        "seed": seed,
        "caught": caught,
        "red": red_keys(entry),
    }
    if not caught:
        result["ok"] = False
        return result
    minimal = shrink_spec(
        spec, fails=fails_matching(result["red"]),
        max_runs=40 if proc else 120,
    )
    result["shrunk_events"] = len(minimal.events)
    result["shrunk_ticks"] = minimal.ticks
    e1, e2 = run_case(minimal), run_case(minimal)
    if proc:
        f1, f2 = proc_fuzz_fingerprint(e1), proc_fuzz_fingerprint(e2)
    else:
        f1, f2 = e1.get("fingerprint"), e2.get("fingerprint")
    result["still_caught"] = bool(
        set(result["red"]) & set(red_keys(e1))
    )
    result["deterministic"] = bool(f1) and f1 == f2
    result["fingerprint"] = f1
    result["ok"] = (
        caught and result["still_caught"] and result["deterministic"]
    )
    return result
