"""Fault- and overload-matrix cases migrated to run THROUGH the engine.

ISSUE 12's shrink-the-bespoke-harnesses leg: each case here used to be a
hand-rolled function in ``tools/fault_matrix.py`` / ``overload_matrix.py``
that invented its own store, wiring, and pass/fail logic. Now it is a
ScenarioSpec — the same workload, the same fault-plan injection, the
same assertions (expressed as checks over the finished run) — replayed
by the one engine every other weather uses. The tools keep their CASES
registries (tests/test_resilience.py and tests/test_overload.py
parametrize over them) but the migrated names delegate here via
``run_matrix_case``.

Original assertions preserved case by case:

  fault-solve-raise        degraded="solve-failed", serial fallback used,
                           queues persisted, serial-oracle parity
  fault-solve-hang         same, with degraded="solve-deadline" under the
                           solve wall deadline
  fault-breaker-cycle      THRESHOLD failures → open → refused tick →
                           half-open probe closes; transition breadcrumbs
  fault-wal-error          group commit raises → persist-failed, next
                           tick clean + full-rewrite, recovery consistent
  fault-wal-torn           torn group frame → per-batch atomicity
  fault-tick-budget-shed   stats shed, planning persisted, no stats span
  fault-lease-steal-mid-commit
                           steal between begin_tick and the flush →
                           fenced tick, shed group, pre-tick WAL only
  overload-event-storm     outbox coalesces at YELLOW, cap holds with
                           counted drops, every send accounted exactly
                           once, ladder returns GREEN
  overload-slow-store-storm
                           commit-latency EWMA drives RED, ticks brown
                           out optional work but keep planning, recovery
                           to GREEN once the store heals
"""
from __future__ import annotations

from typing import Dict, Optional

from ..utils.benchgen import NOW
from .engine import run_scenario
from .spec import Ev, ScenarioSpec

#: breaker knobs mirrored from scheduler/wrapper.py (imported lazily in
#: the factories to keep module import light)


def _seed_problem_event(n_distros=3, n_tasks=60, seed=7,
                        hosts_per_distro=2):
    """The fault matrix's ``_seed_store`` workload as a ``call`` event:
    a small fully-plannable problem, phantom running-task stamps cleared
    so every later dispatch would be a real CAS pair."""

    def seed_fn(run):
        from ..models import distro as distro_mod
        from ..models import host as host_mod
        from ..models import task as task_mod
        from ..utils.benchgen import generate_problem

        distros, tasks_by_distro, hosts_by_distro, _, _ = generate_problem(
            n_distros, n_tasks, seed=seed,
            hosts_per_distro=hosts_per_distro,
        )
        for d in distros:
            distro_mod.insert(run.store, d)
        all_tasks = [t for ts in tasks_by_distro.values() for t in ts]
        task_mod.insert_many(run.store, all_tasks)
        for hs in hosts_by_distro.values():
            for h in hs:
                h.running_task = ""
                h.running_task_group = ""
                h.running_task_build_variant = ""
                h.running_task_version = ""
                h.running_task_project = ""
            host_mod.insert_many(run.store, hs)

    return Ev(0, "call", {"fn": seed_fn})


def _serial_parity(run, tick: int = 0) -> Optional[str]:
    """The degraded tick's persisted queues must equal the serial
    oracle's ordering — the fault matrix's fallback-parity contract."""
    from ..models.task_queue import COLLECTION as TQ_COLLECTION
    from ..models.task_queue import SECONDARY_COLLECTION, doc_column
    from ..scheduler import serial
    from ..scheduler.wrapper import ALIAS_SUFFIX, gather_tick_inputs

    now = NOW + (tick + 1) * run.spec.tick_s
    distros, tbd, _, _, _ = gather_tick_inputs(run.store, now)
    for d in distros:
        is_alias = d.id.endswith(ALIAS_SUFFIX)
        doc = run.store.collection(
            SECONDARY_COLLECTION if is_alias else TQ_COLLECTION
        ).get(d.id.split("::")[0])
        if doc is None:
            return f"no queue doc for {d.id}"
        want = [
            t.id
            for t in serial.plan_distro_queue(
                d, tbd.get(d.id, []), now
            )[0]
        ]
        if doc_column(doc, "id") != want:
            return f"queue for {d.id} diverged from the serial oracle"
    return None


def _log_has(run, message: str, **fields) -> bool:
    for r in run.logs:
        if r.get("message") != message:
            continue
        if all(r.get(k) == v for k, v in fields.items()):
            return True
    return False


# --------------------------------------------------------------------------- #
# fault matrix migrations
# --------------------------------------------------------------------------- #


def _fault_solve_raise(seed: int = 0) -> ScenarioSpec:
    def check(run):
        res = run.tick_results[0]
        if res.degraded != "solve-failed":
            return f"degraded={res.degraded!r}"
        if res.planner_used != "serial":
            return f"planner_used={res.planner_used!r}"
        if sum(res.queues.values()) == 0:
            return "no queues persisted"
        return _serial_parity(run)

    return ScenarioSpec(
        name="fault-solve-raise",
        description="injected solve raise degrades one tick to the "
                    "serial oracle with parity",
        ticks=1,
        seed=seed,
        events=[
            _seed_problem_event(seed=seed + 7),
            Ev(0, "fault", {"seam": "scheduler.solve", "at": 0}),
        ],
        checks=[("solve-raise-degrades-with-parity", check)],
        invariants=("store_consistent", "counters_match_records"),
        service_loop=False,
    )


def _fault_solve_hang(seed: int = 0) -> ScenarioSpec:
    def check(run):
        res = run.tick_results[0]
        if res.degraded != "solve-deadline":
            return f"degraded={res.degraded!r}"
        if res.planner_used != "serial":
            return f"planner_used={res.planner_used!r}"
        if sum(res.queues.values()) == 0:
            return "no queues persisted"
        return _serial_parity(run)

    return ScenarioSpec(
        name="fault-solve-hang",
        description="a solve hanging past its wall deadline degrades "
                    "the tick to the serial oracle",
        ticks=1,
        seed=seed,
        events=[
            _seed_problem_event(seed=seed + 11),
            Ev(0, "fault", {"seam": "scheduler.solve", "at": 0,
                            "kind": "hang", "delay_s": 0.3}),
        ],
        tick_options={"solve_deadline_s": 0.05},
        checks=[("solve-hang-degrades-with-parity", check)],
        invariants=("store_consistent", "counters_match_records"),
        service_loop=False,
    )


def _fault_breaker_cycle(seed: int = 0) -> ScenarioSpec:
    from ..scheduler.wrapper import SOLVE_BREAKER_THRESHOLD

    def check(run):
        n = SOLVE_BREAKER_THRESHOLD
        states = [r.degraded for r in run.tick_results[:n]]
        if any(s != "solve-failed" for s in states):
            return f"failing ticks degraded as {states}"
        open_tick = run.tick_results[n]
        if open_tick.degraded != "breaker-open":
            return f"open tick degraded={open_tick.degraded!r}"
        probe = run.tick_results[-1]
        if probe.planner_used != "tpu" or probe.degraded != "":
            return (
                f"probe tick planner={probe.planner_used!r} "
                f"degraded={probe.degraded!r}"
            )
        transitions = [
            (r.get("from_state"), r.get("to_state"))
            for r in run.logs
            if r.get("message") == "breaker-transition"
        ]
        for want in (("closed", "open"), ("open", "half-open"),
                     ("half-open", "closed")):
            if want not in transitions:
                return f"missing breaker transition {want}"
        return None

    from ..scheduler.wrapper import SOLVE_BREAKER_COOLDOWN_S

    n = SOLVE_BREAKER_THRESHOLD
    # ticks: n failing + 1 refused-open + enough 15s ticks to pass the
    # cooldown so the final tick is the half-open probe that closes it
    extra = int(SOLVE_BREAKER_COOLDOWN_S // 15) + 2
    return ScenarioSpec(
        name="fault-breaker-cycle",
        description="threshold solve failures trip the breaker open; "
                    "the cooled-down half-open probe closes it",
        ticks=n + 1 + extra,
        seed=seed,
        events=[
            _seed_problem_event(seed=seed + 13),
            *[
                Ev(0, "fault", {"seam": "scheduler.solve", "at": i})
                for i in range(n)
            ],
        ],
        checks=[("breaker-cycle", check)],
        invariants=("store_consistent",),
        service_loop=False,
    )


def _fault_wal_error(seed: int = 0) -> ScenarioSpec:
    def check(run):
        res0, res1 = run.tick_results[0], run.tick_results[1]
        if res0.degraded != "persist-failed":
            return f"tick0 degraded={res0.degraded!r}"
        if res1.degraded != "" or sum(res1.queues.values()) == 0:
            return f"tick1 degraded={res1.degraded!r}"
        if not _log_has(run, "wal-group-commit-failed"):
            return "missing wal-group-commit-failed breadcrumb"
        return None

    return ScenarioSpec(
        name="fault-wal-error",
        description="WAL group-commit write error degrades the tick; "
                    "the next tick full-rewrites and recovery replays "
                    "to the same state",
        ticks=2,
        seed=seed,
        durable=True,
        events=[
            _seed_problem_event(seed=seed + 17),
            Ev(0, "fault", {"seam": "wal.commit", "at": 0}),
        ],
        checks=[("wal-error-degrades-then-heals", check)],
        invariants=(
            "store_consistent", "resume_equals_rerun",
            "monotone_epochs",
        ),
        service_loop=False,
    )


def _fault_wal_torn(seed: int = 0) -> ScenarioSpec:
    def check(run):
        res0, res1 = run.tick_results[0], run.tick_results[1]
        if res0.degraded != "persist-failed":
            return f"tick0 degraded={res0.degraded!r}"
        if sum(res1.queues.values()) == 0:
            return "tick1 persisted no queues"
        return None

    return ScenarioSpec(
        name="fault-wal-torn",
        description="a torn group frame loses the whole tick "
                    "atomically — never a partial tick",
        ticks=2,
        seed=seed,
        durable=True,
        events=[
            _seed_problem_event(seed=seed + 19),
            Ev(0, "fault", {"seam": "wal.commit", "at": 0,
                            "kind": "torn"}),
        ],
        checks=[("wal-torn-atomic", check)],
        invariants=(
            "store_consistent", "resume_equals_rerun",
            "monotone_epochs",
        ),
        service_loop=False,
    )


def _fault_tick_budget_shed(seed: int = 0) -> ScenarioSpec:
    def check(run):
        res = run.tick_results[0]
        if sum(res.queues.values()) == 0:
            return "planning was starved by the budget"
        if "stats" not in res.shed:
            return f"shed={res.shed!r}"
        if not _log_has(run, "degraded-tick"):
            return "missing degraded-tick breadcrumb"
        if run.store.collection("spans").find(
            lambda d: d.get("name") == "tick_stats"
        ):
            return "tick_stats span written despite the shed"
        return None

    return ScenarioSpec(
        name="fault-tick-budget-shed",
        description="a blown tick budget sheds stats, never planning",
        ticks=1,
        seed=seed,
        events=[_seed_problem_event(seed=seed + 23)],
        tick_options={"tick_budget_s": 1e-9},
        checks=[("budget-sheds-stats-only", check)],
        invariants=(
            "store_consistent", "planning_never_starves",
            "counters_match_records",
        ),
        service_loop=False,
    )


def _fault_lease_steal(seed: int = 0) -> ScenarioSpec:
    def check(run):
        import os

        res = run.tick_results[0]
        if res.degraded != "fenced":
            return f"degraded={res.degraded!r}"
        if not getattr(run.store, "fenced", False):
            return "store not fenced"
        if not run.lease.lost:
            return "deposed holder does not observe the loss"
        wal_path = os.path.join(run.data_dir, "wal.log")
        wal = (
            open(wal_path, encoding="utf-8").read()
            if os.path.exists(wal_path) else ""
        )
        if '"o":"g"' in wal:
            return "the fenced tick's group frame reached the WAL"
        from ..storage.durable import DurableStore

        recovered = DurableStore(run.data_dir)
        try:
            if recovered.collection("task_queues").find(lambda d: True):
                return "recovered store holds fenced-tick queue docs"
            if len(recovered.collection("tasks").key_order()) != len(
                run.store.collection("tasks").key_order()
            ):
                return "pre-tick task set did not survive"
        finally:
            recovered.close()
        if not _log_has(run, "epoch-fenced"):
            return "missing epoch-fenced breadcrumb"
        if not _log_has(run, "tick-fenced"):
            return "missing tick-fenced breadcrumb"
        return None

    return ScenarioSpec(
        name="fault-lease-steal-mid-commit",
        description="a steal between begin_tick and the group flush "
                    "fences the holder: the buffered group is shed and "
                    "recovery sees pre-tick state only",
        ticks=1,
        seed=seed,
        durable=True,
        events=[
            _seed_problem_event(seed=seed + 31),
            Ev(0, "call", {"fn": lambda run: run.store.checkpoint()}),
            Ev(0, "lease_steal", {"failover": False}),
        ],
        checks=[("fenced-holder-sheds-tick", check)],
        invariants=("monotone_epochs",),
        service_loop=False,
    )


# --------------------------------------------------------------------------- #
# overload matrix migrations
# --------------------------------------------------------------------------- #


def _overload_event_storm(seed: int = 0) -> ScenarioSpec:
    def check(run):
        from ..utils import overload

        undelivered_peak = run.stats.get("outbox_undelivered_peak", 0)
        if undelivered_peak > 40:
            return f"outbox cap breached: {undelivered_peak}"
        if run.stats.get("outbox_peak_level", 0) < overload.RED:
            return "storm never drove the ladder to RED"
        coalesced = run.counter_delta("overload.outbox_coalesced")
        dropped = run.counter_delta("overload.outbox_dropped")
        if dropped <= 0:
            return "cap enforced no counted drops"
        if coalesced <= 0:
            return "duplicate notifications did not coalesce"
        inserted = run.store.collection("slack_outbox").count(
            lambda d: True
        )
        if inserted + coalesced + dropped != 150:
            return (
                f"sends unaccounted: inserted={inserted} "
                f"coalesced={coalesced} dropped={dropped} != 150"
            )
        if run.tick_results[-1].overload != "green":
            return (
                "ladder did not return to GREEN: "
                f"{run.tick_results[-1].overload}"
            )
        if not _log_has(run, "outbox-row-dropped"):
            return "missing outbox-row-dropped breadcrumb"
        return None

    def snapshot_peak(run):
        from ..utils import overload

        monitor = overload.monitor_for(run.store)
        # the engine disarms gauge-push auto-evaluation (determinism);
        # evaluate explicitly at the storm's peak like the tick would
        monitor.evaluate(run.now)
        run.stats["outbox_peak_level"] = monitor.level()
        run.stats["outbox_undelivered_peak"] = run.store.collection(
            "slack_outbox"
        ).count(lambda d: not d.get("delivered") and not d.get("failed"))

    return ScenarioSpec(
        name="overload-event-storm",
        description="notification fan-out storm: coalesce at YELLOW, "
                    "counted drops at the cap, exactly-once accounting "
                    "of every send, GREEN after the drain",
        ticks=8,
        seed=seed,
        events=[
            Ev(0, "fleet", {"distros": [
                {"id": "dev0", "provider": "mock", "hosts": 0},
            ]}),
            # phase A: 100 distinct sends against a 40-row cap
            Ev(0, "outbox", {"n": 100}),
            # the matrix auto-evaluated on every insert
            # (eval_interval_s=0); the engine evaluates deterministically
            # between the phases instead
            Ev(0, "call", {"fn": lambda run: __import__(
                "evergreen_tpu.utils.overload", fromlist=["overload"]
            ).monitor_for(run.store).evaluate(run.now)}),
            # phase B: 50 repeats of one still-undelivered notification
            # — these must coalesce, not insert or drop
            Ev(0, "outbox", {"n": 50, "distinct": False,
                             "key": "storm-0-2"}),
            Ev(0, "call", {"fn": snapshot_peak}),
            Ev(2, "drain_outbox", {}),
        ],
        overload={
            "outbox_cap": 40,
            "outbox_depth_levels": [10.0, 20.0, 40.0],
            "hysteresis_ticks": 2,
        },
        checks=[("event-storm-contract", check)],
        invariants=("counters_match_records",),
        service_loop=False,
    )


def _overload_slow_store(seed: int = 0) -> ScenarioSpec:
    def check(run):
        storm = run.tick_results[:4]
        recovery = run.tick_results[4:]
        if any(sum(r.queues.values()) == 0 for r in storm):
            return "a storm tick starved planning"
        if any(sum(r.queues.values()) == 0 for r in recovery):
            return "a recovery tick starved planning"
        browned = [
            r for r in storm
            if r.overload in ("red", "black") and "stats" in r.shed
        ]
        if not browned:
            return "slow store never browned a tick out"
        if run.tick_results[-1].overload != "green":
            return (
                "ladder did not recover to GREEN: "
                f"{run.tick_results[-1].overload}"
            )
        if run.tick_results[-1].shed:
            return f"recovered tick still sheds: {run.tick_results[-1].shed}"
        if not _log_has(run, "degraded-tick", reason="overload"):
            return "missing overload degraded-tick breadcrumb"
        return None

    return ScenarioSpec(
        name="overload-slow-store-storm",
        description="a crawling WAL (hang at wal.commit) drives the "
                    "commit-latency EWMA to RED; ticks brown out "
                    "optional work, planning persists, and the ladder "
                    "steps back down once the store heals",
        # 4 storm ticks + a long recovery runway: the EWMA decays ~0.6x
        # per healthy tick, and on a loaded box the REAL commit latency
        # rides near the 3ms YELLOW rung — give hysteresis room
        ticks=28,
        seed=seed,
        durable=True,
        deterministic=False,  # real commit-latency EWMA drives the ladder
        events=[
            _seed_problem_event(seed=seed + 59),
            Ev(0, "fault", {"seam": "wal.commit", "kind": "hang",
                            "delay_s": 0.03, "always": True}),
            Ev(4, "clear_faults", {"seam": "wal.commit"}),
        ],
        overload={
            "store_latency_ms_levels": [3.0, 8.0, 100000.0],
            "hysteresis_ticks": 2,
        },
        checks=[("slow-store-contract", check)],
        invariants=(
            "store_consistent", "planning_never_starves",
            "counters_match_records", "resume_equals_rerun",
        ),
        service_loop=False,
    )


FAULT_SCENARIO_CASES: Dict[str, callable] = {
    "solve-raise": _fault_solve_raise,
    "solve-hang": _fault_solve_hang,
    "breaker-cycle": _fault_breaker_cycle,
    "wal-error": _fault_wal_error,
    "wal-torn": _fault_wal_torn,
    "tick-budget-shed": _fault_tick_budget_shed,
    "lease-steal-mid-commit": _fault_lease_steal,
}

OVERLOAD_SCENARIO_CASES: Dict[str, callable] = {
    "event-storm": _overload_event_storm,
    "slow-store-storm": _overload_slow_store,
}


def run_matrix_case(kind: str, name: str, seed: int = 0) -> dict:
    """Run one migrated matrix case through the engine. Returns the
    legacy ``{"ok": bool, ...}`` shape the tools' CASES registries (and
    the tests parametrizing over them) consume, with the full scorecard
    entry riding along."""
    registry = (
        FAULT_SCENARIO_CASES if kind == "fault"
        else OVERLOAD_SCENARIO_CASES
    )
    spec = registry[name](seed)
    entry = run_scenario(spec)
    return {"ok": entry["ok"], "entry": entry}
