"""Declarative scenario specs for the trace-driven scenario engine.

A scenario is a *timeline*: a fleet description, a list of events pinned
to virtual ticks (commits arriving, patch stacks landing, tasks failing,
spot instances vanishing, a lease being stolen mid-commit, load gauges
ramping through a compressed week), and a contract — the cross-cutting
invariants every scenario must keep (resume ≡ rerun, no duplicate
dispatch, planning never starves, monotone epochs, counters == records)
plus scenario-specific SLOs evaluated over the run's stats.

The engine (scenarios/engine.py) compiles a spec into a deterministic
seeded replay against a full in-process plane and emits one scorecard
entry per scenario; ``tools/scenario_engine.py`` aggregates them into
``SCORECARD.json`` and ``tools/gate.py --scenarios`` diffs that against
the last green run.

Specs stay declarative where the vocabulary allows (every stock event
kind is data → EVENT_HANDLERS), with two escape hatches the matrix
migrations need: a ``call`` event running an arbitrary function at a
tick, and ``checks`` — named predicates over the finished run that
express case-specific assertions the SLO vocabulary cannot.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Tuple

#: invariant names every scenario asserts unless the spec says otherwise
#: (scenarios/invariants.py maps them to checkers; durable-only checks
#: skip themselves on in-memory runs)
DEFAULT_INVARIANTS: Tuple[str, ...] = (
    "no_duplicate_dispatch",
    "store_consistent",
    "planning_never_starves",
    "monotone_epochs",
    "counters_match_records",
    "resume_equals_rerun",
)


@dataclasses.dataclass(frozen=True)
class Ev:
    """One timeline entry: at virtual tick ``tick`` (before that tick's
    scheduler pass), run the ``kind`` handler with ``args``."""

    tick: int
    kind: str
    args: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class SLO:
    """One scenario-specific service-level objective, evaluated over the
    run's stats dict. ``op`` is one of "<=", ">=", "==", "truthy".
    The scorecard records value, bound, pass/fail, and the margin (the
    relative headroom left before the bound — the number the gate's
    diff watches shrink)."""

    name: str
    metric: str
    op: str
    bound: float

    def evaluate(self, stats: Dict) -> Dict:
        value = stats.get(self.metric)
        ok = False
        margin = 0.0
        if value is not None:
            v = float(value)
            b = float(self.bound)
            if self.op == "<=":
                ok = v <= b
                margin = (b - v) / max(abs(b), 1.0)
            elif self.op == ">=":
                ok = v >= b
                margin = (v - b) / max(abs(b), 1.0)
            elif self.op == "==":
                ok = v == b
                margin = 0.0 if ok else -abs(v - b) / max(abs(b), 1.0)
            elif self.op == "truthy":
                ok = bool(value)
                margin = 0.0
        return {
            "metric": self.metric,
            "op": self.op,
            "bound": self.bound,
            "value": value,
            "ok": ok,
            "margin": round(margin, 4),
        }


@dataclasses.dataclass
class ScenarioSpec:
    """One replayable weather. See the module docstring; the library of
    shipped scenarios lives in scenarios/library.py."""

    name: str
    description: str
    ticks: int
    events: List[Ev] = dataclasses.field(default_factory=list)
    slos: List[SLO] = dataclasses.field(default_factory=list)
    #: named predicates over the finished run: fn(run) -> None | problem
    checks: List[Tuple[str, Callable]] = dataclasses.field(
        default_factory=list
    )
    invariants: Tuple[str, ...] = DEFAULT_INVARIANTS
    seed: int = 0
    #: virtual seconds between scheduler ticks (the compressed clock:
    #: a week-long trace replays in minutes by stretching this)
    tick_s: float = 15.0
    #: run against a DurableStore + writer lease in a temp data dir
    #: (failover / WAL scenarios) instead of an in-memory Store
    durable: bool = False
    #: same seed ⇒ same scorecard fingerprint. Engine-driven scenarios
    #: keep this True by running everything on the virtual clock with
    #: no worker threads; migrated storm cases that exercise real
    #: threads/timers opt out (their assertions still run).
    deterministic: bool = True
    #: ticks a dispatched task runs before the engine completes it
    default_task_ticks: int = 1
    #: run the between-ticks service pass (cloud reconcile, provisioning,
    #: the deterministic agent). Migrated matrix cases turn it off — they
    #: assert on the tick pipeline alone, exactly like the bespoke
    #: harnesses they replace.
    service_loop: bool = True
    #: TickOptions overrides (dataclasses.replace kwargs)
    tick_options: Dict = dataclasses.field(default_factory=dict)
    #: OverloadConfig overrides. The engine BASE config neutralizes every
    #: wall-clock-coupled signal (store latency, api rate, tick lag) so
    #: a slow CI box cannot flip a deterministic scenario's ladder; a
    #: spec re-arms exactly the signals its trace drives.
    overload: Dict = dataclasses.field(default_factory=dict)
    #: extra config sections to set: {SectionClassName: {field: value}}
    config: Dict[str, Dict] = dataclasses.field(default_factory=dict)
    #: run in the tier-1 fast subset (tests/test_scenarios.py); the full
    #: sweep always runs everything
    tier1: bool = True


def scorecard_entry_fingerprint(entry: Dict) -> str:
    """Stable hash of one scenario's scorecard entry, excluding the
    wall-clock fields — the determinism contract is over decisions and
    counters, never over how fast this box ran them."""
    import hashlib
    import json

    def scrub(obj):
        if isinstance(obj, dict):
            return {
                k: scrub(v)
                for k, v in sorted(obj.items())
                if k not in ("timing", "wall_ms", "fingerprint")
            }
        if isinstance(obj, list):
            return [scrub(v) for v in obj]
        if isinstance(obj, float):
            return round(obj, 6)
        return obj

    payload = json.dumps(scrub(entry), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]
