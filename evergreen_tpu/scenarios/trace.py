"""Trace capture: round-trip a live plane's event stream into a spec.

Any run — an in-process scenario, a crash-matrix session against the
supervised fleet, a production incident under ``service`` — leaves two
kinds of evidence: the durable story (WAL segments + snapshots in the
data dir, storage/durable.py) and the runtime story (structured log
records, supervisor control-IPC traffic). This module turns either into
a deterministic, seeded ``ScenarioSpec`` whose replay carries a diffable
scorecard:

  ``events_from_wal(data_dir)``   offline: parse every WAL segment +
                                  snapshot in a data dir into semantic
                                  ``TraceEvent``s (task arrivals with
                                  their dependency edges, completions
                                  with failure class, distro/host
                                  inventory, fence frames)
  ``TraceRecorder``               live: tap the WAL journal
                                  (storage/durable.py journal taps), the
                                  structured-log stream (dispatch/agent/
                                  fault breadcrumbs) and the supervisor
                                  control IPC (runtime/supervisor.py ipc
                                  taps) into a JSONL trace file
  ``trace_to_spec(events)``       compile semantic events into a replay
                                  spec: the fleet at tick 0, exact task
                                  DAGs bucketed into virtual ticks,
                                  originally-failed tasks armed as
                                  exact-match ``fail_next`` plans
  ``spec_to_jsonable`` / ``spec_from_jsonable`` / ``save_regression_spec``
  / ``load_regression_specs``     the checked-in regression format every
                                  fuzz-found minimal timeline ships in
                                  (scenarios/regressions/*.json)

The round-trip contract is over the **canonical surface** (the same
tasks+queues view resume ≡ rerun compares): replaying the captured spec
must converge to the captured run's canonical fingerprint, and the
replay itself is deterministic — same seed ⇒ same scorecard
fingerprint. Wall-clock shape (which host ran what, how long a tick
took) is deliberately NOT part of the contract; decisions and converged
state are.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time as _time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..utils import lockcheck as _lockcheck

from ..globals import Provider, TaskStatus
from .spec import DEFAULT_INVARIANTS, Ev, SLO, ScenarioSpec

#: statuses that mean a task's story ended (the completion the replay's
#: deterministic agent must reproduce)
_FINISHED = (TaskStatus.SUCCEEDED.value, TaskStatus.FAILED.value)

#: providers ev_fleet can faithfully re-create; anything else (a real
#: cloud only production talks to) replays against the mock provider
_REPLAYABLE_PROVIDERS = {
    Provider.MOCK.value,
    Provider.DOCKER_MOCK.value,
    Provider.EC2_FLEET.value,
    Provider.EC2_ONDEMAND.value,
}

REGRESSIONS_DIR = os.path.join(os.path.dirname(__file__), "regressions")


@dataclasses.dataclass(frozen=True)
class TraceEvent:
    """One semantic capture record. ``ts`` is the wall/frame timestamp
    when one was recoverable, else None (snapshot-resident docs: their
    arrival order was compacted away)."""

    kind: str
    data: dict
    ts: Optional[float] = None


# --------------------------------------------------------------------------- #
# WAL → semantic events
# --------------------------------------------------------------------------- #


class _FleetStateTracker:
    """Replays raw WAL op records over a minimal doc model of the three
    collections a spec can re-create (distros / tasks / hosts), emitting
    a semantic TraceEvent at each first-seen and each finish transition.
    Shared by the offline parser and the live recorder so the two can
    never diverge on what a record means."""

    _COLLS = ("distros", "tasks", "hosts")

    def __init__(self) -> None:
        self.docs: Dict[str, Dict[str, dict]] = {c: {} for c in self._COLLS}
        self.first_ts: Dict[Tuple[str, str], Optional[float]] = {}
        self.events: List[TraceEvent] = []
        self._finished: set = set()

    # -- feeding ---------------------------------------------------------- #

    def feed_snapshot(self, collections: Dict[str, list]) -> None:
        """Compacted history: docs whose arrival records were truncated
        away. They land with ts=None — the spec builder buckets them at
        tick 0."""
        for coll in self._COLLS:
            for doc in collections.get(coll, ()):
                self._upsert(coll, dict(doc), ts=None)

    def feed_record(self, rec: dict, ts: Optional[float] = None) -> None:
        op = rec.get("o")
        if op == "g":
            frame_ts = rec.get("ts", ts)
            for sub in rec.get("rs", ()):
                self.feed_record(sub, ts=frame_ts)
            return
        if op == "f":
            self.events.append(TraceEvent(
                "fence", {"epoch": int(rec.get("e", 0) or 0)}, ts=ts,
            ))
            return
        coll = rec.get("c")
        if coll not in self.docs:
            return
        if op == "p":
            self._upsert(coll, dict(rec["d"]), ts=ts)
        elif op == "pm":
            for d in rec.get("ds", ()):
                self._upsert(coll, dict(d), ts=ts)
        elif op == "u":
            self._patch(coll, rec.get("i"), rec.get("f") or {}, ts=ts)
        elif op == "um":
            for i in rec.get("is", ()):
                self._patch(coll, i, rec.get("f") or {}, ts=ts)
        elif op in ("pl", "qs"):
            f = rec.get("f")
            if f:
                self._patch(coll, rec.get("i"), f, ts=ts)
        elif op == "r":
            self.docs[coll].pop(rec.get("i"), None)
        elif op == "x":
            self.docs[coll].clear()

    # -- doc model --------------------------------------------------------- #

    def _upsert(self, coll: str, doc: dict, ts: Optional[float]) -> None:
        did = doc.get("_id")
        if did is None:
            return
        fresh = did not in self.docs[coll]
        if fresh:
            self.first_ts[(coll, did)] = ts
            self.events.append(TraceEvent(
                {"distros": "distro", "tasks": "task_arrival",
                 "hosts": "host"}[coll],
                {"id": did}, ts=ts,
            ))
        self.docs[coll][did] = doc
        if coll == "tasks":
            self._note_finish(did, doc, ts)

    def _patch(self, coll: str, did, fields: dict,
               ts: Optional[float]) -> None:
        if did is None:
            return
        doc = self.docs[coll].get(did)
        if doc is None:
            # base write lost to a torn frame — synthesize the doc so a
            # later finish transition is still observed
            doc = {"_id": did}
            self.docs[coll][did] = doc
            self.first_ts[(coll, did)] = ts
        doc.update(fields)
        if coll == "tasks":
            self._note_finish(did, doc, ts)

    def _note_finish(self, tid: str, doc: dict,
                     ts: Optional[float]) -> None:
        if doc.get("status") in _FINISHED and tid not in self._finished:
            self._finished.add(tid)
            self.events.append(TraceEvent(
                "task_finish",
                {"id": tid, "status": doc["status"],
                 "details_type": doc.get("details_type", "")},
                ts=ts,
            ))


def _iter_segments(data_dir: str):
    """Yield ``(shard_id, snapshot_doc_or_None, wal_records)`` per
    durability segment in ``data_dir`` (unsharded classic files and the
    fleet's per-shard segments alike)."""
    from ..parallel.topology import snapshot_segment_name, wal_segment_name
    from ..storage.durable import fleet_segment_ids

    for shard in fleet_segment_ids(data_dir):
        snap_doc = None
        snap_path = os.path.join(data_dir, snapshot_segment_name(shard))
        try:
            with open(snap_path, encoding="utf-8") as fh:
                snap_doc = json.load(fh)
        except (OSError, ValueError):
            snap_doc = None
        records: List[dict] = []
        wal_path = os.path.join(data_dir, wal_segment_name(shard))
        try:
            with open(wal_path, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        records.append(json.loads(line))
                    except ValueError:
                        continue  # torn/repaired stub: skip, keep reading
        except OSError:
            pass
        yield shard, snap_doc, records


def events_from_wal(data_dir: str) -> List[TraceEvent]:
    """Parse every durability segment in ``data_dir`` into semantic
    trace events. Snapshots contribute the compacted prefix (ts=None),
    WAL lines the live tail (frame ``ts`` when present). The final doc
    state rides along in one trailing ``state`` event so the spec
    builder sees exactly what the plane converged to."""
    tracker = _FleetStateTracker()
    for _shard, snap_doc, records in _iter_segments(data_dir):
        if snap_doc:
            tracker.feed_snapshot(snap_doc.get("collections", {}))
        for rec in records:
            tracker.feed_record(rec)
    tracker.events.append(TraceEvent(
        "state",
        {"docs": tracker.docs,
         "first_ts": {
             f"{coll}/{did}": ts
             for (coll, did), ts in tracker.first_ts.items()
         }},
    ))
    return tracker.events


# --------------------------------------------------------------------------- #
# semantic events → ScenarioSpec
# --------------------------------------------------------------------------- #


def _dep_depth(tasks: Dict[str, dict]) -> int:
    memo: Dict[str, int] = {}

    def depth(tid: str, stack: frozenset) -> int:
        if tid in memo:
            return memo[tid]
        if tid in stack:
            return 0  # cycle guard: corrupt capture must not recurse out
        doc = tasks.get(tid)
        deps = [d.get("task_id") for d in (doc or {}).get("depends_on", [])]
        memo[tid] = 1 + max(
            (depth(d, stack | {tid}) for d in deps if d in tasks),
            default=0,
        )
        return memo[tid]

    return max((depth(t, frozenset()) for t in tasks), default=0)


def trace_to_spec(
    events: List[TraceEvent],
    name: str = "captured-trace",
    tick_s: float = 15.0,
    seed: int = 0,
    max_arrival_ticks: int = 24,
) -> ScenarioSpec:
    """Compile captured events into a replayable spec.

    The fleet (distros + host counts) lands at tick 0; tasks are
    re-created exactly (ids, dependency edges, requester, priority,
    revision order) as ``dag`` events bucketed into virtual ticks by
    their captured arrival timestamps; every originally-FAILED task arms
    one exact-match ``fail_next`` plan so the deterministic agent
    reproduces the failure pattern. ``ticks`` is sized so the replay
    converges: arrival span + dependency depth + drain time at the
    captured host capacity."""
    state = next(
        (e.data for e in reversed(events) if e.kind == "state"), None,
    )
    if state is None:
        # live-recorder path: rebuild the state from the event stream
        tracker = _FleetStateTracker()
        for e in events:
            if e.kind == "wal_record":
                tracker.feed_record(e.data["rec"], ts=e.ts)
        state = {"docs": tracker.docs, "first_ts": {
            f"{coll}/{did}": ts
            for (coll, did), ts in tracker.first_ts.items()
        }}
    docs = state["docs"]
    first_ts = state.get("first_ts", {})
    distros = docs.get("distros", {})
    tasks = docs.get("tasks", {})
    hosts = docs.get("hosts", {})

    hosts_by_distro: Dict[str, int] = {}
    for h in hosts.values():
        did = h.get("distro_id", "")
        hosts_by_distro[did] = hosts_by_distro.get(did, 0) + 1

    fleet = []
    for did in sorted(distros):
        d = distros[did]
        provider = d.get("provider", Provider.MOCK.value)
        if provider not in _REPLAYABLE_PROVIDERS:
            provider = Provider.MOCK.value
        fleet.append({
            "id": did,
            "provider": provider,
            "hosts": hosts_by_distro.get(did, 0),
            "max_hosts": max(
                100, int(
                    (d.get("host_allocator_settings") or {})
                    .get("maximum_hosts", 100) or 100
                ),
            ),
        })
    # tasks referencing a distro that never had a doc (partial capture)
    # still need a home for the queue to exist
    for t in tasks.values():
        did = t.get("distro_id", "")
        if did and all(f["id"] != did for f in fleet):
            fleet.append({
                "id": did, "provider": Provider.MOCK.value,
                "hosts": max(1, hosts_by_distro.get(did, 0)),
            })

    # arrival ticks: anchor at the earliest timestamped arrival;
    # snapshot-resident docs (ts None) land at tick 0
    stamps = [
        ts for key, ts in first_ts.items()
        if ts is not None and key.startswith("tasks/")
    ]
    anchor = min(stamps) if stamps else None

    def arrival_tick(tid: str) -> int:
        ts = first_ts.get(f"tasks/{tid}")
        if ts is None or anchor is None:
            return 0
        return min(int((ts - anchor) // tick_s), max_arrival_ticks)

    nodes_by_tick: Dict[int, Dict[str, list]] = {}
    fail_events: List[Ev] = []
    for tid in sorted(tasks):
        t = tasks[tid]
        did = t.get("distro_id", "")
        node = {
            "id": tid,
            "display_name": t.get("display_name", tid),
            "project": t.get("project", "proj"),
            "version": t.get("version", f"{tid}-v"),
            "build_variant": t.get("build_variant", "bv0"),
            "activated": bool(t.get("activated", True)),
            "requester": t.get("requester", ""),
            "priority": int(t.get("priority", 0) or 0),
            "revision_order": int(
                t.get("revision_order_number", 0) or 0
            ),
            "expected_s": float(t.get("expected_duration_s", 300.0) or 300.0),
            "deps": [
                d.get("task_id") for d in t.get("depends_on", [])
                if d.get("task_id")
            ],
        }
        nodes_by_tick.setdefault(arrival_tick(tid), {}) \
            .setdefault(did, []).append(node)
        if t.get("status") == TaskStatus.FAILED.value:
            fail_events.append(Ev(0, "fail_next", {
                "match": tid, "exact": True, "count": 1,
                "details_type": t.get("details_type", "") or "test",
            }))

    spec_events: List[Ev] = [Ev(0, "fleet", {"distros": fleet})]
    spec_events.extend(fail_events)
    for tick in sorted(nodes_by_tick):
        for did in sorted(nodes_by_tick[tick]):
            spec_events.append(Ev(tick, "dag", {
                "distro": did, "nodes": nodes_by_tick[tick][did],
            }))

    n_hosts = max(1, sum(f.get("hosts", 0) for f in fleet))
    arrival_span = max(nodes_by_tick, default=0)
    drain = -(-len(tasks) // n_hosts)  # ceil
    ticks = arrival_span + 2 * (_dep_depth(tasks) + drain) + 6
    return ScenarioSpec(
        name=name,
        description=(
            f"captured trace: {len(tasks)} tasks across "
            f"{len(fleet)} distros, {len(fail_events)} failures "
            "re-armed; replay converges to the captured canonical "
            "fingerprint"
        ),
        ticks=ticks,
        events=spec_events,
        seed=seed,
        tick_s=tick_s,
        invariants=DEFAULT_INVARIANTS,
    )


def capture_data_dir(
    data_dir: str, name: str = "captured-trace", **kw
) -> ScenarioSpec:
    """One-call offline capture: WAL segments + snapshots → spec."""
    return trace_to_spec(events_from_wal(data_dir), name=name, **kw)


# --------------------------------------------------------------------------- #
# canonical fingerprints (the round-trip parity surface)
# --------------------------------------------------------------------------- #


def canonical_fingerprint_of_state(state: dict) -> str:
    """Stable hash of a canonical_state() dict (tasks + queues)."""
    payload = json.dumps(state, sort_keys=True, default=str)
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def canonical_fingerprint(store) -> str:
    from .invariants import canonical_state

    return canonical_fingerprint_of_state(canonical_state(store))


# --------------------------------------------------------------------------- #
# spec (de)serialization + the checked-in regression corpus
# --------------------------------------------------------------------------- #


def spec_to_jsonable(spec: ScenarioSpec, lossy: bool = False) -> dict:
    """Serialize a spec to the checked-in regression format. ``call``
    events and ``checks`` hold live callables and cannot round-trip;
    without ``lossy`` they are an error so a regression spec can never
    silently lose the assertion that made it red."""
    dropped = []
    events = []
    for ev in spec.events:
        if ev.kind == "call":
            if not lossy:
                raise ValueError(
                    f"spec {spec.name!r} has a 'call' event at tick "
                    f"{ev.tick}: callables don't serialize (pass "
                    "lossy=True to drop it, recorded as such)"
                )
            dropped.append(f"call@{ev.tick}")
            continue
        events.append({"tick": ev.tick, "kind": ev.kind, "args": ev.args})
    if spec.checks and not lossy:
        raise ValueError(
            f"spec {spec.name!r} carries {len(spec.checks)} live "
            "check callables (pass lossy=True to drop them)"
        )
    doc = {
        "schema": 1,
        "name": spec.name,
        "description": spec.description,
        "ticks": spec.ticks,
        "seed": spec.seed,
        "tick_s": spec.tick_s,
        "durable": spec.durable,
        "deterministic": spec.deterministic,
        "default_task_ticks": spec.default_task_ticks,
        "service_loop": spec.service_loop,
        "tick_options": spec.tick_options,
        "overload": spec.overload,
        "config": spec.config,
        "tier1": spec.tier1,
        "invariants": list(spec.invariants),
        "events": events,
        "slos": [
            {"name": s.name, "metric": s.metric, "op": s.op,
             "bound": s.bound}
            for s in spec.slos
        ],
    }
    if dropped or (spec.checks and lossy):
        doc["lossy"] = {
            "dropped_events": dropped,
            "dropped_checks": [name for name, _ in spec.checks],
        }
    return doc


def spec_from_jsonable(doc: dict) -> ScenarioSpec:
    return ScenarioSpec(
        name=doc["name"],
        description=doc.get("description", ""),
        ticks=int(doc["ticks"]),
        events=[
            Ev(int(e["tick"]), e["kind"], dict(e.get("args", {})))
            for e in doc.get("events", ())
        ],
        slos=[
            SLO(s["name"], s["metric"], s["op"], s["bound"])
            for s in doc.get("slos", ())
        ],
        invariants=tuple(doc.get("invariants", DEFAULT_INVARIANTS)),
        seed=int(doc.get("seed", 0)),
        tick_s=float(doc.get("tick_s", 15.0)),
        durable=bool(doc.get("durable", False)),
        deterministic=bool(doc.get("deterministic", True)),
        default_task_ticks=int(doc.get("default_task_ticks", 1)),
        service_loop=bool(doc.get("service_loop", True)),
        tick_options=dict(doc.get("tick_options", {})),
        overload=dict(doc.get("overload", {})),
        config=dict(doc.get("config", {})),
        tier1=bool(doc.get("tier1", True)),
    )


def save_regression_spec(
    spec: ScenarioSpec, out_dir: Optional[str] = None,
    lossy: bool = False,
) -> str:
    """Write one fuzz-found minimal timeline as a ready-to-check-in
    regression spec; returns the path. The repo rule (ARCHITECTURE.md):
    every such spec IS checked in under scenarios/regressions/ so the
    weather that broke an invariant replays in CI forever."""
    out_dir = out_dir or REGRESSIONS_DIR
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{spec.name}.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(spec_to_jsonable(spec, lossy=lossy), fh, indent=1,
                  sort_keys=True)
        fh.write("\n")
    return path


def load_regression_specs(
    reg_dir: Optional[str] = None,
) -> Dict[str, Callable[[], ScenarioSpec]]:
    """The checked-in fuzz-regression corpus as scenario factories
    (same shape as library.SCENARIOS — tools/scenario_engine.py and the
    tier-1 green test run them alongside the shipped weathers)."""
    reg_dir = reg_dir or REGRESSIONS_DIR
    out: Dict[str, Callable[[], ScenarioSpec]] = {}
    try:
        names = sorted(os.listdir(reg_dir))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".json"):
            continue
        path = os.path.join(reg_dir, fname)
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            raise RuntimeError(
                f"unreadable regression spec {path}: {exc}"
            ) from exc

        def factory(doc=doc):
            return spec_from_jsonable(doc)

        out[doc["name"]] = factory
    return out


# --------------------------------------------------------------------------- #
# live capture
# --------------------------------------------------------------------------- #

#: structured-log events worth a trace line (dispatch/agent timings,
#: fault + lease breadcrumbs); everything else is volume without signal
_LOG_EVENT_MARKERS = (
    "dispatch", "agent", "fault", "lease", "tick", "recovery",
)


class TraceRecorder:
    """Tap a live plane's three streams into one timeline.

    ``start()`` installs a WAL journal tap (every line any _Journal in
    the process writes), a structured-log sink (filtered to dispatch/
    agent/fault/lease breadcrumbs), and a supervisor control-IPC tap
    (every command sent to and message received from a worker).
    ``stop()`` removes them and returns the events; with ``path`` set,
    every event is also appended to a JSONL trace file as it happens, so
    a crashed process still leaves its timeline behind."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path
        self.events: List[TraceEvent] = []
        self._lock = _lockcheck.make_lock("scenarios.trace.recorder")
        self._fh = None
        self._started = False

    # -- lifecycle --------------------------------------------------------- #

    def start(self) -> "TraceRecorder":
        from ..runtime import supervisor as supervisor_mod
        from ..storage import durable as durable_mod
        from ..utils import log as log_mod

        if self._started:
            return self
        if self.path:
            self._fh = open(self.path, "a", encoding="utf-8")
        durable_mod.add_journal_tap(self._on_wal_line)
        log_mod.add_sink(self._on_log)
        supervisor_mod.add_ipc_tap(self._on_ipc)
        self._started = True
        return self

    def stop(self) -> List[TraceEvent]:
        from ..runtime import supervisor as supervisor_mod
        from ..storage import durable as durable_mod
        from ..utils import log as log_mod

        if self._started:
            durable_mod.remove_journal_tap(self._on_wal_line)
            log_mod.remove_sink(self._on_log)
            supervisor_mod.remove_ipc_tap(self._on_ipc)
            self._started = False
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        return list(self.events)

    def __enter__(self) -> "TraceRecorder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- taps --------------------------------------------------------------- #

    def _emit(self, ev: TraceEvent) -> None:
        with self._lock:
            self.events.append(ev)
            if self._fh is not None:
                self._fh.write(json.dumps(
                    {"t": ev.ts, "kind": ev.kind, "data": ev.data},
                    separators=(",", ":"), default=str,
                ) + "\n")
                self._fh.flush()

    def _on_wal_line(self, path: str, line: str) -> None:
        try:
            rec = json.loads(line)
        except ValueError:
            return
        self._emit(TraceEvent(
            "wal_record",
            {"segment": os.path.basename(path), "rec": rec},
            ts=round(_time.time(), 3),
        ))

    def _on_log(self, record: dict) -> None:
        event = str(record.get("message", ""))
        if not any(m in event for m in _LOG_EVENT_MARKERS):
            return
        self._emit(TraceEvent(
            "log", dict(record), ts=round(_time.time(), 3),
        ))

    def _on_ipc(self, direction: str, shard, msg: dict) -> None:
        self._emit(TraceEvent(
            "ipc",
            {"direction": direction, "shard": shard,
             "op": msg.get("op", ""),
             "req": msg.get("req"), "epoch": msg.get("epoch")},
            ts=round(_time.time(), 3),
        ))

    # -- compile ------------------------------------------------------------ #

    def spec(self, name: str = "captured-trace", **kw) -> ScenarioSpec:
        return trace_to_spec(list(self.events), name=name, **kw)


def read_trace_file(path: str) -> List[TraceEvent]:
    events: List[TraceEvent] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                continue
            events.append(TraceEvent(
                doc.get("kind", ""), doc.get("data", {}), ts=doc.get("t"),
            ))
    return events


def spec_from_trace_file(path: str, name: str = "captured-trace",
                         **kw) -> ScenarioSpec:
    """Compile a recorder's JSONL trace file back into a replay spec
    (the incident-to-regression path: copy the trace off the box, run
    this, check the spec in)."""
    return trace_to_spec(read_trace_file(path), name=name, **kw)
