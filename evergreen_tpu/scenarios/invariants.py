"""Cross-cutting invariants every scenario asserts.

These are the same contracts the bespoke matrices enforced point-wise
(tools/crash_matrix.py structural checks, tools/overload_matrix.py
zero-silent-discard audit), lifted into one vocabulary the scenario
engine applies to every weather:

  * ``no_duplicate_dispatch`` — at most one host claims a task; claims
    and in-flight statuses agree; no two TASK_DISPATCHED events for the
    same (task, timestamp).
  * ``store_consistent`` — legal task statuses, non-negative executions,
    queue doc columns aligned, no claim of a finished task.
  * ``planning_never_starves`` — every tick with plannable work persisted
    queues, and "planning" never appears in a tick's shed list.
  * ``monotone_epochs`` — the writer-lease epoch observed tick over tick
    never decreases (it strictly increases across a failover).
  * ``counters_match_records`` — the overload ladder's shed counters
    equal the ``overload_sheds`` aggregate records: nothing was dropped
    silently.
  * ``resume_equals_rerun`` — durable runs only: reopening the data dir
    from WAL + snapshot converges to the live store's canonical state.

Each checker takes the finished ScenarioRun and returns None (pass) or a
problem string (fail). The scorecard records one entry per invariant.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..globals import TaskStatus


def check_store_consistent(store) -> List[str]:
    """Structural store invariants (the crash matrix's recovered-store
    checks, applied to any store at any point)."""
    problems: List[str] = []
    legal = {s.value for s in TaskStatus}
    claims: Dict[str, str] = {}
    claimed_tasks = set()
    for doc in store.collection("hosts").find():
        rt = doc.get("running_task", "")
        if not rt:
            continue
        if rt in claimed_tasks:
            problems.append(f"duplicate claim of task {rt}")
        claimed_tasks.add(rt)
        claims[doc["_id"]] = rt
    for doc in store.collection("tasks").find():
        if doc["status"] not in legal:
            problems.append(
                f"illegal status {doc['status']} on {doc['_id']}"
            )
        if doc.get("execution", 0) < 0:
            problems.append(f"negative execution on {doc['_id']}")
        if doc["status"] in ("dispatched", "started"):
            hid = doc.get("host_id", "")
            hdoc = store.collection("hosts").get(hid)
            if hdoc is None or hdoc.get("running_task") != doc["_id"]:
                problems.append(
                    f"in-flight task {doc['_id']} not claimed by "
                    f"host {hid!r}"
                )
    for hid, rt in claims.items():
        tdoc = store.collection("tasks").get(rt)
        if tdoc is None or tdoc["status"] not in ("dispatched", "started"):
            problems.append(
                f"host {hid} claims task {rt} that is not in flight"
            )
    for coll_name in ("task_queues", "task_secondary_queues"):
        for doc in store.collection(coll_name).find():
            n = len(doc.get("rows", []))
            for col in ("sort_value", "dependencies_met"):
                if len(doc.get(col, [])) != n:
                    problems.append(
                        f"misaligned {col} in {coll_name}/{doc['_id']}"
                    )
    return problems


def check_duplicate_dispatch(store) -> List[str]:
    """No two hosts ever won the same dispatch CAS: duplicate
    TASK_DISPATCHED events for one (task, timestamp) mean two winners."""
    problems: List[str] = []
    seen: Dict[tuple, int] = {}
    for doc in store.collection("events").find(
        lambda d: d.get("event_type") == "TASK_DISPATCHED"
    ):
        key = (doc.get("resource_id"), doc.get("timestamp"))
        seen[key] = seen.get(key, 0) + 1
        if seen[key] == 2:
            problems.append(f"duplicate dispatch event {key}")
    return problems


def canonical_state(store) -> dict:
    """The resume ≡ rerun comparison surface: converged task state +
    queue contents (doc versions / timestamps / host identities excluded
    — replays regenerate those; content must not differ)."""
    from ..models.task_queue import doc_column

    tasks = {
        d["_id"]: [d["status"], d.get("execution", 0)]
        for d in store.collection("tasks").find()
    }
    queues = {
        d["_id"]: doc_column(d, "id")
        for d in store.collection("task_queues").find()
    }
    return {"tasks": tasks, "queues": queues}


# --------------------------------------------------------------------------- #
# run-level checkers (fn(run) -> None | problem)
# --------------------------------------------------------------------------- #


def _inv_no_duplicate_dispatch(run) -> Optional[str]:
    problems = check_duplicate_dispatch(run.store)
    return "; ".join(problems[:3]) if problems else None


def _inv_store_consistent(run) -> Optional[str]:
    problems = check_store_consistent(run.store)
    return "; ".join(problems[:3]) if problems else None


def _inv_planning_never_starves(run) -> Optional[str]:
    for i, res in enumerate(run.tick_results):
        if res is None:
            continue  # failover gap: no tick ran this slot
        if "planning" in res.shed:
            return f"tick {i} shed planning"
        if (
            res.degraded not in ("", "fenced")
            and sum(res.queues.values()) == 0
            and res.n_tasks > 0
        ):
            return (
                f"tick {i} degraded={res.degraded!r} persisted no queues "
                f"for {res.n_tasks} plannable tasks"
            )
    return None


def _inv_monotone_epochs(run) -> Optional[str]:
    seq = run.epochs
    for a, b in zip(seq, seq[1:]):
        if b < a:
            return f"lease epoch regressed {a} -> {b}"
    return None


def _inv_counters_match_records(run) -> Optional[str]:
    """Zero-silent-discard audit (the overload matrix's two-books
    balance): the run's overload_sheds_total counter delta must equal
    the sum of the run's ``overload_sheds`` aggregate records (fresh
    store per run, so the records ARE the delta)."""
    from ..utils import overload

    recorded = sum(
        d.get("count", 0)
        for d in run.store.collection(overload.SHEDS_COLLECTION).find()
    )
    counted = run.counter_delta("overload.shed")
    if recorded != counted:
        return (
            f"shed counters ({counted}) != shed records ({recorded}): "
            "something was dropped silently"
        )
    return None


def _inv_resume_equals_rerun(run) -> Optional[str]:
    """Durable runs: a cold reopen of the data dir (WAL replay +
    snapshot) must converge to the live store's canonical state — the
    in-process analog of the crash matrix's restart-and-compare."""
    if not run.spec.durable or run.data_dir is None:
        return None  # in-memory run: nothing to replay
    from ..storage.durable import DurableStore

    run.store.sync_persist()
    recovered = DurableStore(run.data_dir)
    try:
        live = canonical_state(run.store)
        replayed = canonical_state(recovered)
    finally:
        recovered.close()
    if live != replayed:
        diffs = []
        for key in ("tasks", "queues"):
            a, b = live[key], replayed[key]
            for k in sorted(set(a) | set(b)):
                if a.get(k) != b.get(k):
                    diffs.append(f"{key}/{k}: {a.get(k)} != {b.get(k)}")
                if len(diffs) >= 3:
                    break
        return "replay diverged: " + "; ".join(diffs[:3])
    return None


INVARIANT_CHECKS: Dict[str, Callable] = {
    "no_duplicate_dispatch": _inv_no_duplicate_dispatch,
    "store_consistent": _inv_store_consistent,
    "planning_never_starves": _inv_planning_never_starves,
    "monotone_epochs": _inv_monotone_epochs,
    "counters_match_records": _inv_counters_match_records,
    "resume_equals_rerun": _inv_resume_equals_rerun,
}
