"""Global constants for the evergreen_tpu framework.

Mirrors the semantics of the reference's top-level ``globals.go`` constants
(reference: globals.go:185,264,267,301-304) without copying its structure:
only the constants the scheduling/dispatch/agent planes consume are defined,
and numeric scheduling constants also exist as entries in the device-side
settings matrix (see evergreen_tpu/scheduler/snapshot.py).
"""
from __future__ import annotations

import enum

# --------------------------------------------------------------------------- #
# Task statuses (reference: globals.go task status block + apimodels)
# --------------------------------------------------------------------------- #


class TaskStatus(str, enum.Enum):
    UNDISPATCHED = "undispatched"
    DISPATCHED = "dispatched"
    STARTED = "started"
    SUCCEEDED = "success"
    FAILED = "failed"
    ABORTED = "aborted"
    INACTIVE = "inactive"
    # Display-only statuses derived from failure details:
    SYSTEM_FAILED = "system-failed"
    SETUP_FAILED = "setup-failed"
    TIMED_OUT = "task-timed-out"
    BLOCKED = "blocked"
    WILL_RUN = "will-run"


#: Statuses in which a task has finished running.
TASK_COMPLETED_STATUSES = frozenset(
    {TaskStatus.SUCCEEDED.value, TaskStatus.FAILED.value}
)

#: Statuses in which a task occupies (or is about to occupy) a host.
TASK_IN_PROGRESS_STATUSES = frozenset(
    {TaskStatus.DISPATCHED.value, TaskStatus.STARTED.value}
)


class HostStatus(str, enum.Enum):
    """Host lifecycle states (reference: model/host state machine, host.go)."""

    UNINITIALIZED = "initializing"  # intent host, not yet materialized
    BUILDING = "building"
    BUILDING_FAILED = "building-failed"
    STARTING = "starting"
    PROVISIONING = "provisioning"
    PROVISION_FAILED = "provision failed"
    RUNNING = "running"
    STOPPING = "stopping"
    STOPPED = "stopped"
    QUARANTINED = "quarantined"
    DECOMMISSIONED = "decommissioned"
    TERMINATED = "terminated"


#: States counted as "active" capacity by the allocator
#: (reference: model/host/host.go AllActiveHosts / IsActive).
HOST_ACTIVE_STATUSES = frozenset(
    {
        HostStatus.UNINITIALIZED.value,
        HostStatus.BUILDING.value,
        HostStatus.STARTING.value,
        HostStatus.PROVISIONING.value,
        HostStatus.RUNNING.value,
    }
)

HOST_UP_STATUSES = frozenset(
    {
        HostStatus.RUNNING.value,
        HostStatus.STARTING.value,
        HostStatus.PROVISIONING.value,
    }
)


class BuildStatus(str, enum.Enum):
    CREATED = "created"
    STARTED = "started"
    SUCCEEDED = "success"
    FAILED = "failed"


class VersionStatus(str, enum.Enum):
    CREATED = "created"
    STARTED = "started"
    SUCCEEDED = "success"
    FAILED = "failed"


class PatchStatus(str, enum.Enum):
    CREATED = "created"
    STARTED = "started"
    SUCCEEDED = "success"
    FAILED = "failed"
    CANCELLED = "cancelled"


# --------------------------------------------------------------------------- #
# Requesters (reference: globals.go requester constants)
# --------------------------------------------------------------------------- #


class Requester(str, enum.Enum):
    REPOTRACKER = "gitter_request"  # mainline commit builds
    PATCH = "patch_request"  # CLI patches
    GITHUB_PR = "github_pull_request"
    GITHUB_MERGE = "github_merge_request"  # merge queue
    AD_HOC = "ad_hoc"  # periodic builds
    TRIGGER = "trigger_request"  # downstream project triggers


PATCH_REQUESTERS = frozenset(
    {Requester.PATCH.value, Requester.GITHUB_PR.value, Requester.GITHUB_MERGE.value}
)

#: plain-string constant — enum attribute access costs show up in the
#: 50k-task snapshot hot loop
GITHUB_MERGE_REQUESTER = Requester.GITHUB_MERGE.value


def is_patch_requester(requester: str) -> bool:
    return requester in PATCH_REQUESTERS


def is_github_merge_queue_requester(requester: str) -> bool:
    return requester == GITHUB_MERGE_REQUESTER


def is_mainline_requester(requester: str) -> bool:
    return requester in (Requester.REPOTRACKER.value, Requester.AD_HOC.value,
                         Requester.TRIGGER.value)


# --------------------------------------------------------------------------- #
# Task activators (reference: globals.go activator constants)
# --------------------------------------------------------------------------- #

STEPBACK_TASK_ACTIVATOR = "stepback-activator"
API_TASK_ACTIVATOR = "apiv2-task-activator"
GENERATE_TASKS_ACTIVATOR = "generate-tasks-activator"

# --------------------------------------------------------------------------- #
# Scheduling constants (reference: globals.go:185,267; units/host_allocator.go:35)
# --------------------------------------------------------------------------- #

#: Target queue turnaround per host in seconds (reference 30min,
#: globals.go:267 MaxDurationPerDistroHost).
MAX_DURATION_PER_DISTRO_HOST_S = 30 * 60

#: Maximum user-settable task priority (reference globals.go:185).
MAX_TASK_PRIORITY = 100

#: Expected duration assumed for tasks with no runtime history
#: (reference model/task/task.go:65 defaultTaskDuration, 10 min).
DEFAULT_TASK_DURATION_S = 10 * 60

#: Priority value used to disable a task (reference: priority < 0 semantics).
DISABLED_TASK_PRIORITY = -1

#: Global cap on in-flight intent hosts (reference units/host_allocator.go:35).
MAX_INTENT_HOSTS_IN_FLIGHT = 5000

#: Tasks stale in the queue longer than this get unscheduled
#: (reference: task.UnscheduleStaleUnderwaterHostTasks, one week).
UNDERWATER_UNSCHEDULE_THRESHOLD_S = 7 * 24 * 3600

#: Per-task time-in-queue is clamped here in BOTH solver paths (device
#: snapshot + serial oracle + evgpack).  Rationale: the device solve
#: accumulates unit TIQ in float32, and unbounded ages (months-old tasks ×
#: large units) push the sum past the 2^24 mantissa where rounding can flip
#: the floor((tiq/60)/len) rank boundaries against the float64 oracle.  Two
#: weeks is semantically safe: mainline rank already zeroes out past one
#: week (planner.go:253-259) and the underwater unscheduler removes
#: week-old tasks anyway; the clamp just bounds the float32 mass.
MAX_TASK_TIME_IN_QUEUE_S = 14 * 24 * 3600

#: Alert threshold for estimated makespan at max hosts
#: (reference scheduler/wrapper.go:22, 24h).
DYNAMIC_DISTRO_RUNTIME_ALERT_THRESHOLD_S = 24 * 3600

#: generate.tasks limits (reference model/generate.go:24-25).
MAX_GENERATED_BUILD_VARIANTS = 200
MAX_GENERATED_TASKS = 25_000

#: Consecutive system failures before a host is disabled
#: (reference rest/route/host_agent.go:32).
CONSECUTIVE_SYSTEM_FAILURE_THRESHOLD = 3

#: Default seconds between scheduler ticks (reference operations/service.go:99).
SCHEDULER_TICK_INTERVAL_S = 15

#: Suffix appended to a distro id for its secondary-queue alias row.
#: Lives here (not scheduler/wrapper.py) so the snapshot packer and the
#: capacity plane can test for alias rows without importing the wrapper.
ALIAS_SUFFIX = "::alias"

# --------------------------------------------------------------------------- #
# Planner / allocator enum knobs (reference model/distro/distro.go:267-300)
# --------------------------------------------------------------------------- #


class PlannerVersion(str, enum.Enum):
    TUNABLE = "tunable"  # reference's tunable planner semantics, serial
    TPU = "tpu"  # batched JAX solve (this framework's north star)
    #: the reference's alternative comparator-chain planner
    #: (scheduler/task_prioritizer.go); planned host-side per distro
    CMP_BASED = "cmpbased"


class DispatcherVersion(str, enum.Enum):
    REVISED_WITH_DEPENDENCIES = "revised-with-dependencies"


class HostAllocatorVersion(str, enum.Enum):
    UTILIZATION = "utilization"


class FinderVersion(str, enum.Enum):
    LEGACY = "legacy"
    PARALLEL = "parallel"
    PIPELINE = "pipeline"
    ALTERNATE = "alternate"


class RoundingRule(str, enum.Enum):
    DEFAULT = ""
    DOWN = "round-down"
    UP = "round-up"


class FeedbackRule(str, enum.Enum):
    DEFAULT = ""
    WAITS_OVER_THRESH = "waits-over-thresh"
    NO_FEEDBACK = "no-feedback"


class OverallocatedRule(str, enum.Enum):
    DEFAULT = ""
    TERMINATE = "terminate-hosts-when-overallocated"
    IGNORE = "no-terminations-when-overallocated"


# --------------------------------------------------------------------------- #
# Cloud providers (reference cloud/cloud.go provider names)
# --------------------------------------------------------------------------- #


class Provider(str, enum.Enum):
    EC2_FLEET = "ec2-fleet"
    EC2_ONDEMAND = "ec2-ondemand"
    DOCKER = "docker"
    STATIC = "static"
    MOCK = "mock"
    DOCKER_MOCK = "docker-mock"


#: Providers whose hosts are dynamically spawned/terminated
#: (reference distro.IsEphemeral).
EPHEMERAL_PROVIDERS = frozenset(
    {
        Provider.EC2_FLEET.value,
        Provider.EC2_ONDEMAND.value,
        Provider.DOCKER.value,
        Provider.MOCK.value,
        Provider.DOCKER_MOCK.value,
    }
)

#: Sentinel commit-queue boost added to unit priority
#: (reference scheduler/planner.go:299-301).
COMMIT_QUEUE_PRIORITY_BOOST = 200
