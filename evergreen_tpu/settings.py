"""Runtime settings: store-backed config sections + service flags.

Mirrors the reference's two-tier config system (SURVEY §5): a bootstrap
Settings object plus DB-backed config sections editable at runtime
(reference config_sections.go:23-68 registry; config_serviceflags.go
kill-switches checked at the top of every job/route).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Type

from .storage.store import Store

CONFIG_COLLECTION = "config"


class ConfigSection:
    """Subclasses are dataclasses with a ``section_id`` class attr
    (reference ConfigSection interface: SectionId/Get/Set/ValidateAndDefault).
    """

    section_id: str = ""

    @classmethod
    def get(cls, store: Store) -> "ConfigSection":
        doc = store.collection(CONFIG_COLLECTION).get(cls.section_id)
        if doc is None:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})

    def set(self, store: Store) -> None:
        doc = dataclasses.asdict(self)
        doc["_id"] = self.section_id
        store.collection(CONFIG_COLLECTION).upsert(doc)


_SECTIONS: Dict[str, Type[ConfigSection]] = {}


def register_section(cls: Type[ConfigSection]) -> Type[ConfigSection]:
    assert cls.section_id
    _SECTIONS[cls.section_id] = cls
    return cls


def get_section(store: Store, section_id: str) -> Optional[ConfigSection]:
    cls = _SECTIONS.get(section_id)
    return cls.get(store) if cls else None


def all_sections() -> Dict[str, Type[ConfigSection]]:
    return dict(_SECTIONS)


@register_section
@dataclasses.dataclass
class ServiceFlags(ConfigSection):
    """Per-subsystem kill-switches (reference config_serviceflags.go;
    checked e.g. units/scheduler.go:66, rest/route/host_agent.go:168)."""

    section_id = "service_flags"

    scheduler_disabled: bool = False
    host_allocator_disabled: bool = False
    host_init_disabled: bool = False
    monitor_disabled: bool = False
    agent_start_disabled: bool = False
    repotracker_disabled: bool = False
    task_dispatch_disabled: bool = False
    event_processing_disabled: bool = False
    alerts_disabled: bool = False
    background_stats_disabled: bool = False
    task_logging_disabled: bool = False
    cache_stats_job_disabled: bool = False
    stepback_disabled: bool = False
    patching_disabled: bool = False
    generate_tasks_disabled: bool = False


@register_section
@dataclasses.dataclass
class SchedulerConfig(ConfigSection):
    """Global scheduler knobs (reference config_scheduler.go)."""

    section_id = "scheduler"

    target_time_seconds: int = 0
    patch_factor: int = 0
    patch_time_in_queue_factor: int = 0
    commit_queue_factor: int = 0
    mainline_time_in_queue_factor: int = 0
    expected_runtime_factor: int = 0
    generate_task_factor: int = 0
    num_dependents_factor: float = 0.0
    stepback_task_factor: int = 0
    max_scheduled_tasks_per_distro: int = 0


@register_section
@dataclasses.dataclass
class TaskLimitsConfig(ConfigSection):
    """reference config_task_limits.go."""

    section_id = "task_limits"

    max_tasks_per_version: int = 0
    max_pending_generated_tasks: int = 0
    max_generate_task_json_size_kb: int = 0
    max_concurrent_large_parser_project_tasks: int = 0
    max_hourly_patch_tasks: int = 0
    max_exec_timeout_secs: int = 0
    max_task_execution: int = 9  # max automatic restarts


@register_section
@dataclasses.dataclass
class HostInitConfig(ConfigSection):
    """reference config_hostinit.go."""

    section_id = "host_init"

    host_throttle: int = 32
    provisioning_throttle: int = 200
    cloud_status_batch_size: int = 100
    max_total_dynamic_hosts: int = 5000


@register_section
@dataclasses.dataclass
class NotifyConfig(ConfigSection):
    section_id = "notify"

    buffer_target_per_interval: int = 20
    buffer_interval_seconds: int = 60
    eventual_consistency_delay_s: float = 0.0


@register_section
@dataclasses.dataclass
class ApiConfig(ConfigSection):
    """HTTP surface settings (reference config_api.go + the webhook secret
    the GitHub hook route validates against, rest/route/github.go)."""

    section_id = "api"

    url: str = ""
    github_webhook_secret: str = ""
    max_request_body_bytes: int = 32 * 1024 * 1024
