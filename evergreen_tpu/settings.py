"""Runtime settings: store-backed config sections + service flags.

Mirrors the reference's two-tier config system (SURVEY §5): a bootstrap
Settings object plus DB-backed config sections editable at runtime
(reference config_sections.go:23-68 registry; config_serviceflags.go
kill-switches checked at the top of every job/route).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Type

from .storage.store import Store
from .utils import metrics as _metrics

CONFIG_STALE_KEYS = _metrics.counter(
    "config_stale_keys_total",
    "Config-section loads that found keys a migration moved elsewhere "
    "(the silent-weakening failure mode; warned loudly on every load).",
    legacy="config.okta_service.stale_keys",
)

CONFIG_COLLECTION = "config"


class ConfigSection:
    """Subclasses are dataclasses with a ``section_id`` class attr
    (reference ConfigSection interface: SectionId/Get/Set/ValidateAndDefault).
    """

    section_id: str = ""

    @classmethod
    def get_base(cls, store: Store) -> "ConfigSection":
        """The stored section WITHOUT overrides — what admin edits must
        start from, or a get→set round trip would bake override values
        into the base document."""
        doc = store.collection(CONFIG_COLLECTION).get(cls.section_id)
        if doc is None:
            return cls()
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in doc.items() if k in known})

    @classmethod
    def get(cls, store: Store) -> "ConfigSection":
        section = cls.get_base(store)
        if _apply_overrides(store, section):
            # an override produced an invalid section (e.g. a type the
            # validator rejects): fail safe to the stored base rather
            # than hand consumers a poisoned config
            if section.validate_and_default():
                section = cls.get_base(store)
        return section

    def set(self, store: Store) -> None:
        err = self.validate_and_default()
        if err:
            raise ValueError(f"config section {self.section_id!r}: {err}")
        doc = dataclasses.asdict(self)
        doc["_id"] = self.section_id
        store.collection(CONFIG_COLLECTION).upsert(doc)

    def validate_and_default(self) -> str:
        """Normalize fields and return "" or an error message (reference
        ConfigSection.ValidateAndDefault). Subclasses override as needed;
        a failed validation blocks ``set``."""
        return ""


def _apply_overrides(store: Store, section: "ConfigSection") -> bool:
    """Field-level overrides from the ``overrides`` section (reference
    config_overrides.go: Override{SectionID, Field, Value}), applied on
    every read so the stored base document is never clobbered.  Returns
    True iff any override was applied."""
    if section.section_id == OverridesConfig.section_id:
        return False  # the overrides section itself is never overridden
    doc = store.collection(CONFIG_COLLECTION).get(OverridesConfig.section_id)
    if not doc:
        return False
    known = {f.name for f in dataclasses.fields(section)}
    applied = False
    for ov in doc.get("overrides", []):
        if ov.get("section_id") == section.section_id and ov.get("field") in known:
            setattr(section, ov["field"], ov.get("value"))
            applied = True
    return applied


_SECTIONS: Dict[str, Type[ConfigSection]] = {}


def register_section(cls: Type[ConfigSection]) -> Type[ConfigSection]:
    assert cls.section_id
    _SECTIONS[cls.section_id] = cls
    return cls


def get_section(store: Store, section_id: str) -> Optional[ConfigSection]:
    cls = _SECTIONS.get(section_id)
    return cls.get(store) if cls else None


def all_sections() -> Dict[str, Type[ConfigSection]]:
    return dict(_SECTIONS)


@register_section
@dataclasses.dataclass
class ServiceFlags(ConfigSection):
    """Per-subsystem kill-switches (reference config_serviceflags.go;
    checked e.g. units/scheduler.go:66, rest/route/host_agent.go:168)."""

    section_id = "service_flags"

    scheduler_disabled: bool = False
    host_allocator_disabled: bool = False
    host_init_disabled: bool = False
    monitor_disabled: bool = False
    agent_start_disabled: bool = False
    repotracker_disabled: bool = False
    task_dispatch_disabled: bool = False
    event_processing_disabled: bool = False
    alerts_disabled: bool = False
    background_stats_disabled: bool = False
    task_logging_disabled: bool = False
    cache_stats_job_disabled: bool = False
    stepback_disabled: bool = False
    patching_disabled: bool = False
    generate_tasks_disabled: bool = False
    release_mode_disabled: bool = False


@register_section
@dataclasses.dataclass
class SchedulerConfig(ConfigSection):
    """Global scheduler knobs (reference config_scheduler.go)."""

    section_id = "scheduler"

    target_time_seconds: int = 0
    patch_factor: int = 0
    patch_time_in_queue_factor: int = 0
    commit_queue_factor: int = 0
    mainline_time_in_queue_factor: int = 0
    expected_runtime_factor: int = 0
    generate_task_factor: int = 0
    num_dependents_factor: float = 0.0
    stepback_task_factor: int = 0
    max_scheduled_tasks_per_distro: int = 0


@register_section
@dataclasses.dataclass
class ShardingConfig(ConfigSection):
    """Sharded control plane knobs (scheduler/sharded_plane.py +
    parallel/topology.py). ``n_shards`` > 1 turns the 15s tick into a
    fleet round over N scheduler shards — each with its own lease, WAL
    segment and resident plane — partitioned by consistent hash. See
    docs/DEPLOY.md "Shard count sizing"."""

    section_id = "sharding"

    #: 1 = the classic single-scheduler plane
    n_shards: int = 1
    #: stacked multi-device solve: "auto" (stack when the backend has
    #: >= n_shards devices), "never", "always"
    stacked_solve: str = "auto"
    #: ladder-driven distro migration off YELLOW shards
    rebalance_enabled: bool = True
    #: whole-distro handoffs a single round may initiate (migrations are
    #: cheap but re-prime the target's caches — trickle, don't slosh)
    max_handoffs_per_round: int = 1
    #: stacked-round barrier timeout before per-shard local solves
    barrier_timeout_s: float = 30.0
    # -- process-per-shard runtime (runtime/supervisor.py; `service
    # -- --shards N`) -------------------------------------------------
    #: per-shard lease TTL in process mode — ALSO the worst-case fenced
    #: takeover latency (a replacement steals a dead worker's lease
    #: only after it goes stale)
    worker_lease_ttl_s: float = 5.0
    #: worker heartbeat cadence on the control pipe
    worker_heartbeat_s: float = 1.0
    #: missed-heartbeat deadline after which the supervisor kills +
    #: restarts a worker (hang / pipe-partition detection)
    worker_heartbeat_deadline_s: float = 5.0
    #: exponential restart backoff bounds (PR-1 RetryPolicy shape)
    worker_restart_backoff_s: float = 0.25
    worker_restart_backoff_max_s: float = 30.0
    #: how long a worker outlives a dead supervisor (orphan mode: keeps
    #: its shard lease, ticks locally, waits for adoption on its
    #: control socket), then drains and releases; 0 restores the old
    #: exit-on-EOF behavior. This bounds a supervisor outage's blast
    #: radius: restart within the grace = zero lost work
    orphan_grace_s: float = 300.0
    #: worker-side command-staleness deadline (one-way partition
    #: detection): an ATTACHED worker that hears no supervisor command
    #: for this long — while its own heartbeats may still be getting
    #: through — enters orphan mode (bounded by orphan_grace_s) instead
    #: of trusting the silent channel forever; a resumed command heals
    #: it in place. Must comfortably exceed the round cadence; 0
    #: disables the deadline
    worker_command_silence_s: float = 120.0
    #: fleet-scope supervisor lease TTL — ALSO the worst-case takeover
    #: latency after a supervisor death (the successor steals the
    #: fencing epoch only once the lease goes stale)
    supervisor_lease_ttl_s: float = 5.0
    #: solver-leader plane (runtime/solver.py): "auto" serves one
    #: stacked shard_map solve per fleet round over cross-process
    #: shared-memory arenas when the backend has >= n_shards devices;
    #: "never" keeps every worker on its local solve
    solver_leader: str = "auto"
    #: solver lease TTL — worst-case window a dead leader's rounds
    #: degrade to local solves before a successor steals the lease at
    #: a strictly higher epoch (independent of the supervisor lease:
    #: data plane and control plane re-elect separately)
    solver_lease_ttl_s: float = 5.0
    #: per-round worker wait on the leader's solved block before the
    #: local-solve fallback (see docs/DEPLOY.md "Solver-leader sizing")
    solver_timeout_s: float = 10.0

    def validate_and_default(self) -> str:
        if self.n_shards < 1:
            return "n_shards must be >= 1"
        if self.stacked_solve not in ("auto", "never", "always"):
            return "stacked_solve must be auto/never/always"
        if self.max_handoffs_per_round < 0:
            return "max_handoffs_per_round cannot be negative"
        if self.barrier_timeout_s <= 0:
            return "barrier_timeout_s must be > 0"
        if self.worker_lease_ttl_s <= 0:
            return "worker_lease_ttl_s must be > 0"
        if self.worker_heartbeat_s <= 0:
            return "worker_heartbeat_s must be > 0"
        if self.worker_heartbeat_deadline_s < self.worker_heartbeat_s:
            return (
                "worker_heartbeat_deadline_s must be >= "
                "worker_heartbeat_s"
            )
        if self.worker_restart_backoff_s <= 0:
            return "worker_restart_backoff_s must be > 0"
        if (self.worker_restart_backoff_max_s
                < self.worker_restart_backoff_s):
            return (
                "worker_restart_backoff_max_s must be >= "
                "worker_restart_backoff_s"
            )
        if self.orphan_grace_s < 0:
            return "orphan_grace_s cannot be negative"
        if self.worker_command_silence_s < 0:
            return "worker_command_silence_s cannot be negative"
        if self.supervisor_lease_ttl_s <= 0:
            return "supervisor_lease_ttl_s must be > 0"
        if self.solver_leader not in ("auto", "never"):
            return "solver_leader must be auto/never"
        if self.solver_lease_ttl_s <= 0:
            return "solver_lease_ttl_s must be > 0"
        if self.solver_timeout_s <= 0:
            return "solver_timeout_s must be > 0"
        return ""


@register_section
@dataclasses.dataclass
class CapacityConfig(ConfigSection):
    """Capacity-plane knobs (ops/capacity.py program weights + the pool
    vocabulary's prices and quotas; consumed by
    scheduler/capacity_plane.py). Pools are providers — a distro's
    hosts can only come from its own provider — so both dicts are keyed
    by provider name ("ec2-fleet", "docker", …). Per-distro opt-in is
    separate: ``planner_settings.capacity = "tpu"`` on the distro. See
    docs/DEPLOY.md "Capacity plane tuning"."""

    section_id = "capacity"

    #: master switch; off = every distro uses the per-distro heuristic
    enabled: bool = True
    #: relative $/host-hour per pool; empty falls back to the provider
    #: defaults (cloud/manager.py default_pool_prices)
    pool_prices: Dict[str, float] = dataclasses.field(default_factory=dict)
    #: hard per-pool host caps over capacity-managed distros (0/absent =
    #: unlimited)
    pool_quotas: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: weight of the provider-price objective term (0 = drain-only).
    #: Keep both weights small relative to the drain term's marginal
    #: host value (demand/x² in threshold units) or the program pins
    #: every distro to its current fleet — see DEPLOY.md.
    price_weight: float = 0.02
    #: weight of the churn/preemption term penalizing targets far from
    #: the current fleet (spawn storms AND drawdown storms); quadratic
    #: in the host delta, so keep it small — it is a tiebreaker, not a
    #: rival of the drain term
    preemption_cost: float = 0.001
    #: fleet-wide cap on new hosts one capacity solve may request
    #: (0 → globals.MAX_INTENT_HOSTS_IN_FLIGHT)
    fleet_intent_budget: int = 0
    #: damped-Newton + projection sweeps on device
    iterations: int = 48
    #: fused capacity solve (ops/solve.py capacity_affinity): "auto"
    #: rides the capacity program + pool affinity inside the ONE packed
    #: planning solve whenever the tick's preconditions hold (packed
    #: solve succeeded, no cmp-planned distros, fused breaker closed);
    #: "two_call" still packs the capacity page (the device block runs
    #: and its outputs are discarded) but serves from the dedicated
    #: second solve — the A/B knob for the fused-vs-fallback rung
    #: comparison without sabotage faults; "never" skips the page
    #: entirely and pins the classic pre-fused pipeline
    fused: str = "auto"
    #: initial softmax temperature of the annealed task-group→pool
    #: affinity block (higher = softer early assignments)
    affinity_temperature: float = 1.0
    #: per-iteration temperature decay (clipped to [0.5, 1.0] on
    #: device; values near 1 anneal slowly)
    affinity_anneal: float = 0.92

    def validate_and_default(self) -> str:
        if self.price_weight < 0 or self.preemption_cost < 0:
            return "capacity weights must be >= 0"
        if self.fleet_intent_budget < 0:
            return "fleet_intent_budget must be >= 0"
        if not isinstance(self.iterations, int) or not (
            1 <= self.iterations <= 512
        ):
            return "iterations must be an int in [1, 512]"
        if self.fused not in ("auto", "two_call", "never"):
            return "fused must be auto/two_call/never"
        if self.affinity_temperature <= 0:
            return "affinity_temperature must be > 0"
        if not 0.5 <= self.affinity_anneal <= 1.0:
            return "affinity_anneal must be in [0.5, 1.0]"
        for name, d in (("pool_prices", self.pool_prices),
                        ("pool_quotas", self.pool_quotas)):
            if not isinstance(d, dict):
                return f"{name} must be a mapping"
            for k, v in d.items():
                if not isinstance(v, (int, float)) or v < 0:
                    return f"{name}[{k!r}] must be a number >= 0"
        return ""


@register_section
@dataclasses.dataclass
class TaskLimitsConfig(ConfigSection):
    """reference config_task_limits.go."""

    section_id = "task_limits"

    max_tasks_per_version: int = 0
    max_pending_generated_tasks: int = 0
    max_generate_task_json_size_kb: int = 0
    max_concurrent_large_parser_project_tasks: int = 0
    max_hourly_patch_tasks: int = 0
    max_exec_timeout_secs: int = 0
    max_task_execution: int = 9  # max automatic restarts


@register_section
@dataclasses.dataclass
class HostInitConfig(ConfigSection):
    """reference config_hostinit.go."""

    section_id = "host_init"

    host_throttle: int = 32
    provisioning_throttle: int = 200
    cloud_status_batch_size: int = 100
    max_total_dynamic_hosts: int = 5000


@register_section
@dataclasses.dataclass
class NotifyConfig(ConfigSection):
    section_id = "notify"

    buffer_target_per_interval: int = 20
    buffer_interval_seconds: int = 60
    eventual_consistency_delay_s: float = 0.0
    #: master egress switch: off (the in-image default) leaves deliveries
    #: in the per-channel outboxes; on drains them through the real
    #: transports (events/transports.py)
    egress_enabled: bool = False
    smtp_host: str = ""
    smtp_port: int = 25
    smtp_from: str = "evergreen@localhost"
    webhook_timeout_s: float = 10.0
    github_api_url: str = "https://api.github.com"
    github_status_token: str = ""


@register_section
@dataclasses.dataclass
class ApiConfig(ConfigSection):
    """HTTP surface settings (reference config_api.go + the webhook secret
    the GitHub hook route validates against, rest/route/github.go)."""

    section_id = "api"

    url: str = ""
    github_webhook_secret: str = ""
    #: path token for the SNS intake route /hooks/aws/{token} (reference
    #: sns.go verifies the signed SNS payload; zero-egress deployments
    #: cannot fetch the signing cert, so the subscribe URL carries this
    #: secret instead)
    sns_secret: str = ""
    max_request_body_bytes: int = 32 * 1024 * 1024


@register_section
@dataclasses.dataclass
class OverridesConfig(ConfigSection):
    """Field-level overrides over other sections (reference
    config_overrides.go Override{SectionID, Field, Value})."""

    section_id = "overrides"

    #: list of {"section_id": ..., "field": ..., "value": ...}
    overrides: List[Dict] = dataclasses.field(default_factory=list)

    def validate_and_default(self) -> str:
        for ov in self.overrides:
            if not ov.get("section_id") or not ov.get("field"):
                return "every override needs section_id and field"
            if "value" not in ov:
                return f"override of {ov['field']!r} has no value"
            if ov["section_id"] == self.section_id:
                return "the overrides section cannot override itself"
            cls = _SECTIONS.get(ov["section_id"])
            if cls is None:
                return f"unknown section {ov['section_id']!r}"
            if ov["field"] not in {f.name for f in dataclasses.fields(cls)}:
                return (
                    f"section {ov['section_id']!r} has no field "
                    f"{ov['field']!r}"
                )
        return ""


@register_section
@dataclasses.dataclass
class AuthConfig(ConfigSection):
    """User-manager selection + per-manager settings (reference
    config_auth.go:103-116; consumed by auth.load_user_manager)."""

    section_id = "auth"

    #: naive | github | okta | api_only | external | multi
    preferred_type: str = "naive"
    allow_service_users: bool = False
    background_reauth_minutes: int = 0
    #: manager kinds chained in order when preferred_type == "multi"
    #: (reference AuthConfig.Multi read-write list)
    multi_managers: List[str] = dataclasses.field(default_factory=list)
    #: naive manager: [{"username", "password"|"sha256:<hex>",
    #: "display_name", "email"}] (reference NaiveAuthConfig.Users)
    naive_users: List[Dict] = dataclasses.field(default_factory=list)
    github_client_id: str = ""
    github_client_secret: str = ""
    github_organization: str = ""
    #: explicit GitHub allow-list admitted without org membership
    github_users: List[str] = dataclasses.field(default_factory=list)
    okta_client_id: str = ""
    okta_client_secret: str = ""
    okta_issuer: str = ""
    #: DELIBERATE EXTENSION — no reference counterpart. The reference's
    #: Okta config (config_okta_service.go:14-19) carries only
    #: ClientID/ClientSecret/Scopes/Audience/Issuer; this group gate and
    #: ``okta_expected_email_domains`` below are this repo's additional
    #: interactive-login guards (kept on purpose, VERDICT r5 ask #8).
    okta_user_group: str = ""
    #: OIDC scopes requested on the authorize redirect (reference
    #: OktaConfig.Scopes, config_auth.go:38-44); empty uses the
    #: manager's openid/email/profile/groups default
    okta_scopes: List[str] = dataclasses.field(default_factory=list)
    #: DELIBERATE EXTENSION — see okta_user_group above
    okta_expected_email_domains: List[str] = dataclasses.field(
        default_factory=list
    )
    external_validation_url: str = ""
    #: when True the loader builds REAL IdP HTTP clients (GitHub token
    #: exchange, OIDC code exchange + JWKS verification); off — the
    #: in-image default — keeps the injectable fakes (same seam as
    #: NotifyConfig.egress_enabled / events/transports.py)
    egress_enabled: bool = False

    def validate_and_default(self) -> str:
        kinds = ("naive", "github", "okta", "api_only", "external")
        if self.preferred_type not in kinds + ("multi",):
            return f"unknown auth manager type {self.preferred_type!r}"
        if self.preferred_type == "multi" and not self.multi_managers:
            return "multi auth needs a multi_managers list"
        for k in self.multi_managers:
            if k not in kinds:
                return f"unknown manager kind {k!r} in multi_managers"
        for u in self.naive_users:
            if not u.get("username"):
                return "every naive auth user needs a username"
        return ""


@register_section
@dataclasses.dataclass
class RepotrackerConfig(ConfigSection):
    """reference config_repotracker.go:11-15."""

    section_id = "repotracker"

    revs_to_fetch: int = 25
    max_revs_to_search: int = 50
    max_concurrent_requests: int = 0

    def validate_and_default(self) -> str:
        if self.revs_to_fetch <= 0:
            self.revs_to_fetch = 25
        if self.max_revs_to_search <= 0:
            self.max_revs_to_search = 2 * self.revs_to_fetch
        return ""


@register_section
@dataclasses.dataclass
class UiConfig(ConfigSection):
    """reference config_ui.go:18-33."""

    section_id = "ui"

    url: str = ""
    http_listen_addr: str = ""
    secret: str = ""
    default_project: str = ""
    csrf_key: str = ""
    cors_origins: List[str] = dataclasses.field(default_factory=list)
    login_domain: str = ""
    #: site-wide announcement banner (reference admin settings Banner /
    #: BannerTheme, surfaced to Spruce via spruceConfig)
    banner: str = ""
    banner_theme: str = "ANNOUNCEMENT"

    def validate_and_default(self) -> str:
        if self.csrf_key and len(self.csrf_key) != 32:
            return "csrf_key must be 32 characters"
        return ""


@register_section
@dataclasses.dataclass
class RateLimitConfig(ConfigSection):
    """Per-surface request budgets (reference config_ratelimit.go;
    consumed by RestApi's limiter when no explicit limit is passed)."""

    section_id = "rate_limit"

    requests_per_minute: int = 0  # 0 = unlimited
    pre_auth_multiplier: int = 4

    def validate_and_default(self) -> str:
        if self.pre_auth_multiplier <= 0:
            self.pre_auth_multiplier = 4
        return ""


@register_section
@dataclasses.dataclass
class ReadPathConfig(ConfigSection):
    """Read-serving plane knobs (ISSUE 11): follower reads off the WAL-
    tailing replica, the fingerprint ETag/response cache, and the
    sharded long-poll dispatch hub. Consumed by api/rest.py (routing +
    cache), api/readcache.py, storage/replica.py (poll cadence is the
    replica's own knob), and dispatch/longpoll.py."""

    section_id = "read_path"

    #: master switch for replica-backed follower reads (the ETag cache
    #: and long-poll hub have their own switches below)
    follower_reads_enabled: bool = True
    #: serve a list/read from the replica only when its staleness is
    #: under this bound; above it the primary serves as before
    staleness_bound_ms: float = 2000.0
    #: at RED, expensive reads degrade to replica serving under this
    #: LOOSER bound (with a Warning header) before falling back to 429
    degraded_staleness_bound_ms: float = 30000.0
    #: readiness probe (GET /healthz/ready): a replica-process server
    #: answers 503 once its staleness exceeds this, so load balancers
    #: stop routing to a lagging follower. 0 = fall back to
    #: staleness_bound_ms. Deliberately looser than the serving bound:
    #: a replica slightly over the SERVING bound still forwards reads
    #: to the primary, which beats ejecting it from rotation.
    readiness_staleness_bound_ms: float = 10000.0
    #: fingerprint ETag + in-process response cache
    cache_enabled: bool = True
    cache_max_entries: int = 256
    #: long-poll dispatch: agents may park on next_task up to this long
    #: (?wait= is clamped to it); 0 disables server-side parking
    longpoll_max_wait_s: float = 30.0
    #: condition-variable shards the parked agents spread across (bounds
    #: the wake-storm convoy on any single mutex)
    longpoll_shards: int = 32
    #: parked waiters re-check their queue generation at least this
    #: often even without a wake (starvation bound for bounded wakes)
    longpoll_recheck_s: float = 1.0

    def validate_and_default(self) -> str:
        if (
            self.staleness_bound_ms < 0
            or self.degraded_staleness_bound_ms < 0
            or self.readiness_staleness_bound_ms < 0
        ):
            return "staleness bounds must be >= 0"
        if self.degraded_staleness_bound_ms < self.staleness_bound_ms:
            return (
                "degraded_staleness_bound_ms must be >= staleness_bound_ms"
            )
        if self.cache_max_entries < 0:
            return "cache_max_entries must be >= 0"
        if self.longpoll_max_wait_s < 0 or self.longpoll_recheck_s <= 0:
            return "long-poll waits must be >= 0 (recheck > 0)"
        if self.longpoll_shards < 1:
            self.longpoll_shards = 1
        return ""


@register_section
@dataclasses.dataclass
class OverloadConfig(ConfigSection):
    """Overload-protection ladder knobs (consumed by
    utils/overload.LoadMonitor and every seam that consults it: the
    JobQueue's bounded pending set, the event outbox caps, the REST
    surface's adaptive 429s, the tick pipeline's brownout shedding).

    Each ``*_levels`` list is the [yellow, red, black] threshold triple
    for one fused signal; a 0 threshold disables that rung for that
    signal. See docs/DEPLOY.md "Overload & brownout tuning"."""

    section_id = "overload"

    enabled: bool = True
    #: the scheduler cadence the tick-lag signal is measured against
    tick_cadence_s: float = 15.0
    #: consecutive calm evaluations before the level steps DOWN
    hysteresis_ticks: int = 3
    #: how often gauge pushes may auto-re-evaluate the ladder
    eval_interval_s: float = 1.0
    #: hard cap on the JobQueue pending set (sheds lowest class only)
    queue_max_pending: int = 1000
    #: hard cap on undelivered rows per notification outbox collection
    outbox_cap: int = 5000
    queue_pending_levels: List[float] = dataclasses.field(
        default_factory=lambda: [200.0, 500.0, 1000.0]
    )
    outbox_depth_levels: List[float] = dataclasses.field(
        default_factory=lambda: [1000.0, 3000.0, 5000.0]
    )
    wal_backlog_levels: List[float] = dataclasses.field(
        default_factory=lambda: [4.0, 16.0, 64.0]
    )
    store_latency_ms_levels: List[float] = dataclasses.field(
        default_factory=lambda: [250.0, 1000.0, 5000.0]
    )
    #: seconds the tick runs PAST its cadence
    tick_lag_levels_s: List[float] = dataclasses.field(
        default_factory=lambda: [10.0, 30.0, 90.0]
    )
    api_rps_levels: List[float] = dataclasses.field(
        default_factory=lambda: [200.0, 500.0, 2000.0]
    )
    #: Retry-After the API sends while shedding at each level
    retry_after_red_s: float = 30.0
    retry_after_black_s: float = 60.0

    def validate_and_default(self) -> str:
        for name in (
            "queue_pending_levels",
            "outbox_depth_levels",
            "wal_backlog_levels",
            "store_latency_ms_levels",
            "tick_lag_levels_s",
            "api_rps_levels",
        ):
            levels = getattr(self, name)
            if not isinstance(levels, list) or len(levels) != 3:
                return f"{name} must be a [yellow, red, black] triple"
            if any(not isinstance(v, (int, float)) or v < 0 for v in levels):
                return f"{name} entries must be numbers >= 0"
            active = [v for v in levels if v > 0]
            if active != sorted(active):
                return f"{name} must be non-decreasing"
        if self.hysteresis_ticks < 1:
            self.hysteresis_ticks = 1
        if self.queue_max_pending < 0 or self.outbox_cap < 0:
            return "caps cannot be negative"
        return ""


@register_section
@dataclasses.dataclass
class SpawnHostConfig(ConfigSection):
    """reference config_spawnhost.go."""

    section_id = "spawnhost"

    unexpirable_hosts_per_user: int = 1
    unexpirable_volumes_per_user: int = 1
    spawn_hosts_per_user: int = 3
    max_volume_size_gb: int = 500

    def validate_and_default(self) -> str:
        if self.spawn_hosts_per_user < 0:
            return "spawn_hosts_per_user cannot be negative"
        return ""


@register_section
@dataclasses.dataclass
class SleepScheduleConfig(ConfigSection):
    """reference config_sleep_schedule.go."""

    section_id = "sleep_schedule"

    permanently_exempt_hosts: List[str] = dataclasses.field(
        default_factory=list
    )


@register_section
@dataclasses.dataclass
class TriggerConfig(ConfigSection):
    """Downstream project triggers (reference config_triggers.go)."""

    section_id = "triggers"

    generate_task_distro: str = ""


@register_section
@dataclasses.dataclass
class LoggerConfig(ConfigSection):
    """reference config_logger.go."""

    section_id = "logger_config"

    buffer_count: int = 100
    buffer_interval_seconds: int = 20
    default_level: str = "info"
    #: fraction of HTTP requests logged as structured records (reference
    #: service/sampled_request_logger.go); 0 disables
    request_sample_ratio: float = 0.0

    def validate_and_default(self) -> str:
        if self.default_level not in ("debug", "info", "warning", "error"):
            return f"unknown log level {self.default_level!r}"
        # overrides arrive untyped — a TypeError here would defeat the
        # fail-safe-to-base path in ConfigSection.get
        if not isinstance(self.request_sample_ratio, (int, float)) or (
            not 0.0 <= self.request_sample_ratio <= 1.0
        ):
            return "request_sample_ratio must be a number within [0, 1]"
        return ""


@register_section
@dataclasses.dataclass
class AmboyConfig(ConfigSection):
    """Background job plane sizing (reference config_amboy.go; consumed
    by queue.jobs.JobQueue via cli service startup)."""

    section_id = "amboy"

    pool_size_local: int = 8
    retry_max_attempts: int = 10
    lock_timeout_minutes: int = 10

    def validate_and_default(self) -> str:
        if self.pool_size_local <= 0:
            self.pool_size_local = 8
        return ""


@register_section
@dataclasses.dataclass
class CloudProvidersConfig(ConfigSection):
    """Provider credentials/regions (reference config_cloud.go — secrets
    referenced via the parameter store, never inline)."""

    section_id = "providers"

    aws_default_region: str = "us-east-1"
    aws_allowed_regions: List[str] = dataclasses.field(
        default_factory=lambda: ["us-east-1"]
    )
    aws_parameter_prefix: str = ""
    docker_default_registry: str = ""


@register_section
@dataclasses.dataclass
class ContainerPoolsConfig(ConfigSection):
    """reference config_containerpools.go:10-28."""

    section_id = "container_pools"

    #: list of {"id": ..., "distro": ..., "max_containers": N, "port": N}
    pools: List[Dict] = dataclasses.field(default_factory=list)

    def validate_and_default(self) -> str:
        seen = set()
        for p in self.pools:
            if not p.get("id"):
                return "every container pool needs an id"
            if p["id"] in seen:
                return f"duplicate container pool id {p['id']!r}"
            seen.add(p["id"])
            if int(p.get("max_containers", 0)) <= 0:
                return f"pool {p['id']!r} needs max_containers > 0"
        return ""


@register_section
@dataclasses.dataclass
class CostConfig(ConfigSection):
    """reference config_cost.go (cost attribution at MarkEnd)."""

    section_id = "cost"

    financial_formula_savings_plan_rate: float = 0.0
    on_demand_discount: float = 0.0
    savings_plan_discount: float = 0.0


@register_section
@dataclasses.dataclass
class ParameterStoreConfig(ConfigSection):
    """reference cloud/parameterstore config section."""

    section_id = "parameter_store"

    prefix: str = ""


@register_section
@dataclasses.dataclass
class ProjectCreationConfig(ConfigSection):
    """reference config_project_creation.go."""

    section_id = "project_creation"

    total_project_limit: int = 0
    repo_project_limit: int = 0
    jira_project: str = ""


@register_section
@dataclasses.dataclass
class SingleTaskDistroConfig(ConfigSection):
    """reference config_single_task_distro.go."""

    section_id = "single_task_distro"

    #: project -> allowed task name patterns
    project_tasks_pairs: List[Dict] = dataclasses.field(default_factory=list)


@register_section
@dataclasses.dataclass
class TestSelectionConfig(ConfigSection):
    """reference config_test_selection.go."""

    section_id = "test_selection"

    url: str = ""
    default_strategies: List[str] = dataclasses.field(default_factory=list)


@register_section
@dataclasses.dataclass
class TracerConfig(ConfigSection):
    """OTel-shaped trace export (reference config_tracer.go:11-23;
    consumed by utils/tracing.py's exporter)."""

    section_id = "tracer"

    enabled: bool = False
    collector_endpoint: str = ""
    sample_ratio: float = 1.0
    #: when set, the batched solve runs under the XLA/JAX profiler and
    #: writes its trace here (SURVEY §5's TPU-equivalent ask: profiler
    #: hooks next to the OTel control-plane spans)
    xla_profile_dir: str = ""

    def validate_and_default(self) -> str:
        if not isinstance(self.sample_ratio, (int, float)) or (
            not 0.0 <= self.sample_ratio <= 1.0
        ):
            return "sample_ratio must be a number within [0, 1]"
        if self.enabled and not self.collector_endpoint:
            return "enabled tracer needs a collector_endpoint"
        return ""


@register_section
@dataclasses.dataclass
class SlackConfig(ConfigSection):
    """reference config.go SlackConfig (notification channel)."""

    section_id = "slack"

    token: str = ""
    level: str = "error"
    name: str = ""
    #: message-post endpoint; configurable so tests aim a local fake
    api_url: str = ""


@register_section
@dataclasses.dataclass
class JiraConfig(ConfigSection):
    """reference config.go JIRAConfig (build-baron ticketing)."""

    section_id = "jira"

    host: str = ""
    default_project: str = ""
    email: str = ""


@register_section
@dataclasses.dataclass
class SplunkConfig(ConfigSection):
    """reference config_splunk.go (log shipping)."""

    section_id = "splunk"

    server_url: str = ""
    token: str = ""
    channel: str = ""


@register_section
@dataclasses.dataclass
class GithubCheckRunConfig(ConfigSection):
    """reference config_github_check_run.go."""

    section_id = "github_check_run"

    check_run_limit: int = 0


@register_section
@dataclasses.dataclass
class BucketsConfig(ConfigSection):
    """Blob-store layout for task output (reference config_buckets.go;
    consumed by models/artifact.py's content-addressed store)."""

    section_id = "buckets"

    log_bucket_name: str = ""
    test_results_bucket_name: str = ""
    long_retention_name: str = ""


@register_section
@dataclasses.dataclass
class OktaServiceConfig(ConfigSection):
    """Machine-to-machine Okta/OIDC credentials (reference
    config_okta_service.go:14-19: ClientID, ClientSecret, Scopes,
    Audience, Issuer — used for token-exchange grants, e.g. the spawn
    host workflow). The user-manager loader (api/auth.py
    load_user_manager) falls back to this section when the auth
    section's okta fields are empty — one credential set can serve both
    interactive login and service auth. Unlike the auth section it
    carries no user-group or email-domain fields: those gate
    interactive logins only, and are DELIBERATE EXTENSIONS of the auth
    section beyond config_okta_service.go:14-19 (see
    AuthConfig.okta_user_group / okta_expected_email_domains)."""

    section_id = "okta_service"

    client_id: str = ""
    client_secret: str = ""
    scopes: List[str] = dataclasses.field(default_factory=list)
    audience: str = ""
    issuer: str = ""

    #: legacy keys from when the interactive-login gates lived on THIS
    #: section; migration 0004 copies them into the auth section (where
    #: load_user_manager actually reads them) — a stored doc still
    #: carrying them predates the migration or was written by old code
    STALE_KEYS = ("user_group", "expected_email_domains")

    @classmethod
    def get_base(cls, store: Store) -> "ConfigSection":
        doc = store.collection(CONFIG_COLLECTION).get(cls.section_id)
        if doc:
            stale = [k for k in cls.STALE_KEYS if k in doc]
            if stale:
                # LOUD on every load: an operator who upgraded with
                # these keys set believes a login gate is active that
                # this section no longer enforces (the silent-weakening
                # failure mode) — migration 0004 copies the values to
                # auth.okta_user_group / auth.okta_expected_email_domains
                from .utils.log import get_logger

                CONFIG_STALE_KEYS.inc()
                get_logger("config").warning(
                    "okta_service carries stale login-gate keys — the "
                    "group/email-domain gates are enforced from the "
                    "auth section only (see migration "
                    "0004-okta-service-gates-to-auth)",
                    stale_keys=stale,
                )
        return super().get_base(store)

    def validate(self) -> str:
        """Full-credential check for when the token-exchange flow runs
        (reference config_okta_service.go Validate — deliberately NOT
        part of validate_and_default, which accepts an empty section)."""
        missing = [
            name
            for name, val in (
                ("client_id", self.client_id),
                ("client_secret", self.client_secret),
                ("scopes", self.scopes),
                ("audience", self.audience),
                ("issuer", self.issuer),
            )
            if not val
        ]
        return ", ".join(f"{m} is required" for m in missing)


@register_section
@dataclasses.dataclass
class SshConfig(ConfigSection):
    """SSH key pairs + connection options for host transports (reference
    config_ssh.go SSHConfig/SSHKeyPair; consumed by
    cloud/provisioning.py SshTransport when a distro bootstraps over
    ssh)."""

    section_id = "ssh"

    task_host_key_name: str = ""
    #: private-key file path (the reference stores a Secrets Manager ARN;
    #: here the parameter-store seam or a file path)
    task_host_key_path: str = ""
    spawn_host_key_name: str = ""
    spawn_host_key_path: str = ""
    user: str = "ubuntu"
    connect_timeout_s: float = 10.0
    #: bound on one deploy/setup script run — unrelated to connect time
    #: (package installs on first provision can take minutes)
    script_timeout_s: float = 1800.0
    #: extra -o options, e.g. ["StrictHostKeyChecking=no"]
    options: List[str] = dataclasses.field(default_factory=list)


@register_section
@dataclasses.dataclass
class JiraNotificationsConfig(ConfigSection):
    """Per-project custom fields/components/labels stamped onto created
    Jira issues (reference config_jira_notifications.go; consumed by
    events/transports.py JiraTransport)."""

    section_id = "jira_notifications"

    #: project key → {"fields": {name: value}, "components": [...],
    #: "labels": [...]}
    custom_fields: Dict[str, Dict] = dataclasses.field(default_factory=dict)


@register_section
@dataclasses.dataclass
class ReleaseModeConfig(ConfigSection):
    """Release-window scheduler overrides (reference
    config_release_mode.go, applied in distro settings resolution
    model/distro/distro.go:680-748): scale auto-tunable distros' max
    hosts, and override planner target time / host idle time. Gated by
    service_flags.release_mode_disabled. Consumed by
    scheduler/wrapper.py (settings resolution) and
    units/host_jobs.py (idle termination)."""

    section_id = "release_mode"

    distro_max_hosts_factor: float = 0.0
    target_time_seconds_override: int = 0
    idle_time_seconds_override: int = 0

    def validate_and_default(self) -> str:
        if self.distro_max_hosts_factor < 0:
            return "distro_max_hosts_factor must be >= 0"
        if self.target_time_seconds_override < 0:
            return "target_time_seconds_override must be >= 0"
        if self.idle_time_seconds_override < 0:
            # a negative cutoff would instantly reap every free host
            return "idle_time_seconds_override must be >= 0"
        return ""
