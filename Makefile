# Workflow entry points. `make hooks` once per clone; after that every
# `git commit` runs the full-suite gate (tools/hooks/pre-commit) and a
# red suite refuses the commit — this is the only documented commit path.

.PHONY: test gate hooks bench multichip native

hooks:
	sh tools/install_hooks.sh

test:
	python -m pytest tests/ -q

gate:
	python tools/gate.py

bench:
	python bench.py

multichip:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

native:
	$(MAKE) -C native/evgsolve
	python -c "from evergreen_tpu.utils.native import get_evgpack; \
	           print('evgpack:', get_evgpack())"
