# Workflow entry points. The ONLY documented commit path is
#
#     make commit MSG="what the milestone is"
#
# which runs the full-suite gate UNCONDITIONALLY (no skip env, no
# --no-verify analogue) and only then commits the staged+working tree.
# Every gate run — from this target or the hook — is appended to
# GATE_LOG.jsonl with the outcome, so a skipped gate is visible in
# history. `make hooks` additionally installs the pre-commit hook as
# belt-and-suspenders for anyone committing by hand.

.PHONY: test gate hooks bench multichip native commit perf-guard crash-matrix disk-matrix net-matrix overload-matrix resident-parity capacity-parity read-parity metrics-lint lint scenarios fleet-runtime fuzz fuzz-soak soak

commit:
	@test -n "$(MSG)" || { echo "usage: make commit MSG='message'"; exit 1; }
	python tools/gate.py
	git add -A && git commit -m "$(MSG)"

hooks:
	sh tools/install_hooks.sh

test:
	python -m pytest tests/ -q

gate:
	python tools/gate.py

bench:
	python bench.py

# store-path regression guard (slow; excluded from tier-1): churn ticks
# must stay <= 2x store-backed steady ticks and the churn store
# component must hold the checked-in floor (tools/perf_floor.json;
# refresh with `python tools/perf_guard.py --write-floor`)
perf-guard:
	python tools/perf_guard.py

# process-level crash/failover matrix (slow; tier-1 runs a reduced
# sample via tests/test_crash_recovery.py): SIGKILL-shaped deaths at
# solve / WAL-append / group-flush / dispatch / recovery seams, every
# run must recover to an invariant-clean store with monotone lease
# epochs, plus the two-process SIGSTOP-steal-SIGCONT failover case
crash-matrix:
	python tools/crash_matrix.py

# disk-fault matrix (gate-blocking via tools/gate.py --disk-matrix):
# the crash matrix's sibling — the process LIVES while the disk rots
# under it. Fault seams (WAL append/commit, snapshot publish) x kinds
# (ENOSPC, EIO, torn, short, bitrot) x store configs (classic,
# durable+lease, 2-shard fleet), the same seams driven through the
# scenario engine's disk_fault weathers, bespoke cases (unstamped-WAL
# upgrade compat, manifest/lease rot, replica read-repair), and fuzzer
# disk_fault reachability. Every point must detect, quarantine with a
# forensic .corrupt-<ts> copy, self-heal while serving, and hold
# resume == rerun with zero corrupt frames applied.
disk-matrix:
	env JAX_PLATFORMS=cpu python tools/disk_matrix.py

# network-chaos matrix (gate-blocking via tools/gate.py --net-matrix):
# the disk matrix's sibling — the processes LIVE while the wires between
# them fail. Transport faults (partition one-way + symmetric, drop,
# delay, duplicate, reorder, half-open) at every seam (supervisor IPC
# send/recv, socket adoption, solver publish/return, agent request,
# replica tail) x plane configs (classic, 2-shard fleet, fleet +
# solver-leader), plus the shipped net weathers, bespoke seam cases
# (wait_reply reorder/duplication hardening, dispatch-CAS duplicate
# delivery, adoption half-open, full-jitter retry spread), and fuzzer
# net_fault reachability with a shrunk deterministic timeline. Every
# point must detect, degrade boundedly (orphan/fenced-restart — never
# split-brain, never double-dispatch, stale-accepted == 0), and hold
# resume == rerun. The unfenced-duplicate sabotage self-test runs first.
net-matrix:
	env JAX_PLATFORMS=cpu python tools/net_matrix.py

# storm-soak matrix (fast; tier-1 runs the same cases via
# tests/test_overload.py): seeded task-churn / event / API / slow-store
# storms must brown out low-value work only — planning never starves,
# agent-critical traffic is never shed, the pending/outbox caps hold,
# and the monitor returns to GREEN with hysteresis after each storm
overload-matrix:
	env JAX_PLATFORMS=cpu python tools/overload_matrix.py

# resident ≡ rebuild parity: the device-resident state plane's columns
# must canonicalize identically to a from-scratch snapshot after every
# step of randomized churn (tests/test_resident_state.py fuzz), plus a
# mid-scale churn micro-bench asserting the run was delta-shaped (zero
# fallbacks, one cold rebuild, skip/patch/splice persists dominating)
resident-parity:
	env JAX_PLATFORMS=cpu python tools/resident_parity.py

# sharded tick == single-scheduler oracle at 2/4/8 shards (local +
# stacked solve modes); gate-blocking via tools/gate.py --shard-parity
shard-parity:
	env JAX_PLATFORMS=cpu python tools/bench_sharded.py --parity

# capacity-plane gate: the joint (distros x pools) solve must always be
# feasible (min/max/quota/fleet-cap), match-or-beat the serial
# utilization heuristic's time-to-empty on the bench workload, trade
# capacity across a shared quota the per-distro heuristic cannot see,
# and fall back to bit-identical heuristic behavior when the solver
# fails; gate-blocking via tools/gate.py --capacity-parity
capacity-parity:
	env JAX_PLATFORMS=cpu python tools/capacity_parity.py

# read-serving-plane gate: replica answers ≡ primary at lag 0,
# bounded-stale answers are a prefix of primary history, a fenced
# (deposed) primary's frames are never served and the replica withholds
# serving until the new holder's state arrives, the fingerprint ETag
# cache 304s >90% of an unchanged-queue scrape storm, and the 10k-agent
# long-poll soak dispatches every task exactly once; gate-blocking via
# tools/gate.py --read-parity
read-parity:
	env JAX_PLATFORMS=cpu python tools/read_parity.py

# trace-driven scenario sweep (gate-blocking via tools/gate.py
# --scenarios): six realistic weathers (merge-queue storm, DAG+stepback,
# spot reclamation, region failover, spawn burst, compressed-week
# seasonality) plus the migrated fault/overload matrix cases, replayed
# deterministically through ONE engine; emits SCORECARD.json and diffs
# it against SCORECARD_GREEN.json — graceful-degradation regressions
# fail CI like perf regressions. Refresh the baseline deliberately with
# `python tools/scenario_engine.py --write-green`.
scenarios:
	env JAX_PLATFORMS=cpu python tools/scenario_engine.py --sabotage
	env JAX_PLATFORMS=cpu python tools/scenario_engine.py --check-determinism --diff

# supervised-fleet smoke (gate-blocking via tools/gate.py
# --fleet-runtime): 2 shard worker processes under the production
# supervisor (runtime/), one induced SIGKILL-at-a-WAL-seam + one
# induced hang — fenced takeover at a strictly higher lease epoch,
# zero duplicate dispatch, exactly-one-owner, resume == rerun — plus
# the SUPERVISOR-kill weathers (orphan workers adopted live, zero
# shard-lease epoch bumps, mid-handoff reconciled), a sample of the
# crash-matrix points migrated to the engine's child-process backend
# (the full 13 run under `make crash-matrix`), and the split-brain
# sabotage self-test (stale supervisor: every command rejected)
fleet-runtime:
	env JAX_PLATFORMS=cpu python tools/fleet_runtime.py

# property-based weather fuzzing (gate-blocking via tools/gate.py
# --fuzz): sabotage self-test first — a seeded duplicate-dispatch
# corruption must be FOUND by the invariant net, shrink to a minimal
# timeline, and replay deterministically (same seed => identical
# fingerprints) on BOTH the in-process and child-process backends —
# then a pinned-seed randomized campaign over the engine's whole event
# vocabulary. Failures shrink and land in FUZZ_FINDINGS/ as
# ready-to-check-in regression specs; FUZZCARD.json diffs against
# FUZZCARD_GREEN.json. `make fuzz-soak` explores fresh seeds with a
# bigger box (not gate-blocking; findings are the point).
fuzz:
	env JAX_PLATFORMS=cpu python tools/fuzz_matrix.py --sabotage
	env JAX_PLATFORMS=cpu python tools/fuzz_matrix.py --diff

fuzz-soak:
	env JAX_PLATFORMS=cpu python tools/fuzz_matrix.py \
	  --budget 300 --proc-budget 120 \
	  --start-seed $$(date +%s)

# always-on soak (not gate-blocking; findings are the point):
# SOAK_MINUTES (default 10) of fresh-seed weather fuzzing — sabotage
# self-test first, then the budget split between the in-process arm
# and the supervised 2-shard child-process arm, disk_fault weathers
# included — with the FUZZCARD diffed against green. docs/DEPLOY.md
# documents the N-hour deployment invocation.
soak:
	env JAX_PLATFORMS=cpu python tools/soak.py

# N-process sharded-plane churn throughput vs the single-shard plane
bench-sharded-plane:
	env JAX_PLATFORMS=cpu python tools/bench_sharded_plane.py

# evglint: all six static passes (lockgraph, tracercheck, fencecheck,
# shedcheck, seamcheck, metrics) over the whole package, milliseconds
# fast; the sabotage self-test runs first so a pass that has gone blind
# cannot hand back a trusted "clean". Suppressions require a
# justification (`# evglint: disable=<pass> -- <why>`); the gate runs
# the same two commands unconditionally.
lint:
	python -m tools.evglint --sabotage
	python -m tools.evglint

# static metrics-plane lint (fast; the gate's evglint stage includes it
# as the `metrics` pass): every instrument registered exactly once,
# literal snake_case names with a known subsystem prefix, labels from
# the allowed vocabulary, no f-string metric names, no stray
# incr_counter call sites. Kept as a standalone alias of that pass.
metrics-lint:
	python tools/metrics_lint.py

multichip:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

native:
	$(MAKE) -C native/evgsolve
	python -c "from evergreen_tpu.utils.native import get_evgpack; \
	           print('evgpack:', get_evgpack())"
