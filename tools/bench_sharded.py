#!/usr/bin/env python
"""Sharded-solve scaling bench at BASELINE config-3 scale — plus the
gate-blocking SHARD PARITY matrix (``--parity``).

Validates parallel/sharded.py's linear-scaling claim with numbers
(VERDICT r3 weak #4): 200 distros / 50k tasks partitioned over 8
virtual devices, reporting per-shard task counts, per-shard local solve
wall-clock (each shard solved alone — the time a dedicated device would
take), the stacked shard_map execution, and the load imbalance factor.

On virtual CPU devices all shards share the host's cores, so the
stacked wall-clock is NOT 1/8th of the single-device time — the
scaling evidence is the balance of the per-shard loads and times (a
dedicated-device deployment is bounded by the slowest shard, i.e.
max/mean imbalance over the single-shard times).

    python tools/bench_sharded.py [--devices 8]

Prints one JSON line, then a per-shard table on stderr.

``--parity`` runs the multichip equality check PROMOTED from dry-run to
the live tick path (tools/gate.py --shard-parity): a seeded fleet is
partitioned across 2/4/8 scheduler shards (scheduler/sharded_plane.py,
consistent-hash topology with alias affinity), every shard runs the real
run_tick — in per-shard local-solve mode AND, when the backend has
enough devices, the stacked one-shard_map-solve-per-round mode — and the
merged queue documents must canonically equal a single-scheduler oracle
run over the same documents at the same ticks. Exits non-zero on any
divergence or exactly-one-owner violation.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from evergreen_tpu.utils.jaxenv import force_cpu  # noqa: E402

N_DISTROS = 200
N_TASKS = 50_000


# --------------------------------------------------------------------------- #
# shard parity (gate --shard-parity)
# --------------------------------------------------------------------------- #

PARITY_DISTROS = 24
PARITY_TASKS = 2400
PARITY_TICKS = 2


def _parity_seed(store, capacity=False):
    """Deterministic fleet with alias coupling: even/odd distro pairs
    share tasks through secondary queues, so placement affinity is
    exercised (coupled distros must co-locate or the alias queue would
    lose its rows). ``capacity=True`` opts every distro into the joint
    capacity program (the fused round)."""
    import dataclasses

    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.utils.benchgen import generate_problem

    distros, tbd, hbd, _, _ = generate_problem(
        PARITY_DISTROS, PARITY_TASKS, seed=11, task_group_fraction=0.3,
        hosts_per_distro=3,
    )
    if capacity:
        for d in distros:
            d.planner_settings.capacity = "tpu"
    for di in range(0, len(distros) - 1, 2):
        src, dst = distros[di].id, distros[di + 1].id
        ts = tbd[src]
        for j in range(0, len(ts), 20):
            ts[j] = dataclasses.replace(ts[j], secondary_distros=[dst])
    for d in distros:
        distro_mod.insert(store, d)
    task_mod.insert_many(store, [t for ts in tbd.values() for t in ts])
    for hs in hbd.values():
        host_mod.insert_many(store, hs)


def _canonical_queues(store) -> dict:
    """The parity comparison surface: every queue doc's ordered task
    ids, sort values and deps-met columns, primary + secondary."""
    from evergreen_tpu.models.task_queue import doc_column

    out = {}
    for coll in ("task_queues", "task_secondary_queues"):
        for d in store.collection(coll).find():
            out[(coll, d["_id"])] = (
                doc_column(d, "id"),
                [round(float(v), 6) for v in d.get("sort_value", [])],
                [bool(v) for v in d.get("dependencies_met", [])],
            )
    return out


def run_parity(shard_counts=(2, 4, 8)) -> int:
    import jax

    from evergreen_tpu.scheduler.sharded_plane import (
        ShardedScheduler,
        fleet_owner_violations,
        merge_fleet_state,
    )
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW

    opts = TickOptions(create_intent_hosts=False, use_cache=True,
                       underwater_unschedule=False)
    oracle = Store()
    _parity_seed(oracle)
    for i in range(PARITY_TICKS):
        res = run_tick(oracle, opts, now=NOW + 15.0 * i)
        assert res.planner_used == "tpu", res.degraded
    want = _canonical_queues(oracle)
    n_dev = len(jax.devices())

    failures = 0
    for n in shard_counts:
        modes = ["never"] + (["always"] if n_dev >= n else [])
        for stacked in modes:
            source = Store()
            _parity_seed(source)
            plane = ShardedScheduler.build(
                n, tick_opts=opts, rebalance_enabled=False,
                stacked=stacked,
            )
            try:
                plane.seed_partition(source)
                modes_seen = []
                for i in range(PARITY_TICKS):
                    r = plane.tick(now=NOW + 15.0 * i)
                    modes_seen.append(r.solve_mode)
                    if r.degraded:
                        failures += 1
                        print(json.dumps({
                            "shards": n, "stacked": stacked,
                            "error": f"degraded: {r.degraded}",
                        }))
                violations = fleet_owner_violations(plane.stores)
                got = _canonical_queues(merge_fleet_state(plane.stores))
                ok = got == want and not violations
                stacked_ran = "stacked" in modes_seen
                if stacked == "always" and not stacked_ran:
                    ok = False
                record = {
                    "shards": n,
                    "stacked": stacked,
                    "solve_modes": modes_seen,
                    "queues": len(got),
                    "owner_violations": violations,
                    "parity": got == want,
                    "ok": ok,
                }
                print(json.dumps(record))
                if not ok:
                    failures += 1
                    diff = [
                        k for k in want
                        if got.get(k) != want[k]
                    ][:5]
                    print(f"# diverged queues: {diff}", file=sys.stderr)
            finally:
                plane.close()
    failures += run_fused_round()
    print(json.dumps({
        "shard_parity_failures": failures,
        "shard_counts": list(shard_counts),
        "n_devices": n_dev,
    }))
    return 1 if failures else 0


def run_fused_round(shards=2) -> int:
    """Fused-mode stacked round (PR 18): two identical sharded fleets
    with the capacity plane ON — one serving from the fused view (the
    capacity program inside the one stacked solve), one pinned to the
    classic two-call rung — must agree queue-for-queue after ticking
    with intent creation live. The fused fleet must actually be served
    by the fused rung (counter delta = shards × ticks) while
    scheduler_capacity_solves_total stays flat — the saved device call,
    asserted fleet-wide."""
    import jax

    from evergreen_tpu.scheduler import capacity_plane as cp
    from evergreen_tpu.scheduler.sharded_plane import (
        ShardedScheduler,
        merge_fleet_state,
    )
    from evergreen_tpu.scheduler.wrapper import TickOptions
    from evergreen_tpu.settings import CapacityConfig
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW

    n_dev = len(jax.devices())
    stacked = "always" if n_dev >= shards else "never"
    opts = TickOptions(create_intent_hosts=True, use_cache=True,
                       underwater_unschedule=False)
    failures = 0
    queues = {}
    counters = {}
    for label, fused_knob in (("fused", "auto"), ("two_call", "never")):
        source = Store()
        _parity_seed(source, capacity=True)
        plane = ShardedScheduler.build(
            shards, tick_opts=opts, rebalance_enabled=False,
            stacked=stacked,
        )
        try:
            plane.seed_partition(source)
            for st in plane.stores:
                CapacityConfig(
                    pool_quotas={"mock": 30}, fused=fused_knob
                ).set(st)
            cap0 = cp.CAPACITY_SOLVES.total()
            fused0 = cp.FUSED_SOLVES.value(mode="fused")
            modes_seen = []
            for i in range(PARITY_TICKS):
                r = plane.tick(now=NOW + 15.0 * i)
                modes_seen.append(r.solve_mode)
                if r.degraded:
                    failures += 1
                    print(json.dumps({
                        "round": "fused", "mode": label,
                        "error": f"degraded: {r.degraded}",
                    }))
            queues[label] = _canonical_queues(
                merge_fleet_state(plane.stores)
            )
            counters[label] = {
                "capacity_solves_delta":
                    cp.CAPACITY_SOLVES.total() - cap0,
                "fused_delta":
                    cp.FUSED_SOLVES.value(mode="fused") - fused0,
                "solve_modes": modes_seen,
            }
        finally:
            plane.close()
    ok = queues["fused"] == queues["two_call"]
    served_fused = counters["fused"]["fused_delta"] >= shards * PARITY_TICKS
    saved_calls = counters["fused"]["capacity_solves_delta"] == 0
    record = {
        "round": "fused",
        "shards": shards,
        "stacked": stacked,
        "queue_parity": ok,
        "fused_served_all_ticks": served_fused,
        "capacity_solves_flat": saved_calls,
        "counters": {
            k: {kk: vv for kk, vv in v.items() if kk != "solve_modes"}
            for k, v in counters.items()
        },
        "ok": ok and served_fused and saved_calls,
    }
    print(json.dumps(record))
    return 0 if record["ok"] else failures + 1


def main() -> int:
    n_devices = 8
    if "--devices" in sys.argv:
        n_devices = int(sys.argv[sys.argv.index("--devices") + 1])
    force_cpu(n_devices)
    if "--parity" in sys.argv:
        return run_parity()
    import jax

    from evergreen_tpu.ops.solve import run_solve
    from evergreen_tpu.parallel.mesh import make_mesh
    from evergreen_tpu.parallel.sharded import (
        build_sharded_snapshot,
        sharded_solve_fn,
    )
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    problem = generate_problem(
        N_DISTROS, N_TASKS, seed=3, task_group_fraction=0.25,
        patch_fraction=0.6, hosts_per_distro=25,
    )
    memos: dict = {}
    t0 = time.perf_counter()
    subs, stacked = build_sharded_snapshot(
        *problem, NOW, n_devices, memos=memos
    )
    build_ms = (time.perf_counter() - t0) * 1e3
    # warm rebuild: sticky partition + per-shard membership/dims memos —
    # the deployed multichip tick cadence (VERDICT r4 ask #5)
    t0 = time.perf_counter()
    subs, stacked = build_sharded_snapshot(
        *problem, NOW, n_devices, memos=memos
    )
    warm_build_ms = (time.perf_counter() - t0) * 1e3

    # per-shard solo solves: what a dedicated device per shard would do
    solo_ms = []
    for sub in subs:
        run_solve(sub.arrays)  # warm this shard's (shared) shape
        t1 = time.perf_counter()
        run_solve(sub.arrays)
        solo_ms.append((time.perf_counter() - t1) * 1e3)

    # stacked shard_map execution over the mesh
    mesh = make_mesh(n_devices)
    fn = sharded_solve_fn(mesh)
    jax.block_until_ready(fn(stacked))  # compile
    t2 = time.perf_counter()
    out = fn(stacked)
    jax.block_until_ready(out)
    stacked_ms = (time.perf_counter() - t2) * 1e3

    tasks = [s.n_tasks for s in subs]
    mean_tasks = sum(tasks) / len(tasks)
    mean_solo = statistics.mean(solo_ms)
    result = {
        "metric": f"sharded_solve_{N_TASKS // 1000}k_{N_DISTROS}d",
        "n_devices": n_devices,
        "per_shard_tasks": tasks,
        "task_imbalance": round(max(tasks) / mean_tasks, 4),
        "per_shard_solo_ms": [round(x, 2) for x in solo_ms],
        "solo_imbalance": round(max(solo_ms) / mean_solo, 4),
        "bound_ms": round(max(solo_ms), 2),
        "stacked_virtual_ms": round(stacked_ms, 2),
        "build_ms": round(build_ms, 2),
        "warm_build_ms": round(warm_build_ms, 2),
    }
    print(json.dumps(result))
    print("# shard  tasks  solo_solve_ms", file=sys.stderr)
    for i, (n, ms) in enumerate(zip(tasks, solo_ms)):
        print(f"#  {i:4d}  {n:6d}  {ms:8.2f}", file=sys.stderr)
    print(
        f"# dedicated-device tick bound = max(solo) = {max(solo_ms):.1f}ms; "
        f"imbalance {result['solo_imbalance']:.3f} "
        f"(1.0 = perfectly linear)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
