#!/usr/bin/env python
"""Sharded-solve scaling bench at BASELINE config-3 scale.

Validates parallel/sharded.py's linear-scaling claim with numbers
(VERDICT r3 weak #4): 200 distros / 50k tasks partitioned over 8
virtual devices, reporting per-shard task counts, per-shard local solve
wall-clock (each shard solved alone — the time a dedicated device would
take), the stacked shard_map execution, and the load imbalance factor.

On virtual CPU devices all shards share the host's cores, so the
stacked wall-clock is NOT 1/8th of the single-device time — the
scaling evidence is the balance of the per-shard loads and times (a
dedicated-device deployment is bounded by the slowest shard, i.e.
max/mean imbalance over the single-shard times).

    python tools/bench_sharded.py [--devices 8]

Prints one JSON line, then a per-shard table on stderr.
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from evergreen_tpu.utils.jaxenv import force_cpu  # noqa: E402

N_DISTROS = 200
N_TASKS = 50_000


def main() -> int:
    n_devices = 8
    if "--devices" in sys.argv:
        n_devices = int(sys.argv[sys.argv.index("--devices") + 1])
    force_cpu(n_devices)
    import jax

    from evergreen_tpu.ops.solve import run_solve
    from evergreen_tpu.parallel.mesh import make_mesh
    from evergreen_tpu.parallel.sharded import (
        build_sharded_snapshot,
        sharded_solve_fn,
    )
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    problem = generate_problem(
        N_DISTROS, N_TASKS, seed=3, task_group_fraction=0.25,
        patch_fraction=0.6, hosts_per_distro=25,
    )
    memos: dict = {}
    t0 = time.perf_counter()
    subs, stacked = build_sharded_snapshot(
        *problem, NOW, n_devices, memos=memos
    )
    build_ms = (time.perf_counter() - t0) * 1e3
    # warm rebuild: sticky partition + per-shard membership/dims memos —
    # the deployed multichip tick cadence (VERDICT r4 ask #5)
    t0 = time.perf_counter()
    subs, stacked = build_sharded_snapshot(
        *problem, NOW, n_devices, memos=memos
    )
    warm_build_ms = (time.perf_counter() - t0) * 1e3

    # per-shard solo solves: what a dedicated device per shard would do
    solo_ms = []
    for sub in subs:
        run_solve(sub.arrays)  # warm this shard's (shared) shape
        t1 = time.perf_counter()
        run_solve(sub.arrays)
        solo_ms.append((time.perf_counter() - t1) * 1e3)

    # stacked shard_map execution over the mesh
    mesh = make_mesh(n_devices)
    fn = sharded_solve_fn(mesh)
    jax.block_until_ready(fn(stacked))  # compile
    t2 = time.perf_counter()
    out = fn(stacked)
    jax.block_until_ready(out)
    stacked_ms = (time.perf_counter() - t2) * 1e3

    tasks = [s.n_tasks for s in subs]
    mean_tasks = sum(tasks) / len(tasks)
    mean_solo = statistics.mean(solo_ms)
    result = {
        "metric": f"sharded_solve_{N_TASKS // 1000}k_{N_DISTROS}d",
        "n_devices": n_devices,
        "per_shard_tasks": tasks,
        "task_imbalance": round(max(tasks) / mean_tasks, 4),
        "per_shard_solo_ms": [round(x, 2) for x in solo_ms],
        "solo_imbalance": round(max(solo_ms) / mean_solo, 4),
        "bound_ms": round(max(solo_ms), 2),
        "stacked_virtual_ms": round(stacked_ms, 2),
        "build_ms": round(build_ms, 2),
        "warm_build_ms": round(warm_build_ms, 2),
    }
    print(json.dumps(result))
    print("# shard  tasks  solo_solve_ms", file=sys.stderr)
    for i, (n, ms) in enumerate(zip(tasks, solo_ms)):
        print(f"#  {i:4d}  {n:6d}  {ms:8.2f}", file=sys.stderr)
    print(
        f"# dedicated-device tick bound = max(solo) = {max(solo_ms):.1f}ms; "
        f"imbalance {result['solo_imbalance']:.3f} "
        f"(1.0 = perfectly linear)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
