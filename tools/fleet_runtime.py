#!/usr/bin/env python
"""Fleet-runtime smoke: the process-per-shard deployment, end to end.

    python tools/fleet_runtime.py              # the full smoke
    python tools/fleet_runtime.py --scenario proc-fleet-sigkill
    python tools/fleet_runtime.py --points     # crash points only
    python tools/fleet_runtime.py --sabotage   # split-brain self-test

Runs (gate-blocking via ``tools/gate.py --fleet-runtime`` /
``make fleet-runtime``):

  1. the supervised-fleet weathers (scenarios/procs.py
     ``PROC_SCENARIOS``): a 2-shard fleet with one induced
     SIGKILL-shaped worker death at a WAL seam (``proc_kill``), one
     induced hang (``proc_hang`` → missed-heartbeat kill + restart) —
     each must converge with a fenced takeover at a strictly higher
     lease epoch, zero duplicate dispatch, exactly-one-owner, and
     resume ≡ rerun state vs an uninterrupted run — plus the two
     SUPERVISOR-kill weathers (``sup_kill`` mid-round / mid-handoff →
     orphan workers, fleet-lease steal, live adoption with zero
     shard-lease epoch bumps and zero recovery passes,
     exactly-one-owner after the mid-handoff point) — plus the
     solver-LEADER death weathers (``leader_kill`` at each solver
     seam / ``leader_hang`` past the worker timeout → every shard
     degrades to a LOCAL solve that round, the successor re-elects
     the solver lease at a strictly higher epoch, stacked rounds
     resume, zero stale results accepted, zero shm segments leaked);
  2. a sample of the migrated crash-matrix engine points
     (``run_crash_point`` — the backend ``crash-matrix`` runs all 13
     through): one kill inside a WAL group commit, one between the
     dispatch CAS pair, one inside the startup recovery pass;
  3. the split-brain sabotage run: a SECOND supervisor against a held
     fleet lease must fail to acquire it AND see every command it
     forces over the worker control sockets rejected (``stale_sup``) —
     if any lands, the smoke exits non-zero (the scenario engine's
     sabotage pattern: prove the guard catches the attack).

Prints one JSON line per case; exits non-zero on any failure.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# runtime lock-order witness for the parent harness AND (via inherited
# env) every child process: an inversion in a surviving child fails that
# child's exit code; parent-side inversions fail the matrix at the end
os.environ.setdefault("EVERGREEN_TPU_LOCKCHECK", "1")

#: the smoke's crash-point sample (the full 13 run under
#: ``gate.py --crash-matrix``; these three cover a group commit, the
#: dispatch CAS pair, and the recovery pass itself)
SMOKE_POINTS: List[Tuple[str, int]] = [
    ("wal.commit", 1),
    ("dispatch.assign", 0),
    ("recovery.pass", 0),
]


def _force_cpu() -> None:
    from evergreen_tpu.utils.jaxenv import force_cpu

    # 2 host devices: the solver-leader weathers run the leader's
    # stacked shard_map solve IN THIS PROCESS, one device per shard
    force_cpu(n_devices=2)


def run_weathers(names: Optional[List[str]] = None) -> int:
    from evergreen_tpu.scenarios.library import PROC_WEATHERS
    from evergreen_tpu.scenarios.procs import (
        PROC_SCENARIOS,
        run_proc_scenario,
    )

    failures = 0
    suite = {**PROC_SCENARIOS, **PROC_WEATHERS}
    for name, factory in suite.items():
        if names and name not in names:
            continue
        entry = run_proc_scenario(factory())
        print(json.dumps({
            "scenario": name,
            "ok": entry["ok"],
            "stats": entry["stats"],
            "wall_ms": entry["timing"]["wall_ms"],
        }))
        if not entry["ok"]:
            failures += 1
            bad = {
                section: {
                    k: v for k, v in entry.get(section, {}).items()
                    if not v.get("ok")
                }
                for section in ("invariants", "checks", "slos")
            }
            print(json.dumps({"scenario": name, "failed": bad}),
                  file=sys.stderr)
    return failures


def run_points() -> int:
    from evergreen_tpu.scenarios.procs import (
        proc_reference_state,
        run_crash_point,
    )

    reference = proc_reference_state()
    failures = 0
    for seam, idx in SMOKE_POINTS:
        out = run_crash_point(seam, idx, reference=reference)
        print(json.dumps({
            k: out[k]
            for k in ("point", "ok", "crashed", "epochs",
                      "parity_ok", "problems")
        }))
        if not out["ok"]:
            failures += 1
    return failures


def run_sabotage() -> int:
    """Split-brain self-test (worker-side stale-epoch guard): boot a
    real 2-shard fleet, then play a SECOND supervisor against it —
    it must fail to acquire the held fleet lease, and every command it
    forces over the worker control sockets (stamped with its stale
    epoch 0) must come back ``stale_sup`` without executing. Returns
    the failure count; any command that LANDS is a failure."""
    import shutil
    import tempfile
    import threading

    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.runtime.protocol import parse_line, send_msg
    from evergreen_tpu.runtime.supervisor import FleetSupervisor
    from evergreen_tpu.scenarios.procs import _seed_fleet
    from evergreen_tpu.storage.lease import (
        FileLease,
        supervisor_lease_path,
    )
    from evergreen_tpu.utils.benchgen import NOW
    from evergreen_tpu.utils.retry import RetryPolicy

    problems: List[str] = []
    data_dir = tempfile.mkdtemp(prefix="fleet-sabotage-")
    sup = FleetSupervisor(
        data_dir, 2, ttl_s=1.0, hb_interval_s=0.25,
        hb_deadline_s=1.5, harness=True, recovery_anchor=NOW,
        restart_policy=RetryPolicy(
            attempts=1_000_000, base_backoff_s=0.25,
            max_backoff_s=2.0, jitter=0.0,
        ),
        worker_stderr="devnull",
        orphan_grace_s=60.0, supervisor_lease_ttl_s=2.0,
    )
    try:
        _seed_fleet(data_dir, 2, {"distros": 4, "tasks": 24,
                                  "seed": 11})
        sup.start()
        sup.round(now=NOW + 15.0)
        pre_ticks = {
            k: r.get("tick", -1)
            for k, r in sup.statuses().items()
        }

        # (a) the held fleet lease cannot be acquired
        rogue_lease = FileLease(
            supervisor_lease_path(data_dir), ttl_s=2.0
        )
        if rogue_lease.try_acquire():
            problems.append(
                "rogue supervisor ACQUIRED the held fleet lease"
            )

        # (b) every forced command is rejected with stale_sup —
        # including an adopt REPLAYING the current epoch (a rogue can
        # read the lease file; only a strictly-higher epoch, i.e. an
        # actual steal, may adopt a foreign channel)
        import json as _json

        with open(supervisor_lease_path(data_dir),
                  encoding="utf-8") as fh:
            held_epoch = int(_json.load(fh)["epoch"])
        lock = threading.Lock()
        landed = 0
        rejected = 0
        for shard in range(2):
            entry = manifest.read_entry(data_dir, shard)
            if entry is None:
                problems.append(f"no manifest entry for shard {shard}")
                continue
            conn = manifest.connect(entry["sock"], timeout_s=5.0)
            rf = conn.makefile("r", encoding="utf-8")
            wf = conn.makefile("w", encoding="utf-8")
            try:
                for op, sup_e in (("adopt", held_epoch), ("adopt", 0),
                                  ("tick", 0), ("release", 0),
                                  ("prime", 0), ("drain", 0),
                                  ("shutdown", 0)):
                    req = f"rogue-{shard}-{op}-{sup_e}"
                    send_msg(wf, lock, op=op, sup=sup_e, req=req,
                             now=NOW + 30.0, distro="d-000", target=1,
                             record={}, handoff="h")
                    reply = None
                    while True:
                        line = rf.readline()
                        if not line:
                            break
                        msg = parse_line(line)
                        if msg is not None and msg.get("req") == req:
                            reply = msg
                            break
                    if reply is None:
                        problems.append(
                            f"shard {shard}: no reply to rogue {op!r}"
                        )
                    elif reply["op"] != "stale_sup":
                        landed += 1
                        problems.append(
                            f"shard {shard}: rogue {op!r} LANDED "
                            f"(reply {reply['op']!r})"
                        )
                    else:
                        rejected += 1
            finally:
                for f in (rf, wf, conn):
                    try:
                        f.close()
                    except OSError:
                        pass

        # (c) the live fleet is untouched: same workers, rounds work,
        # no rogue tick executed
        post = sup.statuses()
        if sorted(post) != [0, 1]:
            problems.append(
                f"live fleet lost workers after sabotage: {sorted(post)}"
            )
        for k, r in post.items():
            if r.get("tick", -1) != pre_ticks.get(k):
                problems.append(
                    f"shard {k} ticked under a rogue command "
                    f"({pre_ticks.get(k)} -> {r.get('tick')})"
                )
        if not sup.round(now=NOW + 30.0):
            problems.append("live supervisor round failed after sabotage")
        print(json.dumps({
            "sabotage": "stale-supervisor",
            "ok": not problems,
            "rejected": rejected,
            "landed": landed,
            "problems": problems,
        }))
        return 1 if problems else 0
    finally:
        sup.stop(graceful=True)
        shutil.rmtree(data_dir, ignore_errors=True)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="",
                   help="run one supervised-fleet weather only")
    p.add_argument("--points", action="store_true",
                   help="run only the crash-point sample")
    p.add_argument("--weathers", action="store_true",
                   help="run only the supervised-fleet weathers")
    p.add_argument("--sabotage", action="store_true",
                   help="run only the split-brain sabotage self-test")
    args = p.parse_args()

    exclusive = [
        n for n, v in (("--scenario", args.scenario),
                       ("--points", args.points),
                       ("--sabotage", args.sabotage))
        if v
    ]
    if len(exclusive) > 1:
        # any combination would skip blocks and report a green smoke
        # that ran nothing
        print(f"{' and '.join(exclusive)} are mutually exclusive",
              file=sys.stderr)
        return 2
    _force_cpu()
    if args.scenario:
        from evergreen_tpu.scenarios.library import PROC_WEATHERS
        from evergreen_tpu.scenarios.procs import PROC_SCENARIOS

        known = {**PROC_SCENARIOS, **PROC_WEATHERS}
        if args.scenario not in known:
            # a typo must never read as "smoke passed"
            print(
                f"unknown scenario {args.scenario!r}; known: "
                f"{sorted(known)}", file=sys.stderr,
            )
            return 2
    failures = 0
    if args.sabotage:
        failures += run_sabotage()
    if not args.points and not args.sabotage:
        failures += run_weathers(
            [args.scenario] if args.scenario else None
        )
    if not args.weathers and not args.scenario and not args.sabotage:
        failures += run_points()
    if not (args.weathers or args.scenario or args.sabotage
            or args.points):
        # the full smoke ends with the split-brain self-test: the
        # stale-supervisor guard must CATCH the attack
        failures += run_sabotage()
    from evergreen_tpu.utils import lockcheck

    inversions = lockcheck.violations()
    if inversions:
        print(json.dumps({"lockcheck_inversions": len(inversions)}))
        failures += len(inversions)
    print(json.dumps({"fleet_runtime_ok": failures == 0}))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
