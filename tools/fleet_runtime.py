#!/usr/bin/env python
"""Fleet-runtime smoke: the process-per-shard deployment, end to end.

    python tools/fleet_runtime.py              # the full smoke
    python tools/fleet_runtime.py --scenario proc-fleet-sigkill
    python tools/fleet_runtime.py --points     # crash points only

Runs (gate-blocking via ``tools/gate.py --fleet-runtime`` /
``make fleet-runtime``):

  1. the supervised-fleet weathers (scenarios/procs.py
     ``PROC_SCENARIOS``): a 2-shard fleet with one induced
     SIGKILL-shaped worker death at a WAL seam (``proc_kill``) and one
     induced hang (``proc_hang`` → missed-heartbeat kill + restart) —
     each must converge with a fenced takeover at a strictly higher
     lease epoch, zero duplicate dispatch, exactly-one-owner, and
     resume ≡ rerun state vs an uninterrupted run;
  2. a sample of the migrated crash-matrix engine points
     (``run_crash_point`` — the backend ``crash-matrix`` runs all 13
     through): one kill inside a WAL group commit, one between the
     dispatch CAS pair, one inside the startup recovery pass.

Prints one JSON line per case; exits non-zero on any failure.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

#: the smoke's crash-point sample (the full 13 run under
#: ``gate.py --crash-matrix``; these three cover a group commit, the
#: dispatch CAS pair, and the recovery pass itself)
SMOKE_POINTS: List[Tuple[str, int]] = [
    ("wal.commit", 1),
    ("dispatch.assign", 0),
    ("recovery.pass", 0),
]


def _force_cpu() -> None:
    from evergreen_tpu.utils.jaxenv import force_cpu

    force_cpu(n_devices=1)


def run_weathers(names: Optional[List[str]] = None) -> int:
    from evergreen_tpu.scenarios.procs import (
        PROC_SCENARIOS,
        run_proc_scenario,
    )

    failures = 0
    for name, factory in PROC_SCENARIOS.items():
        if names and name not in names:
            continue
        entry = run_proc_scenario(factory())
        print(json.dumps({
            "scenario": name,
            "ok": entry["ok"],
            "stats": entry["stats"],
            "wall_ms": entry["timing"]["wall_ms"],
        }))
        if not entry["ok"]:
            failures += 1
            bad = {
                section: {
                    k: v for k, v in entry.get(section, {}).items()
                    if not v.get("ok")
                }
                for section in ("invariants", "checks", "slos")
            }
            print(json.dumps({"scenario": name, "failed": bad}),
                  file=sys.stderr)
    return failures


def run_points() -> int:
    from evergreen_tpu.scenarios.procs import (
        proc_reference_state,
        run_crash_point,
    )

    reference = proc_reference_state()
    failures = 0
    for seam, idx in SMOKE_POINTS:
        out = run_crash_point(seam, idx, reference=reference)
        print(json.dumps({
            k: out[k]
            for k in ("point", "ok", "crashed", "epochs",
                      "parity_ok", "problems")
        }))
        if not out["ok"]:
            failures += 1
    return failures


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="",
                   help="run one supervised-fleet weather only")
    p.add_argument("--points", action="store_true",
                   help="run only the crash-point sample")
    p.add_argument("--weathers", action="store_true",
                   help="run only the supervised-fleet weathers")
    args = p.parse_args()

    if args.scenario and args.points:
        # the combination would skip BOTH blocks and report a green
        # smoke that ran nothing
        print("--scenario and --points are mutually exclusive",
              file=sys.stderr)
        return 2
    _force_cpu()
    if args.scenario:
        from evergreen_tpu.scenarios.procs import PROC_SCENARIOS

        if args.scenario not in PROC_SCENARIOS:
            # a typo must never read as "smoke passed"
            print(
                f"unknown scenario {args.scenario!r}; known: "
                f"{sorted(PROC_SCENARIOS)}", file=sys.stderr,
            )
            return 2
    failures = 0
    if not args.points:
        failures += run_weathers(
            [args.scenario] if args.scenario else None
        )
    if not args.weathers and not args.scenario:
        failures += run_points()
    print(json.dumps({"fleet_runtime_ok": failures == 0}))
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
