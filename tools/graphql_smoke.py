#!/usr/bin/env python
"""Happy-path smoke execution of every served GraphQL operation.

Behavior-parity backend for tools/graphql_diff.py (VERDICT r3 ask #2):
an operation only counts as *served* if one real invocation against a
seeded store returns data with no error entry. Arguments are generated
from the typed schema — required args are filled from a name-based
fixture mapping into the seeded world; per-op overrides cover the few
operations whose happy path needs specific shapes.

Run directly for a human-readable report of any non-executing ops:

    python tools/graphql_smoke.py
"""
from __future__ import annotations

import os
import sys
from typing import Any, Dict, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


# --------------------------------------------------------------------------- #
# Seeded world
# --------------------------------------------------------------------------- #

#: canonical fixture ids, used both by the seeder and the arg filler
IDS = {
    "task": "smoke-task",
    "exec0_task": "smoke-task",
    "host": "smoke-host",
    "spawn_host": "smoke-spawn-host",
    "distro": "smoke-distro",
    "project": "smoke-project",
    "repo": "smoke-repo",
    "version": "smoke-version",
    "build": "smoke-build",
    "patch": "smoke-patch",
    "volume": "smoke-volume",
    "user": "smoke-admin",
    "subscription": "smoke-sub",
    "image": "ubuntu2204",
}


def seed():
    """A fresh store holding one of everything, owned by IDS['user']."""
    import time as _time

    from evergreen_tpu.cloud.volumes import VOLUMES_COLLECTION, Volume
    from evergreen_tpu.globals import Requester, TaskStatus
    from evergreen_tpu.ingestion.patches import Patch
    from evergreen_tpu.ingestion.repotracker import (
        ProjectRef,
        upsert_project_ref,
    )
    from evergreen_tpu.models import build as build_mod
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import event as event_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models import user as user_mod
    from evergreen_tpu.models import version as version_mod
    from evergreen_tpu.models.build import Build
    from evergreen_tpu.models.distro import Distro, HostAllocatorSettings
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.models.version import Version
    from evergreen_tpu.storage.store import Store

    store = Store()
    me = IDS["user"]
    user_mod.create_user(store, me, display_name="Smoke Admin")
    user_mod.grant_role(store, me, "superuser")
    user_mod.add_public_key(store, me, "laptop", "ssh-rsa AAAA smoke")

    d = Distro(
        id=IDS["distro"],
        provider="mock",
        host_allocator_settings=HostAllocatorSettings(maximum_hosts=10),
    )
    d.provider_settings["spawn_allowed"] = True
    distro_mod.insert(store, d)

    upsert_project_ref(store, ProjectRef(
        id=IDS["project"], owner="org", repo="code", branch="main",
        enabled=True,
    ))
    # repo-level ref + attach the project to it (the shape
    # attachProjectToRepo writes)
    store.collection("repo_refs").insert({
        "_id": IDS["repo"], "owner": "org", "repo": "code",
    })
    store.collection("project_refs").update(
        IDS["project"], {"repo_ref_id": IDS["repo"]}
    )

    now = _time.time()
    version_mod.insert(store, Version(
        id=IDS["version"], project=IDS["project"],
        requester=Requester.REPOTRACKER.value, revision="abc123",
        revision_order_number=7, author=me, message="smoke commit",
        create_time=now, status="failed", activated=True,
    ))
    build_mod.insert(store, Build(
        id=IDS["build"], version=IDS["version"], project=IDS["project"],
        build_variant="bv1", display_name="BV 1", status="failed",
    ))
    task_mod.insert(store, Task(
        id=IDS["task"], distro_id=IDS["distro"], project=IDS["project"],
        version=IDS["version"], build_id=IDS["build"], build_variant="bv1",
        display_name="unit-tests", status=TaskStatus.FAILED.value,
        activated=True, requester=Requester.REPOTRACKER.value,
        revision="abc123", finish_time=now, start_time=now - 60.0,
    ))

    host_mod.insert(store, Host(
        id=IDS["host"], distro_id=IDS["distro"], provider="mock",
        status="running", started_by="mci",
    ))
    host_mod.insert(store, Host(
        id=IDS["spawn_host"], distro_id=IDS["distro"], provider="mock",
        status="running", started_by=me, user_host=True,
        no_expiration=False,
    ))

    store.collection(VOLUMES_COLLECTION).insert(Volume(
        id=IDS["volume"], created_by=me, size_gb=100,
        availability_zone="us-east-1a",
    ).to_doc())

    patch_doc = Patch(
        id=IDS["patch"], project=IDS["project"], author=me,
        description="smoke patch", status="created",
    ).to_doc()
    store.collection("patches").insert(patch_doc)

    event_mod.log(store, event_mod.RESOURCE_ADMIN, "SMOKE", "smoke", {})
    return store


# --------------------------------------------------------------------------- #
# Argument generation from the typed schema
# --------------------------------------------------------------------------- #

#: arg-name → fixture value; matched case-insensitively, most specific
#: name wins (exact match first, then suffix match)
ARG_VALUES: Dict[str, Any] = {
    "taskid": IDS["task"], "taskids": [IDS["task"]],
    "hostid": IDS["spawn_host"], "hostids": [IDS["spawn_host"]],
    "distroid": IDS["distro"], "distroids": [IDS["distro"]],
    "projectid": IDS["project"], "projectids": [IDS["project"]],
    "identifier": IDS["project"],
    "projectidentifier": IDS["project"],
    "repoid": IDS["repo"],
    "versionid": IDS["version"], "versionids": [IDS["version"]],
    "buildid": IDS["build"],
    "patchid": IDS["patch"], "patchids": [IDS["patch"]],
    "volumeid": IDS["volume"],
    "userid": IDS["user"],
    "subscriptionids": [IDS["subscription"]],
    "imageid": IDS["image"],
    "execution": 0,
    "priority": 50,
    "limit": 5,
    "page": 0,
    "testname": "",
    "taskname": "unit-tests",
    "buildvariant": "bv1", "variant": "bv1",
    "displayname": "Smoke Name",
    "name": "laptop",
    "key": "ssh-rsa AAAA smoke2",
    "keyname": "laptop",
    "note": "smoke note",
    "owner": "org", "repo": "code", "branch": "main",
    "url": "https://jira.example.com/SMOKE-1",
    "issuekey": "SMOKE-1",
    "isissue": True,
    "section": "GENERAL",
    "varnames": [],
    "revision": "abc123",
}


def _unwrap(t: dict) -> Tuple[str, Optional[str], bool]:
    """(kind, name, required) of a type ref with NON_NULL/LIST peeled."""
    required = t.get("kind") == "NON_NULL"
    while t and t.get("kind") in ("NON_NULL", "LIST"):
        t = t.get("ofType") or {}
    return t.get("kind", "SCALAR"), t.get("name"), required


def _is_list(t: dict) -> bool:
    while t and t.get("kind") == "NON_NULL":
        t = t.get("ofType") or {}
    return bool(t) and t.get("kind") == "LIST"


def value_for(name: str, type_ref: dict, reg: Dict[str, dict]):
    """A fixture value for one argument/input field, or None."""
    key = name.lower()
    if key in ARG_VALUES:
        return ARG_VALUES[key]
    kind, tname, _ = _unwrap(type_ref)
    listy = _is_list(type_ref)
    if kind == "INPUT_OBJECT":
        inner = input_object_value(tname, reg)
        return [inner] if listy else inner
    for suffix, v in ARG_VALUES.items():
        if key.endswith(suffix):
            return v
    if tname == "Boolean":
        return True
    if tname == "Int":
        return [1] if listy else 1
    if tname == "Float":
        return [1.0] if listy else 1.0
    if tname == "JSON":
        return {}
    return [] if listy else ""


def input_object_value(tname: str, reg: Dict[str, dict]) -> dict:
    """Minimal happy-path dict for an input object: required fields only,
    plus any field with a direct fixture mapping."""
    tdef = reg.get(tname) or {}
    out = {}
    fields = tdef.get("inputFields") or tdef.get("fields") or {}
    for fname, fdef in fields.items():
        _, _, required = _unwrap(fdef.get("type") or {})
        if required or fname.lower() in ARG_VALUES:
            out[fname] = value_for(fname, fdef.get("type") or {}, reg)
    return out


def selection_for(type_ref: dict, reg: Dict[str, dict]) -> str:
    """A minimal selection set for the op's result type ('' for scalars)."""
    kind, tname, _ = _unwrap(type_ref)
    if kind != "OBJECT":
        return ""
    fields = (reg.get(tname) or {}).get("fields") or {}
    for cand in ("id", "name", "status"):
        if cand in fields:
            return "{ %s }" % cand
    for fname, fdef in fields.items():
        fkind, _, _ = _unwrap(fdef.get("type") or {})
        if fkind == "SCALAR" and not (fdef.get("args") or {}):
            return "{ %s }" % fname
    return "{ __typename }"


# --------------------------------------------------------------------------- #
# Per-op overrides: ops whose generic happy path needs a specific shape
# --------------------------------------------------------------------------- #

OVERRIDES: Dict[str, Dict[str, Any]] = {
    "spawnHost": {"spawnHostInput": {"distroId": IDS["distro"]}},
    "editSpawnHost": {"spawnHost": {
        "hostId": IDS["spawn_host"], "displayName": "smokebox"}},
    "updateSpawnHostStatus": {"updateSpawnHostStatusInput": {
        "hostId": IDS["spawn_host"], "action": "STOP"}},
    "spawnVolume": {"spawnVolumeInput": {
        "size": 100, "availabilityZone": "us-east-1a"}},
    "updateVolume": {"updateVolumeInput": {
        "volumeId": IDS["volume"], "name": "smokevol"}},
    "attachVolumeToHost": {"volumeAndHost": {
        "volumeId": IDS["volume"], "hostId": IDS["spawn_host"]}},
    "migrateVolume": {
        "volumeId": IDS["volume"],
        "spawnHostInput": {"distroId": IDS["distro"]},
    },
    "updateHostStatus": {
        "hostIds": [IDS["host"]], "status": "quarantined"},
    "saveAdminSettings": {"adminSettings": {
        "banner": {"text": "smoke", "theme": "ANNOUNCEMENT"}}},
    "setServiceFlags": {"updatedFlags": [
        {"name": "alerts_disabled", "enabled": True}]},
    "restartAdminTasks": {"opts": {
        "startTime": 0.0, "endTime": 4102444800.0}},
    "adminTasksToRestart": {"opts": {
        "startTime": 0.0, "endTime": 4102444800.0}},
    "adminEvents": {"opts": {}},
    "mainlineCommits": {"options": {"projectIdentifier": IDS["project"]}},
    "setLastRevision": {"opts": {
        "projectIdentifier": IDS["project"], "revision": "abc123"}},
    "saveSubscription": {"subscription": {
        "resource_type": "TASK", "trigger": "outcome",
        "selectors": [{"type": "id", "data": IDS["task"]}],
        "subscriber": {"type": "email", "target": "smoke@example.com"},
    }},
    "saveDistro": {"opts": {"distro": {"id": IDS["distro"]}}},
    "saveProjectSettingsForSection": {
        "projectSettings": {"projectId": IDS["project"]},
        "section": "GENERAL"},
    "saveRepoSettingsForSection": {
        "repoSettings": {"repoId": IDS["repo"]}, "section": "GENERAL"},
    "setTaskPriorities": {"taskPriorities": [
        {"taskId": IDS["task"], "priority": 50}]},
    "updateUserSettings": {"userSettings": {"timezone": "UTC"}},
    "updateBetaFeatures": {"opts": {"betaFeatures": {}}},
    "copyDistro": {"opts": {
        "distroIdToCopy": IDS["distro"], "newDistroId": "smoke-distro-2"}},
    "createDistro": {"opts": {"newDistroId": "smoke-distro-new"}},
    "copyProject": {"project": {
        "projectIdToCopy": IDS["project"],
        "newProjectIdentifier": "smoke-project-2"}},
    "createProject": {"project": {
        "identifier": "smoke-project-new", "owner": "org", "repo": "code"}},
    "attachProjectToNewRepo": {"project": {
        "projectId": IDS["project"], "newOwner": "org2", "newRepo": "code2"}},
    "bbCreateTicket": {"taskId": IDS["task"]},
    "setAnnotationMetadataLinks": {
        "taskId": IDS["task"], "execution": 0,
        "metadataLinks": [{"url": "https://x", "text": "x"}]},
    "overrideTaskDependencies": {"taskId": IDS["task"]},
    "setPatchVisibility": {
        "patchIds": [IDS["patch"]], "hidden": True},
    "deleteSubscriptions": {"subscriptionIds": []},
    "removePublicKey": {"keyName": "laptop"},
    "updatePublicKey": {
        "targetKeyName": "laptop",
        "updateInfo": {"name": "laptop2", "key": "ssh-rsa BBBB smoke"}},
    "createPublicKey": {"publicKeyInput": {
        "name": "desktop", "key": "ssh-rsa CCCC smoke"}},
    "taskTestSample": {
        "versionId": IDS["version"], "taskIds": [IDS["task"]],
        "filters": []},
    "buildVariantsForTaskName": {
        "projectIdentifier": IDS["project"], "taskName": "unit-tests"},
    "taskNamesForBuildVariant": {
        "projectIdentifier": IDS["project"], "buildVariant": "bv1"},
    "githubProjectConflicts": {"projectId": IDS["project"]},
    "restartVersions": {
        "versionId": IDS["version"], "abort": False,
        "versionsToRestart": [{"versionId": IDS["version"]}]},
}

#: ops that need extra world state beyond seed(); name → setup(store)
SETUP: Dict[str, Any] = {}


def _setup_quarantined_task(store):
    from evergreen_tpu.models import task as task_mod

    task_mod.coll(store).update(IDS["task"], {"status": "quarantined"})


SETUP["unquarantineTask"] = _setup_quarantined_task


def _setup_detached_volume_host(store):
    pass


def _setup_attached_volume(store):
    from evergreen_tpu.cloud.volumes import VOLUMES_COLLECTION

    store.collection(VOLUMES_COLLECTION).update(
        IDS["volume"], {"host_id": IDS["spawn_host"]}
    )


SETUP["detachVolumeFromHost"] = _setup_attached_volume


def _setup_subscription(store):
    store.collection("subscriptions").insert({
        "_id": IDS["subscription"], "owner": IDS["user"],
        "resource_type": "TASK", "trigger": "outcome",
        "selectors": [{"type": "id", "data": IDS["task"]}],
        "subscriber": {"type": "email", "target": "smoke@example.com"},
    })


SETUP["deleteSubscriptions"] = _setup_subscription


# --------------------------------------------------------------------------- #
# Runner
# --------------------------------------------------------------------------- #


def run_all() -> Dict[str, Dict[str, str]]:
    """op name → {kind, ok, error} for every served operation."""
    from evergreen_tpu.api.graphql import GraphQLApi
    from evergreen_tpu.api.schema import schema

    reg = schema()
    results: Dict[str, Dict[str, str]] = {}
    for opname, root in [
        *((q, "Query") for q in GraphQLApi(seed()).queries),
        *((m, "Mutation") for m in GraphQLApi(seed()).mutations),
    ]:
        store = seed()
        if opname in SETUP:
            SETUP[opname](store)
        api = GraphQLApi(store, acting_user=IDS["user"])
        fdef = (reg.get(root, {}).get("fields") or {}).get(opname)
        if fdef is None:
            # op served but not declared in the typed schema
            results[opname] = {
                "kind": root, "ok": False, "error": "not in typed schema"}
            continue
        args = dict(OVERRIDES.get(opname) or {})
        for aname, adef in (fdef.get("args") or {}).items():
            if aname in args:
                continue
            _, _, required = _unwrap(adef.get("type") or {})
            if required and not adef.get("has_default"):
                args[aname] = value_for(aname, adef.get("type") or {}, reg)
        sel = selection_for(fdef.get("type") or {}, reg)
        if args:
            var_defs = ", ".join(f"$a{i}: JSON" for i in range(len(args)))
            arg_list = ", ".join(
                f"{a}: $a{i}" for i, a in enumerate(args))
            doc = (
                f"{root.lower()}({var_defs}) "
                f"{{ {opname}({arg_list}) {sel} }}"
            )
            variables = {f"a{i}": v for i, v in enumerate(args.values())}
        else:
            doc = f"{root.lower()} {{ {opname} {sel} }}"
            variables = {}
        try:
            out = api.execute(doc, variables)
        except Exception as e:  # noqa: BLE001 — report, don't crash sweep
            out = {"errors": [{"message": f"raised {type(e).__name__}: {e}"}]}
        if "errors" in out:
            results[opname] = {
                "kind": root, "ok": False,
                "error": out["errors"][0]["message"]}
        else:
            results[opname] = {"kind": root, "ok": True, "error": ""}
    return results


def main() -> int:
    results = run_all()
    bad = {k: v for k, v in results.items() if not v["ok"]}
    ok_n = len(results) - len(bad)
    print(f"executed clean: {ok_n}/{len(results)}")
    for name, r in sorted(bad.items()):
        print(f"  FAIL {r['kind']}.{name}: {r['error']}")
    return 1 if bad else 0


if __name__ == "__main__":
    raise SystemExit(main())
