set -e
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu PYTHONPATH=/root/repo
cd /root/repo
PORT=19251
python -m evergreen_tpu service --port $PORT > /tmp/soak_svc2.log 2>&1 &
SVC=$!
trap "kill $SVC 2>/dev/null; pkill -f 'evergreen_tpu agent' 2>/dev/null" EXIT
for i in $(seq 40); do curl -s localhost:$PORT/rest/v2/status >/dev/null 2>&1 && break; sleep 0.5; done

python - <<'PY' &
import json, textwrap, time, urllib.request
base = "http://127.0.0.1:19251"
def call(method, path, body=None):
    req = urllib.request.Request(base + path,
        data=json.dumps(body).encode() if body is not None else None,
        method=method, headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read() or b"{}")
call("PUT", "/rest/v2/distros/soak",
     {"provider": "mock", "host_allocator_settings": {"maximum_hosts": 6}})
call("PUT", "/rest/v2/projects/soakproj", {})
cfg = textwrap.dedent("""
tasks:
  - name: work-a
    commands: [{command: shell.exec, params: {script: "sleep 0.2 && echo a"}}]
  - name: work-b
    depends_on: [{name: work-a}]
    commands: [{command: shell.exec, params: {script: "sleep 0.1 && echo b"}}]
  - name: work-c
    commands: [{command: shell.exec, params: {script: "sleep 0.15 && echo c"}}]
buildvariants:
  - name: bv
    run_on: [soak]
    tasks: [{name: work-a}, {name: work-b}, {name: work-c}]
""")
for i in range(1, 6):
    out = call("POST", "/rest/v2/projects/soakproj/revisions",
               {"revision": f"rev{i:08d}", "config_yaml": cfg})
    print("pushed", out, flush=True)
    time.sleep(10)
PY
PUSHER=$!

for i in $(seq 120); do
  N=$(curl -s localhost:$PORT/rest/v2/hosts | python -c "import json,sys; print(sum(1 for h in json.load(sys.stdin) if h['status']=='running'))" 2>/dev/null || echo 0)
  [ "${N:-0}" -ge 1 ] && break
  sleep 2
done
HOSTS=$(curl -s localhost:$PORT/rest/v2/hosts | python -c "import json,sys; print(' '.join(h['_id'] for h in json.load(sys.stdin) if h['status']=='running'))")
echo "agents on: $HOSTS"
for H in $HOSTS; do
  python -m evergreen_tpu agent --host-id "$H" --api-server http://127.0.0.1:$PORT > /tmp/soak_agent2_$H.log 2>&1 &
done

wait $PUSHER || true
sleep 100

python - <<'PY'
import collections, json, urllib.request
base = "http://127.0.0.1:19251"
def get(p):
    return json.load(urllib.request.urlopen(base + p, timeout=30))
print("status:", get("/rest/v2/status"))
counts = collections.Counter()
pending = []
for i in range(1, 6):
    vid = f"soakproj_{i}_rev0000000"
    try:
        v = get(f"/rest/v2/versions/{vid}")
        counts[v["status"]] += 1
        if v["status"] not in ("success", "failed"):
            pending.append(vid)
    except Exception:
        counts["missing"] += 1
print("version outcomes:", dict(counts), "pending:", pending)
failed_jobs = [e for e in get("/rest/v2/events") if e["event_type"] == "JOB_FAILED"]
print("failed jobs:", len(failed_jobs))
for e in failed_jobs[:3]:
    print("  ", e["data"].get("type"), (e["data"].get("error") or "")[-200:])
PY
