#!/bin/sh
# Install the repo's git hooks (currently: the pre-commit test gate).
# This is the ONLY supported way to set up a working copy for commits.
set -e
cd "$(git rev-parse --show-toplevel)"
mkdir -p .git/hooks
cp tools/hooks/pre-commit .git/hooks/pre-commit
chmod +x .git/hooks/pre-commit
echo "installed .git/hooks/pre-commit (full-suite gate)"
