#!/usr/bin/env python
"""Process-level crash/failover matrix: real kills, real recovery.

The fault matrix (tools/fault_matrix.py) injects failures INSIDE a live
process; this harness proves the other half of the robustness story —
the process itself dying at the worst possible instruction. A child
process runs a deterministic tick+dispatch workload against a temp data
dir behind a ``FileLease``; the parent arranges its death at env-selected
crash points (utils/faults.py seam names with the ``crash`` kind →
``os._exit``, the SIGKILL shape: no atexit, no finally, no flushes beyond
what already hit the OS), restarts it, and asserts invariants:

  * resume ≡ rerun — the crashed-and-recovered run converges to the same
    final task/queue state as one uninterrupted run of the same workload;
  * monotone lease epochs — every restart steals at a strictly higher
    fencing epoch;
  * no duplicate dispatch — at most one host claims a task, claims and
    task docs stay coherent;
  * no torn group applied — the recovered store passes structural
    invariants (aligned queue columns, legal statuses).

Plus the two-process failover case: the holder is SIGSTOPped mid-commit
(a ``hang`` fault at the ``wal.fence`` seam widens the window), a standby
steals the lease and runs its own ticks, the holder is SIGCONTed — its
resumed commit must be rejected with ``EpochFencedError`` and ZERO frames
with a superseded epoch may survive past the fence point in the WAL.

Run standalone (``make crash-matrix`` / ``python tools/crash_matrix.py``)
or through the gate (``python tools/gate.py --crash-matrix``);
tests/test_crash_recovery.py runs a reduced kill-point sample in tier-1.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time as _time
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# runtime lock-order witness for the parent harness AND (via inherited
# env) every child process: an inversion in a surviving child fails that
# child's exit code; parent-side inversions fail the matrix at the end
os.environ.setdefault("EVERGREEN_TPU_LOCKCHECK", "1")

#: deterministic workload clock (same anchor the fault matrix uses)
NOW = 1_700_000_000.0
TICK_S = 15.0
#: enough ticks for the 24-task workload to fully drain (every task
#: succeeded, queues empty): resume ≡ rerun is asserted at CONVERGENCE —
#: a crash mid-dispatch-phase legitimately shifts which tick a task
#: finishes on (the dispatch path is per-op incremental, not
#: group-atomic), but the converged state must be identical
DEFAULT_TICKS = 9
LEASE_TTL_S = 0.75

#: the ≥12-point kill matrix: (seam, call-index) pairs covering solve,
#: WAL-append, group-flush (commit + fence), dispatch, recovery-pass and
#: lease-renewal seams. Indices are per-seam call counts inside the child.
KILL_POINTS: List[Tuple[str, int]] = [
    ("recovery.pass", 0),     # dies INSIDE the reconciliation pass
    ("wal.commit", 0),        # the seed frame's flush
    ("wal.commit", 1),        # tick 0's group flush
    ("wal.commit", 3),        # a mid-run group flush
    ("wal.fence", 1),         # just before tick 0's fence check
    ("scheduler.solve", 0),   # first device solve
    ("scheduler.solve", 2),   # a warm solve
    ("wal.append", 0),        # first per-op append (dispatch-phase write)
    ("wal.append", 7),        # a later per-op append
    ("dispatch.assign", 0),   # between the dispatch CAS pair
    ("dispatch.assign", 3),   # a later half-assignment
    ("lease.renew", 0),       # the renewer thread's first beat
    ("lease.renew", 1),       # a later renewal
]

#: distro-handoff kill points (sharded control plane,
#: scheduler/sharded_plane.py): the child runs a 2-shard plane with a
#: deterministic mid-run migration; a SIGKILL at any protocol step must
#: converge to exactly-one-owner with zero duplicate dispatch across
#: shards after restart + reconcile_handoffs.
SHARDED_KILL_POINTS: List[Tuple[str, int]] = [
    ("handoff.release", 0),   # inside the source's release WAL group —
    #                           the group never commits; no handoff at all
    ("handoff.record", 0),    # release durable, target NOT primed —
    #                           reconciliation re-primes from the record
    ("handoff.prime", 0),     # target primed, done-mark missing —
    #                           reconciliation completes it idempotently
]
#: which tick of the sharded child triggers the migration
MIGRATE_TICK = 2


# --------------------------------------------------------------------------- #
# child: the deterministic workload
# --------------------------------------------------------------------------- #


def _seed_problem(store) -> None:
    """Small deterministic problem, seeded idempotently (upserts — a
    crash mid-seed must not make the reseed raise on duplicates) and
    committed as ONE WAL group so the seed is crash-atomic."""
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.utils.benchgen import generate_problem

    distros, tasks_by_distro, hosts_by_distro, _, _ = generate_problem(
        2, 24, seed=11, hosts_per_distro=3, dep_fraction=0.25
    )
    store.begin_tick()
    try:
        for d in distros:
            distro_mod.coll(store).upsert(d.to_doc())
        for ts in tasks_by_distro.values():
            for t in ts:
                task_mod.coll(store).upsert(t.to_doc())
        for hs in hosts_by_distro.values():
            for h in hs:
                # benchgen stamps phantom running tasks for allocator
                # realism; the harness needs free hosts whose every
                # dispatch is a real CAS pair
                h.running_task = ""
                h.running_task_group = ""
                h.running_task_build_variant = ""
                h.running_task_version = ""
                h.running_task_project = ""
                host_mod.coll(store).upsert(h.to_doc())
        store.collection("harness").upsert({"_id": "progress", "ticks": 0})
    finally:
        store.end_tick()


def _agent_sim(store, now: float) -> None:
    """One deterministic agent step: finish everything in flight (tasks
    run exactly one tick), then dispatch every free host from the queues
    the tick just persisted — the real CAS pair, including its crash
    seam."""
    from evergreen_tpu.dispatch.assign import assign_next_available_task
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models.lifecycle import mark_end, mark_task_started

    c = task_mod.coll(store)
    in_flight = sorted(
        d["_id"] for d in c.find(
            lambda d: d["status"]
            in (TaskStatus.DISPATCHED.value, TaskStatus.STARTED.value)
        )
    )
    for tid in in_flight:
        mark_task_started(store, tid, now=now)
        mark_end(store, tid, TaskStatus.SUCCEEDED.value, now=now)
    svc = DispatcherService(store)  # fresh per step: no TTL staleness
    hosts = sorted(
        (h for h in host_mod.find(store) if h.can_run_tasks()
         and not h.running_task),
        key=lambda h: h.id,
    )
    for h in hosts:
        assign_next_available_task(store, svc, h, now=now)


def child_main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--data-dir", required=True)
    p.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    p.add_argument("--crash", default="", help="seam@index kill point")
    p.add_argument("--stall", type=float, default=0.0,
                   help="hang this long at the wal.fence seam each tick")
    p.add_argument("--ttl", type=float, default=LEASE_TTL_S)
    p.add_argument("--hold", action="store_true",
                   help="after the ticks, keep the lease until stdin EOF")
    p.add_argument("--sharded", type=int, default=0,
                   help="run the N-shard control-plane workload instead")
    args = p.parse_args(argv)

    from evergreen_tpu.utils import faults

    plan = faults.FaultPlan()
    if args.crash:
        seam, _, idx = args.crash.partition("@")
        plan.at(seam.strip(), int(idx or 0), faults.Fault("crash"))
    if args.stall > 0:
        plan.always("wal.fence", faults.Fault("hang", delay_s=args.stall))
    if args.crash or args.stall > 0:
        faults.install(plan)

    if args.sharded > 0:
        return sharded_child_main(args)

    from evergreen_tpu.scheduler.recovery import run_recovery_pass
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.lease import EpochFencedError, FileLease

    lease = FileLease(
        os.path.join(args.data_dir, "writer.lease"), ttl_s=args.ttl
    )
    if not lease.acquire(timeout_s=30.0, poll_s=0.1):
        print("LEASE-TIMEOUT", flush=True)
        return 3
    store = DurableStore(args.data_dir, lease=lease)
    lease.start_renewing(on_lost=lambda: None)  # loss observed via .lost

    prog = store.collection("harness").get("progress")
    done = prog["ticks"] if prog else 0
    report = run_recovery_pass(store, now=NOW + done * TICK_S)
    print("EPOCH " + str(lease.epoch), flush=True)
    print("RECOVERY " + json.dumps(report.to_doc()), flush=True)

    if prog is None:
        _seed_problem(store)

    opts = TickOptions(
        create_intent_hosts=False,  # intent ids are uuids: keeping the
        # tick idempotent keeps resume ≡ rerun byte-comparable
        underwater_unschedule=False,
        use_cache=False,
    )
    try:
        for i in range(done, args.ticks):
            now = NOW + (i + 1) * TICK_S
            res = run_tick(store, opts, now=now)
            if res.degraded == "fenced":
                print("FENCED", flush=True)
                os._exit(75)
            if lease.lost:
                print("LOST", flush=True)
                os._exit(70)
            _agent_sim(store, now)
            store.collection("harness").upsert(
                {"_id": "progress", "ticks": i + 1}
            )
            print(f"TICK-DONE {i}", flush=True)
        store.sync_persist()
    except EpochFencedError:
        # any fenced write rejection (dispatch/progress per-op appends
        # included) stands the stale holder down
        print("FENCED", flush=True)
        os._exit(75)
    print("DONE", flush=True)
    if args.hold:
        print("HOLDING", flush=True)
        sys.stdin.readline()  # parent signals; lease stays held meanwhile
    lease.release()
    # a surviving child audits the lock-order witness before reporting
    # success: an inversion on any of its threads is a failure even
    # though the workload converged
    from evergreen_tpu.utils import lockcheck

    if lockcheck.violations():
        print("LOCK-INVERSION", flush=True)
        os._exit(77)
    # no store.close(): the WAL must keep its frames for the parent's
    # epoch scan (everything is already flushed; close() would compact)
    os._exit(0)
    return 0


def sharded_child_main(args) -> int:
    """The 2-shard control-plane workload: per-shard DurableStores (own
    lease + WAL segment in ONE data dir), per-shard recovery + handoff
    reconciliation at startup, deterministic ticks with a forced
    migration of ``d000`` at MIGRATE_TICK, and a global agent pull
    dispatching every shard's hosts each step."""
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.scheduler.recovery import run_recovery_pass
    from evergreen_tpu.scheduler.sharded_plane import ShardedScheduler
    from evergreen_tpu.scheduler.wrapper import TickOptions
    from evergreen_tpu.storage.store import Store

    n = args.sharded
    plane = ShardedScheduler.build(
        n, data_dir=args.data_dir, lease_ttl_s=args.ttl,
        rebalance_enabled=False, stacked="never",
        tick_opts=TickOptions(
            create_intent_hosts=False, underwater_unschedule=False,
            use_cache=False,
        ),
    )
    for k, s in enumerate(plane.stores):
        s._lease.start_renewing(on_lost=lambda: None)
        print(f"EPOCH{k} {s._lease.epoch}", flush=True)

    prog = plane.stores[0].collection("harness").get("progress")
    done = prog["ticks"] if prog else 0
    for k, s in enumerate(plane.stores):
        report = run_recovery_pass(s, now=NOW + done * TICK_S)
        print(f"RECOVERY{k} " + json.dumps(report.to_doc()), flush=True)
    healed = plane.reconcile_handoffs(now=NOW + done * TICK_S)
    print("RECONCILED " + json.dumps(healed), flush=True)

    if prog is None:
        tmp = Store()
        _seed_problem(tmp)  # includes the progress doc
        tmp.collection("harness").remove("progress")
        plane.seed_partition(tmp)
        plane.stores[0].collection("harness").upsert(
            {"_id": "progress", "ticks": 0}
        )

    # the deterministic migration: d000 moves AWAY from its hash owner
    # (idempotent across restarts — a completed or reconciled handoff
    # already flipped the override, so the re-run skips it)
    mig_src = plane.topology.hash_shard_for("d000")
    mig_dst = (mig_src + 1) % n

    def agent_sim(now: float) -> None:
        from evergreen_tpu.globals import TaskStatus
        from evergreen_tpu.models import task as task_mod
        from evergreen_tpu.models.lifecycle import (
            mark_end,
            mark_task_started,
        )

        for store in plane.stores:
            c = task_mod.coll(store)
            for tid in sorted(
                d["_id"] for d in c.find(
                    lambda d: d["status"] in (
                        TaskStatus.DISPATCHED.value,
                        TaskStatus.STARTED.value,
                    )
                )
            ):
                mark_task_started(store, tid, now=now)
                mark_end(store, tid, TaskStatus.SUCCEEDED.value, now=now)
        plane._dispatchers.clear()  # fresh per step: no TTL staleness
        hosts = sorted(
            (
                Host.from_doc(doc)
                for store in plane.stores
                for doc in store.collection("hosts").find()
            ),
            key=lambda h: h.id,
        )
        for h in hosts:
            if h.can_run_tasks() and not h.running_task:
                plane.assign_next_task(h, now=now)

    from evergreen_tpu.storage.lease import EpochFencedError

    try:
        for i in range(done, args.ticks):
            now = NOW + (i + 1) * TICK_S
            if i == MIGRATE_TICK and plane.owner_of("d000") != mig_dst:
                rec = plane.migrate("d000", mig_dst, now=now)
                print("MIGRATED " + json.dumps(rec["group"]), flush=True)
            res = plane.tick(now=now)
            if any(r.degraded == "fenced" for r in res.results.values()):
                print("FENCED", flush=True)
                os._exit(75)
            agent_sim(now)
            plane.stores[0].collection("harness").upsert(
                {"_id": "progress", "ticks": i + 1}
            )
            print(f"TICK-DONE {i}", flush=True)
        for s in plane.stores:
            s.sync_persist()
    except EpochFencedError:
        print("FENCED", flush=True)
        os._exit(75)
    print("DONE", flush=True)
    for s in plane.stores:
        s._lease.release()
    # no close(): the WAL segments must keep their frames for inspection
    os._exit(0)
    return 0


# --------------------------------------------------------------------------- #
# parent: orchestration + invariants
# --------------------------------------------------------------------------- #


def _child_cmd(data_dir: str, ticks: int, crash: str = "",
               stall: float = 0.0, hold: bool = False,
               sharded: int = 0) -> List[str]:
    cmd = [
        sys.executable, os.path.abspath(__file__), "--child",
        "--data-dir", data_dir, "--ticks", str(ticks),
    ]
    if crash:
        cmd += ["--crash", crash]
    if stall > 0:
        cmd += ["--stall", str(stall)]
    if hold:
        cmd += ["--hold"]
    if sharded:
        cmd += ["--sharded", str(sharded)]
    return cmd


def _child_env() -> dict:
    return {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "EVG_FAULTS": "",
    }


def _run_child(data_dir: str, ticks: int, crash: str = "",
               timeout_s: float = 240.0) -> Tuple[int, str]:
    proc = subprocess.run(
        _child_cmd(data_dir, ticks, crash=crash),
        env=_child_env(), cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout_s,
    )
    return proc.returncode, proc.stdout.decode(errors="replace")


def wal_frame_epochs(data_dir: str) -> List[int]:
    """The ``e`` stamp of every parseable group frame, in file order."""
    out: List[int] = []
    path = os.path.join(data_dir, "wal.log")
    if not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            if not line.endswith("\n"):
                break
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("o") == "g":
                out.append(int(rec.get("e", 0) or 0))
    return out


def check_invariants(store) -> List[str]:
    """Structural invariants every recovered store must satisfy."""
    from evergreen_tpu.globals import TaskStatus

    problems: List[str] = []
    legal = {s.value for s in TaskStatus}
    claims: Dict[str, str] = {}
    for doc in store.collection("hosts").find():
        rt = doc.get("running_task", "")
        if not rt:
            continue
        if rt in claims.values():
            problems.append(f"duplicate claim of task {rt}")
        claims[doc["_id"]] = rt
    for doc in store.collection("tasks").find():
        if doc["status"] not in legal:
            problems.append(f"illegal status {doc['status']} on {doc['_id']}")
        if doc.get("execution", 0) < 0:
            problems.append(f"negative execution on {doc['_id']}")
        if doc["status"] in ("dispatched", "started"):
            hid = doc.get("host_id", "")
            hdoc = store.collection("hosts").get(hid)
            if hdoc is None or hdoc.get("running_task") != doc["_id"]:
                problems.append(
                    f"in-flight task {doc['_id']} not claimed by host {hid!r}"
                )
    for hid, rt in claims.items():
        tdoc = store.collection("tasks").get(rt)
        if tdoc is None or tdoc["status"] not in ("dispatched", "started"):
            problems.append(
                f"host {hid} claims task {rt} that is not in flight"
            )
    for coll_name in ("task_queues", "task_secondary_queues"):
        for doc in store.collection(coll_name).find():
            n = len(doc.get("rows", []))
            for col in ("sort_value", "dependencies_met"):
                if len(doc.get(col, [])) != n:
                    problems.append(
                        f"misaligned {col} in {coll_name}/{doc['_id']}"
                    )
    # duplicate dispatch: two TASK_DISPATCHED events for the same task at
    # the same tick timestamp would mean two hosts won the same CAS
    seen: Dict[tuple, int] = {}
    for doc in store.collection("events").find(
        lambda d: d.get("event_type") == "TASK_DISPATCHED"
    ):
        key = (doc.get("resource_id"), doc.get("timestamp"))
        seen[key] = seen.get(key, 0) + 1
        if seen[key] > 1:
            problems.append(f"duplicate dispatch event {key}")
    return problems


def canonical_state(store) -> dict:
    """The resume ≡ rerun comparison surface: converged task state +
    queue contents (doc versions/timestamps excluded — reruns bump them;
    content must not differ)."""
    from evergreen_tpu.models.task_queue import doc_column

    tasks = {
        d["_id"]: [d["status"], d.get("execution", 0)]
        for d in store.collection("tasks").find()
    }
    queues = {
        d["_id"]: doc_column(d, "id")
        for d in store.collection("task_queues").find()
    }
    return {"tasks": tasks, "queues": queues}


def _open_for_inspection(data_dir: str):
    from evergreen_tpu.storage.durable import DurableStore

    return DurableStore(data_dir)


def run_point(seam: str, index: int, ticks: int = DEFAULT_TICKS,
              reference: Optional[dict] = None) -> dict:
    """One kill point: run-with-crash, restart clean, check everything."""
    data_dir = tempfile.mkdtemp(prefix=f"crash-{seam.replace('.', '-')}-")
    crash = f"{seam}@{index}"
    rc1, out1 = _run_child(data_dir, ticks, crash=crash)
    crashed = rc1 == 86
    rc2, out2 = _run_child(data_dir, ticks)
    epochs = [
        int(line.split()[1])
        for line in (out1 + out2).splitlines()
        if line.startswith("EPOCH ")
    ]
    store = _open_for_inspection(data_dir)
    problems = check_invariants(store)
    prog = store.collection("harness").get("progress")
    if not prog or prog["ticks"] != ticks:
        problems.append(f"workload did not converge: progress={prog}")
    if not crashed and rc1 != 0:
        problems.append(f"first run died unexpectedly: rc={rc1}")
    if rc2 != 0:
        problems.append(f"recovery run failed: rc={rc2}")
    if epochs != sorted(set(epochs)):
        problems.append(f"epochs not strictly increasing: {epochs}")
    parity_ok = True
    if reference is not None:
        parity_ok = canonical_state(store) == reference
        if not parity_ok:
            problems.append("resume != rerun")
    return {
        "point": crash,
        "ok": crashed and not problems,
        "crashed": crashed,
        "rc": (rc1, rc2),
        "epochs": epochs,
        "parity_ok": parity_ok,
        "problems": problems,
        "data_dir": data_dir,
        "out": (out1 + out2) if problems else "",
    }


def reference_state(ticks: int = DEFAULT_TICKS) -> dict:
    """One uninterrupted run of the same workload — the rerun side of
    resume ≡ rerun."""
    data_dir = tempfile.mkdtemp(prefix="crash-reference-")
    rc, out = _run_child(data_dir, ticks)
    if rc != 0:
        raise RuntimeError(f"reference run failed rc={rc}:\n{out}")
    state = canonical_state(_open_for_inspection(data_dir))
    undrained = [
        tid for tid, (status, _) in state["tasks"].items()
        if status != "success"
    ]
    if undrained:
        raise RuntimeError(
            f"reference workload did not drain in {ticks} ticks "
            f"({len(undrained)} unfinished: {undrained[:5]}) — parity at "
            "convergence needs every task finished; raise ticks"
        )
    return state


def _run_sharded_child(data_dir: str, ticks: int, crash: str = "",
                       n: int = 2,
                       timeout_s: float = 240.0) -> Tuple[int, str]:
    proc = subprocess.run(
        _child_cmd(data_dir, ticks, crash=crash, sharded=n),
        env=_child_env(), cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=timeout_s,
    )
    return proc.returncode, proc.stdout.decode(errors="replace")


def _open_fleet_for_inspection(data_dir: str, n: int) -> list:
    from evergreen_tpu.storage.durable import DurableStore

    return [DurableStore(data_dir, shard_id=k) for k in range(n)]


def run_sharded_point(seam: str, index: int, ticks: int = DEFAULT_TICKS,
                      n: int = 2,
                      reference: Optional[dict] = None) -> dict:
    """One distro-handoff kill point on the 2-shard plane: crash at the
    protocol seam, restart (per-shard WAL replay + recovery +
    reconcile_handoffs), then assert exactly-one-owner, no duplicate
    dispatch across shards, monotone per-shard epochs, and resume ≡
    rerun at convergence against an uninterrupted sharded run."""
    from evergreen_tpu.scheduler.sharded_plane import (
        fleet_owner_violations,
        merge_fleet_state,
    )

    data_dir = tempfile.mkdtemp(
        prefix=f"crash-{seam.replace('.', '-')}-"
    )
    crash = f"{seam}@{index}"
    rc1, out1 = _run_sharded_child(data_dir, ticks, crash=crash, n=n)
    crashed = rc1 == 86
    rc2, out2 = _run_sharded_child(data_dir, ticks, n=n)
    out = out1 + out2
    problems: List[str] = []
    stores = _open_fleet_for_inspection(data_dir, n)
    problems.extend(fleet_owner_violations(stores))
    parity_ok = True
    try:
        merged = merge_fleet_state(stores)
    except ValueError as exc:
        problems.append(str(exc))
        merged = None
    if merged is not None:
        problems.extend(check_invariants(merged))
        prog = stores[0].collection("harness").get("progress")
        if not prog or prog["ticks"] != ticks:
            problems.append(f"workload did not converge: progress={prog}")
        if reference is not None:
            parity_ok = canonical_state(merged) == reference
            if not parity_ok:
                problems.append("resume != rerun")
    # the migration must actually have happened (a kill point that
    # silently skips the handoff proves nothing)
    migrated = any(
        line.startswith("MIGRATED") for line in out.splitlines()
    )
    if not migrated and seam != "handoff.release":
        # release-crash reruns may reconcile instead of re-migrating;
        # every other point re-prints MIGRATED on the run that did it
        migrated = any(
            s.collection("shard_handoffs").count() > 0 for s in stores
        )
    if not migrated:
        problems.append("no migration was attempted")
    # monotone epochs per shard
    epochs: Dict[int, List[int]] = {k: [] for k in range(n)}
    for line in out.splitlines():
        for k in range(n):
            if line.startswith(f"EPOCH{k} "):
                epochs[k].append(int(line.split()[1]))
    for k, es in epochs.items():
        if es != sorted(set(es)):
            problems.append(f"shard {k} epochs not increasing: {es}")
    if not crashed and rc1 != 0:
        problems.append(f"first run died unexpectedly: rc={rc1}")
    if rc2 != 0:
        problems.append(f"recovery run failed: rc={rc2}")
    return {
        "point": f"sharded:{crash}",
        "ok": crashed and not problems,
        "crashed": crashed,
        "rc": (rc1, rc2),
        "epochs": epochs,
        "parity_ok": parity_ok,
        "problems": problems,
        "data_dir": data_dir,
        "out": out if problems else "",
    }


def sharded_reference_state(ticks: int = DEFAULT_TICKS,
                            n: int = 2) -> dict:
    """One uninterrupted 2-shard run with the same forced migration —
    the rerun side of the sharded resume ≡ rerun."""
    from evergreen_tpu.scheduler.sharded_plane import merge_fleet_state

    data_dir = tempfile.mkdtemp(prefix="crash-sharded-reference-")
    rc, out = _run_sharded_child(data_dir, ticks, n=n)
    if rc != 0:
        raise RuntimeError(f"sharded reference failed rc={rc}:\n{out}")
    if "MIGRATED" not in out:
        raise RuntimeError("sharded reference never migrated d000")
    merged = merge_fleet_state(_open_fleet_for_inspection(data_dir, n))
    state = canonical_state(merged)
    undrained = [
        tid for tid, (status, _) in state["tasks"].items()
        if status != "success"
    ]
    if undrained:
        raise RuntimeError(
            f"sharded reference did not drain in {ticks} ticks "
            f"({len(undrained)} unfinished)"
        )
    return state


def run_sharded_points(ticks: int = DEFAULT_TICKS) -> int:
    """The distro-handoff kill points against one shared sharded
    reference; prints one JSON line per point, returns the failure
    count (shared by the full matrix and ``--sharded-only``)."""
    ref = sharded_reference_state(ticks)
    failures = 0
    for seam, idx in SHARDED_KILL_POINTS:
        out = run_sharded_point(seam, idx, ticks=ticks, reference=ref)
        print(json.dumps({
            k: out[k]
            for k in ("point", "ok", "crashed", "rc", "epochs",
                      "parity_ok", "problems")
        }))
        if not out["ok"]:
            failures += 1
            sys.stderr.write(out["out"] + "\n")
    return failures


def run_sup_points() -> int:
    """The SUPERVISOR-crash points (ISSUE 14) through the proc
    backend: SIGKILL mid-round fan-out and between the handoff
    release→prime legs on a supervised 2-shard fleet. Each must end in
    live ADOPTION — zero shard-lease epoch bumps, zero recovery
    passes, exactly-one-owner, resume ≡ rerun (the sup_kill weathers;
    the gate's fleet-runtime smoke runs the same two)."""
    from evergreen_tpu.scenarios.procs import (
        PROC_SCENARIOS,
        SUP_KILL_SCENARIOS,
        run_proc_scenario,
    )

    failures = 0
    for name in SUP_KILL_SCENARIOS:
        entry = run_proc_scenario(PROC_SCENARIOS[name]())
        stats = entry.get("stats", {})
        print(json.dumps({
            "point": name,
            "ok": entry["ok"],
            "adoptions": stats.get("adoptions_total", 0),
            "epoch_bumps": stats.get("adoption_epoch_bumps", 0),
            "reconciled": stats.get("reconciled_handoffs", 0),
            "restarts": stats.get("restarts_total", 0),
        }))
        if not entry["ok"]:
            failures += 1
            sys.stderr.write(
                json.dumps(entry, default=str) + "\n"
            )
    return failures


def run_solver_points() -> int:
    """The solver-LEADER death points (ISSUE 17) through the proc
    backend: the supervisor (= elected solver leader) dies at each
    solver seam — after collecting publications, after the stacked
    solve, after writing the FIRST shard's result, and at round start
    — plus a hang past the worker timeout. Every shard must degrade
    to a local solve that round (fenced at the shm header, never a
    torn fleet solve), the successor must re-elect the solver lease
    at a strictly higher epoch, stacked rounds must resume, and zero
    stale results / zero leaked shm segments are tolerated."""
    from evergreen_tpu.scenarios.procs import (
        PROC_SCENARIOS,
        SOLVER_SCENARIOS,
        run_proc_scenario,
    )

    failures = 0
    for name in SOLVER_SCENARIOS:
        entry = run_proc_scenario(PROC_SCENARIOS[name]())
        stats = entry.get("stats", {})
        print(json.dumps({
            "point": name,
            "ok": entry["ok"],
            "stacked": stats.get("solver_stacked_replies", 0),
            "local": stats.get("solver_local_replies", 0),
            "reelections": stats.get("solver_reelections", 0),
            "stale_accepted": stats.get("solver_stale_accepted", 0),
            "shm_leaked": stats.get("shm_leaked", 0),
        }))
        if not entry["ok"]:
            failures += 1
            sys.stderr.write(
                json.dumps(entry, default=str) + "\n"
            )
    return failures


def failover_case(ticks: int = 4, stall_s: float = 2.0) -> dict:
    """Two-process failover: holder SIGSTOPped mid-commit, standby steals
    and runs, holder SIGCONTed → its resumed commit is fenced; the WAL
    carries zero superseded-epoch frames past the fence point."""
    data_dir = tempfile.mkdtemp(prefix="crash-failover-")
    problems: List[str] = []
    holder = subprocess.Popen(
        _child_cmd(data_dir, 999, stall=stall_s),
        env=_child_env(), cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    holder_out: List[str] = []
    procs = [holder]
    try:
        # wait until the holder has completed a tick, then freeze it —
        # with the wal.fence stall dominating each tick, the stop lands
        # inside the begin_tick→flush window with high probability
        deadline = _time.time() + 120
        while _time.time() < deadline:
            line = holder.stdout.readline().decode(errors="replace")
            if not line:
                break
            holder_out.append(line)
            if line.startswith("TICK-DONE 0"):
                break
        else:
            problems.append("holder never finished tick 0")
        _time.sleep(stall_s / 2)  # land inside tick 1's fence stall
        os.kill(holder.pid, signal.SIGSTOP)

        # standby steals after the ttl and runs its own ticks, then HOLDS
        # the lease so the resumed holder fences against a live newer
        # epoch (not a missing file)
        standby = subprocess.Popen(
            _child_cmd(data_dir, ticks, hold=True),
            env=_child_env(), cwd=_REPO_ROOT,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        procs.append(standby)
        standby_out: List[str] = []
        deadline = _time.time() + 240
        while _time.time() < deadline:
            line = standby.stdout.readline().decode(errors="replace")
            if not line:
                break
            standby_out.append(line)
            if line.startswith("HOLDING"):
                break
        else:
            problems.append("standby never reached HOLDING")

        # resume the stale holder: its in-flight commit must fence
        os.kill(holder.pid, signal.SIGCONT)
        try:
            holder.wait(timeout=60)
        except subprocess.TimeoutExpired:
            holder.kill()
            problems.append("resumed holder did not stand down")
        holder_out.append(
            holder.stdout.read().decode(errors="replace")
        )
        if holder.returncode not in (70, 75):
            problems.append(
                f"holder exit {holder.returncode}, want 70 (lost) or 75 "
                "(EpochFencedError at commit)"
            )

        standby.stdin.close()  # let the standby release and exit
        try:
            standby.wait(timeout=30)
        except subprocess.TimeoutExpired:
            standby.kill()
            problems.append("standby did not exit after release")
        standby_text = "".join(standby_out)
        holder_text = "".join(holder_out)

        holder_epoch = standby_epoch = 0
        for line in holder_text.splitlines():
            if line.startswith("EPOCH "):
                holder_epoch = int(line.split()[1])
        for line in standby_text.splitlines():
            if line.startswith("EPOCH "):
                standby_epoch = int(line.split()[1])
        if standby_epoch <= holder_epoch:
            problems.append(
                f"standby epoch {standby_epoch} !> holder {holder_epoch}"
            )

        # the acceptance grep: zero frames with a superseded epoch after
        # the fence point
        epochs = wal_frame_epochs(data_dir)
        fence_at = next(
            (i for i, e in enumerate(epochs) if e >= standby_epoch), None
        )
        stale_after_fence = (
            [] if fence_at is None
            else [e for e in epochs[fence_at:] if 0 < e < standby_epoch]
        )
        if standby_epoch and fence_at is None:
            problems.append("standby committed no frames")
        if stale_after_fence:
            problems.append(
                f"stale-epoch frames past the fence: {stale_after_fence}"
            )

        store = _open_for_inspection(data_dir)
        problems.extend(check_invariants(store))
        return {
            "ok": not problems,
            "problems": problems,
            "holder_exit": holder.returncode,
            "holder_epoch": holder_epoch,
            "standby_epoch": standby_epoch,
            "frame_epochs": epochs,
            "fenced_at_commit": "FENCED" in holder_text,
            "data_dir": data_dir,
            "holder_out": holder_text if problems else "",
            "standby_out": standby_text if problems else "",
        }
    finally:
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGCONT)
                except OSError:
                    pass
                proc.kill()


def run_matrix(points: Optional[List[Tuple[str, int]]] = None,
               ticks: int = DEFAULT_TICKS) -> int:
    """The full matrix. The 13 process-SIGKILL points run THROUGH the
    scenario engine's child-process backend (scenarios/procs.py
    ``run_crash_point``: a 1-shard supervised fleet whose worker dies
    at the seam and is restarted fenced by the production supervisor)
    — the same delegation PR 10 gave the fault/overload matrices.
    ``run_point`` above remains the bespoke single-point harness for
    ``--point`` and the tier-1 reduced sample
    (tests/test_crash_recovery.py); the failover and distro-handoff
    cases stay bespoke (two live processes / a live 2-shard plane have
    no engine analog yet)."""
    from evergreen_tpu.scenarios.procs import (
        proc_reference_state,
        run_crash_point,
    )

    points = points if points is not None else KILL_POINTS
    reference = proc_reference_state(ticks)
    failures = 0
    for seam, idx in points:
        out = run_crash_point(seam, idx, ticks=ticks,
                              reference=reference)
        print(json.dumps({
            k: out[k]
            for k in ("point", "ok", "crashed", "rc", "epochs",
                      "parity_ok", "problems")
        }))
        if not out["ok"]:
            failures += 1
            sys.stderr.write(
                json.dumps(out.get("entry", {}), default=str) + "\n"
            )
    fo = failover_case()
    print(json.dumps({
        k: fo[k]
        for k in ("ok", "problems", "holder_exit", "holder_epoch",
                  "standby_epoch", "frame_epochs", "fenced_at_commit")
    }))
    if not fo["ok"]:
        failures += 1
        sys.stderr.write(fo["holder_out"] + "\n" + fo["standby_out"] + "\n")
    # distro-handoff kill points on the 2-shard plane
    failures += run_sharded_points(ticks)
    # supervisor-crash points: mid-round + mid-handoff SIGKILL of the
    # SUPERVISOR itself, resolved by orphan mode + live adoption
    n_sup = run_sup_points()
    failures += n_sup
    # solver-leader death points: the leader dies (or stalls) at each
    # solver seam, resolved by degrade-to-local + re-election
    from evergreen_tpu.scenarios.procs import SOLVER_SCENARIOS

    failures += run_solver_points()
    print(json.dumps({
        "crash_matrix_failures": failures,
        "points": len(points) + 1 + len(SHARDED_KILL_POINTS) + 2
        + len(SOLVER_SCENARIOS),
    }))
    return 1 if failures else 0


def main() -> int:
    if "--child" in sys.argv:
        argv = [a for a in sys.argv[1:] if a != "--child"]
        return child_main(argv)
    import argparse

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--point", default="",
                   help="run one kill point only (seam@index)")
    p.add_argument("--failover-only", action="store_true")
    p.add_argument("--sharded-only", action="store_true",
                   help="run only the distro-handoff kill points")
    p.add_argument("--sup-only", action="store_true",
                   help="run only the supervisor-crash points")
    p.add_argument("--solver-only", action="store_true",
                   help="run only the solver-leader death points")
    p.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    args = p.parse_args()
    # the sup/solver points run supervisors IN THIS PROCESS; the
    # solver-leader's stacked shard_map solve needs a device per shard
    # — pin the backend before anything initializes jax
    from evergreen_tpu.utils.jaxenv import force_cpu

    force_cpu(n_devices=2)
    if args.solver_only:
        return 1 if run_solver_points() else 0
    if args.sup_only:
        return 1 if run_sup_points() else 0
    if args.sharded_only:
        return 1 if run_sharded_points(args.ticks) else 0
    if args.failover_only:
        out = failover_case()
        print(json.dumps({k: v for k, v in out.items()
                          if not k.endswith("_out")}))
        return 0 if out["ok"] else 1
    if args.point:
        seam, _, idx = args.point.partition("@")
        out = run_point(seam, int(idx or 0), ticks=args.ticks,
                        reference=reference_state(args.ticks))
        print(json.dumps({k: v for k, v in out.items() if k != "out"}))
        return 0 if out["ok"] else 1
    rc = run_matrix(ticks=args.ticks)
    # parent-side witness audit: the harness itself runs stores, leases
    # and dispatch in-process; any inversion recorded here is a failure
    from evergreen_tpu.utils import lockcheck

    inversions = lockcheck.violations()
    if inversions:
        print(json.dumps({"lockcheck_inversions": len(inversions)}))
        rc = rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
