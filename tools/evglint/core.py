"""evglint core: module walker, finding model, suppressions, runner.

Passes are plain modules (tools/evglint/passes/*) exporting:

  ``NAME``      the pass id used in suppressions and --pass
  ``run(modules) -> List[Finding]``   whole-project analysis
  ``SABOTAGE``  {"rel": ..., "source": ...} — a synthetic module seeded
                with exactly the violation class the pass exists to
                catch; the --sabotage self-test asserts it is caught.

The core owns suppression semantics so every pass inherits them: a
``# evglint: disable=<pass>[,<pass>] -- <reason>`` comment suppresses
that pass's findings on its own line (trailing comment) or on the next
line (standalone comment). The justification after ``--`` is mandatory;
a suppression without one is a finding from the ``core`` pseudo-pass.
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Set

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PACKAGE_DIR = os.path.join(REPO_ROOT, "evergreen_tpu")

_SUPPRESS_RE = re.compile(
    r"#\s*evglint:\s*disable=([a-zA-Z0-9_,\-]+)(?:\s+--\s*(\S.*))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One violation: where, which pass, and what to do about it."""

    passname: str
    rel: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.rel}:{self.line}: [{self.passname}] {self.message}"


class Module:
    """One parsed source file plus its suppression map."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel
        self.source = source
        self.lines = source.split("\n")
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(source, filename=rel)
        except SyntaxError as exc:
            self.parse_error = exc
        #: line -> set of pass names suppressed there (reason present)
        self.suppressions: Dict[int, Set[str]] = {}
        #: distinct justified suppression COMMENTS (a trailing comment
        #: may map to two lines; audits count comments, not mappings)
        self.n_suppression_comments = 0
        #: suppressions missing the mandatory justification
        self.bad_suppressions: List[Finding] = []
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            passes = {p.strip() for p in m.group(1).split(",") if p.strip()}
            reason = (m.group(2) or "").strip()
            if not reason:
                self.bad_suppressions.append(
                    Finding(
                        "core", self.rel, i,
                        "suppression without justification — write "
                        "`# evglint: disable=<pass> -- <why this is "
                        "safe>` naming the invariant that holds",
                    )
                )
                continue
            self.n_suppression_comments += 1
            code_before = text[: m.start()].strip()
            target = i if code_before else i + 1
            self.suppressions.setdefault(target, set()).update(passes)
            # a trailing suppression also covers a multi-line statement
            # that ENDS on this line — but only the INNERMOST one.
            # Mapping every enclosing stmt that happens to end here
            # (the function whose last line this is, an enclosing
            # with/try) would silently widen the suppression to
            # findings its justification never argued for.
            if code_before and self.tree is not None:
                candidates = [
                    node for node in ast.walk(self.tree)
                    if (
                        getattr(node, "end_lineno", None) == i
                        and isinstance(node, ast.stmt)
                        and node.lineno < i
                        and not isinstance(
                            node,
                            (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef),
                        )
                    )
                ]
                if candidates:
                    innermost = max(candidates, key=lambda n: n.lineno)
                    self.suppressions.setdefault(
                        innermost.lineno, set()
                    ).update(passes)

    def is_suppressed(self, passname: str, line: int) -> bool:
        return passname in self.suppressions.get(line, ())

    def segment(self, node: ast.AST) -> str:
        """Source text of a node ('' when unavailable)."""
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:
            return ""


def iter_modules(root: str = PACKAGE_DIR) -> List[Module]:
    out: List[Module] = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, REPO_ROOT)
            with open(path, encoding="utf-8") as fh:
                out.append(Module(rel, fh.read()))
    return out


def load_passes(names: Optional[Iterable[str]] = None) -> List:
    from .passes import ALL_PASSES

    if names is None:
        return list(ALL_PASSES)
    by_name = {p.NAME: p for p in ALL_PASSES}
    missing = [n for n in names if n not in by_name]
    if missing:
        raise SystemExit(
            f"evglint: unknown pass(es) {', '.join(missing)} "
            f"(known: {', '.join(sorted(by_name))})"
        )
    return [by_name[n] for n in names]


def run_passes(
    passes: List, modules: Optional[List[Module]] = None
) -> List[Finding]:
    """Run the passes, apply suppressions, and fold in core findings
    (parse errors, justification-less suppressions)."""
    if modules is None:
        modules = iter_modules()
    findings: List[Finding] = []
    for m in modules:
        findings.extend(m.bad_suppressions)
        if m.parse_error is not None:
            findings.append(
                Finding("core", m.rel, m.parse_error.lineno or 0,
                        f"unparseable: {m.parse_error.msg}")
            )
    parseable = [m for m in modules if m.tree is not None]
    by_rel = {m.rel: m for m in modules}
    for p in passes:
        for f in p.run(parseable):
            mod = by_rel.get(f.rel)
            if mod is not None and mod.is_suppressed(f.passname, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.rel, f.line, f.passname))
    return findings


def sabotage_selftest(passes: List) -> int:
    """Seed one violation per pass, assert the pass catches it. Returns
    the count of passes whose seed ESCAPED (0 == healthy)."""
    escaped = 0
    for p in passes:
        sab = getattr(p, "SABOTAGE", None)
        if not sab:
            print(f"evglint sabotage: {p.NAME}: NO SELF-TEST SEED")
            escaped += 1
            continue
        module = Module(sab["rel"], sab["source"])
        assert module.parse_error is None, (p.NAME, module.parse_error)
        caught = [f for f in p.run([module]) if f.rel == sab["rel"]]
        if caught:
            print(
                f"evglint sabotage: {p.NAME}: caught seeded violation "
                f"({caught[0].message[:60]}…)"
            )
        else:
            print(
                f"evglint sabotage: {p.NAME}: seeded violation ESCAPED"
            )
            escaped += 1
    return escaped
