"""diskcheck — durable-plane writes go through the checksummed writers.

The storage integrity contract (end-to-end CRC32, PR 19) holds because
every byte the durable plane persists is written by one of the two
sanctioned paths: the journal/snapshot machinery in
``storage/durable.py`` (WAL line stamps + snapshot digests) or
``storage/integrity.py``'s ``atomic_write_json`` (document stamps,
guaranteed tmp cleanup, the disk-fault seams). A raw ``open(..., 'w')``
or ``os.replace`` against a store path from elsewhere in the durable
plane publishes bytes NO DIGEST EVER COVERS: bitrot there replays as
truth, and an ENOSPC there strands tmp files the way the old manifest
writer did.

Scope: ``evergreen_tpu/storage/`` and ``evergreen_tpu/runtime/`` — the
modules that own or sit beside the data dir. (fencecheck polices the
rest of the tree, where the failure mode is fence bypass rather than
unstamped bytes; these two passes meet at the storage/ boundary each
exempts for the other.) ``storage/durable.py`` and
``storage/integrity.py`` ARE the sanctioned writers, so they are exempt.
A suppression must name the invariant that makes the unstamped write
safe (e.g. a self-validating payload).

Heuristic: identical to fencecheck's — a mutating filesystem call whose
argument text (or local-variable taint) mentions a store-path marker.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Module
from .fencecheck import _mutator_name, _tainted_names, _MARKERS

NAME = "diskcheck"

#: the durable plane this pass polices
_SCOPE_PREFIXES = ("evergreen_tpu/storage/", "evergreen_tpu/runtime/")
#: the sanctioned checksummed writers themselves
_EXEMPT = (
    "evergreen_tpu/storage/durable.py",
    "evergreen_tpu/storage/integrity.py",
)


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.rel.startswith(_SCOPE_PREFIXES):
            continue
        if m.rel in _EXEMPT or "/tests/" in m.rel:
            continue
        taint_cache = {}
        parents = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _mutator_name(node)
            if name is None:
                continue
            seg = m.segment(node).lower()
            hit = any(mk in seg for mk in _MARKERS)
            if not hit:
                anc = node
                while anc in parents and not isinstance(
                    anc, ast.FunctionDef
                ):
                    anc = parents[anc]
                if isinstance(anc, ast.FunctionDef):
                    if anc not in taint_cache:
                        taint_cache[anc] = _tainted_names(anc, m)
                    refs = {
                        n.id for n in ast.walk(node)
                        if isinstance(n, ast.Name)
                    }
                    hit = bool(refs & taint_cache[anc])
            if hit:
                findings.append(Finding(
                    NAME, m.rel, node.lineno,
                    f"direct {name} against a store path from the "
                    "durable plane — bytes published here carry no CRC "
                    "stamp, so bitrot replays as truth and a full disk "
                    "strands tmp files; route through "
                    "storage/integrity.py atomic_write_json (or the "
                    "journal/snapshot machinery) or suppress naming "
                    "the invariant that makes the unstamped write safe",
                ))
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/storage/sabotage_disk.py",
    "source": '''\
import os


def publish_unstamped(data_dir):
    snap = os.path.join(data_dir, "snapshot.json")
    with open(snap + ".tmp", "w") as f:   # seeded: unstamped tmp write
        f.write("{}")
    os.replace(snap + ".tmp", snap)       # seeded: unstamped publish
''',
}
