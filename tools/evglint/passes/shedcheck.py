"""shedcheck — zero silent discards, enforced at parse time.

The "counters == records" invariant (PR 4, scenario engine): any path
that drops, sheds, or evicts work must increment a registered
instrument, so the scorecard's counter deltas reconcile against store
records and a regression in graceful degradation is visible. Two rules:

1. A function whose name says it discards work (a ``shed``/``evict``/
   ``discard`` name segment) must touch an
   instrument (``.inc()`` / ``.observe()`` / ``record_shed`` /
   ``log_event``) somewhere in its body — otherwise the drop is
   invisible to the zero-silent-discards reconciliation.

2. A broad handler (``except Exception`` / ``except BaseException`` /
   bare ``except``) whose body neither calls anything nor raises is a
   silent swallow — the one shape of ``except`` that can hide dropped
   work, a dead thread, or a poisoned job with no trace. Narrow
   handlers (``except OSError: pass`` teardown) are left alone.
"""
from __future__ import annotations

import ast
import re
from typing import List

from ..core import Finding, Module

NAME = "shedcheck"

#: segment-aware: `shed` must start a name segment (``is_finished`` and
#: spec-factory names like ``*_budget_shed`` in scenarios/ are not
#: discard paths — the former by tokenization, the latter by scope)
_DISCARD_NAME = re.compile(r"(^|_)(shed|evict|discard)")
_INSTRUMENT_ATTRS = {"inc", "observe", "set"}
_INSTRUMENT_NAMES = {"record_shed", "log_event", "incr_counter"}
_BROAD = {"Exception", "BaseException"}


def _touches_instrument(fnode: ast.FunctionDef) -> bool:
    for node in ast.walk(fnode):
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and (
                fn.attr in _INSTRUMENT_ATTRS
                or fn.attr in _INSTRUMENT_NAMES
            ):
                return True
            if isinstance(fn, ast.Name) and fn.id in _INSTRUMENT_NAMES:
                return True
    return False


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    for n in ast.walk(t):
        if isinstance(n, ast.Name):
            names.append(n.id)
        elif isinstance(n, ast.Attribute):
            names.append(n.attr)
    return any(n in _BROAD for n in names)


def _swallows(handler: ast.ExceptHandler) -> bool:
    """Only a PURE swallow counts: every statement is ``pass`` /
    ``continue``. A handler that raises, calls, returns, or assigns a
    fallback has taken a visible degradation action — that shape is the
    caller's design, not a silent discard."""
    return all(
        isinstance(node, (ast.Pass, ast.Continue)) for node in handler.body
    )


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if "/tests/" in m.rel:
            continue
        spec_module = m.rel.startswith("evergreen_tpu/scenarios/")
        for node in ast.walk(m.tree):
            if (
                isinstance(node, ast.FunctionDef)
                and _DISCARD_NAME.search(node.name)
                and not spec_module
            ):
                if not _touches_instrument(node):
                    findings.append(Finding(
                        NAME, m.rel, node.lineno,
                        f"{node.name}() discards work without touching "
                        "an instrument — every shed/evict path must "
                        "increment a registered counter (counters == "
                        "records, zero silent discards)",
                    ))
            elif isinstance(node, ast.ExceptHandler):
                if _is_broad(node) and _swallows(node):
                    findings.append(Finding(
                        NAME, m.rel, node.lineno,
                        "broad except swallows silently — log a "
                        "breadcrumb or bump a counter so the discard "
                        "reconciles (counters == records), or narrow "
                        "the exception type",
                    ))
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/queue/sabotage_shed.py",
    "source": '''\
def shed_overflow(queue, n):
    del queue[:n]                  # seeded: uninstrumented shed


def tick(work):
    try:
        work()
    except Exception:              # seeded: silent broad swallow
        pass
''',
}
