"""fencecheck — data-dir mutations go through the epoch-stamped store.

The split-brain contract (PR 3) holds because every byte under the data
dir is written by ``storage/`` code that stamps the holder's lease epoch
and re-checks the fence at commit. A direct ``open(..., 'w')`` /
``os.rename`` / ``shutil.rmtree`` against a store path from anywhere
else bypasses the fence: a deposed holder could clobber the new
holder's state and no epoch would ever say so.

Heuristic: outside ``evergreen_tpu/storage/``, a mutating filesystem
call whose argument text mentions a store-path marker (``data_dir``,
``wal``, ``snapshot``, ``lease``, ``manifest``) is a finding. Mutations
of unrelated paths (task workdirs, bench outputs) don't match and are
ignored. Legitimate non-store files living beside the store (the
supervisor's fleet manifest) carry a suppression naming the invariant
that makes the bypass safe.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Module

NAME = "fencecheck"

_EXEMPT_PREFIX = "evergreen_tpu/storage/"
_MARKERS = ("data_dir", "wal", "snapshot", "lease", "manifest")
_WRITE_MODES = ("w", "a", "x", "+")

#: (module alias, attr) mutating calls
_MUTATORS = {
    ("os", "rename"), ("os", "replace"), ("os", "remove"),
    ("os", "unlink"), ("os", "truncate"),
    ("shutil", "rmtree"), ("shutil", "move"),
}
_PATH_WRITERS = {"write_text", "write_bytes"}


def _open_write_mode(node: ast.Call) -> bool:
    fn = node.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return any(c in mode.value for c in _WRITE_MODES)
    return False


def _mutator_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value.id if isinstance(fn.value, ast.Name) else ""
        if (recv, fn.attr) in _MUTATORS:
            return f"{recv}.{fn.attr}"
        if fn.attr in _PATH_WRITERS:
            return f".{fn.attr}"
    if _open_write_mode(node):
        return "open(…, 'w')"
    return None


def _tainted_names(fnode: ast.FunctionDef, m: Module) -> set:
    """Names assigned (directly or transitively) from a marker-bearing
    expression inside this function — ``tmp = f\"{path}.{pid}\"`` after
    ``path = entry_path(data_dir, shard)`` is still a store path even
    though the mutating call's own text never says so."""
    tainted: set = set()
    assigns = [n for n in ast.walk(fnode) if isinstance(n, ast.Assign)]
    # marker-bearing params count as sources too (data_dir et al.)
    for a in fnode.args.args + fnode.args.kwonlyargs:
        if any(mk in a.arg.lower() for mk in _MARKERS):
            tainted.add(a.arg)
    changed = True
    while changed:
        changed = False
        for node in assigns:
            seg = m.segment(node.value).lower()
            refs = {
                n.id for n in ast.walk(node.value)
                if isinstance(n, ast.Name)
            }
            if any(mk in seg for mk in _MARKERS) or refs & tainted:
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name) and n.id not in tainted:
                            tainted.add(n.id)
                            changed = True
    return tainted


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if m.rel.startswith(_EXEMPT_PREFIX) or "/tests/" in m.rel:
            continue
        taint_cache = {}
        parents = {}
        for node in ast.walk(m.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _mutator_name(node)
            if name is None:
                continue
            seg = m.segment(node).lower()
            hit = any(mk in seg for mk in _MARKERS)
            if not hit:
                # variable indirection: walk up to the enclosing
                # function and consult its store-path taint set
                anc = node
                while anc in parents and not isinstance(
                    anc, ast.FunctionDef
                ):
                    anc = parents[anc]
                if isinstance(anc, ast.FunctionDef):
                    if anc not in taint_cache:
                        taint_cache[anc] = _tainted_names(anc, m)
                    refs = {
                        n.id for n in ast.walk(node)
                        if isinstance(n, ast.Name)
                    }
                    hit = bool(refs & taint_cache[anc])
            if hit:
                findings.append(Finding(
                    NAME, m.rel, node.lineno,
                    f"direct {name} against a store path — data-dir "
                    "mutations must go through the epoch-stamped "
                    "DurableStore/lease APIs in storage/ (a deposed "
                    "holder bypasses the fence here); route through "
                    "the store or suppress naming the fencing invariant",
                ))
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/scheduler/sabotage_fence.py",
    "source": '''\
import os


def clobber(data_dir):
    with open(os.path.join(data_dir, "snapshot.json"), "w") as f:
        f.write("{}")              # seeded: unfenced store write
    os.rename(
        os.path.join(data_dir, "wal.log"),
        os.path.join(data_dir, "wal.old"),
    )


def clobber_indirect(data_dir):
    p = os.path.join(data_dir, "wal.log")
    tmp = p + ".tmp"
    os.rename(tmp, p)              # seeded: marker hidden behind locals
''',
}
