"""tracercheck — JIT-purity / static-shape discipline in ``ops/``.

The recompile/TPU-divergence bug class: code inside a jitted body that
forces a tracer to a Python value (``.item()``, ``float()``/``int()``/
``bool()``), calls host NumPy, or branches Python-side on a traced
value either crashes under jit, silently recompiles per value, or — the
worst case — bakes one trace's value into every later call. The pass
finds jitted bodies (``@jax.jit`` / ``functools.partial(jax.jit, …)``
decorators, ``jax.jit(fn, …)`` wrap sites, and ``pl.pallas_call``
kernels) and walks them with a traced-name set:

  * parameters are traced, minus ``static_argnames``/``static_argnums``;
  * assignments from traced expressions propagate taint, EXCEPT values
    derived from ``.shape``/``.ndim``/``.dtype``/``.size``/``len()`` —
    those are static under tracing and branching on them is the
    intended idiom;
  * ``if``/``while`` on tainted names, ``.item()``, non-constant
    ``float()/int()/bool()``, and ``np.*()`` calls (other than literal
    dtype casts like ``np.float32(0.5)``, the weak-type-control idiom)
    are findings.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, Module

NAME = "tracercheck"

_SCOPE_PREFIX = "evergreen_tpu/ops/"

#: np.<attr>(...) calls that are literal casts / host-side constants —
#: the deliberate f32-literal weak-type idiom, not host compute
_NP_CAST_OK = {
    "float32", "float64", "int32", "int64", "uint8", "uint16",
    "uint32", "uint64", "int8", "int16", "bool_", "dtype",
}
#: deriving these from a tracer yields a STATIC value — names assigned
#: from them are not tainted and branching on them is fine
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "weak_type"}


def _is_jit_call(node: ast.Call) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``pl.pallas_call(...)``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in ("jit", "pallas_call")
    return isinstance(fn, ast.Name) and fn.id == "jit"


def _static_names_from_call(call: ast.Call, fnode) -> Set[str]:
    """static_argnames/static_argnums resolved to parameter names."""
    out: Set[str] = set()
    params = [a.arg for a in fnode.args.args] if fnode is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, int):
                    if 0 <= el.value < len(params):
                        out.add(params[el.value])
    return out


def _collect_jitted(module: Module) -> Dict[ast.FunctionDef, Set[str]]:
    """jitted FunctionDef → static param names."""
    funcs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            funcs.setdefault(node.name, []).append(node)

    jitted: Dict[ast.FunctionDef, Set[str]] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.FunctionDef):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Attribute) and dec.attr == "jit":
                    jitted[node] = set()
                elif isinstance(dec, ast.Name) and dec.id == "jit":
                    jitted[node] = set()
                elif isinstance(dec, ast.Call):
                    # functools.partial(jax.jit, static_argnames=…) or
                    # jax.jit(static_argnums=…) as a decorator factory
                    inner_names = {
                        a.attr if isinstance(a, ast.Attribute)
                        else getattr(a, "id", "")
                        for a in ast.walk(dec)
                    }
                    if "jit" in inner_names:
                        jitted[node] = _static_names_from_call(dec, node)
        elif isinstance(node, ast.Call) and _is_jit_call(node):
            # jax.jit(fn, …) / pl.pallas_call(kernel, …) wrap sites
            if node.args and isinstance(node.args[0], ast.Name):
                for f in funcs.get(node.args[0].id, []):
                    jitted[f] = _static_names_from_call(node, f)
    return jitted


def _refs(expr: ast.AST) -> Set[str]:
    return {
        n.id for n in ast.walk(expr)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
    }


def _is_static_expr(expr: ast.AST, module: Module) -> bool:
    """True when the expression only consumes trace-static facts."""
    seg = module.segment(expr)
    if any(f".{a}" in seg for a in _STATIC_ATTRS):
        return True
    if "len(" in seg or "isinstance(" in seg:
        return True
    if " is None" in seg or " is not None" in seg:
        return True
    return False


def _check_body(
    fnode: ast.FunctionDef, static: Set[str], module: Module,
    findings: List[Finding],
) -> None:
    tainted: Set[str] = {
        a.arg
        for a in (
            fnode.args.args + fnode.args.kwonlyargs
            + ([fnode.args.vararg] if fnode.args.vararg else [])
        )
        if a is not None and a.arg not in static and a.arg != "self"
    }

    for node in ast.walk(fnode):
        if isinstance(node, ast.Assign) and not _is_static_expr(
            node.value, module
        ):
            if _refs(node.value) & tainted:
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
        elif isinstance(node, (ast.If, ast.While)):
            if (
                _refs(node.test) & tainted
                and not _is_static_expr(node.test, module)
            ):
                findings.append(Finding(
                    NAME, module.rel, node.lineno,
                    "Python branch on a traced value inside a jitted "
                    "body — each value recompiles (or the first trace's "
                    "branch is baked in); use jnp.where/lax.cond, or "
                    "hoist the value to a static arg",
                ))
        elif isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item":
                findings.append(Finding(
                    NAME, module.rel, node.lineno,
                    ".item() inside a jitted body forces a device sync "
                    "and fails under trace — return the array and read "
                    "it host-side",
                ))
            elif (
                isinstance(fn, ast.Name)
                and fn.id in ("float", "int", "bool")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
                and not _is_static_expr(node.args[0], module)
                and _refs(node.args[0]) & tainted
            ):
                findings.append(Finding(
                    NAME, module.rel, node.lineno,
                    f"{fn.id}() on a traced value inside a jitted body "
                    "— a ConcretizationTypeError on TPU; keep it an "
                    "array or make the input static",
                ))
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in ("np", "numpy")
                and fn.attr not in _NP_CAST_OK
            ):
                findings.append(Finding(
                    NAME, module.rel, node.lineno,
                    f"host NumPy call np.{fn.attr}() inside a jitted "
                    "body — runs at trace time on tracer inputs (crash) "
                    "or bakes a constant; use jnp",
                ))


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if not m.rel.startswith(_SCOPE_PREFIX):
            continue
        for fnode, static in _collect_jitted(m).items():
            _check_body(fnode, static, m, findings)
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/ops/sabotage_ops.py",
    "source": '''\
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad(x):
    if x > 0:                      # seeded: branch on a traced value
        x = x + 1
    y = float(x)                   # seeded: tracer concretization
    z = np.argsort(x)              # seeded: host NumPy in a jitted body
    return jnp.sum(x) + y + z[0] + x.item()   # seeded: .item()
''',
}
