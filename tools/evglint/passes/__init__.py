"""Pass registry. Order is the report order; names are the suppression
vocabulary (``# evglint: disable=<name> -- reason``)."""
from . import (  # noqa: F401
    diskcheck,
    fencecheck,
    lockgraph,
    metricscheck,
    seamcheck,
    shedcheck,
    tracercheck,
)

ALL_PASSES = [
    lockgraph,
    tracercheck,
    fencecheck,
    diskcheck,
    shedcheck,
    seamcheck,
    metricscheck,
]
