"""lockgraph — lock inventory, static acquisition order, blocking calls.

Three rules over the whole package:

1. **Inventory**: every lock is created through the
   ``utils/lockcheck.py`` factories (``make_lock``/``make_rlock``/
   ``make_condition``) so the runtime lock-order witness can see it. A
   raw ``threading.Lock()`` / ``RLock()`` / zero-arg ``Condition()``
   creation is a finding. (``threading.Condition(existing_lock)`` is
   fine — the condition adds no lock of its own.)

2. **Order graph**: within each function, nested ``with <lock>`` blocks
   contribute ``outer → inner`` edges to one project-wide order graph,
   with lock identity resolved through the factory ROLE strings
   (``self._x = make_lock("role")`` class attrs, module globals, and
   ``Condition(shared_lock)`` aliases). An edge pair seen in both
   directions, or any longer cycle, is a finding at every contributing
   site. This is the static half of the witness: it proves ordering over
   acquisitions the runtime may never exercise.

3. **Lock-held-across-blocking-call**: a call that can block on the
   outside world (sleep, subprocess, socket IO, ``urlopen``, ``fsync``,
   ``wait_reply``, ``communicate``) while a registered lock is held
   starves every contender of that lock for the call's duration — a
   finding unless suppressed with the invariant that bounds the wait.
   (``cv.wait()`` is exempt: it releases the lock.)
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, Module

NAME = "lockgraph"

#: attribute tails that block on the outside world when called
_BLOCKING_ATTRS = {
    "sleep", "wait_reply", "communicate", "urlopen", "fsync",
    "check_call", "check_output", "accept", "connect", "recv",
    "sendall", "getaddrinfo",
}
#: (receiver, attr) pairs that block (receiver alias substring match)
_BLOCKING_RECEIVER_ATTRS = {("subprocess", "run"), ("subprocess", "call")}

_FACTORIES = {"make_lock", "make_rlock", "make_condition"}


def _factory_role(node: ast.AST) -> Optional[str]:
    """role string when ``node`` is ``[_lockcheck.]make_*("role"...)``."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = (
        fn.attr if isinstance(fn, ast.Attribute)
        else fn.id if isinstance(fn, ast.Name) else ""
    )
    if name not in _FACTORIES:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return "<dynamic>"


def _threading_receiver(expr: ast.AST) -> bool:
    """True for a receiver that denotes the threading module: a plain
    alias Name, or the ``__import__("threading")`` dodge."""
    if isinstance(expr, ast.Name):
        return "threading" in expr.id
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Name)
        and expr.func.id == "__import__"
        and expr.args
        and isinstance(expr.args[0], ast.Constant)
        and expr.args[0].value == "threading"
    )


def _raw_threading_lock(node: ast.Call) -> Optional[str]:
    """'Lock'/'RLock'/'Condition' when node creates a RAW primitive the
    witness cannot see."""
    fn = node.func
    if (
        isinstance(fn, ast.Attribute)
        and _threading_receiver(fn.value)
        and fn.attr in ("Lock", "RLock", "Condition")
    ):
        if fn.attr == "Condition" and node.args:
            return None  # wraps an existing (witnessed) lock
        return fn.attr
    return None


class _LockSymbols(ast.NodeVisitor):
    """module globals + class attrs that hold factory-made locks."""

    def __init__(self) -> None:
        self.globals: Dict[str, str] = {}  # name -> role
        self.attrs: Dict[Tuple[str, str], str] = {}  # (class, attr) -> role
        self._class: Optional[str] = None

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        prev, self._class = self._class, node.name
        self.generic_visit(node)
        self._class = prev

    def _record(self, target: ast.AST, role: str) -> None:
        if isinstance(target, ast.Name) and self._class is None:
            self.globals[target.id] = role
        elif isinstance(target, ast.Name) and self._class is not None:
            self.attrs[(self._class, target.id)] = role
        elif (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and self._class is not None
        ):
            self.attrs[(self._class, target.attr)] = role

    def visit_Assign(self, node: ast.Assign) -> None:
        role = _factory_role(node.value)
        if role is None and isinstance(node.value, ast.Call):
            # Condition(shared_lock) aliases the shared lock's role
            fn = node.value.func
            if (
                isinstance(fn, ast.Attribute) and fn.attr == "Condition"
                and node.value.args
            ):
                arg = node.value.args[0]
                if (
                    isinstance(arg, ast.Attribute)
                    and isinstance(arg.value, ast.Name)
                    and arg.value.id == "self"
                    and self._class is not None
                ):
                    role = self.attrs.get((self._class, arg.attr))
                elif isinstance(arg, ast.Name):
                    role = self.globals.get(arg.id)
        if role is not None:
            for t in node.targets:
                self._record(t, role)
        self.generic_visit(node)


def _resolve_lock(
    expr: ast.AST, syms: _LockSymbols, cls: Optional[str]
) -> Optional[str]:
    """role of a ``with``-statement context expr, if it names a lock."""
    if isinstance(expr, ast.Name):
        return syms.globals.get(expr.id)
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
        and cls is not None
    ):
        return syms.attrs.get((cls, expr.attr))
    return None


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value.id if isinstance(fn.value, ast.Name) else ""
        if fn.attr in _BLOCKING_ATTRS:
            return f"{recv + '.' if recv else ''}{fn.attr}"
        for rsub, attr in _BLOCKING_RECEIVER_ATTRS:
            if fn.attr == attr and rsub in recv:
                return f"{recv}.{attr}"
    elif isinstance(fn, ast.Name) and fn.id in ("urlopen", "sleep"):
        return fn.id
    return None


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    #: (held_role, inner_role) -> first "rel:line" that witnessed it
    edges: Dict[Tuple[str, str], str] = {}

    for m in modules:
        if m.rel.endswith("utils/lockcheck.py") or "/tests/" in m.rel:
            continue
        syms = _LockSymbols()
        syms.visit(m.tree)

        # rule 1: inventory
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Call):
                raw = _raw_threading_lock(node)
                if raw is not None:
                    findings.append(Finding(
                        NAME, m.rel, node.lineno,
                        f"raw threading.{raw}() — create it through "
                        "utils/lockcheck.make_lock/make_rlock/"
                        "make_condition with a role name so the runtime "
                        "lock-order witness can see it",
                    ))

        # rules 2+3: walk each function with a held-lock stack
        def own_exprs(stmt):
            """Expression nodes belonging to THIS statement (stop at
            nested statement suites — those recurse via walk())."""
            for _field, value in ast.iter_fields(stmt):
                vals = value if isinstance(value, list) else [value]
                for v in vals:
                    if isinstance(v, ast.expr):
                        yield from ast.walk(v)
                    elif isinstance(v, ast.withitem):
                        yield from ast.walk(v.context_expr)

        def scan_blocking(stmt, held) -> None:
            if not held:
                return
            for sub in own_exprs(stmt):
                if isinstance(sub, ast.Call):
                    blk = _is_blocking_call(sub)
                    if blk is not None:
                        roles = ", ".join(r for r, _ in held)
                        findings.append(Finding(
                            NAME, m.rel, sub.lineno,
                            f"blocking call {blk}() while holding "
                            f"lock(s) {roles} — every contender stalls "
                            "for the call's duration; move it outside "
                            "the lock or suppress naming the bound",
                        ))

        def walk(body, held: List[Tuple[str, int]], cls) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk(stmt.body, [], cls)
                    continue
                if isinstance(stmt, ast.ClassDef):
                    walk(stmt.body, [], stmt.name)
                    continue
                if isinstance(stmt, ast.With):
                    # the context expressions themselves run while the
                    # CURRENT locks are held — `with urlopen(req) as r:`
                    # under a lock is the dominant blocking idiom
                    scan_blocking(stmt, held)
                    acquired: List[Tuple[str, int]] = []
                    for item in stmt.items:
                        role = _resolve_lock(item.context_expr, syms, cls)
                        if role is not None:
                            for outer, _ in held:
                                if outer != role:
                                    edges.setdefault(
                                        (outer, role),
                                        f"{m.rel}:{stmt.lineno}",
                                    )
                            acquired.append((role, stmt.lineno))
                    walk(stmt.body, held + acquired, cls)
                    continue
                scan_blocking(stmt, held)
                # recurse into nested suites (if/for/try/while bodies)
                for field in ("body", "orelse", "finalbody"):
                    sub_body = getattr(stmt, field, None)
                    if sub_body:
                        walk(sub_body, held, cls)
                for handler in getattr(stmt, "handlers", []):
                    walk(handler.body, held, cls)

        walk(m.tree.body, [], None)

    # rule 2 verdicts: pairwise inversions + longer cycles
    seen_pairs: Set[frozenset] = set()
    for (a, b), site in sorted(edges.items()):
        if (b, a) in edges and frozenset((a, b)) not in seen_pairs:
            seen_pairs.add(frozenset((a, b)))
            other = edges[(b, a)]
            rel, line = site.rsplit(":", 1)
            findings.append(Finding(
                NAME, rel, int(line),
                f"lock-order inversion: {a!r} → {b!r} here but "
                f"{b!r} → {a!r} at {other} — pick one order and make "
                "the other side drop/retake",
            ))
    # longer cycles: DFS over the remaining digraph
    graph: Dict[str, List[str]] = {}
    for (a, b) in edges:
        if frozenset((a, b)) not in seen_pairs:
            graph.setdefault(a, []).append(b)
    state: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        state[n] = 1
        stack.append(n)
        for nxt in graph.get(n, ()):
            if state.get(nxt, 0) == 1:
                return stack[stack.index(nxt):] + [nxt]
            if state.get(nxt, 0) == 0:
                cyc = dfs(nxt)
                if cyc:
                    return cyc
        stack.pop()
        state[n] = 2
        return None

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc:
                site = edges[(cyc[0], cyc[1])]
                rel, line = site.rsplit(":", 1)
                findings.append(Finding(
                    NAME, rel, int(line),
                    "lock-order cycle: " + " → ".join(cyc),
                ))
                break
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/sabotage/locks.py",
    "source": '''\
import threading

from ..utils import lockcheck as _lockcheck

_raw = threading.Lock()          # seeded: invisible to the witness
_dodge = __import__("threading").Lock()   # seeded: the import-dodge form
_a = _lockcheck.make_lock("sab.a")
_b = _lockcheck.make_lock("sab.b")


def forward():
    with _a:
        with _b:
            pass


def backward():
    with _b:
        with _a:                  # seeded: inversion of forward()
            import time
            time.sleep(1)         # seeded: blocking under two locks
''',
}
