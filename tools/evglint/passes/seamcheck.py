"""seamcheck — external side-effects stay on the injection surface.

The scenario engine and fault matrix can only prove degradation for
failures they can INJECT. Every place the process touches the outside
world — sockets, subprocesses, HTTP — must therefore live in a module
wired to the fault-seam registry (``faults.fire(...)``) or the unified
``RetryPolicy``, so a fault plan can reach it and a retry budget bounds
it. An external call in a module with neither is a hole in the
injection surface: the one seam the crash matrix cannot exercise is the
one production will.

Heuristic: a call to ``subprocess.run/Popen/check_*``, ``urlopen``,
``socket.socket``/``create_connection``, or an ``HTTP(S)Connection``
constructor, in a module whose source never mentions ``faults.fire`` or
``RetryPolicy``, is a finding. Harness/bootstrap code that is itself
the failure-observer (smoke drivers, the native-lib builder) suppresses
with that justification.

Canonical transport-seam names (the network-chaos plane's injection
surface, exercised by ``tools/net_matrix.py``; keep this inventory in
sync with ARCHITECTURE.md's "Network chaos" section):

- ``ipc.send`` / ``ipc.recv`` — supervisor↔worker control IPC, both
  directions, each with per-shard aliases (``ipc.send.<shard>``) so a
  plan can partition ONE worker;
- ``sock.adopt`` — the orphan-adoption socket connect;
- ``solver.publish`` / ``solver.return`` — the solver-leader's shm
  legs (delay/stale shapes only: the payload plane is checksummed);
- ``agent.request`` — the agent's REST pull (drop/duplicate/half-open
  feed the dispatch CAS its duplicate-delivery diet);
- ``replica.tail`` — the read replica's WAL tail poll.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Finding, Module

NAME = "seamcheck"

_SEAM_TOKENS = ("faults.fire", "RetryPolicy", "retry_policy")

_EXTERNAL = {
    ("subprocess", "run"), ("subprocess", "call"),
    ("subprocess", "check_call"), ("subprocess", "check_output"),
    ("subprocess", "Popen"),
    ("socket", "socket"), ("socket", "create_connection"),
}
_EXTERNAL_ATTRS = {"urlopen", "HTTPConnection", "HTTPSConnection"}


def _external_call(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value.id if isinstance(fn.value, ast.Name) else ""
        if (recv, fn.attr) in _EXTERNAL:
            return f"{recv}.{fn.attr}"
        if fn.attr in _EXTERNAL_ATTRS:
            return f"{recv + '.' if recv else ''}{fn.attr}"
    elif isinstance(fn, ast.Name) and fn.id in _EXTERNAL_ATTRS:
        return fn.id
    return None


def run(modules: List[Module]) -> List[Finding]:
    findings: List[Finding] = []
    for m in modules:
        if "/tests/" in m.rel:
            continue
        if any(tok in m.source for tok in _SEAM_TOKENS):
            continue  # module is on the injection surface already
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _external_call(node)
            if name is not None:
                findings.append(Finding(
                    NAME, m.rel, node.lineno,
                    f"external side-effect {name}() in a module with "
                    "no fault seam or RetryPolicy — the scenario "
                    "engine cannot inject failure here; wrap it in a "
                    "registered seam/policy or suppress naming why "
                    "this surface needs neither",
                ))
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/cloud/sabotage_seam.py",
    "source": '''\
import subprocess
from urllib.request import urlopen


def provision(host):
    subprocess.run(["ssh", host, "true"])   # seeded: unseamed subprocess
    return urlopen("http://metadata/latest").read()  # seeded: unseamed HTTP
''',
}
