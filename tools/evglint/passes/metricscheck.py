"""metrics — the metrics-plane registration contract (ISSUE 7),
migrated onto the evglint core as the sixth pass.

Same rules as the original ``tools/metrics_lint.py`` (which now
delegates here so its CLI and output survive):

  * literal snake_case instrument names with a known subsystem prefix;
  * counters end ``_total``; duration histograms end ``_ms``;
  * labels literal and drawn from ``utils/metrics.py ALLOWED_LABELS``;
  * per-shard / per-replica / per-worker series carry the label that
    keeps one sick member from hiding in the aggregate;
  * every name registered exactly once across the tree;
  * no ``incr_counter`` call sites outside utils/log.py / metrics.py.
"""
from __future__ import annotations

import ast
import re
import sys
from typing import Dict, List, Tuple

from ..core import REPO_ROOT, Finding, Module

NAME = "metrics"

if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

REG_FUNCS = {"counter", "gauge", "histogram"}
REG_RECEIVERS = re.compile(r"metrics")
NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")

SUBSYSTEMS = {
    "api", "arena", "breaker", "cloud", "config", "cron", "dispatch",
    "events", "faults", "hosts", "jobs", "lease", "outbox", "overload",
    "recovery", "replica", "resident", "retry", "runtime", "scheduler",
    "storage", "tpu", "trace", "wal",
}

INCR_COUNTER_ALLOWED = {
    "evergreen_tpu/utils/log.py",
    "evergreen_tpu/utils/metrics.py",
}


def _allowed_labels() -> frozenset:
    from evergreen_tpu.utils.metrics import ALLOWED_LABELS

    return ALLOWED_LABELS


def _is_registration(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in REG_FUNCS:
        base = fn.value
        return isinstance(base, ast.Name) and bool(
            REG_RECEIVERS.search(base.id)
        )
    return False


def _literal_str(node) -> Tuple[bool, str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True, node.value
    return False, ""


def _labels_node(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def _label_values(call: ast.Call) -> List[str]:
    ln = _labels_node(call)
    if isinstance(ln, (ast.Tuple, ast.List)):
        return [_literal_str(el)[1] for el in ln.elts]
    return []


def run(modules: List[Module]) -> List[Finding]:
    allowed_labels = _allowed_labels()
    findings: List[Finding] = []
    registered: Dict[str, str] = {}

    def emit(rel: str, line: int, msg: str) -> None:
        findings.append(Finding(NAME, rel, line, msg))

    for m in modules:
        rel = m.rel
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr if isinstance(node.func, ast.Attribute)
                else ""
            )
            if fname == "incr_counter" and rel not in INCR_COUNTER_ALLOWED:
                emit(rel, node.lineno,
                     "direct incr_counter() call — register a typed "
                     "instrument in utils/metrics.py terms and let its "
                     "`legacy` mirror feed the flat dict")
            if not _is_registration(node):
                continue
            kind = node.func.attr
            line = node.lineno
            if not node.args:
                emit(rel, line, f"{kind}() with no name")
                continue
            ok, name = _literal_str(node.args[0])
            if not ok:
                emit(rel, line,
                     f"{kind}() name must be a literal string "
                     "(no f-strings, no concatenation, no variables)")
                continue
            if not NAME_RE.match(name):
                emit(rel, line,
                     f"{name!r} is not snake_case with a subsystem prefix")
            else:
                prefix = name.split("_", 1)[0]
                if prefix not in SUBSYSTEMS:
                    emit(rel, line,
                         f"{name!r} claims unknown subsystem prefix "
                         f"{prefix!r} (known: "
                         f"{', '.join(sorted(SUBSYSTEMS))})")
            if kind == "counter" and not name.endswith("_total"):
                emit(rel, line, f"counter {name!r} must end with _total")
            if kind == "histogram" and not name.endswith("_ms"):
                emit(rel, line,
                     f"histogram {name!r} must end with _ms (every "
                     "duration histogram shares the ms bucket "
                     "vocabulary)")
            help_node = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"),
                None,
            )
            hval = ""
            if help_node is not None:
                _hok, hval = _literal_str(help_node)
            if help_node is None or not hval.strip():
                emit(rel, line,
                     f"{name!r} needs a non-empty literal help string")
            # each scope rule is INDEPENDENT (a *_shard_*_replica_*
            # series needs both labels); dedupe only identical demands
            demanded = set()
            for scope, label, folded in (
                ("_shard_", "shard", "every shard"),
                ("_replica_", "replica", "every replica"),
                ("_worker_", "shard", "the whole fleet"),
                ("_workers_", "shard", "the whole fleet"),
            ):
                if scope in name or name.startswith(scope.strip("_") + "_"):
                    if (
                        label not in _label_values(node)
                        and label not in demanded
                    ):
                        demanded.add(label)
                        emit(rel, line,
                             f"per-{scope.strip('_')} instrument "
                             f"{name!r} must carry the {label!r} label "
                             f"(unlabeled series fold {folded} together)")
            ln = _labels_node(node)
            if ln is not None:
                if not isinstance(ln, (ast.Tuple, ast.List)):
                    emit(rel, line,
                         f"{name!r} labels must be a literal tuple/list")
                else:
                    for el in ln.elts:
                        lok, lval = _literal_str(el)
                        if not lok:
                            emit(rel, line,
                                 f"{name!r} has a non-literal label")
                        elif lval not in allowed_labels:
                            emit(rel, line,
                                 f"{name!r} label {lval!r} is not in "
                                 "the allowed vocabulary ("
                                 f"{', '.join(sorted(allowed_labels))})")
            if any(kw.arg == "registry" for kw in node.keywords):
                continue
            prev = registered.get(name)
            if prev is not None:
                emit(rel, line, f"{name!r} already registered at {prev}")
            else:
                registered[name] = f"{rel}:{line}"
    return findings


SABOTAGE = {
    "rel": "evergreen_tpu/utils/sabotage_metrics.py",
    "source": '''\
from . import metrics as _metrics

BAD = _metrics.counter(
    f"dynamic_{1}_name",           # seeded: non-literal instrument name
    "help text",
)
''',
}
