"""evglint — project-wide static analysis for evergreen_tpu.

A shared AST/scope-analysis core (tools/evglint/core.py) plus six
project-specific passes (tools/evglint/passes/):

  lockgraph    lock inventory + static acquisition-order graph: raw
               ``threading.Lock()`` creations (invisible to the runtime
               witness) are findings, nested ``with`` acquisitions build
               an order graph whose inversions/cycles are findings, and
               blocking calls (sleep, subprocess, socket IO, wait_reply)
               under a held lock are findings. Paired with the runtime
               witness in evergreen_tpu/utils/lockcheck.py.
  tracercheck  JIT-purity/static-shape discipline in ops/: no Python
               branching on traced values, no .item()/float() on
               tracers, no NumPy calls inside jitted bodies.
  fencecheck   every mutation of the data dir goes through the
               epoch-stamped DurableStore/lease APIs; a direct
               open(...,'w')/os.rename against store paths outside
               storage/ is a finding.
  shedcheck    every drop/shed/evict path increments a registered
               instrument, and a broad except handler may not swallow
               work silently (counters == records, zero silent
               discards — enforced at parse time).
  seamcheck    external side-effects (sockets, subprocess, HTTP) must
               live in a module wired to a fault seam or RetryPolicy,
               keeping the scenario engine's injection surface complete.
  metrics      the ISSUE-7 metrics-plane lint, migrated onto this core
               (tools/metrics_lint.py is now a thin alias).

Suppressions: ``# evglint: disable=<pass>[,<pass>] -- <reason>`` on the
finding line (or a standalone comment on the line above). The reason is
REQUIRED — a suppression without one is itself a finding.

Run: ``python -m tools.evglint`` (all passes), ``--pass NAME`` for one,
``--sabotage`` for the self-test that seeds one violation per pass and
asserts it is caught. Wired as ``make lint`` and run unconditionally by
``tools/gate.py``.
"""
