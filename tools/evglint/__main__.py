"""CLI: ``python -m tools.evglint [--pass NAME ...] [--sabotage]``.

Exit 0 = clean (or, under --sabotage, every pass caught its seed).
Exit 1 = unsuppressed findings (or a seeded violation escaped).
"""
from __future__ import annotations

import argparse
import sys

from . import core


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="evglint")
    ap.add_argument(
        "--pass", dest="passes", action="append", metavar="NAME",
        help="run only this pass (repeatable; default: all)",
    )
    ap.add_argument(
        "--sabotage", action="store_true",
        help="self-test: seed one violation per pass, assert caught",
    )
    ap.add_argument(
        "--list", action="store_true", help="list passes and exit",
    )
    args = ap.parse_args(argv)

    passes = core.load_passes(args.passes)
    if args.list:
        for p in passes:
            doc = (p.__doc__ or "").strip().split("\n")[0]
            print(f"{p.NAME:12s} {doc}")
        return 0

    if args.sabotage:
        escaped = core.sabotage_selftest(passes)
        if escaped:
            print(f"evglint sabotage: {escaped} pass(es) BLIND",
                  file=sys.stderr)
            return 1
        print(f"evglint sabotage: all {len(passes)} passes catch "
              "their seeded violation")
        return 0

    modules = core.iter_modules()
    findings = core.run_passes(passes, modules)
    n_suppressed = sum(m.n_suppression_comments for m in modules)
    if findings:
        print(f"evglint: {len(findings)} finding(s)")
        for f in findings:
            print(f"  {f}")
        return 1
    print(
        f"evglint: clean ({len(passes)} passes, {len(modules)} files, "
        f"{n_suppressed} suppression(s))"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
