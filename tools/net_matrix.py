#!/usr/bin/env python
"""Network-chaos matrix (ISSUE 20 tentpole) — the Jepsen-style partition
analogue of tools/disk_matrix.py. Where the disk matrix rots the bytes
under a living process, this matrix cuts the WIRES between living
processes — partition (one-way and symmetric), loss, duplication,
reordering, latency, and half-open connections at every transport seam
(utils/faults.py network-chaos vocabulary) — and asserts the detection →
bounded-degradation contract end to end:

  * zero duplicate dispatch: at-least-once delivery (retries after drop,
    half-open re-delivery, outright duplication) never double-claims a
    task — the dispatch CAS and the running-task resume path fence every
    copy;
  * exactly-one-owner + monotone epochs: a partitioned worker orphans on
    its command-staleness deadline (never split-brains), heals in place
    when commands resume, and any fenced restart lands at a strictly
    higher epoch;
  * stale-accepted == 0: delayed solver-leader results past the round's
    timeout are fenced at out_seq, never applied;
  * resume == rerun: a run that rode out the chaos converges to the same
    canonical state as an uninterrupted reference replay;
  * degrade-within-one-round: a leader delay past the solve timeout
    degrades exactly the affected round to local solves, then recovers.

Five arms, all run by default (``make net-matrix`` / ``gate
--net-matrix``); the SABOTAGE self-test runs FIRST — a deliberately
unfenced duplicate delivery (a forged second claim bypassing the CAS)
must be caught red, or the whole matrix refuses to certify anything:

  sabotage  plant an unfenced duplicate delivery; the invariant plane
            must convict it (the matrix's own smoke detector);
  grid      seam x kind points across three plane configs — classic
            (in-process engine: lossy agent claim storms + replica
            tail), fleet2 (2-shard supervised fleet over real worker
            processes: IPC partition/drop/delay/duplicate/reorder), and
            leader2 (solver-leader fleet: delayed publish/return,
            partitioned worker);
  weathers  the shipped scenarios/library.py + procs.py net weathers;
  cases     bespoke seam cases: wait_reply reorder/duplication
            hardening, sock.adopt refused + half-open, duplicate
            delivery against the dispatch CAS, full-jitter retry
            spread;
  fuzz      reachability: the weather fuzzer must actually draw
            ``net_fault`` events, drawn cases must run green, and one
            shrunk net_fault timeline must replay deterministically.

One JSON line per case; summary line; exit 1 on any failure. Failed
proc cases keep their data dirs for inspection (engine runs clean up
through the scenario harness).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from evergreen_tpu.utils.jaxenv import force_cpu  # noqa: E402

# The image's sitecustomize imports jax at interpreter start, so env
# vars alone cannot reach it — and the leader2 arm elects a
# solver-leader, which requires >= n_shards devices. Pin the same
# virtual 8-device CPU mesh the test harness uses (tests/conftest.py);
# without it _start_solver soft-fails and every solver point would
# pass vacuously (guarded by the solver-engaged check below).
force_cpu(n_devices=8)

CONFIGS = ("classic", "fleet2", "leader2")

#: classic (in-process) agent-transport grid: kind x loss rate, each
#: driven through the scenario engine's ``net_fault`` storm + heal
AGENT_KINDS = ("drop", "half_open", "duplicate", "partition")
AGENT_RATES = (0.3, 0.6)

#: classic replica-tail grid: the tail survives every silent-wire shape
REPLICA_KINDS = ("half_open", "drop", "partition")

#: fleet2 (2-shard supervised fleet) grid: (seam, kind, delay_s).
#: ``ipc.send.0`` faults black-hole supervisor→worker commands for ONE
#: shard (the one-way partition: heartbeats still flow back);
#: ``ipc.recv.0`` faults eat worker→supervisor traffic instead, which
#: starves the heartbeat watchdog into a fenced restart — never a
#: split-brain.
FLEET_GRID: List[Tuple[str, str, float]] = [
    ("ipc.send.0", "partition", 0.0),
    ("ipc.send.0", "drop", 0.0),
    ("ipc.send.0", "delay", 0.3),
    ("ipc.send.0", "duplicate", 0.0),
    ("ipc.recv.0", "drop", 0.0),
    ("ipc.recv.0", "duplicate", 0.0),
    ("ipc.recv.0", "reorder", 0.0),
]

#: leader2 (solver-leader fleet) grid: delayed solver legs + a
#: partitioned worker under an elected leader. ``solver.return`` gets a
#: delay PAST the workers' solve timeout (6s): that round must degrade
#: to local solves — and the late result must be fenced, never accepted.
LEADER_GRID: List[Tuple[str, str, float]] = [
    ("solver.publish", "delay", 0.5),
    ("solver.return", "delay", 8.0),
    ("ipc.send.0", "partition", 0.0),
]

WEATHERS = ("net-agent-storm-loss", "net-agent-storm-halfopen",
            "net-replica-halfopen")
PROC_WEATHERS = ("proc-net-oneway-partition",)


def _emit(res: dict) -> dict:
    print(json.dumps(res), flush=True)
    return res


def _entry_result(arm: str, point: str, entry: dict,
                  extra_problems: Optional[List[str]] = None) -> dict:
    problems = list(extra_problems or [])
    if not entry.get("ok"):
        problems.append(json.dumps(entry, default=str)[:2000])
    return {"arm": arm, "point": point, "ok": not problems,
            "problems": problems}


# ------------------------------------------------------------- sabotage arm

def run_sabotage() -> List[dict]:
    """The matrix's own smoke detector, run before anything it would
    certify: a claim storm under half-open responses PLUS a forged
    duplicate claim that bypasses the dispatch CAS entirely (duplicate
    delivery with the fence ripped out). The invariant plane MUST score
    it red with ``no_duplicate_dispatch`` among the convictions — a
    green here means the matrix is blind and every other point is
    vacuous."""
    from evergreen_tpu.globals import Provider
    from evergreen_tpu.scenarios.engine import run_scenario
    from evergreen_tpu.scenarios.library import _sabotage_duplicate_claim
    from evergreen_tpu.scenarios.spec import Ev, ScenarioSpec

    spec = ScenarioSpec(
        name="net-sabotage-unfenced-duplicate",
        description="half-open claim storm with an UNFENCED duplicate "
                    "delivery spliced in (forged second claim, CAS "
                    "bypassed): the invariant plane must convict it",
        ticks=8,
        events=[
            # 4 hosts, 2 tasks: at tick 1 two hosts are mid-task and
            # two are free — the forged duplicate claim has both sides
            # live (same balance as the library's sabotage weather)
            Ev(0, "fleet", {"distros": [
                {"id": "dsab", "provider": Provider.MOCK.value,
                 "hosts": 4},
            ]}),
            Ev(0, "tasks", {"distro": "dsab", "n": 2,
                            "prefix": "dsab-t"}),
            Ev(1, "net_fault", {"target": "agent", "kind": "half_open",
                                "rate": 0.3, "agents": 4}),
            Ev(1, "call", {"fn": _sabotage_duplicate_claim}),
        ],
        tier1=False,
    )
    entry = run_scenario(spec)
    problems: List[str] = []
    if entry.get("ok"):
        problems.append(
            "the planted unfenced duplicate delivery was NOT caught — "
            "the invariant plane is blind; refusing to certify"
        )
    else:
        # the conviction must come from the dispatch-books invariants
        # (a second live claim → no_duplicate_dispatch; a claim that
        # outlived its task → store_consistent), not from an unrelated
        # SLO that happened to trip
        inv = entry.get("invariants", {})
        books = ("no_duplicate_dispatch", "store_consistent")
        if all(inv.get(k, {}).get("ok", True) for k in books):
            problems.append(
                "sabotage scored red, but not by the dispatch-books "
                "invariants: "
                + json.dumps(entry.get("invariants"), default=str)[:800]
            )
    return [_emit({"arm": "sabotage", "point": "unfenced-duplicate",
                   "ok": not problems, "problems": problems})]


# ----------------------------------------------------------------- grid arm

def _classic_agent_spec(kind: str, rate: float):
    from evergreen_tpu.globals import Provider
    from evergreen_tpu.scenarios.spec import SLO, Ev, ScenarioSpec

    return ScenarioSpec(
        name="net-grid-agent-%s-%d" % (kind, int(rate * 100)),
        description="matrix-generated agent chaos: %s at %d%% across "
                    "a claim storm" % (kind, int(rate * 100)),
        ticks=12,
        events=[
            Ev(0, "fleet", {"distros": [
                {"id": "dgrid", "provider": Provider.MOCK.value,
                 "hosts": 6},
            ]}),
            Ev(0, "tasks", {"distro": "dgrid", "n": 12,
                            "prefix": "ng-t"}),
            Ev(2, "net_fault", {"target": "agent", "kind": kind,
                                "rate": rate, "agents": 6}),
            Ev(6, "tasks", {"distro": "dgrid", "n": 4,
                            "prefix": "ng-b"}),
        ],
        slos=[
            SLO("work-survives", "tasks_unfinished", "==", 0),
            SLO("no-failures", "tasks_failed", "==", 0),
        ],
    )


def _classic_replica_spec(kind: str):
    import dataclasses

    from evergreen_tpu.scenarios.library import _net_replica_halfopen
    from evergreen_tpu.scenarios.spec import Ev

    spec = _net_replica_halfopen()
    if kind == "half_open":
        return spec
    events = [
        dataclasses.replace(e, args={**e.args, "kind": kind})
        if e.kind == "net_fault" else e
        for e in spec.events
    ]
    return dataclasses.replace(
        spec, name="net-replica-%s" % kind, events=events,
        description=spec.description.replace("half-open", kind),
    )


def run_classic_grid() -> List[dict]:
    from evergreen_tpu.scenarios.engine import run_scenario

    results = []
    for kind in AGENT_KINDS:
        for rate in AGENT_RATES:
            point = "classic:agent.request:%s@%d" % (kind,
                                                     int(rate * 100))
            entry = run_scenario(_classic_agent_spec(kind, rate))
            results.append(_emit(_entry_result("grid", point, entry)))
    for kind in REPLICA_KINDS:
        point = "classic:replica.tail:%s" % kind
        entry = run_scenario(_classic_replica_spec(kind))
        results.append(_emit(_entry_result("grid", point, entry)))
    return results


def _proc_net_spec(config: str, seam: str, kind: str, delay_s: float):
    """One proc-backend chaos point: arm the fault at tick 2, heal at
    tick 5, converge under the full proc invariant set (duplicate
    dispatch, exactly-one-owner, monotone epochs, resume == rerun)."""
    from evergreen_tpu.scenarios.procs import (
        _SOLVER_WORKLOAD,
        DEFAULT_PROC_INVARIANTS,
    )
    from evergreen_tpu.scenarios.spec import SLO, Ev, ScenarioSpec

    if config == "leader2":
        workload = dict(_SOLVER_WORKLOAD)
        workload["round_timeout_s"] = 30.0
        if not seam.startswith("solver."):
            # the command-silence detector is under test only at the
            # IPC seams; solver points run the shipped leader workload
            # so a silence-orphan cannot disengage the stacked plane
            # mid-measurement
            workload["command_silence_s"] = 2.0
    else:
        workload = {"shards": 2, "distros": 4, "tasks": 32, "seed": 11,
                    "hosts_per_distro": 3, "round_timeout_s": 4.0,
                    "command_silence_s": 2.0}
    fault_args: Dict = {"seam": seam, "kind": kind}
    if delay_s:
        fault_args["delay_s"] = delay_s
    if seam.startswith("solver."):
        # one delayed leg (plan index 0 on the freshly armed plan =
        # the seam's next fire): exactly one round degrades
        fault_args["at"] = 0
    slug = "%s-%s" % (seam.replace(".", "-"), kind)
    checks: List = []
    if config == "leader2":
        checks.append(("stale-accepted-zero", _check_stale_zero))
        if seam.startswith("solver."):
            # anti-vacuity: a solver point where the stacked plane
            # never engaged (no devices, lease lost) proves nothing
            checks.append(("solver-engaged", _check_solver_engaged))
        if seam == "solver.return":
            checks.append(("degrade-within-one-round",
                           _check_degrade_one_round))
    return ScenarioSpec(
        name="net-%s-%s" % (config, slug),
        description="matrix-generated %s chaos point: %s at %s"
                    % (config, kind, seam),
        ticks=14,
        durable=True,
        deterministic=False,
        events=[
            Ev(0, "proc_fleet", workload),
            Ev(2, "net_fault", fault_args),
            Ev(5, "net_heal", {"seam": seam}),
        ],
        slos=[
            # a recv-side blackout starves the heartbeat watchdog once
            # per deadline window until the heal: each cycle is a
            # fenced restart by design, so the drop point's bound is
            # the blackout span, not one-off fencing
            SLO("bounded-restarts", "restarts_total", "<=",
                6 if (seam.startswith("ipc.recv") and kind == "drop")
                else 3),
        ],
        checks=checks,
        invariants=DEFAULT_PROC_INVARIANTS,
        tier1=False,
    )


def _check_solver_engaged(run) -> Optional[str]:
    n = (run.stats.get("solver_stacked_replies", 0)
         + run.stats.get("solver_local_replies", 0))
    if n < 1:
        return ("the solver plane never engaged (no stacked or local "
                "replies) — the point is vacuous")
    return None


def _check_stale_zero(run) -> Optional[str]:
    n = run.stats.get("solver_stale_accepted", 0)
    if n:
        return "a worker accepted a stale solver result: %d" % n
    return None


def _check_degrade_one_round(run) -> Optional[str]:
    """The delayed solver.return must cost at most the round it landed
    in: at least one round degrades to a local solve, and some LATER
    round is fully stacked again (bounded degradation, then recovery)."""
    saw_local = False
    for rnd in run.rounds:
        solves = [r.get("solve") for r in rnd.values()]
        if "local" in solves:
            saw_local = True
        elif saw_local and solves.count("stacked") >= 2:
            return None
    if not saw_local:
        return "the delayed return never degraded any round to local"
    return "no fully stacked round after the degraded one"


def run_proc_grid(config: str) -> List[dict]:
    from evergreen_tpu.scenarios.procs import run_proc_scenario

    grid = FLEET_GRID if config == "fleet2" else LEADER_GRID
    results = []
    for seam, kind, delay_s in grid:
        point = "%s:%s:%s" % (config, seam, kind)
        spec = _proc_net_spec(config, seam, kind, delay_s)
        entry = run_proc_scenario(spec)
        res = _entry_result("grid", point, entry)
        if not res["ok"]:
            res["data_dir"] = entry.get("data_dir")
        results.append(_emit(res))
    return results


def run_grid(only_point: Optional[str] = None) -> List[dict]:
    results = []
    for res in run_classic_grid() if only_point is None else []:
        results.append(res)
    if only_point is not None:
        # single-point mode: route to the owning config
        config = only_point.split(":", 1)[0]
        if config == "classic":
            raise SystemExit(
                "--point supports proc configs (fleet2/leader2); "
                "classic points run via --grid-only"
            )
        from evergreen_tpu.scenarios.procs import run_proc_scenario

        grid = FLEET_GRID if config == "fleet2" else LEADER_GRID
        for seam, kind, delay_s in grid:
            if "%s:%s:%s" % (config, seam, kind) != only_point:
                continue
            spec = _proc_net_spec(config, seam, kind, delay_s)
            entry = run_proc_scenario(spec)
            results.append(_emit(_entry_result("grid", only_point,
                                               entry)))
        return results
    for config in ("fleet2", "leader2"):
        results.extend(run_proc_grid(config))
    return results


# ------------------------------------------------------------- weathers arm

def run_weathers() -> List[dict]:
    from evergreen_tpu.scenarios.engine import run_scenario
    from evergreen_tpu.scenarios.library import SCENARIOS
    from evergreen_tpu.scenarios.procs import (
        PROC_SCENARIOS,
        run_proc_scenario,
    )

    results = []
    for name in WEATHERS:
        entry = run_scenario(SCENARIOS[name]())
        results.append(_emit(_entry_result("weathers", name, entry)))
    for name in PROC_WEATHERS:
        entry = run_proc_scenario(PROC_SCENARIOS[name]())
        results.append(_emit(_entry_result("weathers", name, entry)))
    return results


# ---------------------------------------------------------------- cases arm

def wait_reply_reorder_case() -> dict:
    """A reply reordered past its own wait (delivered after the wait
    timed out and a NEWER request is in flight) must be counted and
    dropped — never matched to the newer wait."""
    from evergreen_tpu.runtime.supervisor import (
        IPC_STALE_REPLIES,
        WorkerHandle,
    )

    problems: List[str] = []
    h = WorkerHandle(0, hb_deadline_s=5.0)
    before = IPC_STALE_REPLIES.value(shard=0)

    # request 1 answers normally and completes
    h.replies.put({"op": "round", "req": 1, "body": "first"})
    got = h.wait_reply("round", 1.0, req=1)
    if not got or got.get("body") != "first":
        problems.append("baseline reply lost: %r" % (got,))

    # the reorder: request 1's LATE duplicate arrives ahead of request
    # 2's real answer
    h.replies.put({"op": "round", "req": 1, "body": "late-dup"})
    h.replies.put({"op": "round", "req": 2, "body": "second"})
    got = h.wait_reply("round", 1.0, req=2)
    if not got or got.get("body") != "second":
        problems.append(
            "reordered stale reply satisfied the newer wait: %r"
            % (got,)
        )
    moved = IPC_STALE_REPLIES.value(shard=0) - before
    if moved != 1:
        problems.append(
            "stale-reply counter moved %s, want exactly 1" % moved
        )
    return {"arm": "cases", "point": "wait-reply-reorder",
            "ok": not problems, "problems": problems}


def wait_reply_duplicate_error_case() -> dict:
    """A duplicated ERROR leg carrying a spent request id must not end
    a newer wait either — the error fence only applies to live ids."""
    from evergreen_tpu.runtime.supervisor import (
        IPC_STALE_REPLIES,
        WorkerHandle,
    )

    problems: List[str] = []
    h = WorkerHandle(1, hb_deadline_s=5.0)
    before = IPC_STALE_REPLIES.value(shard=1)

    h.replies.put({"op": "round", "req": 7, "body": "a"})
    h.wait_reply("round", 1.0, req=7)
    # the transport duplicates the worker's error for the finished
    # request; a fresh request must still get ITS answer
    h.replies.put({"op": "error", "req": 7})
    h.replies.put({"op": "round", "req": 8, "body": "b"})
    got = h.wait_reply("round", 1.0, req=8)
    if not got or got.get("body") != "b":
        problems.append(
            "a stale duplicated error ended the newer wait: %r" % (got,)
        )
    moved = IPC_STALE_REPLIES.value(shard=1) - before
    if moved != 1:
        problems.append(
            "stale-reply counter moved %s, want exactly 1" % moved
        )
    return {"arm": "cases", "point": "wait-reply-duplicate-error",
            "ok": not problems, "problems": problems}


def sock_adopt_refused_case() -> dict:
    """``drop``/``partition`` at sock.adopt surface as a refused
    connect (OSError) — the supervisor's adoption probe falls back to a
    cold spawn instead of hanging."""
    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.utils import faults

    problems: List[str] = []
    for kind in ("drop", "partition"):
        plan = faults.FaultPlan().at("sock.adopt", 0, faults.Fault(kind))
        faults.install(plan)
        try:
            try:
                manifest.connect("/tmp/definitely-not-a-socket.sock")
                problems.append("%s did not refuse the connect" % kind)
            except OSError:
                pass
        finally:
            faults.uninstall()
    return {"arm": "cases", "point": "sock-adopt-refused",
            "ok": not problems, "problems": problems}


def sock_adopt_halfopen_case() -> dict:
    """``half_open`` at sock.adopt hands back a connected-looking
    socket whose peer never answers: reads time out instead of erroring
    — exactly the shape _try_adopt's deadline must bound."""
    import socket as _socket

    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.utils import faults

    problems: List[str] = []
    plan = faults.FaultPlan().at("sock.adopt", 0,
                                 faults.Fault("half_open"))
    faults.install(plan)
    try:
        conn = manifest.connect("/tmp/definitely-not-a-socket.sock")
    finally:
        faults.uninstall()
    try:
        conn.settimeout(0.2)
        try:
            conn.sendall(b'{"op":"adopt"}\n')  # lands in a dead buffer
        except OSError:
            problems.append("half-open socket errored on write")
        try:
            data = conn.recv(64)
            problems.append(
                "half-open socket answered: %r" % (data,)
            )
        except _socket.timeout:
            pass  # the contract: silence, not an error
        except OSError:
            problems.append(
                "half-open socket errored instead of staying silent"
            )
    finally:
        conn.close()
    return {"arm": "cases", "point": "sock-adopt-halfopen",
            "ok": not problems, "problems": problems}


def dispatch_cas_duplicate_case() -> dict:
    """Duplicate delivery against the dispatch CAS, no scenario engine
    in the way: the same next_task claim lands twice (and a third time
    with a STALE host snapshot still claiming to be free). Exactly one
    TASK_DISPATCHED may exist; every redelivery must resolve to the
    SAME task, never a second one."""
    from evergreen_tpu.dispatch.assign import assign_next_available_task
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.globals import HostStatus, TaskStatus
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models import task_queue as tq_mod
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.models.task_queue import TaskQueue, TaskQueueItem
    from evergreen_tpu.storage.store import Store

    problems: List[str] = []
    now = 1_700_000_000.0
    store = Store()
    task_mod.insert(store, Task(
        id="nt1", distro_id="d1",
        status=TaskStatus.UNDISPATCHED.value, activated=True,
    ))
    task_mod.insert(store, Task(
        id="nt2", distro_id="d1",
        status=TaskStatus.UNDISPATCHED.value, activated=True,
    ))
    host_mod.insert(store, Host(
        id="h1", distro_id="d1", status=HostStatus.RUNNING.value,
    ))
    tq_mod.save(store, TaskQueue(
        distro_id="d1",
        queue=[TaskQueueItem(id="nt1", dependencies_met=True),
               TaskQueueItem(id="nt2", dependencies_met=True)],
        generated_at=now,
    ))
    svc = DispatcherService(store)
    stale = host_mod.get(store, "h1")  # pre-claim snapshot

    first = assign_next_available_task(
        store, svc, host_mod.get(store, "h1"), now=now
    )
    if first is None or first.id != "nt1":
        problems.append("baseline claim failed: %r" % (first,))
    second = assign_next_available_task(
        store, svc, host_mod.get(store, "h1"), now=now
    )
    if second is None or second.id != first.id:
        problems.append(
            "duplicate delivery claimed a DIFFERENT task: %r"
            % (second,)
        )
    third = assign_next_available_task(store, svc, stale, now=now)
    if third is not None and third.id != first.id:
        problems.append(
            "stale-snapshot redelivery double-claimed: %r" % (third,)
        )
    dispatched = store.collection("events").find(
        lambda d: d.get("event_type") == "TASK_DISPATCHED"
    )
    if len(dispatched) != 1:
        problems.append(
            "%d TASK_DISPATCHED events for one claim (want 1)"
            % len(dispatched)
        )
    h = host_mod.get(store, "h1")
    if h.running_task != "nt1":
        problems.append(
            "host claim book wrong after redeliveries: %r"
            % (h.running_task,)
        )
    return {"arm": "cases", "point": "dispatch-cas-duplicate",
            "ok": not problems, "problems": problems}


def retry_jitter_spread_case() -> dict:
    """The agent transport's full-jitter backoff must SPREAD a
    correlated retry wave: across a simulated parked fleet, first-retry
    pauses must span most of [0, base] instead of clustering in the
    band-jitter corner — and be replayable from the rng seed."""
    import random

    from evergreen_tpu.agent.rest_comm import RestCommunicator

    problems: List[str] = []
    policy = RestCommunicator("http://127.0.0.1:1").policy
    if not policy.full_jitter:
        problems.append("agent transport policy is not full-jitter")
    pauses = [
        policy.backoff_s(0, random.Random(1000 + i)) for i in range(64)
    ]
    base = policy.base_backoff_s
    if not all(0.0 <= p <= base for p in pauses):
        problems.append("a full-jitter pause escaped [0, base]")
    spread = max(pauses) - min(pauses)
    if spread < 0.5 * base:
        problems.append(
            "fleet retry pauses did not spread: span %.4f of base %.4f"
            % (spread, base)
        )
    # the band-jitter default would keep every pause above base/2;
    # full jitter must reach the low half or the fleet still storms
    if min(pauses) >= 0.5 * base:
        problems.append(
            "no pause landed in [0, base/2): the wave stays "
            "synchronized"
        )
    replay = [
        policy.backoff_s(0, random.Random(1000 + i)) for i in range(64)
    ]
    if replay != pauses:
        problems.append("jitter schedule is not seed-replayable")
    return {"arm": "cases", "point": "retry-full-jitter-spread",
            "ok": not problems, "problems": problems}


def run_cases() -> List[dict]:
    results = []
    for fn in (wait_reply_reorder_case, wait_reply_duplicate_error_case,
               sock_adopt_refused_case, sock_adopt_halfopen_case,
               dispatch_cas_duplicate_case, retry_jitter_spread_case):
        results.append(_emit(fn()))
    return results


# ----------------------------------------------------------------- fuzz arm

def run_fuzz_reachability(want: int = 3,
                          max_probe: int = 200) -> List[dict]:
    """The weather fuzzer must actually draw ``net_fault`` events (the
    vocabulary is reachable, not dead), drawn cases must run green, and
    a sabotaged net timeline must shrink to a minimal reproduction that
    replays deterministically."""
    from evergreen_tpu.scenarios import fuzz as fuzz_mod

    results = []
    found = []
    for seed in range(fuzz_mod.DEFAULT_CAMPAIGN_SEED,
                      fuzz_mod.DEFAULT_CAMPAIGN_SEED + max_probe):
        spec = fuzz_mod.generate_weather(seed)
        if any(e.kind == "net_fault" for e in spec.events):
            found.append((seed, spec))
            if len(found) >= want:
                break
    if len(found) < want:
        return [_emit({
            "arm": "fuzz", "point": "reachability", "ok": False,
            "problems": [
                "only %d/%d probed weathers drew a net_fault in %d "
                "seeds" % (len(found), want, max_probe)
            ],
        })]
    for seed, spec in found:
        entry = fuzz_mod.run_case(spec)
        results.append(_emit(_entry_result(
            "fuzz", "w%d" % seed, entry
        )))
    results.append(_emit(shrunk_net_timeline_case(found[0][0])))
    return results


def shrunk_net_timeline_case(seed: int) -> dict:
    """Plant a sabotage into a weather that drew a net_fault, shrink
    the red timeline, and prove the shrunk reproduction (a) still
    carries the violation and (b) replays fingerprint-identically —
    the fuzzer's net_fault vocabulary round-trips through the whole
    shrink/replay pipeline."""
    import dataclasses

    from evergreen_tpu.scenarios import fuzz as fuzz_mod
    from evergreen_tpu.scenarios.library import _sabotage_duplicate_claim
    from evergreen_tpu.scenarios.spec import Ev

    problems: List[str] = []
    base = fuzz_mod.generate_weather(seed)
    net_evs = [e for e in base.events if e.kind == "net_fault"]
    if not net_evs:
        return {"arm": "fuzz", "point": "shrunk-net-timeline",
                "ok": False,
                "problems": ["seed %d drew no net_fault" % seed]}
    sab_tick = max(1, net_evs[0].tick)
    spec = dataclasses.replace(
        base,
        name="%s-net-sab" % base.name,
        events=list(base.events) + [
            Ev(sab_tick, "call", {"fn": _sabotage_duplicate_claim})
        ],
    )
    entry = fuzz_mod.run_case(spec)
    if entry["ok"]:
        problems.append("the sabotaged net timeline was not caught")
        return {"arm": "fuzz", "point": "shrunk-net-timeline",
                "ok": False, "problems": problems}
    red = fuzz_mod.red_keys(entry)
    minimal = fuzz_mod.shrink_spec(
        spec, fails=fuzz_mod.fails_matching(red), max_runs=60,
    )
    e1 = fuzz_mod.run_case(minimal)
    e2 = fuzz_mod.run_case(minimal)
    if not (set(red) & set(fuzz_mod.red_keys(e1))):
        problems.append(
            "the shrunk timeline lost the original violation"
        )
    f1 = e1.get("fingerprint")
    if not f1 or f1 != e2.get("fingerprint"):
        problems.append(
            "the shrunk net timeline did not replay "
            "deterministically: %r != %r" % (f1, e2.get("fingerprint"))
        )
    return {"arm": "fuzz", "point": "shrunk-net-timeline",
            "ok": not problems, "problems": problems,
            "shrunk_events": len(minimal.events),
            "shrunk_ticks": minimal.ticks}


# -------------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="network-chaos partition matrix"
    )
    parser.add_argument("--grid-only", action="store_true")
    parser.add_argument("--weathers-only", action="store_true")
    parser.add_argument("--cases-only", action="store_true")
    parser.add_argument("--fuzz-only", action="store_true")
    parser.add_argument(
        "--point", default=None,
        help="run one proc grid point: config:seam:kind "
             "(e.g. fleet2:ipc.send.0:partition)",
    )
    args = parser.parse_args(argv)

    selected = [args.grid_only, args.weathers_only, args.cases_only,
                args.fuzz_only]
    run_all = not any(selected)

    results: List[dict] = []
    # the self-test gates EVERYTHING: a matrix that cannot convict a
    # planted violation must not certify a single point
    if args.point is None:
        results.extend(run_sabotage())
        if not results[-1]["ok"]:
            print(json.dumps({
                "net_matrix_points": len(results),
                "net_matrix_failures": 1,
                "failed": [results[-1]["point"]],
                "aborted": "sabotage self-test failed",
            }), flush=True)
            return 1
    if args.point is not None:
        results.extend(run_grid(only_point=args.point))
    else:
        if run_all or args.grid_only:
            results.extend(run_grid())
        if run_all or args.weathers_only:
            results.extend(run_weathers())
        if run_all or args.cases_only:
            results.extend(run_cases())
        if run_all or args.fuzz_only:
            results.extend(run_fuzz_reachability())

    failures = [r for r in results if not r["ok"]]
    print(json.dumps({
        "net_matrix_points": len(results),
        "net_matrix_failures": len(failures),
        "failed": [r["point"] for r in failures],
    }), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
