#!/usr/bin/env python
"""Resident ≡ rebuild parity matrix + churn micro-bench
(``make resident-parity``; ``tools/gate.py --resident-parity``).

Two stages, exit non-zero on any divergence:

1. **Parity fuzz** — the randomized churn property suite
   (tests/test_resident_state.py) in a clean subprocess: after every
   step of add / complete / block / priority-bump / distro-remove /
   host-lifecycle churn, the device-resident state plane's columns must
   canonicalize identically to a from-scratch ``build_snapshot`` of the
   same gather, plus the fenced-epoch / recovery invalidation and
   device-mirror cases.

2. **Churn micro-bench** — mid-scale (60 distros × 12k tasks)
   store-backed churn ticks through the REAL ``run_tick``, resident
   plane vs full-rebuild path in the SAME process (within-run numbers —
   wall clock on shared CI boxes varies ~5x between runs, so only the
   relative comparison is asserted-adjacent; the bound itself lives in
   tools/perf_guard.py). Each resident tick is followed by an
   out-of-band canonical-parity check against a cold rebuild, and the
   run must have been delta-shaped: zero plane fallbacks, exactly one
   cold rebuild, skip/patch/splice persists dominating full rewrites.

Prints one JSON line per stage; the final line is the verdict.
"""
from __future__ import annotations

import dataclasses
import json
import os
import random
import statistics
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

N_DISTROS = 60
N_TASKS = 12_000
RESIDENT_TICKS = 5
REBUILD_TICKS = 3
FINISH_PER_TICK = 120
FRESH_PER_TICK = 60


def run_fuzz() -> int:
    cmd = [
        sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
        os.path.join(ROOT, "tests", "test_resident_state.py"),
    ]
    print("resident-parity:", " ".join(cmd), flush=True)
    return subprocess.call(
        cmd, env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=ROOT
    )


def run_microbench() -> dict:
    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.scheduler.persister import persister_state_for
    from evergreen_tpu.scheduler.resident import (
        canonicalize,
        resident_plane_for,
    )
    from evergreen_tpu.scheduler.snapshot import build_snapshot
    from evergreen_tpu.scheduler.wrapper import (
        TickOptions,
        run_tick,
        tick_cache_for,
    )
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    distros, tbd, hbd, _, _ = generate_problem(
        N_DISTROS, N_TASKS, seed=17, task_group_fraction=0.25,
        dep_fraction=0.25, patch_fraction=0.5, hosts_per_distro=5,
    )
    store = Store()
    for d in distros:
        distro_mod.insert(store, d)
    all_tasks = [t for ts in tbd.values() for t in ts]
    task_mod.insert_many(store, all_tasks)
    for hs in hbd.values():
        host_mod.insert_many(store, hs)

    opts = TickOptions(create_intent_hosts=False, use_cache=True,
                       underwater_unschedule=False)
    run_tick(store, opts, now=NOW)  # cold prime: compile + plane rebuild
    run_tick(store, opts, now=NOW + 0.01)  # absorb the stamp storm

    plane = resident_plane_for(store)
    cache = tick_cache_for(store)
    pstate = persister_state_for(store)
    pstate.skipped = pstate.patched = pstate.rewritten = 0
    pstate.spliced = 0
    rng = random.Random(5)
    coll = task_mod.coll(store)
    failures: list = []

    def churn(tag: str, tick: int) -> None:
        for t in rng.sample(all_tasks, FINISH_PER_TICK):
            coll.update(t.id, {"status": TaskStatus.SUCCEEDED.value})
        fresh = [
            dataclasses.replace(
                rng.choice(all_tasks), id=f"rp-{tag}-{tick}-{j}",
                depends_on=[],
            )
            for j in range(FRESH_PER_TICK)
        ]
        task_mod.insert_many(store, fresh)

    res_ms = []
    for tick in range(RESIDENT_TICKS):
        churn("r", tick)
        now = NOW + 10.0 * (tick + 1)
        t1 = time.perf_counter()
        run_tick(store, opts, now=now)
        res_ms.append((time.perf_counter() - t1) * 1e3)
        # out-of-band parity: re-publish the (already synced) resident
        # columns and canonicalize against a cold rebuild of the gather
        g = cache.gather(now)
        snap = plane.sync(cache, *g, now)
        cold = build_snapshot(*g, now)
        if snap is None:
            failures.append(f"tick {tick}: resident plane fell back")
        elif canonicalize(snap) != canonicalize(cold):
            failures.append(f"tick {tick}: resident != rebuild canonical")

    stats = plane.stats()
    rb_opts = dataclasses.replace(opts, use_resident=False)
    rb_ms = []
    for tick in range(REBUILD_TICKS):
        churn("f", tick)
        t1 = time.perf_counter()
        run_tick(store, rb_opts, now=NOW + 1000.0 + 10.0 * (tick + 1))
        rb_ms.append((time.perf_counter() - t1) * 1e3)

    if stats["fallbacks"]:
        failures.append(f"plane fell back {stats['fallbacks']}x")
    if stats["rebuilds"] != 1:
        failures.append(
            f"expected exactly the cold rebuild, got {stats['rebuilds']} "
            f"({stats['rebuild_reasons']})"
        )
    deltas = pstate.skipped + pstate.patched + pstate.spliced
    if deltas <= pstate.rewritten:
        failures.append(
            f"store path not delta-shaped: skip+patch+splice {deltas} "
            f"<= rewrite {pstate.rewritten}"
        )
    return {
        "config": f"{N_DISTROS}d x {N_TASKS}t",
        "churn_resident_ms": round(statistics.median(res_ms), 1),
        "churn_rebuild_ms": round(statistics.median(rb_ms), 1),
        "persist": {
            "skipped": pstate.skipped, "patched": pstate.patched,
            "spliced": pstate.spliced, "rewritten": pstate.rewritten,
        },
        "resident": stats,
        "failures": failures,
    }


def main() -> int:
    rc = run_fuzz()
    if rc != 0:
        print(json.dumps({"resident_parity": "fuzz RED", "rc": rc}))
        return rc
    result = run_microbench()
    print(json.dumps({"resident_parity_bench": result}))
    if result["failures"]:
        print("resident-parity: RED —", "; ".join(result["failures"]),
              file=sys.stderr)
        return 1
    print("resident-parity: green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
