#!/usr/bin/env python
"""Always-on soak arm (ISSUE 19 satellite): a time-boxed, fresh-seed
randomized-weather campaign sized by ``SOAK_MINUTES`` (default 10).

    make soak                      # 10 minutes
    SOAK_MINUTES=120 make soak     # two hours
    SOAK_SEED=777 make soak        # pin the seed stream (reproduce)

Unlike ``make fuzz`` (pinned seeds, gate-blocking), the soak explores
NEW weather every run: the sabotage self-test proves the invariant net
still bites on both backends, then the budget is split between the
in-process engine arm and the child-process arm (a real supervised
2-shard fleet under worker SIGKILLs / hangs / supervisor kills). The
drawn vocabulary includes the ``disk_fault`` weathers — ENOSPC at a
WAL group commit, snapshot bitrot/short after the rename, EIO — so
every soak also exercises the storage-integrity plane's detection →
quarantine → self-heal path.

Findings shrink and land in ``FUZZ_FINDINGS/`` as ready-to-check-in
regression specs (repo rule: every finding is promoted to
``evergreen_tpu/scenarios/regressions/`` with its fix). The resulting
FUZZCARD.json is diffed against FUZZCARD_GREEN.json — new failures or
a case-throughput collapse fail the soak. See docs/DEPLOY.md for the
N-hour deployment invocation and triage runbook.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

#: fraction of the box spent on the child-process (supervised-fleet)
#: arm; proc cases are ~10x slower per case, so most of the box goes to
#: in-process breadth and the proc arm gets depth on a few seeds
PROC_FRACTION = 0.3


def main(argv: Optional[List[str]] = None) -> int:
    try:
        minutes = float(os.environ.get("SOAK_MINUTES", "10"))
    except ValueError:
        print("soak: SOAK_MINUTES must be a number", file=sys.stderr)
        return 2
    seed_env = os.environ.get("SOAK_SEED", "")
    start_seed = int(seed_env) if seed_env else int(time.time())
    budget_s = max(60.0, minutes * 60.0)
    # clamp each arm to at least the gate's pinned box (45s engine /
    # 25s proc, plus proc headroom): FUZZCARD_GREEN was recorded at
    # that box, and the --diff throughput-collapse check is only
    # meaningful against an equal-or-bigger box
    proc_budget = max(budget_s * PROC_FRACTION, 35.0)
    inproc_budget = max(budget_s - proc_budget, 45.0)
    # the proc arm's case cap scales with the box (the gate default of
    # 6 would silently truncate an N-hour soak to minutes of coverage)
    proc_max_cases = max(6, int(proc_budget / 8.0))

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    fuzz_tool = os.path.join(_REPO_ROOT, "tools", "fuzz_matrix.py")

    print(json.dumps({
        "soak_minutes": minutes, "start_seed": start_seed,
        "inproc_budget_s": round(inproc_budget, 1),
        "proc_budget_s": round(proc_budget, 1),
    }), flush=True)

    # the net must still bite before any green from it can be trusted
    sab = [sys.executable, fuzz_tool, "--sabotage"]
    print("soak:", " ".join(sab), flush=True)
    rc = subprocess.call(sab, env=env, cwd=_REPO_ROOT)
    if rc != 0:
        print("soak: RED — sabotage self-test failed; the invariant "
              "net is blind, nothing below would mean anything",
              file=sys.stderr)
        return rc

    campaign = [
        sys.executable, fuzz_tool,
        "--budget", str(inproc_budget),
        "--proc-budget", str(proc_budget),
        "--proc-max-cases", str(proc_max_cases),
        "--start-seed", str(start_seed),
        "--diff",
    ]
    print("soak:", " ".join(campaign), flush=True)
    rc = subprocess.call(campaign, env=env, cwd=_REPO_ROOT)
    if rc != 0:
        print("soak: RED — campaign found failures (shrunk specs in "
              "FUZZ_FINDINGS/) or throughput collapsed vs green",
              file=sys.stderr)
    else:
        print("soak: green")
    return rc


if __name__ == "__main__":
    sys.exit(main())
