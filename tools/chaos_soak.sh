#!/usr/bin/env bash
# Chaos soak: service + agents + revision pushes + random aborts/restarts.
#
# Fault-matrix mode (`--faults`, or FAULT_MATRIX=1): instead of the
# service soak, run every injected-fault class from tools/fault_matrix.py
# across several seeds — solve raise/hang, WAL error + torn write, lease
# loss, agent-comm timeout, provider error, sender error, breaker cycle,
# job quarantine, tick-budget shed. Exits nonzero if any case fails.
set -e
export PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu PYTHONPATH="$(cd "$(dirname "$0")/.." && pwd)"
cd "$PYTHONPATH"
if [ "${1:-}" = "--faults" ] || [ -n "${FAULT_MATRIX:-}" ]; then
  exec python tools/fault_matrix.py --seeds "${SEEDS:-3}"
fi
PORT=${PORT:-19270}
python -m evergreen_tpu service --port $PORT > /tmp/chaos_svc.log 2>&1 &
SVC=$!
trap "kill -9 $SVC 2>/dev/null; pkill -9 -f 'evergreen_tpu agent' 2>/dev/null || true" EXIT
for i in $(seq 60); do curl -s localhost:$PORT/rest/v2/status >/dev/null 2>&1 && break; sleep 0.5; done

python - <<PY
import json, random, textwrap, threading, time, urllib.request
base = "http://127.0.0.1:$PORT"
def call(m, p, b=None):
    req = urllib.request.Request(base+p, data=json.dumps(b).encode() if b is not None else None,
        method=m, headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read() or b"{}")
call("PUT", "/rest/v2/distros/chaos", {"provider": "mock",
     "host_allocator_settings": {"maximum_hosts": 5}})
call("PUT", "/rest/v2/projects/chaosproj", {})
cfg = textwrap.dedent("""
tasks:
  - name: quick
    commands: [{command: shell.exec, params: {script: "sleep 0.1 && echo q"}}]
  - name: medium
    depends_on: [{name: quick}]
    commands: [{command: shell.exec, params: {script: "sleep 0.6 && echo m"}}]
  - name: slow
    commands: [{command: shell.exec, params: {script: "sleep 2 && echo s"}}]
buildvariants:
  - name: bv
    run_on: [chaos]
    tasks: [{name: quick}, {name: medium}, {name: slow}]
""")
rng = random.Random(7)
for i in range(1, 7):
    call("POST", "/rest/v2/projects/chaosproj/revisions",
         {"revision": f"chaos{i:06d}xx", "config_yaml": cfg})
    time.sleep(8)
    # chaos: abort or restart a random known task
    tasks = []
    for j in range(1, i + 1):
        vid = f"chaosproj_{j}_chaos" + f"{j:06d}"[:5]
        try:
            tasks += call("GET", f"/rest/v2/versions/{vid}/tasks")
        except Exception:
            pass
    if tasks and rng.random() < 0.7:
        t = rng.choice(tasks)
        op = "abort" if t["status"] in ("started", "dispatched") else (
            "restart" if t["status"] in ("success", "failed") else None)
        if op:
            try:
                call("POST", f"/rest/v2/tasks/{t['_id']}/{op}", {"user": "chaos"})
                print("chaos:", op, t["_id"], flush=True)
            except Exception as e:
                print("chaos op failed:", e, flush=True)
print("pushes done", flush=True)
PY

# attach agents as hosts come up (up to 4)
STARTED=""
for i in $(seq 30); do
  for H in $(curl -s localhost:$PORT/rest/v2/hosts | python -c "import json,sys; print(' '.join(h['_id'] for h in json.load(sys.stdin) if h['status']=='running'))" 2>/dev/null); do
    case "$STARTED" in *"$H"*) ;; *)
      python -m evergreen_tpu agent --host-id "$H" --api-server http://127.0.0.1:$PORT > /tmp/chaos_agent_$H.log 2>&1 &
      STARTED="$STARTED $H";;
    esac
  done
  sleep 4
done &
ATTACHER=$!

sleep 120
kill $ATTACHER 2>/dev/null || true

# chaos phase 2: restart finished tasks + abort anything running, then let
# the system re-converge (agents are still polling)
python - <<PY
import json, random, urllib.request
base = "http://127.0.0.1:$PORT"
def call(m, p, b=None):
    req = urllib.request.Request(base+p, data=json.dumps(b).encode() if b is not None else None,
        method=m, headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read() or b"{}")
rng = random.Random(3)
tasks = []
for v in call("GET", "/rest/v2/versions?limit=50"):
    if v["project"] == "chaosproj":
        tasks += call("GET", f"/rest/v2/versions/{v['_id']}/tasks")
done = [t for t in tasks if t["status"] in ("success", "failed")]
for t in rng.sample(done, min(3, len(done))):
    call("POST", f"/rest/v2/tasks/{t['_id']}/restart", {"user": "chaos"})
    print("chaos: restart", t["_id"], flush=True)
running = [t for t in tasks if t["status"] in ("started", "dispatched")]
for t in running[:2]:
    call("POST", f"/rest/v2/tasks/{t['_id']}/abort", {"user": "chaos"})
    print("chaos: abort", t["_id"], flush=True)
PY
sleep 100

python - <<PY
import collections, json, urllib.request
base = "http://127.0.0.1:$PORT"
def get(p):
    return json.load(urllib.request.urlopen(base+p, timeout=30))
print("status:", get("/rest/v2/status"))
counts = collections.Counter()
for v in get("/rest/v2/versions?limit=50"):
    if v["project"] == "chaosproj":
        counts[v["status"]] += 1
print("version outcomes:", dict(counts))
tstat = collections.Counter(t["status"] for v in get("/rest/v2/versions?limit=50")
                            if v["project"]=="chaosproj"
                            for t in get(f"/rest/v2/versions/{v['_id']}/tasks"))
print("task statuses:", dict(tstat))
failed_jobs = [e for e in get("/rest/v2/events") if e["event_type"] == "JOB_FAILED"]
print("failed background jobs:", len(failed_jobs))
for e in failed_jobs[:3]:
    print("  ", e["data"].get("type"), (e["data"].get("error") or "")[-160:])
PY
