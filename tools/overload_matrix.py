#!/usr/bin/env python
"""Storm-soak matrix: seeded storms against the overload-protection
layer (utils/overload.py), one case per storm class from ISSUE 5.

Each case drives sustained overload through a real seam — a task-churn
job flood, an event/notification storm, an API scrape storm, a slow
store (injected via the ``wal.commit`` fault seam) — and asserts the
brownout invariants:

  * planning ticks never starve: every scheduler tick runs and persists
    queues, storm or no storm;
  * agent-critical work is never shed: agent-class jobs and agent
    protocol requests always get through;
  * the caps hold: the JobQueue pending set and the notification
    outboxes stay bounded under sustained pressure (no unbounded memory
    growth);
  * nothing is shed silently: every drop shows up in the counters AND
    the ``overload_sheds`` aggregate records — the two books balance;
  * the monitor recovers: after the storm ends the ladder returns to
    GREEN (through its hysteresis) within a bounded number of
    evaluations.

``tests/test_overload.py`` parametrizes over the same CASES registry;
``make overload-matrix`` / ``tools/gate.py --overload-matrix`` run it
standalone across seeds.

The event-storm and slow-store cases are MIGRATED (ISSUE 12): they
execute as scenario specs through the trace-driven engine
(evergreen_tpu/scenarios/matrix.py) with their original assertions
intact; this module delegates for those names. The task-churn and
API-scrape storms stay bespoke — they exercise real worker threads and
a live HTTP request loop the virtual-clock engine deliberately avoids.
"""
from __future__ import annotations

import os
import sys
import time as _time
from typing import Callable, Dict, List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from evergreen_tpu.queue.jobs import (
    PRIORITY_AGENT,
    PRIORITY_PLANNING,
    PRIORITY_STATS,
    FnJob,
    JobQueue,
)
from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
from evergreen_tpu.settings import OverloadConfig
from evergreen_tpu.storage.store import Store
from evergreen_tpu.utils import overload
from evergreen_tpu.utils import log as log_mod
from evergreen_tpu.utils.benchgen import NOW

from tools.fault_matrix import _capture_logs, _seed_store

OPTS = TickOptions(create_intent_hosts=True, underwater_unschedule=False)

#: bounded post-storm recovery: the ladder must be GREEN within this
#: many explicit evaluations after the storm ends
RECOVERY_EVALS = 12


def _counters() -> Dict[str, int]:
    return log_mod.counters_snapshot()


def _delta(before: Dict[str, int], name: str) -> int:
    return log_mod.get_counter(name) - before.get(name, 0)


def _drain_to_green(monitor: overload.LoadMonitor) -> int:
    """Evaluate until GREEN (or the bound); returns evaluations used.
    The sleep gives time-decayed gauges (api_rps) their idle windows."""
    for i in range(RECOVERY_EVALS):
        if monitor.evaluate() == overload.GREEN:
            return i + 1
        _time.sleep(0.15)
    return RECOVERY_EVALS + 1


def _sheds_balance(store: Store, before: Dict[str, int], kind: str,
                   counter: str) -> bool:
    """Zero-silent-discard audit: the counter delta for one shed class
    must equal the sum of its aggregate records (fresh store, so the
    records ARE the delta)."""
    recorded = sum(
        d.get("count", 0)
        for d in store.collection(overload.SHEDS_COLLECTION).find(
            lambda d: d.get("kind") == kind
        )
    )
    return recorded == _delta(before, counter) and recorded > 0


# --------------------------------------------------------------------------- #
# cases
# --------------------------------------------------------------------------- #


def case_task_churn_storm(seed: int = 0) -> dict:
    """A flood of stats-class churn jobs against a small bounded queue:
    planning ticks and agent jobs must ride through untouched while the
    lowest class browns out."""
    store = Store()
    _seed_store(store, seed=seed + 31)
    OverloadConfig(
        queue_max_pending=24,
        queue_pending_levels=[8.0, 16.0, 24.0],
        hysteresis_ticks=2,
        eval_interval_s=0.0,
        tick_cadence_s=0.05,
    ).set(store)
    monitor = overload.monitor_for(store)
    before = _counters()
    got, stop = _capture_logs()
    q = JobQueue(store, workers=2, name=f"storm-{seed}")

    planning_results: List = []
    agent_runs: List[int] = []
    max_pending = [0]

    def churn(s: Store) -> None:
        _time.sleep(0.004)

    def plan(s: Store) -> None:
        planning_results.append(run_tick(s, OPTS, now=NOW))

    planning_ok: List[bool] = []
    agent_ok: List[bool] = []
    try:
        for i in range(150):
            q.put(
                FnJob(
                    f"churn-{seed}-{i}",
                    churn,
                    job_type="host-stats",
                    priority=PRIORITY_STATS,
                )
            )
            if i % 25 == 0:
                planning_ok.append(
                    bool(
                        q.put(
                            FnJob(
                                f"tick-{seed}-{i}",
                                plan,
                                scopes=["scheduler-tick"],
                                job_type="scheduler-tick",
                                priority=PRIORITY_PLANNING,
                            )
                        )
                    )
                )
                agent_ok.append(
                    bool(
                        q.put(
                            FnJob(
                                f"keepalive-{seed}-{i}",
                                lambda s, _i=i: agent_runs.append(_i),
                                job_type="agent-keepalive",
                                priority=PRIORITY_AGENT,
                            )
                        )
                    )
                )
            max_pending[0] = max(max_pending[0], q.pending_count())
        peaked = monitor.level() >= overload.YELLOW
        q.wait_idle(30.0)
        evals_to_green = _drain_to_green(monitor)
    finally:
        stop()
        q.close()
    shed_docs = store.collection("jobs").find(
        lambda d: d.get("status") == "shed"
    )
    return {
        "ok": (
            all(planning_ok)
            and all(agent_ok)
            and len(planning_results) == len(planning_ok)
            and all(sum(r.queues.values()) > 0 for r in planning_results)
            and len(agent_runs) == len(agent_ok)
            # the cap held: agent/planning jobs may ride over it, churn
            # never (6 = the worst-case over-cap critical jobs in flight)
            and max_pending[0] <= 24 + 6
            and peaked
            and _delta(before, "overload.jobs_shed") > 0
            and _delta(before, "overload.jobs_shed.agent") == 0
            and _delta(before, "overload.jobs_shed.planning") == 0
            and len(shed_docs) > 0
            and _sheds_balance(store, before, "job", "overload.jobs_shed")
            and evals_to_green <= RECOVERY_EVALS
            and any(r.get("message") == "job-shed" for r in got)
        ),
        "max_pending": max_pending[0],
        "evals_to_green": evals_to_green,
        "shed": _delta(before, "overload.jobs_shed"),
        "logs": got,
    }


def _engine_case(name: str):
    """MIGRATED (ISSUE 12): the case runs as a scenario spec through the
    trace-driven engine (evergreen_tpu/scenarios/matrix.py) with its
    original assertions intact; this module only delegates."""

    def run(seed: int = 0) -> dict:
        from evergreen_tpu.scenarios import run_matrix_case

        return run_matrix_case("overload", name, seed)

    run.__name__ = f"case_{name.replace('-', '_')}"
    return run


#: notification fan-out storm: coalesce at YELLOW, counted drops at the
#: cap, exactly-once send accounting, GREEN after the drain
case_event_storm = _engine_case("event-storm")


def case_api_storm(seed: int = 0) -> dict:
    """A scrape storm on the HTTP surface: expensive list endpoints 429
    with a level-derived Retry-After while the agent protocol keeps its
    SLO, then the rate gauge decays and service resumes."""
    from evergreen_tpu.api.rest import RestApi

    store = Store()
    _, tasks_by_distro, _ = _seed_store(store, seed=seed + 47)
    task_id = next(iter(tasks_by_distro.values()))[0].id
    OverloadConfig(
        api_rps_levels=[60.0, 120.0, 100000.0],
        hysteresis_ticks=2,
        eval_interval_s=0.02,
        retry_after_red_s=30.0,
    ).set(store)
    # this case exercises the LADDER, not the read cache: cached
    # answers roughly double the storm loop's attack rate, which only
    # raises the rate-EWMA peak the recovery bound then has to decay
    from evergreen_tpu.settings import ReadPathConfig

    ReadPathConfig(cache_enabled=False).set(store)
    monitor = overload.monitor_for(store)
    before = _counters()
    got, stop = _capture_logs()
    api = RestApi(store)
    shed_status = None
    shed_headers: List = []
    agent_status = None
    cheap_status = None
    try:
        deadline = _time.monotonic() + 5.0
        while monitor.level() < overload.RED:
            api.handle("GET", "/rest/v2/hosts")
            if _time.monotonic() > deadline:
                break
        red = monitor.level() >= overload.RED
        status, payload = api.handle("GET", "/rest/v2/hosts")
        shed_status = status
        shed_headers = list(
            getattr(api._ident, "response_headers", None) or []
        )
        shed_payload = payload
        # agent-critical traffic is never shed
        agent_status, _ = api.handle(
            "POST", f"/rest/v2/tasks/{task_id}/agent/heartbeat"
        )
        # a cheap single-doc read is not an expensive list: at RED it
        # still serves
        cheap_status, _ = api.handle("GET", f"/rest/v2/tasks/{task_id}")
        evals_to_green = _drain_to_green(monitor)
        post_status, _ = api.handle("GET", "/rest/v2/hosts")
    finally:
        stop()
    retry_vals = [v for h, v in shed_headers if h == "Retry-After"]
    return {
        "ok": (
            red
            and shed_status == 429
            and shed_payload.get("level") in ("red", "black")
            and retry_vals == ["30"]
            and agent_status != 429
            and cheap_status != 429
            and _delta(before, "overload.api_shed") > 0
            and evals_to_green <= RECOVERY_EVALS
            and post_status == 200
            and any(r.get("message") == "request-shed" for r in got)
        ),
        "shed_status": shed_status,
        "retry_after": retry_vals,
        "agent_status": agent_status,
        "evals_to_green": evals_to_green,
        "logs": got,
    }


#: crawling WAL (hang at wal.commit): the commit-latency EWMA drives
#: RED, ticks brown out optional work but keep planning, and the level
#: recovers once the store heals
case_slow_store_storm = _engine_case("slow-store-storm")


CASES: Dict[str, Callable[[int], dict]] = {
    "task-churn-storm": case_task_churn_storm,
    "event-storm": case_event_storm,
    "api-storm": case_api_storm,
    "slow-store-storm": case_slow_store_storm,
}


def run_case(name: str, seed: int = 0) -> dict:
    return CASES[name](seed)


def main() -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", type=int, default=3)
    p.add_argument("--case", default="", help="run one case only")
    args = p.parse_args()
    names = [args.case] if args.case else sorted(CASES)
    failures = 0
    for seed in range(args.seeds):
        for name in names:
            out = run_case(name, seed)
            ok = bool(out.get("ok"))
            failures += 0 if ok else 1
            detail = {
                k: v for k, v in out.items() if k not in ("logs", "ok")
            }
            print(
                json.dumps(
                    {"case": name, "seed": seed, "ok": ok, **detail},
                    default=str,
                )
            )
    print(json.dumps({"overload_matrix_failures": failures}))
    return 1 if failures else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
