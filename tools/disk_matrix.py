#!/usr/bin/env python
"""Disk-fault matrix (ISSUE 19 tentpole) — the storage-integrity
analogue of tools/crash_matrix.py. Where the crash matrix kills the
process at every durability boundary, this matrix lets the process
LIVE and rots the disk underneath it, then asserts the detection →
quarantine → self-heal contract end to end:

  * resume == rerun: after any single injected disk fault, a cold
    reopen of the store equals both the still-serving in-memory truth
    and an uninterrupted reference run;
  * zero corrupt frames applied: CRC-failed WAL lines end the valid
    prefix at replay — counted, never applied;
  * quarantine accounting: corruption counters move by exactly the
    injected fault, forensic ``.corrupt-<ts>`` copies are kept, and a
    second scrub after the heal is clean;
  * no stranded temp files: every atomic publish either lands or
    vanishes, even under ENOSPC/EIO mid-write.

Four arms, all run by default (``make disk-matrix`` / ``gate
--disk-matrix``):

  grid    fault seams x kinds x store configurations {classic,
          durable+lease, 2-shard fleet}, driven in-process against a
          deterministic workload;
  engine  the same seams driven through the scenario engine's
          ``disk_fault`` event vocabulary against a scheduling fleet
          (work must finish; counters must move; no stranded tmp);
  cases   bespoke integrity cases: WAL format upgrade-compat
          (unstamped logs replay under a stamping binary), manifest
          bitrot/ENOSPC, lease corruption + TTL-gated steal, replica
          valid-prefix stop + read-repair;
  fuzz    reachability: the weather fuzzer must actually draw
          ``disk_fault`` events, and those cases must run green.

One JSON line per case; summary line; exit 1 on any failure. Failed
cases keep their data dir for inspection.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TICKS = 6
#: checkpoint EARLY (before any armed fault): a later checkpoint would
#: rotate a rotted WAL into ``.prev`` and legitimately retire the rot
#: before the scrub could ever see it
#: (tick 0: in the 2-shard config the shared seam counters reach the
#: armed index during tick 1 already)
CHECKPOINT_TICK = 0
CONFIGS = ("classic", "lease", "fleet2")

#: (seam, kind) grid. ``torn`` is WAL-only (a half-written line with
#: the raise surfaced); snapshots instead get ``short`` (truncated
#: publish) and ``bitrot`` (post-rename rot) — the two ways a rename
#: target goes bad.
GRID: List[Tuple[str, str]] = [
    ("wal.append", "enospc"),
    ("wal.append", "eio"),
    ("wal.append", "torn"),
    ("wal.append", "short"),
    ("wal.append", "bitrot"),
    ("wal.commit", "enospc"),
    ("wal.commit", "eio"),
    ("wal.commit", "torn"),
    ("wal.commit", "short"),
    ("wal.commit", "bitrot"),
    ("snapshot.write", "enospc"),
    ("snapshot.write", "eio"),
    ("snapshot.write", "bitrot"),
    ("snapshot.write", "short"),
]

#: per-seam call index to arm: mid-workload, past the first tick (so
#: there is a valid prefix to keep) and before the last (so serving
#: continues past the fault).
SEAM_INDEX = {"wal.append": 3, "wal.commit": 2, "snapshot.write": 0}

ENGINE_KINDS = ("enospc", "eio", "bitrot", "short")


# ---------------------------------------------------------------- workload

def _tick_ops(store, t: int, shard: int) -> None:
    jobs = store.collection("jobs")
    for j in range(3):
        jobs.upsert({
            "_id": "job-%d-%d-%d" % (shard, t, j),
            "tick": t, "shard": shard, "payload": "p" * 32,
        })
    store.collection("queues").upsert({
        "_id": "q%d" % shard,
        "rows": ["job-%d-%d" % (shard, i) for i in range(t + 1)],
    })


def _one_tick(store, t: int, shard: int) -> None:
    # one per-op write OUTSIDE the tick group (rides the wal.append
    # seam), then a grouped tick (rides wal.commit)
    store.collection("oplog").upsert({"_id": "op-%d-%d" % (shard, t),
                                      "t": t})
    store.begin_tick()
    try:
        _tick_ops(store, t, shard)
    finally:
        store.end_tick()


def _run_workload(stores) -> None:
    """TICKS deterministic ticks per store. A raised disk fault aborts
    a tick mid-flight; the contract is heal-and-redo — the redo is
    idempotent (upserts) and the one-shot fault is already consumed."""
    from evergreen_tpu.utils import faults

    for t in range(TICKS):
        for si, store in enumerate(stores):
            try:
                _one_tick(store, t, si)
            except (OSError, faults.FaultError):
                store.heal_durability()
                _one_tick(store, t, si)
        if t == CHECKPOINT_TICK:
            for store in stores:
                try:
                    store.checkpoint()
                except OSError:
                    # injected ENOSPC/EIO at the publish: the previous
                    # checkpoint (or bare WAL) stays authoritative
                    pass


def canonical(store) -> Dict[str, List[dict]]:
    out: Dict[str, List[dict]] = {}
    for name in sorted(store._collections):
        out[name] = sorted(
            store.collection(name).find(), key=lambda d: d["_id"]
        )
    return out


def _open_stores(config: str, data_dir: str):
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.lease import FileLease

    if config == "classic":
        return [DurableStore(data_dir)], []
    if config == "lease":
        lease = FileLease(os.path.join(data_dir, "writer.lease"),
                          ttl_s=60.0)
        if not lease.acquire(timeout_s=5.0):
            raise RuntimeError("could not acquire writer lease")
        return [DurableStore(data_dir, lease=lease)], [lease]
    if config == "fleet2":
        stores, leases = [], []
        for k in range(2):
            lease = FileLease(
                os.path.join(data_dir, "writer-%d.lease" % k), ttl_s=60.0
            )
            if not lease.acquire(timeout_s=5.0):
                raise RuntimeError("could not acquire shard %d lease" % k)
            stores.append(DurableStore(data_dir, lease=lease, shard_id=k))
            leases.append(lease)
        return stores, leases
    raise ValueError("unknown config %r" % config)


def _close_all(stores, leases) -> None:
    for store in stores:
        try:
            store.close()
        except Exception:
            pass
    for lease in leases:
        try:
            lease.release()
        except Exception:
            pass


def _stranded_tmp(data_dir: str) -> List[str]:
    out = []
    for root, _dirs, names in os.walk(data_dir):
        for n in names:
            if n.endswith(".tmp") or n.endswith(".prevtmp"):
                out.append(os.path.relpath(os.path.join(root, n),
                                           data_dir))
    return out


def _counter_deltas(before: Dict[str, int]) -> Dict[str, int]:
    from evergreen_tpu.utils.log import counters_snapshot

    after = counters_snapshot()
    return {
        k: v - before.get(k, 0)
        for k, v in after.items()
        if k.startswith("storage.") and v != before.get(k, 0)
    }


def expected_counters(seam: str, kind: str) -> Dict[str, Tuple[int, Optional[int]]]:
    """(min, max) bounds on storage.* counter deltas per grid point.
    Raised faults (eio, torn's surfaced OSError, append enospc) leave
    no rot behind — the harness heals and redoes, nothing to count."""
    wal = seam.startswith("wal.")
    if wal and seam == "wal.commit" and kind == "enospc":
        return {"storage.enospc_sheds": (1, 1)}
    if wal and kind in ("short", "bitrot"):
        return {
            "storage.wal_corrupt_frames": (1, 1),
            "storage.rebuilds": (1, None),
        }
    if seam == "snapshot.write" and kind in ("short", "bitrot"):
        return {
            "storage.snapshot_quarantined": (1, 1),
            "storage.rebuilds": (1, None),
        }
    return {}


# ---------------------------------------------------------------- grid arm

def reference_states(config: str) -> List[Dict[str, List[dict]]]:
    data_dir = tempfile.mkdtemp(prefix="diskref-%s-" % config)
    stores, leases = _open_stores(config, data_dir)
    try:
        _run_workload(stores)
        for store in stores:
            store.sync_persist()
        return [canonical(s) for s in stores]
    finally:
        _close_all(stores, leases)
        shutil.rmtree(data_dir, ignore_errors=True)


def run_grid_point(config: str, seam: str, kind: str,
                   reference: List[Dict[str, List[dict]]]) -> dict:
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.utils import faults
    from evergreen_tpu.utils.log import counters_snapshot

    point = "%s:%s:%s" % (config, seam, kind)
    data_dir = tempfile.mkdtemp(
        prefix="diskmx-%s-%s-%s-" % (config, seam.replace(".", "-"), kind)
    )
    problems: List[str] = []
    before = counters_snapshot()
    plan = faults.FaultPlan().at(seam, SEAM_INDEX[seam],
                                 faults.Fault(kind))
    faults.install(plan)
    stores, leases = [], []
    try:
        try:
            stores, leases = _open_stores(config, data_dir)
            _run_workload(stores)
        finally:
            faults.uninstall()
        if len(plan.fired) != 1:
            problems.append(
                "expected exactly one injected fault, fired=%r"
                % (plan.fired,)
            )

        # detection + self-heal while still serving
        for store in stores:
            store.scrub()
            store.sync_persist()
        live = [canonical(s) for s in stores]
        for si, store in enumerate(stores):
            rep = store.scrub()
            dirty = {
                k: rep[k]
                for k in ("wal_corrupt_frames", "snapshot_corrupt",
                          "torn_stub")
                if rep.get(k)
            }
            if dirty:
                problems.append(
                    "store %d: second scrub not clean after heal: %r"
                    % (si, dirty)
                )

        deltas = _counter_deltas(before)
        for name, (lo, hi) in expected_counters(seam, kind).items():
            got = deltas.get(name, 0)
            if got < lo or (hi is not None and got > hi):
                problems.append(
                    "counter %s moved %d, want [%d, %s]"
                    % (name, got, lo, "inf" if hi is None else hi)
                )

        # cold reopen: replay must apply zero corrupt frames and land
        # on the same state as the live store AND an uninterrupted
        # reference run (resume == rerun)
        for si, store in enumerate(stores):
            reopened = DurableStore(data_dir, shard_id=store.shard_id)
            if reopened.replay_report["corrupt_frames"]:
                problems.append(
                    "store %d: cold reopen still sees corrupt frames: %r"
                    % (si, reopened.replay_report)
                )
            got = canonical(reopened)
            if got != live[si]:
                problems.append(
                    "store %d: cold reopen diverged from live state" % si
                )
            if got != reference[si]:
                problems.append(
                    "store %d: resume != rerun (reference mismatch)" % si
                )

        stranded = _stranded_tmp(data_dir)
        if stranded:
            problems.append("stranded temp files: %r" % (stranded,))
        if kind in ("short", "bitrot"):
            names = os.listdir(data_dir)
            if not any(".corrupt-" in n for n in names):
                problems.append(
                    "no forensic .corrupt-<ts> copy kept beside the store"
                )
    finally:
        faults.uninstall()
        _close_all(stores, leases)

    ok = not problems
    if ok:
        shutil.rmtree(data_dir, ignore_errors=True)
    return {
        "arm": "grid", "point": point, "ok": ok,
        "fired": [list(f) for f in plan.fired],
        "counters": _counter_deltas(before),
        "problems": problems,
        "data_dir": None if ok else data_dir,
    }


def run_grid(only_point: Optional[str] = None) -> List[dict]:
    results = []
    for config in CONFIGS:
        reference = None
        for seam, kind in GRID:
            point = "%s:%s:%s" % (config, seam, kind)
            if only_point is not None and point != only_point:
                continue
            if reference is None:
                reference = reference_states(config)
            res = run_grid_point(config, seam, kind, reference)
            print(json.dumps(res), flush=True)
            results.append(res)
    return results


# -------------------------------------------------------------- engine arm

def _counter_check(name: str, lo: int, hi: Optional[int] = None):
    def check(run) -> Optional[str]:
        got = run.counter_delta(name)
        if got < lo or (hi is not None and got > hi):
            return "%s moved %d, want [%d, %s]" % (
                name, got, lo, "inf" if hi is None else hi
            )
        return None
    return check


def _check_no_stranded_tmp(run) -> Optional[str]:
    stranded = _stranded_tmp(run.data_dir)
    if stranded:
        return "stranded temp files beside the store: %r" % (stranded,)
    return None


def _engine_spec(target: str, kind: str):
    from evergreen_tpu.globals import Provider
    from evergreen_tpu.scenarios.spec import SLO, Ev, ScenarioSpec

    events = [
        Ev(0, "fleet", {"distros": [
            {"id": "dgrid", "provider": Provider.MOCK.value, "hosts": 4},
        ]}),
        Ev(0, "tasks", {"distro": "dgrid", "n": 8, "prefix": "dg-t"}),
        Ev(2, "disk_fault", {"target": target, "kind": kind}),
        Ev(6, "tasks", {"distro": "dgrid", "n": 4, "prefix": "dg-b"}),
    ]
    checks = [("no-stranded-tmp", _check_no_stranded_tmp)]
    if target == "wal":
        if kind == "enospc":
            checks.append(("enospc-shed",
                           _counter_check("storage.enospc_sheds", 1, 1)))
        elif kind in ("bitrot", "short"):
            checks.append(("rot-detected",
                           _counter_check("storage.wal_corrupt_frames",
                                          1)))
            checks.append(("rot-healed",
                           _counter_check("storage.rebuilds", 1)))
    else:
        if kind in ("bitrot", "short"):
            checks.append(("snapshot-quarantined",
                           _counter_check("storage.snapshot_quarantined",
                                          1, 1)))
            checks.append(("rot-healed",
                           _counter_check("storage.rebuilds", 1)))
        else:
            # a FAILED publish (ENOSPC/EIO) is not corruption: the old
            # pair stays live, nothing to quarantine
            checks.append(("nothing-quarantined",
                           _counter_check("storage.snapshot_quarantined",
                                          0, 0)))
    slos = [
        SLO("work-survives", "tasks_unfinished", "==", 0),
        SLO("no-failures", "tasks_failed", "==", 0),
    ]
    return ScenarioSpec(
        name="disk-grid-%s-%s" % (target, kind),
        description="matrix-generated disk weather: %s at the %s seam "
                    "against a scheduling fleet" % (kind, target),
        ticks=12,
        durable=True,
        events=events,
        slos=slos,
        checks=checks,
    )


def run_engine_grid() -> List[dict]:
    from evergreen_tpu.scenarios.engine import run_scenario

    results = []
    for target in ("wal", "snapshot"):
        for kind in ENGINE_KINDS:
            spec = _engine_spec(target, kind)
            entry = run_scenario(spec)
            res = {
                "arm": "engine", "point": "%s:%s" % (target, kind),
                "ok": bool(entry.get("ok")),
                "problems": [] if entry.get("ok") else [
                    json.dumps(entry, default=str)[:2000]
                ],
            }
            print(json.dumps(res), flush=True)
            results.append(res)
    return results


# --------------------------------------------------------------- cases arm

def upgrade_compat_case() -> dict:
    """A WAL written by a pre-stamping binary (no ``"k"`` field) must
    replay cleanly and completely under a stamping binary — CRC is an
    upgrade, not a flag day."""
    from evergreen_tpu.storage import integrity
    from evergreen_tpu.storage.durable import DurableStore

    problems: List[str] = []
    data_dir = tempfile.mkdtemp(prefix="diskmx-upgrade-")
    prev = integrity.set_wal_crc_enabled(False)
    old = None
    try:
        old = DurableStore(data_dir)
        for t in range(4):
            _one_tick(old, t, 0)
        old.sync_persist()
        live = canonical(old)
        # no close(): close() checkpoints, which would hide the replay
    finally:
        integrity.set_wal_crc_enabled(prev)

    reopened = DurableStore(data_dir)
    if reopened.replay_report["corrupt_frames"]:
        problems.append(
            "unstamped legacy frames rejected as corrupt: %r"
            % (reopened.replay_report,)
        )
    if reopened.replay_report["frames"] == 0:
        problems.append("no legacy frames were replayed at all")
    if canonical(reopened) != live:
        problems.append("legacy WAL replay lost writes under the "
                        "stamping binary")
    rep = reopened.scrub()
    if rep["wal_corrupt_frames"] or rep["snapshot_corrupt"]:
        problems.append("scrub convicted a healthy legacy log: %r"
                        % (rep,))
    if old is not None:
        old._journal.close()
    ok = not problems
    if ok:
        shutil.rmtree(data_dir, ignore_errors=True)
    return {"arm": "cases", "point": "upgrade-compat", "ok": ok,
            "problems": problems, "data_dir": None if ok else data_dir}


def manifest_case() -> dict:
    """Manifest entries go through the shared checksummed atomic
    writer: rot is refused at read, a failed publish leaves the old
    entry live with no stranded temp file."""
    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.storage import integrity
    from evergreen_tpu.utils import faults

    problems: List[str] = []
    data_dir = tempfile.mkdtemp(prefix="diskmx-manifest-")

    def write(pid: int) -> None:
        manifest.write_entry(data_dir, 0, pid=pid, sock="/tmp/s0.sock",
                             generation=1, epoch=3)

    write(4242)
    ent = manifest.read_entry(data_dir, 0)
    if not ent or ent.get("pid") != 4242:
        problems.append("manifest round-trip failed: %r" % (ent,))

    integrity.corrupt_byte(manifest.entry_path(data_dir, 0))
    if manifest.read_entry(data_dir, 0) is not None:
        problems.append("bitrotted manifest entry was adopted")

    write(4343)  # the next publish self-heals the rotted entry
    plan = faults.FaultPlan().at("manifest.write", 0,
                                 faults.Fault("enospc"))
    faults.install(plan)
    try:
        try:
            write(5555)
            problems.append("ENOSPC manifest publish did not surface")
        except OSError:
            pass
    finally:
        faults.uninstall()
    ent = manifest.read_entry(data_dir, 0)
    if not ent or ent.get("pid") != 4343:
        problems.append(
            "old manifest entry lost after failed publish: %r" % (ent,)
        )
    stranded = _stranded_tmp(data_dir)
    # the manifest writer's temp files are ``<entry>.<pid>``
    fleet = manifest.fleet_dir(data_dir)
    extras = [
        n for n in (os.listdir(fleet) if os.path.isdir(fleet) else [])
        if not n.endswith(".json")
    ]
    if stranded or extras:
        problems.append("stranded manifest temp files: %r"
                        % (stranded + extras,))

    # a torn publish (short write) must be refused at read, not adopted
    plan = faults.FaultPlan().at("manifest.write", 0,
                                 faults.Fault("short"))
    faults.install(plan)
    try:
        write(7777)
    finally:
        faults.uninstall()
    if manifest.read_entry(data_dir, 0) is not None:
        problems.append("torn manifest publish was adopted")
    write(8888)
    ent = manifest.read_entry(data_dir, 0)
    if not ent or ent.get("pid") != 8888:
        problems.append("manifest did not recover after torn publish")

    ok = not problems
    if ok:
        shutil.rmtree(data_dir, ignore_errors=True)
    return {"arm": "cases", "point": "manifest", "ok": ok,
            "problems": problems, "data_dir": None if ok else data_dir}


def lease_case() -> dict:
    """A corrupt lease file reads as None (never garbage ownership),
    is NOT stealable while fresh (the holder may still be renewing),
    and IS stealable once aged past TTL — rot cannot deadlock the
    writer role forever."""
    from evergreen_tpu.storage import integrity
    from evergreen_tpu.storage.lease import FileLease

    problems: List[str] = []
    data_dir = tempfile.mkdtemp(prefix="diskmx-lease-")
    path = os.path.join(data_dir, "writer.lease")
    holder = FileLease(path, ttl_s=10.0)
    if not holder.acquire(timeout_s=5.0):
        problems.append("holder could not acquire a fresh lease")
    holder_epoch = holder.epoch

    integrity.corrupt_byte(path)
    if holder.peek() is not None:
        problems.append("corrupt lease file parsed as a document")

    thief = FileLease(path, ttl_s=1.0)
    if thief.try_acquire():
        problems.append("fresh corrupt lease was stolen before TTL")
    old = time.time() - 60
    os.utime(path, (old, old))
    if not thief.try_acquire():
        problems.append("aged corrupt lease was not stealable")
    elif thief.epoch <= holder_epoch:
        problems.append(
            "steal over a corrupt lease did not advance the epoch "
            "(%d -> %d)" % (holder_epoch, thief.epoch)
        )
    thief.release()

    ok = not problems
    if ok:
        shutil.rmtree(data_dir, ignore_errors=True)
    return {"arm": "cases", "point": "lease", "ok": ok,
            "problems": problems, "data_dir": None if ok else data_dir}


def replica_case() -> dict:
    """A read replica tailing a rotted WAL stops at the end of the
    valid prefix (counted, never applied), keeps serving, and
    read-repairs from the primary's next verified checkpoint."""
    from evergreen_tpu.storage import integrity
    from evergreen_tpu.storage.durable import WAL_FILE, DurableStore
    from evergreen_tpu.storage.replica import ReplicaStore
    from evergreen_tpu.utils.log import counters_snapshot

    problems: List[str] = []
    data_dir = tempfile.mkdtemp(prefix="diskmx-replica-")
    before = counters_snapshot()
    primary = DurableStore(data_dir)
    replica = None
    try:
        for t in range(3):
            _one_tick(primary, t, 0)
        primary.sync_persist()
        replica = ReplicaStore(data_dir, poll_interval_s=3600.0,
                               replica_id="diskmx")
        replica.poll()
        if canonical(replica) != canonical(primary):
            problems.append("replica != primary before the fault")

        wal = os.path.join(data_dir, WAL_FILE)
        consumed = os.path.getsize(wal)
        for t in range(3, 5):
            _one_tick(primary, t, 0)
        primary.sync_persist()
        # rot a byte in the region the replica has NOT consumed yet
        integrity.corrupt_byte(wal, consumed + 16)

        replica.poll()
        deltas = _counter_deltas(before)
        if deltas.get("storage.wal_corrupt_frames", 0) < 1:
            problems.append(
                "replica did not count the corrupt frame: %r" % (deltas,)
            )
        # serving continues on the valid prefix
        if canonical(replica)["jobs"] == canonical(primary)["jobs"]:
            problems.append(
                "replica somehow applied past the corrupt frame"
            )

        rep = primary.scrub()
        if not rep["wal_corrupt_frames"]:
            problems.append("primary scrub missed the rot: %r" % (rep,))
        replica.poll()
        deltas = _counter_deltas(before)
        if deltas.get("storage.replica_read_repairs", 0) < 1:
            problems.append(
                "no read-repair was counted after the heal: %r"
                % (deltas,)
            )
        if canonical(replica) != canonical(primary):
            problems.append("replica != primary after read-repair")
        staleness = replica.staleness_ms()
        if not staleness < 60_000:
            problems.append(
                "replica staleness unbounded after repair: %r"
                % (staleness,)
            )
    finally:
        if replica is not None:
            replica.close()
        try:
            primary.close()
        except Exception:
            pass

    ok = not problems
    if ok:
        shutil.rmtree(data_dir, ignore_errors=True)
    return {"arm": "cases", "point": "replica", "ok": ok,
            "problems": problems, "data_dir": None if ok else data_dir}


def run_cases() -> List[dict]:
    results = []
    for fn in (upgrade_compat_case, manifest_case, lease_case,
               replica_case):
        res = fn()
        print(json.dumps(res), flush=True)
        results.append(res)
    return results


# ---------------------------------------------------------------- fuzz arm

def run_fuzz_reachability(want: int = 3, max_probe: int = 200) -> List[dict]:
    """The weather fuzzer must actually draw ``disk_fault`` events (the
    vocabulary is reachable, not dead), and drawn cases must run
    green."""
    from evergreen_tpu.scenarios import fuzz as fuzz_mod

    results = []
    found = []
    for seed in range(fuzz_mod.DEFAULT_CAMPAIGN_SEED,
                      fuzz_mod.DEFAULT_CAMPAIGN_SEED + max_probe):
        spec = fuzz_mod.generate_weather(seed)
        if any(e.kind == "disk_fault" for e in spec.events):
            found.append((seed, spec))
            if len(found) >= want:
                break
    if len(found) < want:
        res = {
            "arm": "fuzz", "point": "reachability", "ok": False,
            "problems": [
                "only %d/%d probed weathers drew a disk_fault in %d "
                "seeds" % (len(found), want, max_probe)
            ],
        }
        print(json.dumps(res), flush=True)
        return [res]
    for seed, spec in found:
        entry = fuzz_mod.run_case(spec)
        res = {
            "arm": "fuzz", "point": "w%d" % seed,
            "ok": bool(entry.get("ok")),
            "problems": [] if entry.get("ok") else [
                json.dumps(entry, default=str)[:2000]
            ],
        }
        print(json.dumps(res), flush=True)
        results.append(res)
    return results


# -------------------------------------------------------------------- main

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="storage-integrity disk-fault matrix"
    )
    parser.add_argument("--grid-only", action="store_true")
    parser.add_argument("--engine-only", action="store_true")
    parser.add_argument("--cases-only", action="store_true")
    parser.add_argument("--fuzz-only", action="store_true")
    parser.add_argument(
        "--point", default=None,
        help="run one grid point: config:seam:kind "
             "(e.g. classic:wal.commit:enospc)",
    )
    args = parser.parse_args(argv)

    selected = [args.grid_only, args.engine_only, args.cases_only,
                args.fuzz_only]
    run_all = not any(selected)

    results: List[dict] = []
    if run_all or args.grid_only or args.point:
        results.extend(run_grid(only_point=args.point))
    if args.point is None:
        if run_all or args.engine_only:
            results.extend(run_engine_grid())
        if run_all or args.cases_only:
            results.extend(run_cases())
        if run_all or args.fuzz_only:
            results.extend(run_fuzz_reachability())

    failures = [r for r in results if not r["ok"]]
    print(json.dumps({
        "disk_matrix_points": len(results),
        "disk_matrix_failures": len(failures),
        "failed": [r["point"] for r in failures],
    }), flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
