#!/usr/bin/env python
"""Store-path perf guard: churn ticks must stay cheap relative to steady
ticks, and the store component must not regress against a checked-in
floor.

Runs a small store-backed churn config (a scaled-down BASELINE config 5:
steady ticks, then churn ticks with finishes + fresh tasks) through the
REAL run_tick path — TickCache gather, batched solve, delta persister,
device-resident state plane — and fails if:

  * median churn tick > ``RATIO_MAX`` x median store-backed steady tick
    (the delta persister's whole job is keeping that ratio bounded), or
  * the churn STORE component (tick - snapshot - solve) regresses more
    than ``REGRESS_FRAC`` above the checked-in floor in
    ``tools/perf_floor.json``, or
  * the snapshot/solve/store overlap is no longer PROVEN: the pipelined
    resident cadence must beat the sequential one with efficiency ≥
    ``overlap_efficiency_min`` (``tools/perf_floor.json``). r05 shipped
    ``pipelined 61.7ms > sequential 59.1ms`` as a silent bench footnote —
    this guard makes that shape a hard failure, not an annotation.

The floor is wall-clock on whatever machine runs this, so it is set
generously (CI boxes vary ~5x) and the guard is marked ``slow`` —
excluded from tier-1 (`tests/test_perf_guard.py`). Refresh the floor
with ``python tools/perf_guard.py --write-floor`` on a quiet machine.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import statistics
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

FLOOR_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "perf_floor.json")

#: big enough that the steady tick carries real solve+store work — at
#: toy scale the steady tick is nearly free and ANY churn cost breaks a
#: ratio bound, which would test the config instead of the code
N_DISTROS = 100
N_TASKS = 20_000
STEADY_TICKS = 4
CHURN_TICKS = 4
RATIO_MAX = 2.0
REGRESS_FRAC = 0.25
#: tracing-on steady tick may cost at most this fraction over the
#: sampled-off arm (plus OVERHEAD_SLACK_MS of timer noise — best-of
#: estimators on a shared box still jitter by a fraction of a ms)
OVERHEAD_FRAC_MAX = 0.02
OVERHEAD_SLACK_MS = 0.5
#: on/off tick PAIRS in the tracing-overhead comparison; the estimator
#: is the median of per-pair deltas, so up to half the pairs can eat a
#: box spike without moving the verdict
OVERHEAD_PAIRS = 6
#: end-to-end WAL checksum arm (ISSUE 19): stamping every frame with a
#: CRC32 may cost at most this fraction over the unstamped durable tick
#: (plus OVERHEAD_SLACK_MS of timer noise) — integrity rides on the
#: serialize+flush it protects, it must never become a tax
CHECKSUM_FRAC_MAX = 0.03
#: journaled docs per measured durable tick — big enough that the group
#: frame carries real serialize+flush work for the stamp to hide behind
CHECKSUM_DOCS = 1500
CHECKSUM_PAIRS = 6
#: bench.py's proof bar: (pack + solve - pipelined) / min(pack, solve).
#: Overridable via perf_floor.json "overlap_efficiency_min"; a noisy box
#: gets up to two re-measures before the verdict (best-of).
OVERLAP_EFF_MIN = 0.5
#: read-serving plane (ISSUE 11) acceptance bounds: the fingerprint
#: ETag cache must answer >90% of an unchanged-queue scrape storm with
#: 304s, and the long-poll dispatch p99 at 10k parked agents must stay
#: inside 100ms (machine-independent — the woken cohort is bounded by
#: the arrival burst, not the fleet)
CACHE_HIT_RATE_MIN = 0.9
DISPATCH_P99_10K_MAX_MS = 100.0


def run_guard() -> dict:
    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.scheduler.persister import persister_state_for
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import (
        NOW,
        generate_problem,
        measure_resident_overlap,
    )
    from evergreen_tpu.utils.gctune import tune_gc_for_long_lived_heap

    distros, tbd, hbd, _, _ = generate_problem(
        N_DISTROS, N_TASKS, seed=3, task_group_fraction=0.25,
        patch_fraction=0.6, hosts_per_distro=5,
    )
    store = Store()
    for d in distros:
        distro_mod.insert(store, d)
    all_tasks = [t for ts in tbd.values() for t in ts]
    task_mod.insert_many(store, all_tasks)
    for hs in hbd.values():
        host_mod.insert_many(store, hs)

    opts = TickOptions(create_intent_hosts=False, use_cache=True,
                       underwater_unschedule=False)
    run_tick(store, opts, now=NOW)  # warm: compile + cache prime
    run_tick(store, opts, now=NOW + 0.01)
    tune_gc_for_long_lived_heap()

    steady = []
    for k in range(STEADY_TICKS):
        t1 = time.perf_counter()
        run_tick(store, opts, now=NOW + 0.1 * (k + 1))
        steady.append((time.perf_counter() - t1) * 1e3)

    # instrumentation-overhead arm (ISSUE 7): the SAME steady cadence
    # with the tracing plane sampled off vs on, in adjacent PAIRS with
    # the within-pair order alternating — running the arms back to back
    # would fold cache-warmup drift and box noise into whichever arm
    # went first (observed: ±50% either direction), and a fixed on-first
    # order would bias the deltas the same way. GC is quiesced for the
    # comparison: the guard measures what the tracing CODE costs, and a
    # gen2 pass over the 20k-task heap (tens of ms) landing in one arm
    # is the dominant flake source on a shared box. The verdict is the
    # median of per-pair deltas, so isolated spikes can't move it. The
    # gate asserts the tracing-on steady tick costs ≤ OVERHEAD_FRAC_MAX
    # over the off arm — whole-tick spans must stay a rounding error,
    # not a tax.
    import gc

    from evergreen_tpu.utils.tracing import set_tracing_enabled

    def measure_overhead(t_base: float):
        prev_tracing = set_tracing_enabled(True)
        gc_was_enabled = gc.isenabled()
        gc.collect()
        gc.disable()
        try:
            on_ms, off_ms, ds = [], [], []
            for pair in range(OVERHEAD_PAIRS):
                order = (True, False) if pair % 2 == 0 else (False, True)
                times = {}
                for slot, on in enumerate(order):
                    set_tracing_enabled(on)
                    t1 = time.perf_counter()
                    run_tick(
                        store, opts,
                        now=t_base + 0.02 * (2 * pair + slot + 1),
                    )
                    times[on] = (time.perf_counter() - t1) * 1e3
                on_ms.append(times[True])
                off_ms.append(times[False])
                ds.append(times[True] - times[False])
            return statistics.median(ds), on_ms, off_ms
        finally:
            set_tracing_enabled(prev_tracing)
            if gc_was_enabled:
                gc.enable()

    overhead_ms, steady_on, steady_off = measure_overhead(NOW + 0.45)
    # one re-measure before the verdict (the overlap arm's pattern): a
    # multi-second background load episode on a shared box can cover a
    # majority of the pairs and shove the MEDIAN delta tens of ms either
    # way; a true systematic overhead fails both measurements
    if overhead_ms > min(steady_off) * OVERHEAD_FRAC_MAX + OVERHEAD_SLACK_MS:
        o2, on2, off2 = measure_overhead(NOW + 0.7)
        if o2 < overhead_ms:
            overhead_ms, steady_on, steady_off = o2, on2, off2

    rng = random.Random(0)
    coll = task_mod.coll(store)
    pstate = persister_state_for(store)
    pstate.skipped = pstate.patched = pstate.rewritten = 0
    churn, snap_ms, solve_ms = [], [], []
    for tick in range(CHURN_TICKS):
        for t in rng.sample(all_tasks, 100):
            coll.update(t.id, {"status": TaskStatus.SUCCEEDED.value})
        fresh = [
            dataclasses.replace(
                rng.choice(all_tasks), id=f"churn-{tick}-{j}",
                depends_on=[],
            )
            for j in range(50)
        ]
        task_mod.insert_many(store, fresh)
        t1 = time.perf_counter()
        res = run_tick(store, opts, now=NOW + tick + 1)
        churn.append((time.perf_counter() - t1) * 1e3)
        snap_ms.append(res.snapshot_ms)
        solve_ms.append(res.solve_ms)

    # overlap invariant: the steady resident cadence, sequenced vs
    # pipelined, on the store the churn just exercised (the plane is
    # primed and carrying real holes). Box noise gets up to two re-measures —
    # the guard must catch the r05 regression shape, not a cron spike.
    ov = measure_resident_overlap(store, ticks=5, warmup=2)
    for _retry in range(2):
        if ov["overlap_efficiency"] >= OVERLAP_EFF_MIN:
            break
        ov2 = measure_resident_overlap(store, ticks=5, warmup=1)
        if ov2["overlap_efficiency"] > ov["overlap_efficiency"]:
            ov = ov2

    # best-of for ABSOLUTE costs: the guard measures what the CODE
    # costs, and a shared CI box's background spikes land in the slow
    # ticks — min over several ticks is the stable estimator against the
    # machine-relative floor. The churn:steady RATIO compares two
    # distributions, and best-of-each is fragile there — one lucky
    # steady tick (observed: best 97ms vs median 232ms in one run)
    # inflates the ratio past the bound with zero code change — so the
    # ratio is median:median, the typical-tick shape the bound is about.
    churn_best = min(churn)
    steady_best = min(steady)
    steady_off_best = min(steady_off)
    churn_med = statistics.median(churn)
    steady_med = statistics.median(steady)
    store_best = min(
        c - sn - so for c, sn, so in zip(churn, snap_ms, solve_ms)
    )
    shard = run_sharded_guard(distros, tbd, hbd)
    fused = run_fused_guard()
    checksum = run_checksum_guard()
    # read-serving plane (ISSUE 11): replica lag, the fingerprint-ETag
    # 304 hit-rate, and the long-poll dispatch soaks at 1k/10k agents —
    # the SAME measurement bench.py publishes (tools/read_parity.py)
    from tools.read_parity import measure_read_path

    read_path = measure_read_path()
    return {
        **shard,
        **fused,
        **checksum,
        "read_path": read_path,
        "steady_tick_notrace_ms": round(steady_off_best, 2),
        "steady_tick_trace_ms": round(min(steady_on), 2),
        "instrumentation_overhead_ms": round(overhead_ms, 2),
        "instrumentation_overhead_frac": round(
            overhead_ms / max(steady_off_best, 1e-9), 4
        ),
        "overlap_efficiency": round(ov["overlap_efficiency"], 3),
        "resident_pack_ms": round(ov["pack_ms"], 2),
        "resident_sequential_ms": round(ov["sequential_ms"], 2),
        "resident_pipelined_ms": round(ov["pipelined_ms"], 2),
        "steady_tick_ms": round(steady_best, 2),
        "churn_tick_ms": round(churn_best, 2),
        "churn_store_ms": round(max(store_best, 0.0), 2),
        "steady_tick_median_ms": round(steady_med, 2),
        "churn_tick_median_ms": round(churn_med, 2),
        "ratio": round(churn_med / max(steady_med, 1e-9), 3),
        "persist_skipped": pstate.skipped,
        "persist_patched": pstate.patched,
        "persist_rewritten": pstate.rewritten,
    }


#: shards in the per-shard guard arm (the floor is per SHARD, so a
#: shard regression cannot hide inside an improved aggregate)
GUARD_SHARDS = 2
SHARD_CHURN_TICKS = 3


def run_sharded_guard(distros, tbd, hbd) -> dict:
    """Per-shard floor numbers: the SAME problem partitioned across
    GUARD_SHARDS by the production topology, each shard's churn ticks
    measured ALONE (sequentially — the floor is per-shard cost, not
    round wall), plus the overlap invariant proven per shard: every
    shard's pipelined resident cadence must beat its sequential one."""
    import dataclasses

    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.scheduler.sharded_plane import ShardedScheduler
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import (
        NOW,
        measure_resident_overlap,
    )

    source = Store()
    for d in distros:
        distro_mod.insert(source, d)
    task_mod.insert_many(source, [t for ts in tbd.values() for t in ts])
    for hs in hbd.values():
        host_mod.insert_many(source, hs)
    plane = ShardedScheduler.build(
        GUARD_SHARDS, rebalance_enabled=False, stacked="never"
    )
    try:
        plane.seed_partition(source)
        opts = TickOptions(create_intent_hosts=False, use_cache=True,
                           underwater_unschedule=False)
        rng = random.Random(7)
        medians, overlap_effs = [], []
        for k, store in enumerate(plane.stores):
            my_tasks = [
                t for ts in tbd.values() for t in ts
                if plane.owner_of(t.distro_id) == k
            ]
            coll = task_mod.coll(store)
            run_tick(store, opts, now=NOW)  # compile + prime
            run_tick(store, opts, now=NOW + 0.01)
            times = []
            for tick in range(SHARD_CHURN_TICKS):
                for t in rng.sample(my_tasks, 50):
                    coll.update(
                        t.id, {"status": TaskStatus.SUCCEEDED.value}
                    )
                fresh = [
                    dataclasses.replace(
                        rng.choice(my_tasks),
                        id=f"sguard-{k}-{tick}-{j}", depends_on=[],
                    )
                    for j in range(25)
                ]
                task_mod.insert_many(store, fresh)
                t1 = time.perf_counter()
                run_tick(store, opts, now=NOW + tick + 1)
                times.append((time.perf_counter() - t1) * 1e3)
            medians.append(round(statistics.median(times), 2))
            ov = measure_resident_overlap(store, ticks=4, warmup=1)
            for _retry in range(2):
                if ov["overlap_efficiency"] >= OVERLAP_EFF_MIN:
                    break
                ov2 = measure_resident_overlap(store, ticks=4, warmup=1)
                if ov2["overlap_efficiency"] > ov["overlap_efficiency"]:
                    ov = ov2
            overlap_effs.append(round(ov["overlap_efficiency"], 3))
        return {
            "shard_churn_ms": medians,
            "shard_churn_max_ms": max(medians),
            "shard_overlap_efficiency": overlap_effs,
        }
    finally:
        plane.close()


#: fused-capacity arm (ISSUE 18): measured capacity ticks per mode, and
#: the paired-slack bound — the fused tick replaces a two-call tick on
#: the same box in the same run, so it may cost at most this fraction
#: more (pure timer noise headroom; the whole point is that it saves a
#: device round trip, which CPU wall-clock undersells)
FUSED_TICKS = 4
FUSED_SLACK_FRAC = 0.20


def run_fused_guard() -> dict:
    """Fused-capacity arm (ISSUE 18): identical capacity-enabled fleets
    ticked with the capacity targets served from the packed solve
    (``fused="auto"``) vs the two-call rung (``fused="two_call"`` — the
    SAME device program, answered by the dedicated second capacity
    call). The guard pins BOTH halves of the claim: the fused tick
    actually skips the second device call
    (``scheduler_capacity_solves_total`` flat while the fused counter
    advances every tick), and it does not cost more wall-clock than the
    two-call tick it replaces — the saved call is the whole delta."""
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.scheduler import capacity_plane as cp
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.settings import CapacityConfig
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    opts = TickOptions(use_cache=True, underwater_unschedule=False)

    def measure(knob: str) -> dict:
        distros, tbd, hbd, _, _ = generate_problem(
            40, 6000, seed=5, task_group_fraction=0.3,
            hosts_per_distro=4,
        )
        store = Store()
        for d in distros:
            d.planner_settings.capacity = "tpu"
            distro_mod.insert(store, d)
        task_mod.insert_many(
            store, [t for ts in tbd.values() for t in ts]
        )
        for hs in hbd.values():
            host_mod.insert_many(store, hs)
        CapacityConfig(
            pool_quotas={"mock": 300}, fleet_intent_budget=120,
            fused=knob,
        ).set(store)
        run_tick(store, opts, now=NOW)  # warm: compile + cache prime
        run_tick(store, opts, now=NOW + 0.01)
        cap0 = cp.CAPACITY_SOLVES.total()
        fused0 = cp.FUSED_SOLVES.value(mode="fused")
        times = []
        for k in range(FUSED_TICKS):
            t1 = time.perf_counter()
            run_tick(store, opts, now=NOW + 15.0 * (k + 1))
            times.append((time.perf_counter() - t1) * 1e3)
        return {
            "tick_ms": round(min(times), 2),
            "capacity_solves_delta": cp.CAPACITY_SOLVES.total() - cap0,
            "fused_delta": cp.FUSED_SOLVES.value(mode="fused") - fused0,
        }

    two = measure("two_call")
    fus = measure("auto")
    if fus["tick_ms"] > two["tick_ms"] * (1.0 + FUSED_SLACK_FRAC):
        # one paired re-measure before the verdict: a shared box's
        # background spike landing in the fused arm is the flake source
        two2, fus2 = measure("two_call"), measure("auto")
        if fus2["tick_ms"] / max(two2["tick_ms"], 1e-9) < (
            fus["tick_ms"] / max(two["tick_ms"], 1e-9)
        ):
            two, fus = two2, fus2
    return {
        "fused_tick_ms": fus["tick_ms"],
        "two_call_tick_ms": two["tick_ms"],
        "fused_capacity_solves_delta": fus["capacity_solves_delta"],
        "fused_served_ticks": fus["fused_delta"],
        "two_call_capacity_solves_delta": two["capacity_solves_delta"],
    }


def run_checksum_guard() -> dict:
    """WAL end-to-end checksum overhead (ISSUE 19): the SAME durable
    steady tick — one per-op append plus a CHECKSUM_DOCS group frame —
    with line stamping on vs off, in adjacent pairs with the
    within-pair order alternating (the instrumentation arm's pattern)
    and GC quiesced. The verdict is the median of per-pair deltas, with
    one re-measure before failing on a shared-box spike."""
    import gc
    import shutil
    import tempfile

    from evergreen_tpu.storage import integrity
    from evergreen_tpu.storage.durable import DurableStore

    data_dir = tempfile.mkdtemp(prefix="perfguard-crc-")
    store = DurableStore(data_dir)
    payload = "x" * 160
    tick_no = [0]

    def one_tick() -> float:
        tick_no[0] += 1
        t1 = time.perf_counter()
        store.collection("oplog").upsert(
            {"_id": "op-%d" % tick_no[0], "t": tick_no[0]}
        )
        store.begin_tick()
        jobs = store.collection("jobs")
        for j in range(CHECKSUM_DOCS):
            jobs.upsert(
                {"_id": "job-%d" % j, "tick": tick_no[0], "p": payload}
            )
        store.end_tick()
        return (time.perf_counter() - t1) * 1e3

    def measure():
        prev = integrity.set_wal_crc_enabled(True)
        gc_was_enabled = gc.isenabled()
        gc.collect()
        gc.disable()
        try:
            on_ms, off_ms, ds = [], [], []
            one_tick()  # warm: handles, dict shapes, page cache
            for pair in range(CHECKSUM_PAIRS):
                order = (True, False) if pair % 2 == 0 else (False, True)
                times = {}
                for on in order:
                    integrity.set_wal_crc_enabled(on)
                    times[on] = one_tick()
                on_ms.append(times[True])
                off_ms.append(times[False])
                ds.append(times[True] - times[False])
            return statistics.median(ds), on_ms, off_ms
        finally:
            integrity.set_wal_crc_enabled(prev)
            if gc_was_enabled:
                gc.enable()

    try:
        overhead, on_ms, off_ms = measure()
        if overhead > min(off_ms) * CHECKSUM_FRAC_MAX + OVERHEAD_SLACK_MS:
            o2, on2, off2 = measure()
            if o2 < overhead:
                overhead, on_ms, off_ms = o2, on2, off2
    finally:
        store.close()
        shutil.rmtree(data_dir, ignore_errors=True)
    base = min(off_ms)
    return {
        "wal_stamped_tick_ms": round(min(on_ms), 2),
        "wal_unstamped_tick_ms": round(base, 2),
        "checksum_overhead_ms": round(overhead, 2),
        "checksum_overhead_frac": round(overhead / max(base, 1e-9), 4),
    }


def evaluate(result: dict, floor: dict) -> list:
    """Returns a list of failure strings (empty = pass)."""
    failures = []
    if result["ratio"] > RATIO_MAX:
        failures.append(
            f"median churn tick {result['churn_tick_median_ms']}ms > "
            f"{RATIO_MAX}x median steady tick "
            f"{result['steady_tick_median_ms']}ms "
            f"(ratio {result['ratio']})"
        )
    floor_ms = floor.get("churn_store_ms")
    if floor_ms is not None:
        limit = floor_ms * (1.0 + REGRESS_FRAC)
        if result["churn_store_ms"] > limit:
            failures.append(
                f"churn store component {result['churn_store_ms']}ms "
                f"regressed >{int(REGRESS_FRAC * 100)}% over the "
                f"checked-in floor {floor_ms}ms (limit {limit:.1f}ms)"
            )
    overhead = result.get("instrumentation_overhead_ms")
    if overhead is not None:
        base = result.get("steady_tick_notrace_ms", 0.0)
        limit = base * OVERHEAD_FRAC_MAX + OVERHEAD_SLACK_MS
        if overhead > limit:
            failures.append(
                f"instrumentation overhead {overhead}ms over the "
                f"sampled-off steady tick {base}ms exceeds "
                f"{OVERHEAD_FRAC_MAX:.0%} (+{OVERHEAD_SLACK_MS}ms slack; "
                f"limit {limit:.2f}ms) — whole-tick tracing must stay "
                "a rounding error"
            )
    checksum = result.get("checksum_overhead_ms")
    if checksum is not None:
        base = result.get("wal_unstamped_tick_ms", 0.0)
        limit = base * CHECKSUM_FRAC_MAX + OVERHEAD_SLACK_MS
        if checksum > limit:
            failures.append(
                f"WAL checksum overhead {checksum}ms over the unstamped "
                f"durable tick {base}ms exceeds {CHECKSUM_FRAC_MAX:.0%} "
                f"(+{OVERHEAD_SLACK_MS}ms slack; limit {limit:.2f}ms) — "
                "end-to-end integrity must ride the flush it protects, "
                "not tax it"
            )
        floor_crc = floor.get("wal_stamped_tick_ms")
        if floor_crc is not None and result["wal_stamped_tick_ms"] > (
            floor_crc * (1.0 + REGRESS_FRAC)
        ):
            failures.append(
                f"stamped durable tick {result['wal_stamped_tick_ms']}ms "
                f"regressed >{int(REGRESS_FRAC * 100)}% over the "
                f"checked-in floor {floor_crc}ms"
            )
    eff_min = floor.get("overlap_efficiency_min", OVERLAP_EFF_MIN)
    if result.get("overlap_efficiency") is not None and (
        result["overlap_efficiency"] < eff_min
    ):
        failures.append(
            f"overlap NOT proven: efficiency "
            f"{result['overlap_efficiency']} < {eff_min} (pipelined "
            f"{result['resident_pipelined_ms']}ms vs sequential "
            f"{result['resident_sequential_ms']}ms) — the pipelined "
            f"resident cadence must hide pack behind the in-flight solve"
        )
    # per-SHARD floor (sharded control plane): the bound applies to the
    # WORST shard, so one regressed shard cannot hide inside an improved
    # fleet aggregate
    shard_floor = floor.get("shard_churn_ms")
    if shard_floor is not None and "shard_churn_max_ms" in result:
        limit = shard_floor * (1.0 + REGRESS_FRAC)
        if result["shard_churn_max_ms"] > limit:
            failures.append(
                f"worst shard churn tick {result['shard_churn_max_ms']}"
                f"ms (per-shard {result['shard_churn_ms']}) regressed "
                f">{int(REGRESS_FRAC * 100)}% over the per-shard floor "
                f"{shard_floor}ms (limit {limit:.1f}ms)"
            )
    # overlap stays proven PER SHARD, not just on the single plane
    for k, eff in enumerate(result.get("shard_overlap_efficiency", [])):
        if eff < eff_min:
            failures.append(
                f"shard {k} overlap NOT proven: efficiency {eff} < "
                f"{eff_min} — each shard's resident cadence must hide "
                "pack behind its in-flight solve"
            )
    # fused capacity (ISSUE 18): the fused rung must SAVE the second
    # device call — counter-asserted, machine-independent — and the
    # fused tick must not cost more than the two-call tick it replaces
    if result.get("fused_tick_ms") is not None:
        if result.get("fused_capacity_solves_delta", 1) != 0:
            failures.append(
                "fused ticks still paid "
                f"{result['fused_capacity_solves_delta']} dedicated "
                "capacity device calls — scheduler_capacity_solves_total "
                "must stay flat while the fused rung serves"
            )
        if result.get("fused_served_ticks", 0) < FUSED_TICKS:
            failures.append(
                f"only {result.get('fused_served_ticks', 0)}/"
                f"{FUSED_TICKS} measured ticks were served by the fused "
                "rung — the arm measured a fallback, not the fused path"
            )
        limit = result["two_call_tick_ms"] * (1.0 + FUSED_SLACK_FRAC)
        if result["fused_tick_ms"] > limit:
            failures.append(
                f"fused capacity tick {result['fused_tick_ms']}ms > "
                f"two-call tick {result['two_call_tick_ms']}ms "
                f"+{FUSED_SLACK_FRAC:.0%} slack (limit {limit:.1f}ms) — "
                "fusing the capacity solve must not cost wall-clock"
            )
        floor_fused = floor.get("fused_tick_ms")
        if floor_fused is not None and result["fused_tick_ms"] > (
            floor_fused * (1.0 + REGRESS_FRAC)
        ):
            failures.append(
                f"fused capacity tick {result['fused_tick_ms']}ms "
                f"regressed >{int(REGRESS_FRAC * 100)}% over the "
                f"checked-in floor {floor_fused}ms"
            )
    # read-serving plane (ISSUE 11): the 304 hit-rate and the 10k-agent
    # dispatch p99 are machine-independent acceptance bounds; the
    # 1k-agent p99 additionally holds a machine-relative floor so a
    # slow regression is caught before it reaches the hard bound
    rp = result.get("read_path")
    if rp is not None:
        hit = rp.get("hit_rate_304")
        if hit is not None and hit <= CACHE_HIT_RATE_MIN:
            failures.append(
                f"fingerprint-ETag 304 hit-rate {hit} <= "
                f"{CACHE_HIT_RATE_MIN} on an unchanged-queue scrape "
                "storm — the read cache is not answering revalidations"
            )
        p99_10k = rp.get("dispatch_p99_10k_ms")
        if p99_10k is not None and p99_10k > DISPATCH_P99_10K_MAX_MS:
            failures.append(
                f"dispatch p99 {p99_10k}ms at 10k parked agents exceeds "
                f"the {DISPATCH_P99_10K_MAX_MS}ms budget — the sharded "
                "long-poll wake path is convoying"
            )
        dupes = rp.get("dispatch_duplicates")
        if dupes:
            failures.append(
                f"long-poll soak handed {dupes} tasks out twice"
            )
        floor_p99 = floor.get("dispatch_p99_ms")
        p99_1k = rp.get("dispatch_p99_1k_ms")
        if floor_p99 is not None and p99_1k is not None:
            limit = floor_p99 * (1.0 + REGRESS_FRAC)
            if p99_1k > limit:
                failures.append(
                    f"dispatch p99 {p99_1k}ms at 1k agents regressed "
                    f">{int(REGRESS_FRAC * 100)}% over the checked-in "
                    f"floor {floor_p99}ms (limit {limit:.1f}ms)"
                )
    return failures


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--write-floor", action="store_true",
                   help="record this run's store component as the floor")
    args = p.parse_args()
    result = run_guard()
    if args.write_floor:
        # refresh the machine-relative floor; the overlap bar is a
        # machine-independent invariant and stays as configured
        prev = {}
        if os.path.exists(FLOOR_PATH):
            with open(FLOOR_PATH, encoding="utf-8") as fh:
                prev = json.load(fh)
        prev["churn_store_ms"] = result["churn_store_ms"]
        prev["shard_churn_ms"] = result["shard_churn_max_ms"]
        if result.get("fused_tick_ms") is not None:
            prev["fused_tick_ms"] = result["fused_tick_ms"]
        if result.get("wal_stamped_tick_ms") is not None:
            prev["wal_stamped_tick_ms"] = result["wal_stamped_tick_ms"]
        p99_1k = result.get("read_path", {}).get("dispatch_p99_1k_ms")
        if p99_1k is not None:
            prev["dispatch_p99_ms"] = p99_1k
        prev.setdefault("overlap_efficiency_min", OVERLAP_EFF_MIN)
        with open(FLOOR_PATH, "w", encoding="utf-8") as fh:
            json.dump(prev, fh, indent=2)
            fh.write("\n")
        print(json.dumps({"wrote_floor": result}))
        return 0
    floor = {}
    if os.path.exists(FLOOR_PATH):
        with open(FLOOR_PATH, encoding="utf-8") as fh:
            floor = json.load(fh)
    failures = evaluate(result, floor)
    print(json.dumps({"perf_guard": result, "floor": floor,
                      "failures": failures}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
