#!/usr/bin/env python
"""Solver-leader round bench: ONE stacked solve serving a process fleet.

The ``solver_leader_round_ms`` arm (ISSUE 17, toward ROADMAP item 5):
a real 2-shard supervised fleet (``runtime/supervisor.py``, worker
processes, shared-memory arena publication) driven for N rounds with
the solver-leader plane elected (``solver="auto"``) and again with it
disabled (``solver="never"``, every worker solves locally) — same
workload, same sockets, same WAL traffic, so the delta is the
stacked-vs-local solve path itself plus the shm publish/return hops.

    python tools/bench_solver_leader.py [--shards 2] [--rounds 5]
        [--backend cpu|gpu]

``--backend gpu`` is the non-tunnel escape hatch (bench.py routes here
automatically when the TPU-probe taxonomy says the axon tunnel can
never come up on this box: ``cpu-pinned`` / ``no-pool-ips``). Prints
one JSON line; per-round tables go to stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

#: deterministic workload clock (the proc harness anchor)
TICK_S = 15.0
#: first rounds pay XLA compile plus the shape-drift convergence ladder
#: (round 1 declines to establish the common-dims floor) — unmeasured
WARMUP_ROUNDS = 2


def _run_fleet(args, solver_mode: str):
    """One fleet lifetime: seed, boot, N timed rounds, teardown.
    Returns (per-round wall ms, per-round sorted solve outcomes)."""
    from evergreen_tpu.runtime.supervisor import FleetSupervisor
    from evergreen_tpu.scenarios.procs import _seed_fleet
    from evergreen_tpu.utils.benchgen import NOW

    data_dir = tempfile.mkdtemp(prefix=f"bench-solver-{solver_mode}-")
    sup = FleetSupervisor(
        data_dir, args.shards, ttl_s=5.0, hb_interval_s=0.5,
        round_timeout_s=300.0, harness=True, recovery_anchor=NOW,
        worker_stderr="devnull", supervisor_lease_ttl_s=5.0,
        solver=solver_mode, solver_timeout_s=60.0,
    )
    try:
        _seed_fleet(data_dir, args.shards, {
            "distros": args.distros, "tasks": args.tasks, "seed": 3,
            "hosts_per_distro": 4,
        })
        sup.start()
        round_ms, outcomes = [], []
        for i in range(WARMUP_ROUNDS + args.rounds):
            now = NOW + (i + 1) * TICK_S
            t0 = time.perf_counter()
            replies = sup.round(now=now)
            dt = (time.perf_counter() - t0) * 1e3
            solves = sorted(
                r.get("solve", "") for r in replies.values()
            )
            # no agent sim: the queues never drain, every round
            # re-solves the same full problem — a stable measurand
            if i >= WARMUP_ROUNDS:
                round_ms.append(dt)
                outcomes.append(solves)
        return round_ms, outcomes
    finally:
        sup.stop(graceful=True)
        shutil.rmtree(data_dir, ignore_errors=True)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--distros", type=int, default=8)
    p.add_argument("--tasks", type=int, default=240)
    p.add_argument("--backend", default="cpu", choices=("cpu", "gpu"))
    args = p.parse_args()

    if args.backend == "gpu":
        # non-tunnel accelerator: the leader's stacked shard_map solve
        # runs on CUDA devices in this process; workers stay on CPU
        os.environ["JAX_PLATFORMS"] = "cuda"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
    else:
        from evergreen_tpu.utils.jaxenv import force_cpu

        force_cpu(n_devices=args.shards)

    stacked_ms, stacked_out = _run_fleet(args, "auto")
    local_ms, _ = _run_fleet(args, "never")

    stacked_rounds = sum(
        1 for o in stacked_out if o and all(s == "stacked" for s in o)
    )
    for i, (ms, o) in enumerate(zip(stacked_ms, stacked_out)):
        print(f"# round {i}: {ms:.1f}ms {o}", file=sys.stderr)
    value = statistics.median(stacked_ms)
    local = statistics.median(local_ms)
    print(json.dumps({
        "metric": "solver_leader_round_ms",
        "value": round(value, 2),
        "unit": "ms",
        "backend": args.backend,
        "n_shards": args.shards,
        "rounds": args.rounds,
        "stacked_rounds": stacked_rounds,
        "local_round_ms": round(local, 2),
        # >1 means one fleet-wide stacked solve beat N local solves on
        # this box; on shared CPU cores the shm+sync overhead can eat
        # the win — the deployment case is a device mesh the workers
        # don't have
        "vs_local": round(local / value, 2) if value else 0.0,
    }))
    # a bench fleet that never stacked measured nothing — fail loudly
    # instead of recording a local-solve number under the stacked name
    return 0 if stacked_rounds >= max(1, args.rounds - 1) else 1


if __name__ == "__main__":
    sys.exit(main())
