#!/usr/bin/env python
"""Capacity-parity gate (ISSUE 9): the joint capacity program must be

  1. **always feasible** — rounded targets satisfy min/max hosts, pool
     quotas and the fleet intent budget on randomized problems;
  2. **matches-or-beats** — on the bench-shaped 200-distro workload the
     solver's time-to-empty never regresses the serial utilization
     heuristic's (the adoption guard makes this structural; this gate
     pins the guard);
  3. **a real trader** — the two-distro shared-quota scenario from the
     ROADMAP: the per-distro heuristic over-asks past the pool quota
     (it cannot see the coupling), the joint solve fills the quota
     exactly and gives the deep queue the larger share;
  4. **safe to lose** — a fault-injected capacity solve falls the tick
     back to BIT-IDENTICAL per-distro heuristic behavior, and repeated
     failures open the breaker;
  5. **fused ≡ two-call** (PR 18) — the capacity program fused into the
     packed planning solve produces IDENTICAL integral targets and
     rounded allocations as the separate two-call device program at the
     same padded shape (the relaxations agree to float ulps: the
     instances are bit-identical — one Newton step matches exactly —
     but XLA fuses the iterated loop body differently inside the
     larger program); a fused-rung sabotage falls the tick to the
     two-call rung with bit-identical spawn counts; and fused ticks
     never move ``scheduler_capacity_solves_total`` (the saved device
     call, asserted via the counter staying flat).

Wired as ``make capacity-parity`` and ``tools/gate.py
--capacity-parity``. Exits non-zero on any failure; prints one JSON
summary line on stdout.
"""
from __future__ import annotations

import json
import os
import random
import statistics
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

FAILURES: list = []


def check(ok: bool, msg: str) -> None:
    tag = "ok" if ok else "FAIL"
    print(f"capacity-parity: [{tag}] {msg}", file=sys.stderr)
    if not ok:
        FAILURES.append(msg)


# --------------------------------------------------------------------------- #
# 1. feasibility fuzz
# --------------------------------------------------------------------------- #


def random_inputs(seed: int):
    from evergreen_tpu.ops import capacity as cap

    rng = random.Random(seed)
    n = rng.randint(3, 40)
    pools = [cap.pool_index_of(p) for p in ("mock", "docker", "ec2-fleet")]
    demand = np.array([rng.uniform(0, 80_000) for _ in range(n)])
    existing = np.array([float(rng.randint(0, 12)) for _ in range(n)])
    min_h = np.array([float(rng.randint(0, 3)) for _ in range(n)])
    max_h = np.array([float(rng.randint(1, 30)) for _ in range(n)])
    deps = np.array([float(rng.randint(0, 60)) for _ in range(n)])
    free = np.array(
        [float(rng.randint(0, int(e))) if e else 0.0 for e in existing]
    )
    heur = np.array([float(rng.randint(0, 10)) for _ in range(n)])
    quota = np.zeros(cap.P_BUCKET)
    for p in pools:
        if rng.random() < 0.7:
            quota[p] = float(rng.randint(2, 40))
    price = np.zeros(cap.P_BUCKET)
    for p in pools:
        price[p] = rng.uniform(0, 1.0)
    return cap.CapacityInputs(
        distro_ids=[f"d{i}" for i in range(n)],
        demand_s=demand,
        thresh_s=np.full(n, 1800.0),
        existing=existing,
        free=free,
        min_hosts=min_h,
        max_hosts=max_h,
        deps_met=deps,
        pool=np.array([rng.choice(pools) for _ in range(n)], np.int32),
        elig=np.array([rng.random() < 0.9 for _ in range(n)]),
        heuristic_new=heur,
        price=price,
        quota=quota,
        fleet_budget=float(rng.randint(1, 60)),
    )


def run_fuzz(seeds: int = 8) -> None:
    from evergreen_tpu.ops import capacity as cap

    for seed in range(seeds):
        inp = random_inputs(seed)
        targets, x, chosen = cap.solve_capacity(inp)
        problems = cap.check_feasible(targets, inp)
        check(
            not problems,
            f"fuzz seed {seed}: feasible (n={inp.n}, chosen={chosen})"
            + (f" — {problems[:2]}" if problems else ""),
        )
        # matches-or-beats: whenever the heuristic allocation is itself
        # feasible, the adopted allocation's drain must not regress it
        heur = cap.heuristic_allocation(inp)
        if not cap.check_feasible(heur, inp):
            s_total, _ = cap.drain_seconds(targets, inp)
            h_total, _ = cap.drain_seconds(heur, inp)
            check(
                s_total <= h_total + 1e-6,
                f"fuzz seed {seed}: drain {s_total:.0f}s <= "
                f"heuristic {h_total:.0f}s",
            )


# --------------------------------------------------------------------------- #
# 2. bench workload: matches-or-beats the serial oracle
# --------------------------------------------------------------------------- #


def run_bench_workload() -> dict:
    from evergreen_tpu.ops import capacity as cap
    from evergreen_tpu.scheduler import serial
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    distros, tasks_by_distro, hosts_by_distro, estimates, deps_met = (
        generate_problem(200, 20_000, seed=3, hosts_per_distro=10)
    )
    n = len(distros)
    demand = np.zeros(n)
    deps = np.zeros(n)
    existing = np.zeros(n)
    free = np.zeros(n)
    min_h = np.zeros(n)
    max_h = np.zeros(n)
    heur = np.zeros(n)
    t_solve = []
    for i, d in enumerate(distros):
        plan, _ = serial.plan_distro_queue(
            d, tasks_by_distro.get(d.id, []), NOW
        )
        info, n_new = serial.queue_info_and_new_hosts(
            d, plan, deps_met, hosts_by_distro.get(d.id, []),
            estimates, NOW,
        )
        hosts = hosts_by_distro.get(d.id, [])
        demand[i] = info.expected_duration_s
        deps[i] = info.length_with_dependencies_met
        existing[i] = len(hosts)
        free[i] = sum(1 for h in hosts if h.is_free())
        min_h[i] = d.host_allocator_settings.minimum_hosts
        max_h[i] = d.host_allocator_settings.maximum_hosts
        heur[i] = n_new
    inp = cap.CapacityInputs(
        distro_ids=[d.id for d in distros],
        demand_s=demand,
        thresh_s=np.array(
            [d.planner_settings.max_duration_per_host_s() for d in distros]
        ),
        existing=existing,
        free=free,
        min_hosts=min_h,
        max_hosts=np.where(max_h > 0, max_h, 100.0),
        deps_met=deps,
        pool=np.array(
            [cap.pool_index_of(d.provider) for d in distros], np.int32
        ),
        elig=np.ones(n, bool),
        heuristic_new=heur,
        price=np.zeros(cap.P_BUCKET),
        quota=np.zeros(cap.P_BUCKET),
        fleet_budget=5000.0,
    )
    # warm the compile, then measure the solve alone
    cap.solve_capacity(inp)
    for _ in range(5):
        t0 = time.perf_counter()
        targets, x, chosen = cap.solve_capacity(inp)
        t_solve.append((time.perf_counter() - t0) * 1e3)
    problems = cap.check_feasible(targets, inp)
    heur_alloc = cap.heuristic_allocation(inp)
    s_total, s_worst = cap.drain_seconds(targets, inp)
    h_total, h_worst = cap.drain_seconds(heur_alloc, inp)
    check(not problems, f"bench workload: feasible {problems[:2]}")
    if not cap.check_feasible(heur_alloc, inp):
        check(
            s_total <= h_total + 1e-6,
            f"bench workload: drain {s_total:.0f}s <= heuristic "
            f"{h_total:.0f}s (worst {s_worst:.0f}s vs {h_worst:.0f}s)",
        )
        c_total = h_total
    else:
        # the raw per-distro asks violate a coupled cap (exactly the
        # blindness the joint solve fixes): the honest baseline is the
        # heuristic CLAMPED to the same budget — naive proportional
        # scale-down of every increment — which the solver must still
        # match or beat (tolerance 1%: both are integral roundings)
        inc = heur_alloc - inp.existing
        scale = min(1.0, inp.effective_budget() / max(inc.sum(), 1.0))
        clamped = np.floor(inp.existing + inc * scale).astype(np.int64)
        c_total, c_worst = cap.drain_seconds(clamped, inp)
        check(
            s_total <= c_total * 1.01 + 1e-6,
            f"bench workload: drain {s_total:.0f}s <= clamped "
            f"heuristic {c_total:.0f}s (raw heuristic over-asks: "
            f"{inc.sum():.0f} new > budget {inp.effective_budget():.0f})",
        )
    return {
        "capacity_solve_ms": round(statistics.median(t_solve), 2),
        "drain_solver_s": round(s_total, 1),
        "drain_heuristic_s": round(h_total, 1),
        "drain_baseline_s": round(c_total, 1),
        "chosen": chosen,
        "n_distros": n,
    }


# --------------------------------------------------------------------------- #
# 3. two-distro capacity trading
# --------------------------------------------------------------------------- #


def run_trading() -> dict:
    from evergreen_tpu.ops import capacity as cap

    pool = cap.pool_index_of("mock")
    quota = np.zeros(cap.P_BUCKET)
    quota[pool] = 10.0
    inp = cap.CapacityInputs(
        distro_ids=["deep", "shallow"],
        demand_s=np.array([30_000.0, 1_800.0]),
        thresh_s=np.full(2, 1800.0),
        existing=np.array([2.0, 2.0]),
        free=np.zeros(2),
        min_hosts=np.ones(2),
        max_hosts=np.full(2, 20.0),
        deps_met=np.array([40.0, 10.0]),
        pool=np.full(2, pool, np.int32),
        elig=np.ones(2, bool),
        heuristic_new=np.array([14.0, 6.0]),
        price=np.zeros(cap.P_BUCKET),
        quota=quota,
        fleet_budget=100.0,
    )
    targets, x, chosen = cap.solve_capacity(inp)
    heur = cap.heuristic_allocation(inp)
    heur_problems = cap.check_feasible(heur, inp)
    use = float(targets.sum())
    check(
        bool(heur_problems),
        "trading: per-distro heuristic over-asks the shared quota "
        f"({heur.sum():.0f} > 10) — the coupling it cannot see",
    )
    check(chosen == "solver", f"trading: solver adopted ({chosen})")
    check(not cap.check_feasible(targets, inp), "trading: solver feasible")
    check(
        use >= 10.0 - 1e-9,
        f"trading: quota fully used ({use:.0f}/10)",
    )
    check(
        targets[0] > targets[1],
        f"trading: deep queue won the trade ({targets[0]} vs {targets[1]})",
    )
    return {"targets": [int(t) for t in targets]}


# --------------------------------------------------------------------------- #
# 4. breaker fallback: bit-identical heuristic behavior
# --------------------------------------------------------------------------- #


def _seed_capacity_store(capacity_on: bool):
    from evergreen_tpu.globals import Provider
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models.distro import (
        Distro,
        HostAllocatorSettings,
        PlannerSettings,
    )
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.storage.store import Store

    now = 1_700_000_000.0
    store = Store()
    for did, n in (("deep", 24), ("mid", 9), ("shallow", 3)):
        distro_mod.insert(
            store,
            Distro(
                id=did,
                provider=Provider.MOCK.value,
                planner_settings=PlannerSettings(
                    capacity="tpu" if capacity_on else ""
                ),
                host_allocator_settings=HostAllocatorSettings(
                    maximum_hosts=40
                ),
            ),
        )
        task_mod.insert_many(
            store,
            [
                Task(
                    id=f"{did}-t{j}",
                    distro_id=did,
                    project="p",
                    version="v1",
                    build_variant="bv",
                    status="undispatched",
                    activated=True,
                    requester="gitter_request",
                    activated_time=now - 600,
                    create_time=now - 700,
                    scheduled_time=now - 600,
                    expected_duration_s=900.0,
                )
                for j in range(n)
            ],
        )
    return store, now


def run_breaker_fallback() -> None:
    from evergreen_tpu.scheduler.capacity_plane import capacity_plane_for
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.utils import faults

    # reference: capacity disabled entirely → pure heuristic counts
    ref_store, now = _seed_capacity_store(capacity_on=False)
    ref = run_tick(ref_store, TickOptions(), now=now)

    # capacity on, but every solve faulted: the tick must fall back to
    # BIT-IDENTICAL heuristic spawn counts
    store, now = _seed_capacity_store(capacity_on=True)
    faults.install(
        faults.FaultPlan().always("capacity.solve", faults.Fault("raise"))
    )
    try:
        res = run_tick(store, TickOptions(), now=now)
        check(
            res.new_hosts == ref.new_hosts,
            f"breaker fallback: bit-identical heuristic counts "
            f"({res.new_hosts} == {ref.new_hosts})",
        )
        for k in range(2):
            run_tick(store, TickOptions(), now=now + 15 * (k + 1))
        breaker = capacity_plane_for(store).breaker
        check(
            breaker.state == "open",
            f"breaker fallback: breaker open after repeated failures "
            f"(state={breaker.state})",
        )
    finally:
        faults.uninstall()
    # with the fault plan gone and the breaker cooled down, the solver
    # path resumes and diverges from the pure heuristic where it trades
    res2 = run_tick(store, TickOptions(), now=now + 7200.0)
    check(
        res2.degraded == "",
        f"breaker fallback: clean tick after recovery ({res2.degraded!r})",
    )


# --------------------------------------------------------------------------- #
# 5. fused ≡ two-call bit parity
# --------------------------------------------------------------------------- #


def run_fused_bit_parity() -> dict:
    """Device-vs-host relaxation parity at the SAME padded shape: the
    packed solve's ``cap_x`` column must equal ``run_capacity_solve``
    over the full-row instance rebuilt from the fused view, bit for bit
    in f32 — and so must the rounded targets either way."""
    from evergreen_tpu.ops import capacity as cap
    from evergreen_tpu.ops.solve import run_solve_packed
    from evergreen_tpu.scheduler.capacity_plane import (
        CapacityPlane,
        build_fused_inputs,
        extract_fused_view,
    )
    from evergreen_tpu.scheduler.snapshot import (
        build_snapshot,
        pack_capacity_page,
    )
    from evergreen_tpu.settings import CapacityConfig
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW, generate_problem

    distros, tbd, hbd, est, dm = generate_problem(
        40, 2_000, seed=7, hosts_per_distro=4
    )
    for d in distros:
        d.planner_settings.capacity = "tpu"
    snapshot = build_snapshot(distros, tbd, hbd, est, dm, NOW)
    store = Store()
    CapacityConfig(pool_quotas={"mock": 60}).set(store)
    page = CapacityPlane(store).build_capacity_page(intent_budget=500)
    pack_capacity_page(snapshot.arrays, page)
    out = run_solve_packed(snapshot)
    view = extract_fused_view(snapshot, out)
    check(view is not None, "fused parity: view extracted from the solve")
    inp = build_fused_inputs(view)
    check(bool(inp.elig.any()), "fused parity: instance has eligible rows")
    x_host = np.asarray(
        cap.run_capacity_solve(inp, d_pad=view["d_pad"]), np.float32
    )
    x_dev = np.asarray(view["cap_x"][: inp.n], np.float32)
    # the instance bits are identical (a single Newton step matches
    # exactly); across iterations XLA may contract/fuse the loop body
    # differently inside the larger fused program, so the relaxation is
    # pinned to float-ulp agreement while the INTEGRAL artifacts below
    # — the actual contract — must be identical
    max_dx = float(np.abs(x_host - x_dev).max())
    check(
        max_dx <= 1e-5,
        f"fused parity: relaxations agree to float ulps "
        f"(max |Δ| {max_dx:.3e} ≤ 1e-5)",
    )
    t_fused, _, _ = cap.solve_capacity_from_x(inp, view["cap_x"])
    t_two, _, _ = cap.solve_capacity(inp, d_pad=view["d_pad"])
    check(
        np.array_equal(t_fused, t_two),
        "fused parity: rounded targets and allocations identical",
    )
    rounded = cap.round_affinity(view["aff_pool"], view["unit_counts"])
    check(
        bool((rounded.sum(axis=1) == view["unit_counts"]).all()),
        "fused parity: affinity rounding conserves per-unit task counts",
    )
    return {
        "n_distros": int(inp.n),
        "n_elig": int(inp.elig.sum()),
        "targets_total": int(t_fused.sum()),
        "max_relaxation_delta": max_dx,
    }


def run_fused_tick_parity() -> None:
    """Full-tick ladder parity: a fused tick and a fused-sabotaged tick
    (two-call rung) on identically seeded stores produce bit-identical
    spawn counts; fused ticks leave scheduler_capacity_solves_total
    flat while scheduler_fused_solves_total{mode="fused"} counts."""
    from evergreen_tpu.scheduler import capacity_plane as cp
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.settings import CapacityConfig
    from evergreen_tpu.utils import faults

    s_fused, now = _seed_capacity_store(capacity_on=True)
    CapacityConfig(pool_quotas={"mock": 12}).set(s_fused)
    cap0 = cp.CAPACITY_SOLVES.total()
    f0 = cp.FUSED_SOLVES.value(mode="fused")
    r_fused = run_tick(s_fused, TickOptions(), now=now)
    check(
        cp.CAPACITY_SOLVES.total() == cap0,
        "fused tick: scheduler_capacity_solves_total stayed flat "
        "(exactly one device call this tick)",
    )
    check(
        cp.FUSED_SOLVES.value(mode="fused") == f0 + 1,
        "fused tick: served by the fused rung",
    )
    prov = getattr(s_fused, "_last_capacity", None)
    check(
        prov is not None and prov.affinity is not None,
        "fused tick: affinity hints attached to provenance",
    )

    # sabotage ONLY the fused rung: the tick must fall to the two-call
    # rung (same full-row instance, same padded D) bit-identically
    s_two, _ = _seed_capacity_store(capacity_on=True)
    CapacityConfig(pool_quotas={"mock": 12}).set(s_two)
    faults.install(
        faults.FaultPlan().always("capacity.fused", faults.Fault("raise"))
    )
    try:
        t0 = cp.FUSED_SOLVES.value(mode="two_call")
        r_two = run_tick(s_two, TickOptions(), now=now)
        check(
            cp.FUSED_SOLVES.value(mode="two_call") == t0 + 1,
            "fused fallback: served by the two-call rung",
        )
    finally:
        faults.uninstall()
    check(
        r_fused.new_hosts == r_two.new_hosts,
        f"fused fallback: bit-identical spawn counts "
        f"({r_fused.new_hosts} == {r_two.new_hosts})",
    )
    pf = getattr(s_fused, "_last_capacity", None)
    pt = getattr(s_two, "_last_capacity", None)
    same_targets = pf is not None and pt is not None and all(
        pf.target_hosts(d) == pt.target_hosts(d)
        for d in ("deep", "mid", "shallow")
    )
    check(same_targets, "fused fallback: identical adopted targets")


def main() -> int:
    t0 = time.perf_counter()
    run_fuzz()
    bench = run_bench_workload()
    trading = run_trading()
    run_breaker_fallback()
    fused = run_fused_bit_parity()
    run_fused_tick_parity()
    summary = {
        "metric": "capacity_parity",
        "ok": not FAILURES,
        "failures": FAILURES,
        "bench": bench,
        "trading": trading,
        "fused": fused,
        "total_s": round(time.perf_counter() - t0, 1),
    }
    print(json.dumps(summary))
    if FAILURES:
        print(
            f"capacity-parity: RED — {len(FAILURES)} failure(s)",
            file=sys.stderr,
        )
        return 1
    print("capacity-parity: green", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
