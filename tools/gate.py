#!/usr/bin/env python
"""Pre-snapshot gate: run the full suite in the STOCK image environment
(no env overrides beyond what conftest sets itself) and exit non-zero on
any red. Run this before every end-of-round / milestone commit:

    python tools/gate.py            # full suite
    python tools/gate.py tests/test_foo.py   # subset passthrough

A commit must not ship with this gate red (VERDICT r2 weak #1).
"""
from __future__ import annotations

import os
import subprocess
import sys


def tree_hash() -> str:
    """Canonical content hash of the ENTIRE working tree (tracked diffs
    + untracked files), independent of what happens to be staged: build
    a throwaway index with everything added and write-tree it. Used so
    a cached green gate result can never be reused for a different
    tree."""
    import tempfile

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fd, idx = tempfile.mkstemp(prefix="gate-index-")
    os.close(fd)
    env = dict(os.environ, GIT_INDEX_FILE=idx)
    try:
        subprocess.run(
            ["git", "read-tree", "HEAD"], env=env, cwd=root, check=True,
            capture_output=True,
        )
        subprocess.run(
            ["git", "add", "-A"], env=env, cwd=root, check=True,
            capture_output=True,
        )
        # append-only logs grow between the gate run and the hook's
        # check (the gate log from this very run; the probe log from the
        # background daemon) — they must not perturb the hash the reuse
        # window is keyed by, and neither holds code the suite covers
        subprocess.run(
            ["git", "rm", "--cached", "-q", "--ignore-unmatch",
             "GATE_LOG.jsonl", "TPU_PROBE_LOG.jsonl"],
            env=env, cwd=root, capture_output=True,
        )
        out = subprocess.run(
            ["git", "write-tree"], env=env, cwd=root, check=True,
            capture_output=True, text=True,
        ).stdout.strip()
    except subprocess.CalledProcessError:
        return "unknown"
    finally:
        try:
            os.unlink(idx)
        except OSError:
            pass
    return out


def _log_run(rc: int, args: list) -> None:
    """Append the gate outcome to GATE_LOG.jsonl at the repo root so
    every run (and therefore every skip) is visible in history
    (VERDICT r4 ask #10)."""
    import json
    import time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    try:
        with open(os.path.join(root, "GATE_LOG.jsonl"), "a") as f:
            f.write(
                json.dumps(
                    {
                        "t": round(time.time(), 1),
                        "rc": rc,
                        "args": args,
                        "head": subprocess.run(
                            ["git", "rev-parse", "--short", "HEAD"],
                            capture_output=True, text=True, cwd=root,
                        ).stdout.strip(),
                        "tree": tree_hash(),
                    }
                )
                + "\n"
            )
    except OSError:
        pass


def main() -> int:
    if sys.argv[1:] == ["--tree-hash"]:
        print(tree_hash())
        return 0
    # Scrub overrides that could mask a stock-image failure.
    env = dict(os.environ)
    for k in ("EVG_TPU_EGRESS", "EVG_TPU_DATA_DIR"):
        env.pop(k, None)
    args = [a for a in sys.argv[1:] if a != "--crash-matrix"]
    with_crash_matrix = "--crash-matrix" in sys.argv[1:]
    args = args or ["tests/"]
    cmd = [sys.executable, "-m", "pytest", "-q", *args]
    print("gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    if rc == 0 and with_crash_matrix:
        # the full process-kill matrix (make crash-matrix) on top of the
        # suite: real SIGKILL-shaped deaths + the two-process failover
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cm = [sys.executable, os.path.join(root, "tools", "crash_matrix.py")]
        print("gate:", " ".join(cm), flush=True)
        rc = subprocess.call(cm, env={**env, "JAX_PLATFORMS": "cpu"})
        _log_run(rc, [*args, "--crash-matrix"])
    else:
        _log_run(rc, args)
    if rc != 0:
        print("gate: RED — do not commit this snapshot", file=sys.stderr)
    else:
        print("gate: green")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
