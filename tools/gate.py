#!/usr/bin/env python
"""Pre-snapshot gate: run the full suite in the STOCK image environment
(no env overrides beyond what conftest sets itself) and exit non-zero on
any red. Run this before every end-of-round / milestone commit:

    python tools/gate.py            # full suite
    python tools/gate.py tests/test_foo.py   # subset passthrough

A commit must not ship with this gate red (VERDICT r2 weak #1).
"""
from __future__ import annotations

import os
import subprocess
import sys


def tree_hash() -> str:
    """Canonical content hash of the ENTIRE working tree (tracked diffs
    + untracked files), independent of what happens to be staged: build
    a throwaway index with everything added and write-tree it. Used so
    a cached green gate result can never be reused for a different
    tree."""
    import tempfile

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fd, idx = tempfile.mkstemp(prefix="gate-index-")
    os.close(fd)
    env = dict(os.environ, GIT_INDEX_FILE=idx)
    try:
        subprocess.run(
            ["git", "read-tree", "HEAD"], env=env, cwd=root, check=True,
            capture_output=True,
        )
        subprocess.run(
            ["git", "add", "-A"], env=env, cwd=root, check=True,
            capture_output=True,
        )
        # (the append-only runtime logs — GATE_LOG.jsonl,
        # TPU_PROBE_LOG.jsonl — are gitignored, so `git add -A` already
        # leaves them out of the hash.) LAST_GREEN.json is tracked but
        # written BY the gate run this hash keys, so including it would
        # invalidate the pre-commit hook's reuse window on every run.
        subprocess.run(
            ["git", "rm", "--cached", "-q", "--ignore-unmatch",
             "LAST_GREEN.json"],
            env=env, cwd=root, capture_output=True,
        )
        out = subprocess.run(
            ["git", "write-tree"], env=env, cwd=root, check=True,
            capture_output=True, text=True,
        ).stdout.strip()
    except subprocess.CalledProcessError:
        return "unknown"
    finally:
        try:
            os.unlink(idx)
        except OSError:
            pass
    return out


def _log_run(rc: int, args: list) -> None:
    """Append the gate outcome to GATE_LOG.jsonl (an UNtracked,
    gitignored runtime log — every run and therefore every skip stays
    visible locally, VERDICT r4 ask #10) and, on a green full-suite
    run, refresh LAST_GREEN.json — the one auditable summary that IS
    under version control."""
    import json
    import time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    record = {
        "t": round(time.time(), 1),
        "rc": rc,
        "args": args,
        "head": subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, cwd=root,
        ).stdout.strip(),
        "tree": tree_hash(),
    }
    try:
        with open(os.path.join(root, "GATE_LOG.jsonl"), "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError:
        pass
    # only a FULL-suite green refreshes the tracked summary — a passing
    # subset run (including `tests/ --ignore=...` shapes) must not
    # masquerade as a suite-wide green; the only extra args a full run
    # carries are the matrix flags this gate itself appends
    full_suite = bool(args) and args[0] == "tests/" and all(
        a in ("--crash-matrix", "--disk-matrix", "--net-matrix",
              "--overload-matrix",
              "--resident-parity", "--shard-parity", "--capacity-parity",
              "--read-parity", "--scenarios", "--fleet-runtime", "--fuzz")
        for a in args[1:]
    )
    if rc == 0 and full_suite:
        try:
            with open(os.path.join(root, "LAST_GREEN.json"), "w") as f:
                json.dump(record, f, indent=2, sort_keys=True)
                f.write("\n")
        except OSError:
            pass


def main() -> int:
    if sys.argv[1:] == ["--tree-hash"]:
        print(tree_hash())
        return 0
    # Scrub overrides that could mask a stock-image failure.
    env = dict(os.environ)
    for k in ("EVG_TPU_EGRESS", "EVG_TPU_DATA_DIR"):
        env.pop(k, None)
    flags = {"--crash-matrix", "--disk-matrix", "--net-matrix",
             "--overload-matrix",
             "--resident-parity", "--shard-parity", "--capacity-parity",
             "--read-parity", "--scenarios", "--fleet-runtime", "--fuzz"}
    args = [a for a in sys.argv[1:] if a not in flags]
    with_fleet_runtime = "--fleet-runtime" in sys.argv[1:]
    with_scenarios = "--scenarios" in sys.argv[1:]
    with_crash_matrix = "--crash-matrix" in sys.argv[1:]
    with_disk_matrix = "--disk-matrix" in sys.argv[1:]
    with_net_matrix = "--net-matrix" in sys.argv[1:]
    with_overload_matrix = "--overload-matrix" in sys.argv[1:]
    with_resident_parity = "--resident-parity" in sys.argv[1:]
    with_shard_parity = "--shard-parity" in sys.argv[1:]
    with_capacity_parity = "--capacity-parity" in sys.argv[1:]
    with_read_parity = "--read-parity" in sys.argv[1:]
    with_fuzz = "--fuzz" in sys.argv[1:]
    args = args or ["tests/"]
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # evglint first, unconditionally: all six static passes (lockgraph,
    # tracercheck, fencecheck, shedcheck, seamcheck, metrics) take
    # milliseconds, and each guards a bug class that is a runtime crash
    # or a silent correctness hole. The sabotage self-test runs first so
    # a pass that has gone blind fails the gate before a clean report
    # from it could be trusted.
    for lint_args in (["--sabotage"], []):
        el = [sys.executable, "-m", "tools.evglint", *lint_args]
        print("gate:", " ".join(el), flush=True)
        rc = subprocess.call(el, env=env, cwd=root)
        if rc != 0:
            _log_run(rc, ["evglint", *lint_args])
            print("gate: RED — evglint failed", file=sys.stderr)
            return rc
    cmd = [sys.executable, "-m", "pytest", "-q", *args]
    print("gate:", " ".join(cmd), flush=True)
    rc = subprocess.call(cmd, env=env)
    ran_flags = []
    if rc == 0 and with_crash_matrix:
        # the full process-kill matrix (make crash-matrix) on top of the
        # suite: real SIGKILL-shaped deaths + the two-process failover
        cm = [sys.executable, os.path.join(root, "tools", "crash_matrix.py")]
        print("gate:", " ".join(cm), flush=True)
        rc = subprocess.call(cm, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--crash-matrix")
    if rc == 0 and with_disk_matrix:
        # the disk-fault matrix (make disk-matrix): the process LIVES
        # while the disk rots under it — seams x kinds x store configs
        # plus engine-driven disk weathers, bespoke integrity cases
        # (upgrade-compat, manifest, lease, replica read-repair), and
        # fuzzer disk_fault reachability; every point must detect,
        # quarantine, self-heal, and hold resume == rerun
        dm = [sys.executable, os.path.join(root, "tools", "disk_matrix.py")]
        print("gate:", " ".join(dm), flush=True)
        rc = subprocess.call(dm, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--disk-matrix")
    if rc == 0 and with_net_matrix:
        # the network-chaos matrix (make net-matrix): partition/latency/
        # loss/duplication/reordering/half-open at every transport seam,
        # across classic + 2-shard fleet + solver-leader plane configs;
        # every point must detect, degrade boundedly (never split-brain,
        # never double-dispatch, stale-accepted == 0), and hold
        # resume == rerun — with the unfenced-duplicate sabotage
        # self-test run first
        nm = [sys.executable, os.path.join(root, "tools", "net_matrix.py")]
        print("gate:", " ".join(nm), flush=True)
        rc = subprocess.call(nm, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--net-matrix")
    if rc == 0 and with_overload_matrix:
        # the storm-soak matrix (make overload-matrix): seeded storms
        # must brown out low-value work only and recover to GREEN
        om = [sys.executable,
              os.path.join(root, "tools", "overload_matrix.py")]
        print("gate:", " ".join(om), flush=True)
        rc = subprocess.call(om, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--overload-matrix")
    if rc == 0 and with_resident_parity:
        # resident ≡ rebuild parity fuzz + churn micro-bench
        # (make resident-parity): the device-resident state plane must
        # canonicalize identically to a from-scratch snapshot under churn
        rp = [sys.executable,
              os.path.join(root, "tools", "resident_parity.py")]
        print("gate:", " ".join(rp), flush=True)
        rc = subprocess.call(rp, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--resident-parity")
    if rc == 0 and with_shard_parity:
        # sharded tick ≡ single-scheduler oracle at 2/4/8 shards, in
        # local AND stacked solve modes (make shard-parity): the
        # multichip equality check promoted from dry-run to the live
        # tick path — gate-blocking
        spar = [sys.executable,
                os.path.join(root, "tools", "bench_sharded.py"),
                "--parity"]
        print("gate:", " ".join(spar), flush=True)
        rc = subprocess.call(spar, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--shard-parity")
    if rc == 0 and with_capacity_parity:
        # joint capacity solve ≡ feasible, matches-or-beats the
        # utilization oracle's time-to-empty, trades under shared
        # quotas, and the breaker fallback is bit-identical heuristic
        # behavior (make capacity-parity)
        cpar = [sys.executable,
                os.path.join(root, "tools", "capacity_parity.py")]
        print("gate:", " ".join(cpar), flush=True)
        rc = subprocess.call(cpar, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--capacity-parity")
    if rc == 0 and with_scenarios:
        # the trace-driven scenario sweep (make scenarios): six weathers
        # + the migrated fault/overload matrix cases through ONE engine,
        # deterministic (same seed ⇒ same scorecard), a sabotage
        # self-test proving violations are caught, and the scorecard
        # diffed against the last green run — a regression in graceful
        # degradation fails this gate like a perf regression
        sab = [sys.executable,
               os.path.join(root, "tools", "scenario_engine.py"),
               "--sabotage"]
        print("gate:", " ".join(sab), flush=True)
        rc = subprocess.call(sab, env={**env, "JAX_PLATFORMS": "cpu"})
        if rc == 0:
            sc = [sys.executable,
                  os.path.join(root, "tools", "scenario_engine.py"),
                  "--check-determinism", "--diff", "--write-green"]
            print("gate:", " ".join(sc), flush=True)
            rc = subprocess.call(sc, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--scenarios")
    if rc == 0 and with_fleet_runtime:
        # the supervised-fleet smoke (make fleet-runtime): 2 shard
        # worker processes under the production supervisor, one induced
        # SIGKILL at a WAL seam + one induced hang — each must take
        # over fenced at a higher lease epoch with zero duplicate
        # dispatch and resume ≡ rerun — plus the SUPERVISOR-kill
        # weathers (mid-round + mid-handoff: orphan workers adopted
        # live with zero epoch bumps, handoff reconciled to
        # exactly-one-owner), the migrated crash-matrix engine points
        # sample, and the split-brain sabotage self-test (a second
        # supervisor's stale-epoch commands must ALL be rejected)
        fr = [sys.executable,
              os.path.join(root, "tools", "fleet_runtime.py")]
        print("gate:", " ".join(fr), flush=True)
        rc = subprocess.call(fr, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--fleet-runtime")
    if rc == 0 and with_read_parity:
        # follower reads ≡ primary at lag 0, bounded-stale answers are a
        # prefix of primary history, fenced frames never served, the
        # scrape-storm 304 hit-rate holds, and the 10k-agent long-poll
        # soak hands every task out exactly once (make read-parity)
        rpar = [sys.executable,
                os.path.join(root, "tools", "read_parity.py")]
        print("gate:", " ".join(rpar), flush=True)
        rc = subprocess.call(rpar, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--read-parity")
    if rc == 0 and with_fuzz:
        # property-based weather fuzzing (make fuzz): the sabotage
        # self-test runs FIRST — a seeded invariant violation must be
        # found within the time box, shrink to a minimal timeline, and
        # replay deterministically on both the in-process and
        # child-process backends — then a pinned-seed randomized
        # campaign whose FUZZCARD.json diffs against the last green
        # (a fuzzer that stops finding seeded bugs, or whose case
        # throughput collapses, fails this gate)
        fz = [sys.executable, os.path.join(root, "tools", "fuzz_matrix.py"),
              "--sabotage"]
        print("gate:", " ".join(fz), flush=True)
        rc = subprocess.call(fz, env={**env, "JAX_PLATFORMS": "cpu"})
        if rc == 0:
            fc = [sys.executable,
                  os.path.join(root, "tools", "fuzz_matrix.py"),
                  "--diff", "--write-green"]
            print("gate:", " ".join(fc), flush=True)
            rc = subprocess.call(fc, env={**env, "JAX_PLATFORMS": "cpu"})
        ran_flags.append("--fuzz")
    _log_run(rc, [*args, *ran_flags])
    if rc != 0:
        print("gate: RED — do not commit this snapshot", file=sys.stderr)
    else:
        print("gate: green")
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
