#!/usr/bin/env python
"""Fault-matrix soak: every injected fault class must leave the tick
pipeline alive and the store consistent.

One case per fault class from the resilience layer (utils/faults.py
seams). The tick-pipeline cases — solve raise, solve hang past
deadline, breaker cycle, WAL group-commit error, torn group frame,
lease steal mid-commit, tick-budget shed — are MIGRATED (ISSUE 12):
they execute as scenario specs through the trace-driven engine
(evergreen_tpu/scenarios/matrix.py) with their original assertions
intact, and this module only delegates. The remaining bespoke cases
exercise subsystems outside the tick replay: async-WAL deferred
barrier, lease-renewal threads, agent transport retries, cloud-provider
spawn, event senders, and the job quarantine. Each case returns a
result dict with ``ok`` — `tests/test_resilience.py` parametrizes over
the same registry, and ``tools/chaos_soak.sh --faults`` runs it
standalone against several seeds.
"""
from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

# the runtime lock-order witness: every registered lock in the plane is
# wrapped for the whole soak; any acquisition-order inversion recorded
# anywhere in this process fails the matrix (set BEFORE the package
# imports below create their locks)
os.environ.setdefault("EVERGREEN_TPU_LOCKCHECK", "1")

import tempfile
from typing import Callable, Dict, List

from evergreen_tpu.globals import HostStatus, Provider
from evergreen_tpu.models import distro as distro_mod
from evergreen_tpu.models import host as host_mod
from evergreen_tpu.models import task as task_mod
from evergreen_tpu.models.task_queue import COLLECTION as TQ_COLLECTION
from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
from evergreen_tpu.storage.store import Store
from evergreen_tpu.utils import faults
from evergreen_tpu.utils import log as log_mod
from evergreen_tpu.utils.benchgen import NOW, generate_problem
from evergreen_tpu.utils.faults import Fault, FaultPlan

OPTS = TickOptions(create_intent_hosts=True, underwater_unschedule=False)


def _seed_store(store, n_distros: int = 3, n_tasks: int = 60, seed: int = 7):
    """A small, fully-plannable problem inserted into ``store``."""
    distros, tasks_by_distro, hosts_by_distro, _, _ = generate_problem(
        n_distros, n_tasks, seed=seed, hosts_per_distro=2
    )
    for d in distros:
        distro_mod.insert(store, d)
    all_tasks = [t for ts in tasks_by_distro.values() for t in ts]
    task_mod.insert_many(store, all_tasks)
    for hs in hosts_by_distro.values():
        host_mod.insert_many(store, hs)
    return distros, tasks_by_distro, hosts_by_distro


def _capture_logs():
    got: List[dict] = []
    log_mod.add_sink(got.append)
    return got, lambda: log_mod.remove_sink(got.append)


# --------------------------------------------------------------------------- #
# cases
#
# The tick-pipeline cases (solve raise/hang, breaker cycle, WAL
# error/torn, lease-steal-mid-commit, tick-budget shed) are MIGRATED:
# they now run as scenario specs through the trace-driven engine
# (evergreen_tpu/scenarios/matrix.py, ISSUE 12) with their original
# assertions expressed as checks over the replay — this module only
# delegates, so the bespoke wiring below keeps shrinking. The remaining
# bespoke cases exercise non-tick subsystems (lease renewal threads,
# agent transport, provider spawn, senders, job quarantine) plus the
# async-WAL deferred barrier.
# --------------------------------------------------------------------------- #


def _engine_case(name: str):
    def run(seed: int = 0) -> dict:
        from evergreen_tpu.scenarios import run_matrix_case

        return run_matrix_case("fault", name, seed)

    run.__name__ = f"case_{name.replace('-', '_')}"
    return run


case_solve_raise = _engine_case("solve-raise")
case_solve_hang = _engine_case("solve-hang")
case_breaker_cycle = _engine_case("breaker-cycle")
case_wal_error = _engine_case("wal-error")
case_wal_torn = _engine_case("wal-torn")
case_tick_budget_shed = _engine_case("tick-budget-shed")
case_lease_steal_mid_commit = _engine_case("lease-steal-mid-commit")


def case_wal_async_deferred(seed: int = 0) -> dict:
    """Async group commit (the service cadence): tick t's WAL frame fails
    on the background flusher AFTER run_tick returned; the error surfaces
    at tick t+1's barrier as the batched persist-failed degradation, the
    delta fingerprints reset (t+1 full-rewrites), and recovery stays
    consistent."""
    import dataclasses as _dc

    from evergreen_tpu.storage.durable import DurableStore

    data_dir = tempfile.mkdtemp(prefix="fault-walasync-")
    store = DurableStore(data_dir)
    _seed_store(store, seed=seed + 29)
    opts = _dc.replace(OPTS, async_persist=True)
    got, stop = _capture_logs()
    faults.install(
        FaultPlan().at("wal.commit", 0, Fault("raise", OSError("disk full")))
    )
    try:
        res1 = run_tick(store, opts, now=NOW)   # commit fails off-thread
        res2 = run_tick(store, opts, now=NOW + 1)  # barrier surfaces it
    finally:
        faults.uninstall()
        stop()
    res3 = run_tick(store, opts, now=NOW + 2)
    store.sync_persist()
    recovered = DurableStore(data_dir)
    queues_survive = all(
        recovered.collection(TQ_COLLECTION).get(did) is not None
        for did in res3.queues
        if not did.endswith("::alias")
    )
    return {
        "ok": (
            res1.degraded == ""          # the error had not surfaced yet
            and res2.degraded == "persist-failed"
            and res3.degraded == ""
            and sum(res2.queues.values()) > 0
            and queues_survive
            and any(
                r.get("message") == "wal-group-commit-failed"
                and r.get("deferred") is True
                for r in got
            )
        ),
        "logs": got,
    }


def case_lease_loss(seed: int = 0) -> dict:
    import os
    import threading

    from evergreen_tpu.storage.lease import FileLease

    data_dir = tempfile.mkdtemp(prefix="fault-lease-")
    lease = FileLease(os.path.join(data_dir, "lease.json"), ttl_s=0.3)
    assert lease.try_acquire()
    got, stop = _capture_logs()
    lost_evt = threading.Event()
    faults.install(FaultPlan().always("lease.renew", Fault("lost")))
    try:
        lease.start_renewing(on_lost=lost_evt.set)
        fired = lost_evt.wait(timeout=5.0)
    finally:
        lease.stop_renewing()
        faults.uninstall()
        stop()
    return {
        "ok": (
            fired
            and lease.lost
            and any(r.get("message") == "lease-lost" for r in got)
        ),
        "logs": got,
    }


def case_agent_comm(seed: int = 0) -> dict:
    from evergreen_tpu.agent.rest_comm import RestCommunicator

    got, stop = _capture_logs()
    comm = RestCommunicator(
        "http://127.0.0.1:9", retries=3, backoff_s=0.0
    )
    plan = faults.install(
        FaultPlan().always(
            "agent.comm", Fault("raise", TimeoutError("injected timeout"))
        )
    )
    raised = False
    try:
        try:
            comm.next_task("h1")
        except ConnectionError:
            raised = True
    finally:
        faults.uninstall()
        stop()
    return {
        "ok": (
            raised
            and plan._calls.get("agent.comm") == 3  # bounded attempts
            and any(r.get("message") == "retry-exhausted" for r in got)
        ),
        "logs": got,
    }


def case_provider_error(seed: int = 0) -> dict:
    from evergreen_tpu.cloud.provisioning import (
        MAX_PROVISION_ATTEMPTS,
        create_hosts_from_intents,
    )
    from evergreen_tpu.models.distro import Distro
    from evergreen_tpu.models.host import new_intent

    store = Store()
    distro_mod.insert(store, Distro(id="dp", provider=Provider.MOCK.value))
    intent = new_intent("dp", Provider.MOCK.value)
    host_mod.insert(store, intent)
    got, stop = _capture_logs()
    faults.install(FaultPlan().always("cloud.spawn", Fault("raise")))
    try:
        for k in range(MAX_PROVISION_ATTEMPTS):
            spawned = create_hosts_from_intents(store, now=NOW + k)
    finally:
        faults.uninstall()
        stop()
    h = host_mod.get(store, intent.id)
    # _poison marks PROVISION_FAILED then asks the provider to terminate,
    # which may advance it to TERMINATED — both are poisoned end states
    poisoned = h is not None and h.status in (
        HostStatus.PROVISION_FAILED.value,
        HostStatus.TERMINATED.value,
    )
    return {
        "ok": (
            spawned == []
            and poisoned
            and h.provision_attempts == MAX_PROVISION_ATTEMPTS
            and any(
                r.get("message") == "host-spawn-failed" for r in got
            )
        ),
        "logs": got,
    }


def case_sender_error(seed: int = 0) -> dict:
    from evergreen_tpu.events.senders import OUTBOX, insert_outbox_row
    from evergreen_tpu.events.transports import drain_outboxes

    store = Store()
    insert_outbox_row(
        store, OUTBOX["slack"],
        {"channel_type": "slack", "slack_channel": "#x", "text": "hi"},
    )

    class _Recorder:
        def __init__(self):
            self.delivered = []

        def deliver(self, doc):
            self.delivered.append(doc["_id"])

    slack = _Recorder()
    got, stop = _capture_logs()
    faults.install(FaultPlan().always("events.deliver", Fault("raise")))
    try:
        for _ in range(3):
            drain_outboxes(store, transports={"slack": slack}, now=NOW)
    finally:
        faults.uninstall()
        stop()
    row = store.collection(OUTBOX["slack"]).find(lambda d: True)[0]
    # fault cleared: a fresh row delivers — the channel recovered
    insert_outbox_row(
        store, OUTBOX["slack"],
        {"channel_type": "slack", "slack_channel": "#x", "text": "again"},
    )
    drain_outboxes(store, transports={"slack": slack}, now=NOW + 1)
    return {
        "ok": (
            row.get("failed") is True
            and row.get("attempts") == 3
            and len(slack.delivered) == 1
            and any(
                r.get("message") == "outbox-row-abandoned" for r in got
            )
        ),
        "logs": got,
    }


def case_job_quarantine(seed: int = 0) -> dict:
    from evergreen_tpu.queue.jobs import FnJob, JobQueue

    store = Store()
    q = JobQueue(store, workers=1, poison_threshold=2, quarantine_s=60.0)
    got, stop = _capture_logs()

    def boom(s):
        raise RuntimeError("poison")

    try:
        for i in range(2):
            assert q.put(FnJob(f"poison-{i}", boom, job_type="poison"))
            q.wait_idle(5.0)
        dropped = not q.put(FnJob("poison-2", boom, job_type="poison"))
        other_ok = q.put(FnJob("fine-0", lambda s: None, job_type="fine"))
        q.wait_idle(5.0)
        # cooldown elapses → exactly one probe runs; success lifts it
        with q._lock:
            q._quarantined_until["poison"] = 0.0
        probe_ok = q.put(
            FnJob("probe-0", lambda s: None, job_type="poison")
        )
        q.wait_idle(5.0)
        lifted = q.put(FnJob("after-0", lambda s: None, job_type="poison"))
        q.wait_idle(5.0)
    finally:
        stop()
        q.close()
    return {
        "ok": (
            dropped
            and other_ok
            and probe_ok
            and lifted
            and any(r.get("message") == "job-quarantined" for r in got)
            and any(
                r.get("message") == "job-quarantine-lifted" for r in got
            )
        ),
        "logs": got,
    }


CASES: Dict[str, Callable[[int], dict]] = {
    "solve-raise": case_solve_raise,
    "solve-hang": case_solve_hang,
    "breaker-cycle": case_breaker_cycle,
    "wal-error": case_wal_error,
    "wal-torn": case_wal_torn,
    "wal-async-deferred": case_wal_async_deferred,
    "lease-loss": case_lease_loss,
    "lease-steal-mid-commit": case_lease_steal_mid_commit,
    "agent-comm": case_agent_comm,
    "provider-error": case_provider_error,
    "sender-error": case_sender_error,
    "job-quarantine": case_job_quarantine,
    "tick-budget-shed": case_tick_budget_shed,
}


def run_case(name: str, seed: int = 0) -> dict:
    return CASES[name](seed)


def main() -> int:
    import argparse
    import json

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seeds", type=int, default=3)
    p.add_argument("--case", default="", help="run one case only")
    args = p.parse_args()
    names = [args.case] if args.case else sorted(CASES)
    failures = 0
    for seed in range(args.seeds):
        for name in names:
            out = run_case(name, seed)
            ok = bool(out.get("ok"))
            failures += 0 if ok else 1
            print(json.dumps({"case": name, "seed": seed, "ok": ok}))
    from evergreen_tpu.utils import lockcheck

    inversions = lockcheck.violations()
    print(json.dumps({"lockcheck_inversions": len(inversions)}))
    failures += len(inversions)
    print(json.dumps({"fault_matrix_failures": failures}))
    return 1 if failures else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
