#!/usr/bin/env python
"""Read-serving-plane parity gate (ISSUE 11): the follower-read path
must be indistinguishable from the primary wherever it claims to be.

Checks (all gate-blocking via ``tools/gate.py --read-parity`` /
``make read-parity``):

  1. **lag-0 equivalence** — a caught-up replica's collections
     canonicalize identically to the primary's, its applied seq equals
     the primary's WAL seq, and a REST answer set served over the
     replica equals the primary's byte-for-byte.
  2. **bounded-stale prefix** — at any poll point a lagging replica's
     state equals SOME prefix of the primary's history (the monotone
     counter probe: observed values never regress and never exceed the
     primary's write frontier), and checkpoint absorption is
     watermark-cheap (zero full reloads for a caught-up tail).
  3. **fencing on the read path** — a deposed holder's frames written
     past the fence point are never surfaced, and the replica refuses
     to serve (``serve_ready() == False``) between observing a fence
     marker and applying the new holder's first record.
  4. **10k-agent soak** — the sharded long-poll dispatch hands every
     task out exactly once (zero duplicates) with the full fleet
     parked.
  5. **scrape-storm cache** — the fingerprint ETag cache answers an
     unchanged-queue storm with a 304 hit-rate > 0.9.

Also exports ``measure_read_path()`` — the bench payload's
``read_path`` section (replica lag p50/p99 + 304 hit-rate + dispatch
p99 at 1k/10k agents) shared by bench.py and tools/perf_guard.py.
"""
from __future__ import annotations

import json
import os
import shutil
import statistics
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _canon(store, skip=("rate_limits",)) -> dict:
    out = {}
    with store._lock:
        names = sorted(store._collections)
    for name in names:
        if name in skip:
            continue
        docs = sorted(
            (json.dumps(d, sort_keys=True, default=str)
             for d in store.collection(name).find()),
        )
        if docs:
            out[name] = docs
    return out


def check_lag0_equivalence() -> dict:
    from evergreen_tpu.api.rest import RestApi
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.replica import ReplicaStore
    from tools.bench_dispatch import seed

    tmp = tempfile.mkdtemp(prefix="readparity-")
    try:
        primary = DurableStore(tmp)
        seed(primary, 400, 20, group_every=10)
        primary.collection("versions").insert(
            {"_id": "v1", "project": "p", "create_time": 1.0}
        )
        primary.checkpoint()
        primary.collection("tasks").update("t3", {"priority": 9})
        replica = ReplicaStore(tmp, replica_id="parity")
        replica.poll()
        assert replica.applied_seq == primary.wal_seq, (
            f"replica seq {replica.applied_seq} != primary "
            f"{primary.wal_seq}"
        )
        assert _canon(replica) == _canon(primary), (
            "replica collections != primary at lag 0"
        )
        papi, rapi = RestApi(primary), RestApi(replica)
        answers = 0
        for path in (
            "/rest/v2/hosts", "/rest/v2/distros",
            "/rest/v2/distros/d1/queue", "/rest/v2/versions",
            "/rest/v2/tasks/t3",
        ):
            sp, ap = papi.handle("GET", path, {})
            sr, ar = rapi.handle("GET", path, {})
            assert (sp, json.dumps(ap, sort_keys=True, default=str)) == (
                sr, json.dumps(ar, sort_keys=True, default=str)
            ), f"REST divergence on {path}"
            answers += 1
        primary.close()
        replica.close()
        return {"rest_answers_equal": answers, "seq": primary.wal_seq}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_bounded_stale_prefix() -> dict:
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.replica import ReplicaStore

    tmp = tempfile.mkdtemp(prefix="readparity-")
    try:
        primary = DurableStore(tmp)
        replica = ReplicaStore(tmp, replica_id="parity")
        reloads0 = replica.full_reloads
        last_seen = -1
        frontier = -1
        for n in range(400):
            primary.collection("counters").upsert({"_id": "c", "n": n})
            frontier = n
            if n % 7 == 0:
                replica.poll()
                doc = replica.collection("counters").get("c")
                seen = doc["n"] if doc else -1
                assert last_seen <= seen <= frontier, (
                    f"replica state not a prefix: saw {seen} after "
                    f"{last_seen}, frontier {frontier}"
                )
                last_seen = seen
            if n % 101 == 100:
                primary.checkpoint()
        replica.poll()
        assert replica.collection("counters").get("c")["n"] == 399
        # checkpoint absorption must be watermark-cheap: the caught-up
        # tail saw checkpoints at n=100/201/302 AFTER its poll at
        # n=98/196/294 left it slightly behind — at most those reload;
        # a caught-up absorb (the final checkpoint below) must not
        mid_reloads = replica.full_reloads - reloads0
        primary.checkpoint()
        replica.poll()
        assert replica.full_reloads - reloads0 == mid_reloads, (
            "caught-up replica full-reloaded on checkpoint absorb"
        )
        assert replica.applied_seq == primary.wal_seq
        primary.close()
        replica.close()
        return {
            "probes": 400 // 7,
            "behind_cut_reloads": mid_reloads,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_read_fencing() -> dict:
    """A fenced (deposed) primary keeps writing frames past the fence
    point: the replica must drop them AND refuse to serve between the
    fence marker and the new holder's first record."""
    from evergreen_tpu.storage.replica import ReplicaStore

    tmp = tempfile.mkdtemp(prefix="readparity-")
    try:
        wal = os.path.join(tmp, "wal.log")

        def frame(epoch, doc):
            rec = json.dumps(
                {"c": "tasks", "o": "p", "d": doc},
                separators=(",", ":"),
            )
            return (
                '{"o":"g","n":1,"e":%d,"rs":[%s]}\n' % (epoch, rec)
            )

        # epoch-1 holder writes, then a new holder (epoch 2) opens with
        # its fence marker; the deposed holder's async flusher lands two
        # more frames PAST the marker
        with open(wal, "w", encoding="utf-8") as fh:
            fh.write(frame(1, {"_id": "a", "v": "old"}))
            fh.write(frame(1, {"_id": "b", "v": "old"}))
        replica = ReplicaStore(tmp, replica_id="parity")
        replica.poll()
        assert replica.serve_ready(), "fresh tail must serve"
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write('{"o":"f","e":2}\n')
            fh.write(frame(1, {"_id": "a", "v": "stale-after-fence"}))
            fh.write(frame(1, {"_id": "c", "v": "stale-new-doc"}))
        replica.poll()
        # stale frames never surface…
        assert replica.collection("tasks").get("a")["v"] == "old", (
            "deposed holder's frame surfaced past the fence point"
        )
        assert replica.collection("tasks").get("c") is None
        assert replica.stale_frames_skipped >= 2
        # …and serving is withheld until the new holder's state arrives
        assert not replica.serve_ready(), (
            "replica kept serving between fence marker and the new "
            "holder's first record"
        )
        with open(wal, "a", encoding="utf-8") as fh:
            fh.write(frame(2, {"_id": "a", "v": "new-holder"}))
        replica.poll()
        assert replica.serve_ready()
        assert replica.collection("tasks").get("a")["v"] == "new-holder"
        replica.close()
        return {"stale_frames_dropped": replica.stale_frames_skipped}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def check_fencing_via_real_failover() -> dict:
    """The same invariant through REAL stores: holder A is deposed by a
    lease steal; its buffered tick never reaches the replica, and the
    replica converges to holder B's state exactly like crash recovery
    would."""
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.lease import EpochFencedError, FileLease
    from evergreen_tpu.storage.replica import ReplicaStore

    tmp = tempfile.mkdtemp(prefix="readparity-")
    try:
        lease_a = FileLease(os.path.join(tmp, "writer.lease"), ttl_s=0.2)
        lease_a.acquire()
        store_a = DurableStore(tmp, lease=lease_a)
        store_a.collection("tasks").insert({"_id": "t", "by": "a"})
        replica = ReplicaStore(tmp, replica_id="parity")
        replica.poll()
        # B steals the lease (A stalled) and opens the same dir
        time.sleep(0.3)
        lease_b = FileLease(os.path.join(tmp, "writer.lease"), ttl_s=0.2)
        lease_b.acquire()  # steals the stale lease, bumping the epoch
        store_b = DurableStore(tmp, lease=lease_b)
        store_b.collection("tasks").update("t", {"by": "b"})
        # A's late tick must fence, not reach the WAL
        fenced = False
        try:
            store_a.begin_tick()
            store_a.collection("tasks").update("t", {"by": "a-late"})
            store_a.end_tick()
        except EpochFencedError:
            fenced = True
        replica.poll()
        assert fenced, "deposed holder committed past the steal"
        assert replica.collection("tasks").get("t")["by"] == "b", (
            f"replica surfaced {replica.collection('tasks').get('t')}"
        )
        assert replica.serve_ready()
        store_b.close()
        lease_b.release()
        replica.close()
        return {"fenced": True}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure_cache_hit_rate(storm: int = 60) -> dict:
    """Unchanged-queue scrape storm against the fingerprint ETag cache:
    after the first (miss) answer per endpoint, every revalidation must
    304 with zero store reads."""
    from evergreen_tpu.api.rest import RestApi
    from evergreen_tpu.storage.store import Store
    from tools.bench_dispatch import seed

    store = Store()
    seed(store, 300, 5, group_every=10)
    api = RestApi(store)
    endpoints = (
        "/rest/v2/distros/d1/queue", "/rest/v2/hosts", "/rest/v2/distros",
    )
    total = hits = 0
    t0 = time.perf_counter()
    for path in endpoints:
        etag = ""
        for _ in range(storm):
            headers = {"if-none-match": etag} if etag else {}
            status, _payload = api.handle("GET", path, {}, headers)
            total += 1
            if status == 304:
                hits += 1
            else:
                etag = dict(api._ident.response_headers).get("ETag", "")
        assert etag, f"no ETag served on {path}"
    storm_ms = (time.perf_counter() - t0) * 1e3
    return {
        "requests": total,
        "hits_304": hits,
        "hit_rate_304": round(hits / total, 4),
        "storm_ms": round(storm_ms, 1),
    }


def measure_replica_lag(probes: int = 40) -> dict:
    """Write→visible latency through the live tail thread."""
    from evergreen_tpu.storage.durable import DurableStore
    from evergreen_tpu.storage.replica import ReplicaStore

    tmp = tempfile.mkdtemp(prefix="readparity-")
    try:
        primary = DurableStore(tmp)
        replica = ReplicaStore(tmp, poll_interval_s=0.02,
                               replica_id="parity")
        replica.start()
        lags = []
        for n in range(probes):
            t0 = time.perf_counter()
            primary.collection("probe").upsert({"_id": "p", "n": n})
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                doc = replica.collection("probe").get("p")
                if doc is not None and doc["n"] == n:
                    break
                time.sleep(0.002)
            lags.append((time.perf_counter() - t0) * 1e3)
        replica.close()
        primary.close()
        lags.sort()
        qs = statistics.quantiles(lags, n=100)
        return {
            "probes": probes,
            "replica_lag_p50_ms": round(qs[49], 2),
            "replica_lag_p99_ms": round(qs[98], 2),
            "staleness_ms": round(min(replica.staleness_ms(), 1e6), 1),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure_read_path(quick: bool = False) -> dict:
    """The bench payload's ``read_path`` section (shared by bench.py and
    tools/perf_guard.py): replica lag quantiles, the 304 hit-rate, and
    the long-poll dispatch soak at 1k/10k agents."""
    from tools.bench_dispatch import read_path_dispatch_section

    out = {}
    out.update(measure_replica_lag())
    out.update(measure_cache_hit_rate())
    out.update(read_path_dispatch_section(quick=quick))
    return out


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    quick = "--quick" in sys.argv[1:]
    results = {}
    failures = []
    for name, fn in (
        ("lag0_equivalence", check_lag0_equivalence),
        ("bounded_stale_prefix", check_bounded_stale_prefix),
        ("read_fencing", check_read_fencing),
        ("real_failover_fencing", check_fencing_via_real_failover),
        ("cache_hit_rate", measure_cache_hit_rate),
    ):
        try:
            results[name] = fn()
        except AssertionError as exc:
            failures.append(f"{name}: {exc}")
        except Exception as exc:  # noqa: BLE001 — a crash is a failure
            failures.append(f"{name}: crashed: {exc!r}")
    hit = results.get("cache_hit_rate", {}).get("hit_rate_304", 0.0)
    if not failures and hit <= 0.9:
        failures.append(
            f"304 hit-rate {hit} <= 0.9 on an unchanged-queue storm"
        )
    if not failures and not quick:
        from tools.bench_dispatch import run_soak

        soak = run_soak(n_agents=10_000, waves=8, wave_size=100)
        results["soak_10k"] = soak
        if soak["duplicates"]:
            failures.append(
                f"10k soak handed {soak['duplicates']} tasks out twice"
            )
        if soak["stalled"] or soak["assigned"] != soak["fed"]:
            failures.append(
                f"10k soak stalled: assigned {soak['assigned']} of "
                f"{soak['fed']}"
            )
    print(json.dumps({"read_parity": results, "failures": failures}))
    if failures:
        for f in failures:
            print(f"read-parity: FAIL — {f}", file=sys.stderr)
        return 1
    print("read-parity: green", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
