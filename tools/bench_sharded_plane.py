#!/usr/bin/env python
"""Sharded-control-plane churn throughput: N scheduler shards vs ONE.

The headline sharded bench (``sharded_churn_tick_ms``): the BASELINE
config-5 churn workload (200 distros / 50k tasks, ~200 finishes + ~100
fresh tasks per tick) is partitioned across N scheduler shards by the
production consistent-hash topology (parallel/topology.py), each shard
running in its OWN PROCESS — its own store, TickCache, resident plane
and tick loop, exactly the deployment shape of scheduler/sharded_plane.py
— against a single-shard plane carrying the same total load.

Two measurements, same methodology as the multichip dry-run bench
(tools/bench_sharded.py): on a shared-core CI box every worker contends
for the same cores, so the CONCURRENT wall is not the deployment number
— the deployment bound is the **dedicated-shard bound**, each shard
measured alone on the box (its own core/machine in production) with the
round gated by the SLOWEST shard:

  * ``throughput_ratio``   (headline) — aggregate churn throughput at
    equal total load from the dedicated bound:
    ``single_median_ms / max(per_shard_solo_median_ms)``;
  * ``throughput_ratio_observed`` — the contended wall-clock ratio on
    THIS box (approaches the headline as cores approach shards).

Per-shard solo medians also feed the per-shard perf floor
(tools/perf_guard.py) so one slow shard cannot hide inside an improved
aggregate.

    python tools/bench_sharded_plane.py [--shards 4] [--ticks 5]
        [--distros 200] [--tasks 50000]

Prints one JSON line; per-shard tables go to stderr. Workers are real
processes (one python + jax runtime each) — the actual deployment shape
of scheduler/sharded_plane.py: own store, own TickCache, own resident
plane, own tick loop.
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_DISTROS = 200
DEFAULT_TASKS = 50_000
DEFAULT_TICKS = 5
WARMUP_TICKS = 2
SEED = 3


# --------------------------------------------------------------------------- #
# worker: one scheduler shard in its own process
# --------------------------------------------------------------------------- #


def worker_main(args) -> int:
    from evergreen_tpu.utils.jaxenv import force_cpu

    force_cpu()
    import dataclasses
    import random

    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.parallel.topology import ShardTopology
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick
    from evergreen_tpu.storage.store import Store
    from evergreen_tpu.utils.benchgen import NOW, generate_problem
    from evergreen_tpu.utils.gctune import tune_gc_for_long_lived_heap

    distros, tbd, hbd, _, _ = generate_problem(
        args.distros, args.tasks, seed=SEED, task_group_fraction=0.25,
        patch_fraction=0.6, hosts_per_distro=25,
    )
    topo = ShardTopology(args.shards)
    mine = {
        d.id for d in distros if topo.shard_for(d.id) == args.worker
    }
    store = Store()
    store.shard_id = args.worker
    my_tasks = []
    for d in distros:
        if d.id not in mine:
            continue
        distro_mod.insert(store, d)
        my_tasks.extend(tbd[d.id])
        host_mod.insert_many(store, hbd[d.id])
    task_mod.insert_many(store, my_tasks)

    opts = TickOptions(create_intent_hosts=False, use_cache=True,
                       underwater_unschedule=False)
    rng = random.Random(args.worker)
    coll = task_mod.coll(store)
    finish_per_tick = max(1, 200 * len(mine) // max(args.distros, 1))
    fresh_per_tick = max(1, 100 * len(mine) // max(args.distros, 1))

    def churn(tick: int) -> None:
        for t in rng.sample(my_tasks, min(finish_per_tick, len(my_tasks))):
            coll.update(t.id, {"status": TaskStatus.SUCCEEDED.value})
        fresh = [
            dataclasses.replace(
                rng.choice(my_tasks), id=f"shard{args.worker}-c{tick}-{j}",
                depends_on=[],
            )
            for j in range(fresh_per_tick)
        ]
        task_mod.insert_many(store, fresh)

    run_tick(store, opts, now=NOW)  # compile + prime
    run_tick(store, opts, now=NOW + 0.01)  # absorb the stamp storm
    for w in range(WARMUP_TICKS):
        churn(-1 - w)
        run_tick(store, opts, now=NOW + 0.1 * (w + 1))
    tune_gc_for_long_lived_heap()

    print(json.dumps({"ready": args.worker, "n_tasks": len(my_tasks),
                      "n_distros": len(mine)}), flush=True)
    sys.stdin.readline()  # GO

    times = []
    for tick in range(args.ticks):
        churn(tick)
        t1 = time.perf_counter()
        run_tick(store, opts, now=NOW + 10.0 * (tick + 1))
        times.append((time.perf_counter() - t1) * 1e3)
    print(json.dumps({
        "worker": args.worker,
        "tick_ms": [round(t, 2) for t in times],
        "median_ms": round(statistics.median(times), 2),
        "n_tasks": len(my_tasks),
    }), flush=True)
    return 0


# --------------------------------------------------------------------------- #
# parent: one arm (N workers), then the ratio over both arms
# --------------------------------------------------------------------------- #


def _worker_cmd(k: int, n_shards: int, args) -> list:
    return [
        sys.executable, os.path.abspath(__file__), "--worker", str(k),
        "--shards", str(n_shards), "--ticks", str(args.ticks),
        "--distros", str(args.distros), "--tasks", str(args.tasks),
    ]


def _worker_env() -> dict:
    return {**os.environ, "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": ""}


def run_arm(n_shards: int, args, serial: bool = False) -> dict:
    """Launch one worker per shard. ``serial=False``: all workers run
    concurrently between a synchronized GO and the last DONE (the
    contended-wall number for THIS box). ``serial=True``: workers run
    one at a time, each alone on the box — the dedicated-shard
    measurement whose max-median bounds a production round."""
    env = _worker_env()
    reports = []
    wall_s = 0.0
    if serial:
        for k in range(n_shards):
            p = subprocess.Popen(
                _worker_cmd(k, n_shards, args), cwd=_REPO_ROOT, env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            )
            p.stdout.readline()  # READY
            p.stdin.write("GO\n")
            p.stdin.flush()
            reports.append(json.loads(p.stdout.readline()))
            p.wait(timeout=240)
        # a fleet round is gated by its slowest shard
        wall_s = max(r["median_ms"] for r in reports) * args.ticks / 1e3
    else:
        procs = []
        for k in range(n_shards):
            procs.append(subprocess.Popen(
                _worker_cmd(k, n_shards, args), cwd=_REPO_ROOT, env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            ))
        for p in procs:
            p.stdout.readline()  # READY
        t0 = time.perf_counter()
        for p in procs:
            p.stdin.write("GO\n")
            p.stdin.flush()
        for p in procs:
            reports.append(json.loads(p.stdout.readline()))
            p.wait(timeout=240)
        wall_s = time.perf_counter() - t0
    total_tasks = sum(r["n_tasks"] for r in reports)
    return {
        "n_shards": n_shards,
        "serial": serial,
        "wall_s": round(wall_s, 3),
        "per_shard_median_ms": [r["median_ms"] for r in reports],
        "per_shard_tasks": [r["n_tasks"] for r in reports],
        # tasks under management × ticks per wall second — the aggregate
        # churn-replan throughput of the whole plane
        "throughput_tasks_per_s": round(
            total_tasks * args.ticks / wall_s, 1
        ),
        "round_ms": round(wall_s * 1e3 / args.ticks, 2),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    p.add_argument("--distros", type=int, default=DEFAULT_DISTROS)
    p.add_argument("--tasks", type=int, default=DEFAULT_TASKS)
    p.add_argument("--worker", type=int, default=-1,
                   help="(internal) run as shard worker k")
    args = p.parse_args()
    if args.worker >= 0:
        return worker_main(args)

    single = run_arm(1, args)
    dedicated = run_arm(args.shards, args, serial=True)
    observed = run_arm(args.shards, args)
    # median tick vs median tick (the round is gated by the slowest
    # shard): both sides exclude the harness's churn-apply mutations
    single_median = single["per_shard_median_ms"][0]
    ratio = single_median / max(
        max(dedicated["per_shard_median_ms"]), 1e-9
    )
    ratio_obs = (
        observed["throughput_tasks_per_s"]
        / max(single["throughput_tasks_per_s"], 1e-9)
    )
    result = {
        "metric": "sharded_churn_tick_ms",
        "value": dedicated["round_ms"],
        "unit": "ms",
        "n_shards": args.shards,
        "n_distros": args.distros,
        "n_tasks": args.tasks,
        "ticks": args.ticks,
        "dedicated": dedicated,
        "observed": observed,
        "single": single,
        "single_churn_tick_ms": single_median,
        #: headline — dedicated-shard bound (slowest shard gates the
        #: round; each shard on its own core/machine in production)
        "throughput_ratio": round(ratio, 3),
        #: the contended wall-clock ratio on THIS box
        "throughput_ratio_observed": round(ratio_obs, 3),
        "cores": os.cpu_count(),
    }
    print(json.dumps(result))
    print(
        f"# {args.shards}-shard plane: dedicated round="
        f"{dedicated['round_ms']:.0f}ms "
        f"(per-shard solo medians={dedicated['per_shard_median_ms']}) "
        f"vs single-shard {single_median:.0f}ms -> aggregate churn "
        f"throughput x{ratio:.2f} dedicated / x{ratio_obs:.2f} observed "
        f"on {os.cpu_count()} cores (target >= 2.5 dedicated)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
