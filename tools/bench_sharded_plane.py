#!/usr/bin/env python
"""Sharded-control-plane churn throughput: N scheduler shards vs ONE.

The headline sharded bench (``sharded_churn_tick_ms``): the BASELINE
config-5 churn workload (200 distros / 50k tasks, ~200 finishes + ~100
fresh tasks per tick) is partitioned across N scheduler shards by the
production consistent-hash topology (parallel/topology.py), each shard
running in its OWN PROCESS — the **production shard worker entrypoint**
(``python -m evergreen_tpu.runtime.worker --bench``, the same binary
``service --shards N`` supervises; this harness used to carry a private
inline copy) — against a single-shard plane carrying the same total
load.

Two measurements, same methodology as the multichip dry-run bench
(tools/bench_sharded.py): on a shared-core CI box every worker contends
for the same cores, so the CONCURRENT wall is not the deployment number
— the deployment bound is the **dedicated-shard bound**, each shard
measured alone on the box (its own core/machine in production) with the
round gated by the SLOWEST shard:

  * ``throughput_ratio``   (headline) — aggregate churn throughput at
    equal total load from the dedicated bound:
    ``single_median_ms / max(per_shard_solo_median_ms)``;
  * ``throughput_ratio_observed`` — the contended wall-clock ratio on
    THIS box (approaches the headline as cores approach shards).

Per-shard solo medians also feed the per-shard perf floor
(tools/perf_guard.py) so one slow shard cannot hide inside an improved
aggregate.

    python tools/bench_sharded_plane.py [--shards 4] [--ticks 5]
        [--distros 200] [--tasks 50000]

Prints one JSON line; per-shard tables go to stderr. Workers are real
processes (one python + jax runtime each) speaking the fleet runtime's
newline-JSON protocol (runtime/protocol.py): the worker warms up,
reports ``ready``, waits for ``go``, runs the churned+timed ticks and
reports ``report`` — identical timing methodology to the pre-runtime
harness (``tick_ms`` measures ``run_tick`` wall time worker-side, churn
mutations excluded).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_DISTROS = 200
DEFAULT_TASKS = 50_000
DEFAULT_TICKS = 5
SEED = 3


# --------------------------------------------------------------------------- #
# parent: one arm (N production workers), then the ratio over both arms
# --------------------------------------------------------------------------- #


def _worker_cmd(k: int, n_shards: int, args) -> list:
    return [
        sys.executable, "-m", "evergreen_tpu.runtime.worker",
        "--bench", "--shard", str(k), "--shards", str(n_shards),
        "--bench-ticks", str(args.ticks),
        "--bench-distros", str(args.distros),
        "--bench-tasks", str(args.tasks),
        "--bench-seed", str(SEED),
    ]


def _worker_env() -> dict:
    return {**os.environ, "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": ""}


def _read_op(proc, op: str) -> dict:
    """Next protocol message with the given op (heartbeats and stray
    lines skipped — runtime/protocol.py parse_line semantics)."""
    from evergreen_tpu.runtime.protocol import parse_line

    while True:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(
                f"bench worker pipe closed waiting for {op!r} "
                f"(rc={proc.poll()})"
            )
        msg = parse_line(line)
        if msg is not None and msg["op"] == op:
            return msg


def run_arm(n_shards: int, args, serial: bool = False) -> dict:
    """Launch one worker per shard. ``serial=False``: all workers run
    concurrently between a synchronized GO and the last report (the
    contended-wall number for THIS box). ``serial=True``: workers run
    one at a time, each alone on the box — the dedicated-shard
    measurement whose max-median bounds a production round."""
    env = _worker_env()
    reports = []
    wall_s = 0.0
    if serial:
        for k in range(n_shards):
            p = subprocess.Popen(
                _worker_cmd(k, n_shards, args), cwd=_REPO_ROOT, env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            )
            _read_op(p, "ready")
            p.stdin.write('{"op":"go"}\n')
            p.stdin.flush()
            reports.append(_read_op(p, "report"))
            p.wait(timeout=240)
        # a fleet round is gated by its slowest shard
        wall_s = max(r["median_ms"] for r in reports) * args.ticks / 1e3
    else:
        procs = []
        for k in range(n_shards):
            procs.append(subprocess.Popen(
                _worker_cmd(k, n_shards, args), cwd=_REPO_ROOT, env=env,
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
            ))
        for p in procs:
            _read_op(p, "ready")
        t0 = time.perf_counter()
        for p in procs:
            p.stdin.write('{"op":"go"}\n')
            p.stdin.flush()
        for p in procs:
            reports.append(_read_op(p, "report"))
            p.wait(timeout=240)
        wall_s = time.perf_counter() - t0
    total_tasks = sum(r["n_tasks"] for r in reports)
    return {
        "n_shards": n_shards,
        "serial": serial,
        "wall_s": round(wall_s, 3),
        "per_shard_median_ms": [r["median_ms"] for r in reports],
        "per_shard_tasks": [r["n_tasks"] for r in reports],
        # tasks under management × ticks per wall second — the aggregate
        # churn-replan throughput of the whole plane
        "throughput_tasks_per_s": round(
            total_tasks * args.ticks / wall_s, 1
        ),
        "round_ms": round(wall_s * 1e3 / args.ticks, 2),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--ticks", type=int, default=DEFAULT_TICKS)
    p.add_argument("--distros", type=int, default=DEFAULT_DISTROS)
    p.add_argument("--tasks", type=int, default=DEFAULT_TASKS)
    args = p.parse_args()

    single = run_arm(1, args)
    dedicated = run_arm(args.shards, args, serial=True)
    observed = run_arm(args.shards, args)
    # median tick vs median tick (the round is gated by the slowest
    # shard): both sides exclude the harness's churn-apply mutations
    single_median = single["per_shard_median_ms"][0]
    ratio = single_median / max(
        max(dedicated["per_shard_median_ms"]), 1e-9
    )
    ratio_obs = (
        observed["throughput_tasks_per_s"]
        / max(single["throughput_tasks_per_s"], 1e-9)
    )
    result = {
        "metric": "sharded_churn_tick_ms",
        "value": dedicated["round_ms"],
        "unit": "ms",
        "n_shards": args.shards,
        "n_distros": args.distros,
        "n_tasks": args.tasks,
        "ticks": args.ticks,
        "dedicated": dedicated,
        "observed": observed,
        "single": single,
        "single_churn_tick_ms": single_median,
        #: headline — dedicated-shard bound (slowest shard gates the
        #: round; each shard on its own core/machine in production)
        "throughput_ratio": round(ratio, 3),
        #: the contended wall-clock ratio on THIS box
        "throughput_ratio_observed": round(ratio_obs, 3),
        "cores": os.cpu_count(),
    }
    print(json.dumps(result))
    print(
        f"# {args.shards}-shard plane: dedicated round="
        f"{dedicated['round_ms']:.0f}ms "
        f"(per-shard solo medians={dedicated['per_shard_median_ms']}) "
        f"vs single-shard {single_median:.0f}ms -> aggregate churn "
        f"throughput x{ratio:.2f} dedicated / x{ratio_obs:.2f} observed "
        f"on {os.cpu_count()} cores (target >= 2.5 dedicated)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
