#!/usr/bin/env python
"""Static lint for the metrics plane (ISSUE 7 satellite).

Walks every ``evergreen_tpu/**/*.py`` AST and enforces the instrument
registration contract that keeps ``/metrics`` scrape-able forever:

  * every instrument name is a **literal** snake_case string with a
    subsystem prefix from the known registry — no f-strings, no
    concatenation, no variables (a dynamic name is an unbounded series
    leak waiting to happen);
  * counters end ``_total``; duration histograms end ``_ms``;
  * labels are a literal tuple/list drawn from the **allowed
    vocabulary** (``utils/metrics.py ALLOWED_LABELS``; grown
    deliberately — e.g. ``pool``, the fixed provider-pool vocabulary of
    the capacity plane) — task ids, host ids, user ids can never become
    labels;
  * every name is registered **exactly once** across the tree (module
    scope registers on import; a second registration is a startup
    crash);
  * no new ``incr_counter(...)`` call sites outside ``utils/log.py`` /
    ``utils/metrics.py`` — the flat counter dict is a compatibility
    view now, fed only by the instruments' ``legacy`` mirrors.

Wired as ``make metrics-lint`` and run unconditionally by
``tools/gate.py`` (it is static and takes milliseconds).
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from evergreen_tpu.utils.metrics import ALLOWED_LABELS  # noqa: E402

PACKAGE_DIR = os.path.join(_REPO_ROOT, "evergreen_tpu")

#: the registration helpers (module-level attribute calls:
#: ``_metrics.counter(...)``) and the receivers they hang off
REG_FUNCS = {"counter", "gauge", "histogram"}
REG_RECEIVERS = re.compile(r"metrics")

NAME_RE = re.compile(r"^[a-z][a-z0-9]*(_[a-z0-9]+)+$")

#: subsystem prefixes instruments may claim (first name segment); grow
#: this list deliberately — a new prefix is a new dashboard namespace
SUBSYSTEMS = {
    "api", "arena", "breaker", "cloud", "config", "cron", "dispatch",
    "events", "faults", "hosts", "jobs", "lease", "outbox", "overload",
    "recovery", "replica", "resident", "retry", "scheduler", "tpu",
    "trace", "wal",
}

#: files allowed to touch the flat counter dict directly
INCR_COUNTER_ALLOWED = {
    os.path.join("evergreen_tpu", "utils", "log.py"),
    os.path.join("evergreen_tpu", "utils", "metrics.py"),
}


def _iter_py_files() -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(PACKAGE_DIR):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def _is_registration(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in REG_FUNCS:
        # receiver must look like a metrics module alias
        # (metrics / _metrics / metrics_mod); _Instrument subclasses
        # are constructed with CapWords names so they never match
        base = fn.value
        return isinstance(base, ast.Name) and bool(
            REG_RECEIVERS.search(base.id)
        )
    return False


def _literal_str(node) -> Tuple[bool, str]:
    """(is_literal, value). JoinedStr (f-string) and anything computed
    is not literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True, node.value
    return False, ""


def _labels_node(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "labels":
            return kw.value
    if len(call.args) >= 3:
        return call.args[2]
    return None


def lint() -> List[str]:
    violations: List[str] = []
    registered: Dict[str, str] = {}

    for path in _iter_py_files():
        rel = os.path.relpath(path, _REPO_ROOT)
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=rel)
        except SyntaxError as exc:
            violations.append(f"{rel}: unparseable: {exc}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            # rule: the flat dict is fed only through legacy mirrors
            fname = (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr if isinstance(node.func, ast.Attribute)
                else ""
            )
            if fname == "incr_counter" and rel not in INCR_COUNTER_ALLOWED:
                violations.append(
                    f"{rel}:{node.lineno}: direct incr_counter() call — "
                    "register a typed instrument in utils/metrics.py "
                    "terms and let its `legacy` mirror feed the flat dict"
                )
            if not _is_registration(node):
                continue
            kind = node.func.attr
            loc = f"{rel}:{node.lineno}"
            if not node.args:
                violations.append(f"{loc}: {kind}() with no name")
                continue
            ok, name = _literal_str(node.args[0])
            if not ok:
                violations.append(
                    f"{loc}: {kind}() name must be a literal string "
                    "(no f-strings, no concatenation, no variables)"
                )
                continue
            if not NAME_RE.match(name):
                violations.append(
                    f"{loc}: {name!r} is not snake_case with a "
                    "subsystem prefix"
                )
            else:
                prefix = name.split("_", 1)[0]
                if prefix not in SUBSYSTEMS:
                    violations.append(
                        f"{loc}: {name!r} claims unknown subsystem "
                        f"prefix {prefix!r} (known: "
                        f"{', '.join(sorted(SUBSYSTEMS))})"
                    )
            if kind == "counter" and not name.endswith("_total"):
                violations.append(
                    f"{loc}: counter {name!r} must end with _total"
                )
            if kind == "histogram" and not name.endswith("_ms"):
                violations.append(
                    f"{loc}: histogram {name!r} must end with _ms "
                    "(every duration histogram shares the ms bucket "
                    "vocabulary)"
                )
            # help string
            help_node = node.args[1] if len(node.args) >= 2 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"),
                None,
            )
            hval = ""
            if help_node is not None:
                # allow implicit adjacent-literal concatenation: the
                # parser folds it into one Constant already
                hok, hval = _literal_str(help_node)
            if help_node is None or not hval.strip():
                violations.append(
                    f"{loc}: {name!r} needs a non-empty literal help "
                    "string"
                )
            # per-shard instruments must carry the shard label: an
            # instrument observed once per shard (anything named
            # *_shard_* / shard_*) without a shard label silently FOLDS
            # every shard into one series — a shard regression then
            # hides inside an improved aggregate, exactly what the
            # sharded perf floor exists to prevent
            per_shard = "_shard_" in name or name.startswith("shard_")
            if per_shard:
                ln_chk = _labels_node(node)
                label_vals = []
                if isinstance(ln_chk, (ast.Tuple, ast.List)):
                    label_vals = [
                        _literal_str(el)[1] for el in ln_chk.elts
                    ]
                if "shard" not in label_vals:
                    violations.append(
                        f"{loc}: per-shard instrument {name!r} must "
                        "carry the 'shard' label (unlabeled per-shard "
                        "series fold every shard together)"
                    )
            # per-replica instruments likewise: a *_replica_* series
            # observed once per read replica without the 'replica'
            # label silently folds the whole replica fleet into one
            # series — a lagging replica then hides inside a healthy
            # aggregate
            per_replica = (
                "_replica_" in name or name.startswith("replica_")
            )
            if per_replica:
                ln_chk = _labels_node(node)
                label_vals = []
                if isinstance(ln_chk, (ast.Tuple, ast.List)):
                    label_vals = [
                        _literal_str(el)[1] for el in ln_chk.elts
                    ]
                if "replica" not in label_vals:
                    violations.append(
                        f"{loc}: per-replica instrument {name!r} must "
                        "carry the 'replica' label (unlabeled "
                        "per-replica series fold every replica "
                        "together)"
                    )
            # per-worker fleet instruments likewise (fleet runtime,
            # runtime/supervisor.py): a *_worker(s)_* series observed
            # once per shard worker without the 'shard' label folds
            # the whole fleet into one series — one crash-looping or
            # permanently-orphaned worker then hides inside a healthy
            # aggregate
            per_worker = "_worker_" in name or "_workers_" in name
            if per_worker:
                ln_chk = _labels_node(node)
                label_vals = []
                if isinstance(ln_chk, (ast.Tuple, ast.List)):
                    label_vals = [
                        _literal_str(el)[1] for el in ln_chk.elts
                    ]
                if "shard" not in label_vals:
                    violations.append(
                        f"{loc}: per-worker instrument {name!r} must "
                        "carry the 'shard' label (unlabeled per-"
                        "worker series fold the whole fleet together)"
                    )
            # labels
            ln = _labels_node(node)
            if ln is not None:
                if not isinstance(ln, (ast.Tuple, ast.List)):
                    violations.append(
                        f"{loc}: {name!r} labels must be a literal "
                        "tuple/list"
                    )
                else:
                    for el in ln.elts:
                        lok, lval = _literal_str(el)
                        if not lok:
                            violations.append(
                                f"{loc}: {name!r} has a non-literal "
                                "label"
                            )
                        elif lval not in ALLOWED_LABELS:
                            violations.append(
                                f"{loc}: {name!r} label {lval!r} is not "
                                "in the allowed vocabulary "
                                f"({', '.join(sorted(ALLOWED_LABELS))})"
                            )
            # registered exactly once (test-local registries pass
            # registry=..., which exempts them from the global-name rule)
            if any(kw.arg == "registry" for kw in node.keywords):
                continue
            prev = registered.get(name)
            if prev is not None:
                violations.append(
                    f"{loc}: {name!r} already registered at {prev}"
                )
            else:
                registered[name] = loc
    return violations


def main() -> int:
    violations = lint()
    if violations:
        print(f"metrics-lint: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print("metrics-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
