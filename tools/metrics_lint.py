#!/usr/bin/env python
"""Static lint for the metrics plane (ISSUE 7 satellite) — now a thin
alias over the evglint ``metrics`` pass (tools/evglint/passes/
metricscheck.py), where the rules moved verbatim when evglint
generalized this tool into a multi-pass framework (ISSUE 15).

CLI, output format, and exit semantics are preserved so ``make
metrics-lint`` and any scripting against it keep working:

  * every instrument name is a literal snake_case string with a known
    subsystem prefix; counters end ``_total``, histograms ``_ms``;
  * labels literal and from the allowed vocabulary; per-shard /
    per-replica / per-worker series carry their disaggregation label;
  * every name registered exactly once; no stray ``incr_counter``.
"""
from __future__ import annotations

import os
import sys
from typing import List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def lint() -> List[str]:
    from tools.evglint import core
    from tools.evglint.passes import metricscheck

    findings = core.run_passes([metricscheck], core.iter_modules())
    # metrics-pass findings plus the core parse errors the original
    # tool reported (a syntactically broken file must stay a failure
    # here, not just in the full evglint run)
    return [
        f"{f.rel}:{f.line}: {f.message}" for f in findings
        if f.passname == metricscheck.NAME
        or (f.passname == "core" and "unparseable" in f.message)
    ]


def main() -> int:
    violations = lint()
    if violations:
        print(f"metrics-lint: {len(violations)} violation(s)")
        for v in violations:
            print(f"  {v}")
        return 1
    print("metrics-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
