"""Dispatch-path scale benchmark: N threaded agents against a deep queue.

The reference budget: one next_task request should stay under the 1s
slow-path log threshold (rest/route/host_agent.go:103-110). This drives
``assign_next_available_task`` — the same code the REST route runs — from
many concurrent agent threads against one 50k-item distro queue and
reports per-call p50/p99 and throughput.

Usage: python tools/bench_dispatch.py [n_agents] [queue_len] [n_pulls]
"""
from __future__ import annotations

import json
import statistics
import sys
import threading
import time


def seed(store, queue_len: int, n_hosts: int, group_every: int = 0):
    from evergreen_tpu.globals import HostStatus, Provider, TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models import task_queue as tq_mod
    from evergreen_tpu.models.distro import Distro
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.models.task_queue import TaskQueueItem

    distro_mod.insert(store, Distro(id="d1", provider=Provider.MOCK.value))
    tasks, items = [], []
    for i in range(queue_len):
        tid = f"t{i}"
        in_group = group_every and i % group_every == 0
        group = f"g{i % 50}" if in_group else ""
        tasks.append(
            Task(
                id=tid, distro_id="d1", status=TaskStatus.UNDISPATCHED.value,
                activated=True, project="p", build_variant="bv",
                version=f"v{i % 20}", task_group=group,
                task_group_max_hosts=2 if group else 0,
                expected_duration_s=60.0,
            )
        )
        items.append(
            TaskQueueItem(
                id=tid, display_name=tid, project="p", build_variant="bv",
                version=f"v{i % 20}", task_group=group,
                task_group_max_hosts=2 if group else 0,
                task_group_order=i % 4 if group else 0,
                expected_duration_s=60.0, dependencies=[],
                dependencies_met=True,
            )
        )
    task_mod.coll(store).insert_many([t.to_doc() for t in tasks])
    tq_mod.save(
        store,
        tq_mod.TaskQueue(distro_id="d1", queue=items,
                         generated_at=time.time()),
    )
    hosts = [
        Host(
            id=f"h{i}", distro_id="d1", provider=Provider.MOCK.value,
            status=HostStatus.RUNNING.value,
        )
        for i in range(n_hosts)
    ]
    host_mod.insert_many(store, hosts)
    return hosts


def run_bench(n_agents: int = 200, queue_len: int = 50_000,
              pulls_per_agent: int = 250, group_every: int = 10):
    """Defaults fully drain the queue (200 × 250 = 50k pulls) so the
    published numbers are what `python tools/bench_dispatch.py`
    reproduces."""
    from evergreen_tpu.dispatch.assign import assign_next_available_task
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.storage.store import reset_global_store

    store = reset_global_store()
    hosts = seed(store, queue_len, n_agents, group_every)
    svc = DispatcherService(store)
    # pre-warm the dispatcher rebuild (the TTL cache the reference also
    # pays once per rebuild, not per request) but measure it separately
    t0 = time.perf_counter()
    svc.get("d1").refresh(force=True)
    rebuild_ms = (time.perf_counter() - t0) * 1e3

    latencies: list = []
    lat_lock = threading.Lock()
    assigned = [0]

    def agent(h):
        mine = []
        for _ in range(pulls_per_agent):
            fresh = host_mod.get(store, h.id)
            t0 = time.perf_counter()
            t = assign_next_available_task(store, svc, fresh)
            dt = (time.perf_counter() - t0) * 1e3
            mine.append(dt)
            if t is None:
                continue
            # simulate instant task completion so the host frees up, the
            # way a fast agent would between pulls
            from evergreen_tpu.models.lifecycle import mark_task_started

            mark_task_started(store, t.id)
            host_mod.clear_running_task(store, h.id, t.id, time.time())
            with lat_lock:
                assigned[0] += 1
        with lat_lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=agent, args=(h,)) for h in hosts]
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - wall0

    latencies.sort()
    qs = statistics.quantiles(latencies, n=100)
    out = {
        "n_agents": n_agents,
        "queue_len": queue_len,
        "pulls": len(latencies),
        "assigned": assigned[0],
        "rebuild_ms": round(rebuild_ms, 1),
        "p50_ms": round(qs[49], 2),
        "p90_ms": round(qs[89], 2),
        "p99_ms": round(qs[98], 2),
        "max_ms": round(latencies[-1], 2),
        "wall_s": round(wall_s, 2),
        "pulls_per_s": round(len(latencies) / wall_s, 0),
        "budget_ms": 1000.0,
    }
    return out


if __name__ == "__main__":
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    q = int(sys.argv[2]) if len(sys.argv) > 2 else 50_000
    p = int(sys.argv[3]) if len(sys.argv) > 3 else 250
    print(json.dumps(run_bench(n, q, p)))
