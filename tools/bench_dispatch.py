"""Dispatch-path scale benchmark: N threaded agents against a deep queue.

The reference budget: one next_task request should stay under the 1s
slow-path log threshold (rest/route/host_agent.go:103-110). This drives
``assign_next_available_task`` — the same code the REST route runs — from
many concurrent agent threads against one 50k-item distro queue and
reports per-call p50/p99 and throughput.

Two arms (ISSUE 11):

* ``run_bench`` — the classic full-drain hammer: every agent pulls in a
  tight loop until the queue drains. Measures raw handout throughput.
* ``run_soak`` — the 10k-agent deployment shape: agents OUTNUMBER work,
  so idle agents park on the sharded long-poll hub
  (dispatch/longpoll.py) and a feeder lands work in waves (a persisted
  queue doc + a bounded wake, the same signals the persister and
  dependency wake emit). Measures the latency of the pull itself —
  parked time is the design, not the cost — and audits that no task is
  ever handed out twice.

Usage: python tools/bench_dispatch.py [n_agents] [queue_len] [n_pulls]
       python tools/bench_dispatch.py --soak [n_agents]
"""
from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def seed(store, queue_len: int, n_hosts: int, group_every: int = 0):
    from evergreen_tpu.globals import HostStatus, Provider, TaskStatus
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models import task_queue as tq_mod
    from evergreen_tpu.models.distro import Distro
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.models.task_queue import TaskQueueItem

    distro_mod.insert(store, Distro(id="d1", provider=Provider.MOCK.value))
    tasks, items = [], []
    for i in range(queue_len):
        tid = f"t{i}"
        in_group = group_every and i % group_every == 0
        group = f"g{i % 50}" if in_group else ""
        tasks.append(
            Task(
                id=tid, distro_id="d1", status=TaskStatus.UNDISPATCHED.value,
                activated=True, project="p", build_variant="bv",
                version=f"v{i % 20}", task_group=group,
                task_group_max_hosts=2 if group else 0,
                expected_duration_s=60.0,
            )
        )
        items.append(
            TaskQueueItem(
                id=tid, display_name=tid, project="p", build_variant="bv",
                version=f"v{i % 20}", task_group=group,
                task_group_max_hosts=2 if group else 0,
                task_group_order=i % 4 if group else 0,
                expected_duration_s=60.0, dependencies=[],
                dependencies_met=True,
            )
        )
    task_mod.coll(store).insert_many([t.to_doc() for t in tasks])
    tq_mod.save(
        store,
        tq_mod.TaskQueue(distro_id="d1", queue=items,
                         generated_at=time.time()),
    )
    hosts = [
        Host(
            id=f"h{i}", distro_id="d1", provider=Provider.MOCK.value,
            status=HostStatus.RUNNING.value,
        )
        for i in range(n_hosts)
    ]
    host_mod.insert_many(store, hosts)
    return hosts


def run_bench(n_agents: int = 200, queue_len: int = 50_000,
              pulls_per_agent: int = 250, group_every: int = 10):
    """Defaults fully drain the queue (200 × 250 = 50k pulls) so the
    published numbers are what `python tools/bench_dispatch.py`
    reproduces."""
    from evergreen_tpu.dispatch.assign import assign_next_available_task
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.storage.store import reset_global_store

    store = reset_global_store()
    hosts = seed(store, queue_len, n_agents, group_every)
    svc = DispatcherService(store)
    # pre-warm the dispatcher rebuild (the TTL cache the reference also
    # pays once per rebuild, not per request) but measure it separately
    t0 = time.perf_counter()
    svc.get("d1").refresh(force=True)
    rebuild_ms = (time.perf_counter() - t0) * 1e3

    latencies: list = []
    lat_lock = threading.Lock()
    assigned = [0]

    def agent(h):
        mine = []
        for _ in range(pulls_per_agent):
            fresh = host_mod.get(store, h.id)
            t0 = time.perf_counter()
            t = assign_next_available_task(store, svc, fresh)
            dt = (time.perf_counter() - t0) * 1e3
            mine.append(dt)
            if t is None:
                continue
            # simulate instant task completion so the host frees up, the
            # way a fast agent would between pulls
            from evergreen_tpu.models.lifecycle import mark_task_started

            mark_task_started(store, t.id)
            host_mod.clear_running_task(store, h.id, t.id, time.time())
            with lat_lock:
                assigned[0] += 1
        with lat_lock:
            latencies.extend(mine)

    threads = [threading.Thread(target=agent, args=(h,)) for h in hosts]
    wall0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - wall0

    latencies.sort()
    qs = statistics.quantiles(latencies, n=100)
    out = {
        "n_agents": n_agents,
        "queue_len": queue_len,
        "pulls": len(latencies),
        "assigned": assigned[0],
        "rebuild_ms": round(rebuild_ms, 1),
        "p50_ms": round(qs[49], 2),
        "p90_ms": round(qs[89], 2),
        "p99_ms": round(qs[98], 2),
        "max_ms": round(latencies[-1], 2),
        "wall_s": round(wall_s, 2),
        "pulls_per_s": round(len(latencies) / wall_s, 0),
        "budget_ms": 1000.0,
    }
    return out


def run_soak(
    n_agents: int = 10_000,
    waves: int = 8,
    wave_size: int = 500,
    wait_s: float = 120.0,
    wave_timeout_s: float = 30.0,
    group_every: int = 0,
):
    """The 10k-agent long-poll soak: agents outnumber work, park on the
    hub, and a feeder lands ``waves`` queue docs of ``wave_size`` fresh
    tasks (persist → generation bump → bounded wake — the production
    arrival signals). Reports p50/p99 over every TIMED pull (empty
    wake-pulls included; parked time excluded — parking is the design)
    and audits zero duplicate dispatch."""
    from evergreen_tpu.dispatch.assign import assign_next_available_task
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.dispatch.longpoll import hub_for
    from evergreen_tpu.globals import TaskStatus
    from evergreen_tpu.models import host as host_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models import task_queue as tq_mod
    from evergreen_tpu.models.lifecycle import mark_task_started
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.models.task_queue import TaskQueueItem
    from evergreen_tpu.storage.store import reset_global_store

    store = reset_global_store()
    hosts = seed(store, 0, n_agents, group_every=0)
    svc = DispatcherService(store)
    hub = hub_for(store)
    svc.get("d1").refresh(force=True)

    stop = threading.Event()
    #: latency recording starts only once the fleet is parked — the
    #: thread-creation storm's GIL churn is a bench artifact, not a
    #: dispatch cost (a real fleet connects over minutes)
    measuring = threading.Event()
    merge_lock = threading.Lock()
    latencies: list = []
    taken: list = []
    outstanding = [0]

    def agent(h):
        my_lat: list = []
        mine: list = []
        while not stop.is_set():
            gen = hub.generation("d1")
            fresh = host_mod.get(store, h.id)
            t0 = time.perf_counter()
            t = assign_next_available_task(store, svc, fresh)
            if measuring.is_set():
                my_lat.append((time.perf_counter() - t0) * 1e3)
            if t is not None:
                mine.append(t.id)
                mark_task_started(store, t.id)
                host_mod.clear_running_task(store, h.id, t.id, time.time())
                with merge_lock:
                    outstanding[0] -= 1
                continue
            hub.wait("d1", h.id, gen, wait_s)
        with merge_lock:
            latencies.extend(my_lat)
            taken.extend(mine)

    # 10k OS threads: shrink stacks so virtual footprint stays modest
    prev_stack = threading.stack_size()
    try:
        threading.stack_size(256 * 1024)
    except (ValueError, RuntimeError):
        pass
    threads = [threading.Thread(target=agent, args=(h,), daemon=True)
               for h in hosts]
    try:
        threading.stack_size(prev_stack or 0)
    except (ValueError, RuntimeError):
        pass
    spawn0 = time.perf_counter()
    for t in threads:
        t.start()
    # barrier: wait for the WHOLE fleet to take its first (empty) pull
    # and park, so the waves measure the steady parked shape, not the
    # thread-creation storm (a few hundred threads still spawning on a
    # small box stall a wave's herd for seconds)
    spawn_deadline = time.monotonic() + 180.0
    while time.monotonic() < spawn_deadline:
        if hub.waiters >= n_agents:
            break
        time.sleep(0.02)
    spawn_s = time.perf_counter() - spawn0

    measuring.set()

    wall0 = time.perf_counter()
    fed = 0
    stalled = False
    for w in range(waves):
        items, tasks = [], []
        for j in range(wave_size):
            tid = f"soak-{w}-{j}"
            in_group = group_every and j % group_every == 0
            group = f"sg{j % 20}" if in_group else ""
            tasks.append(Task(
                id=tid, distro_id="d1",
                status=TaskStatus.UNDISPATCHED.value, activated=True,
                project="p", build_variant="bv", version=f"sv{w}",
                task_group=group, task_group_max_hosts=2 if group else 0,
                expected_duration_s=60.0,
            ))
            items.append(TaskQueueItem(
                id=tid, display_name=tid, project="p",
                build_variant="bv", version=f"sv{w}", task_group=group,
                task_group_max_hosts=2 if group else 0,
                task_group_order=j % 4 if group else 0,
                expected_duration_s=60.0, dependencies=[],
                dependencies_met=True,
            ))
        task_mod.coll(store).insert_many([t.to_doc() for t in tasks])
        with merge_lock:
            outstanding[0] += wave_size
        # the production arrival signal pair: persist the plan (the
        # collection listener bumps the hub generation) then a BOUNDED
        # wake sized to the work that landed
        tq_mod.save(store, tq_mod.TaskQueue(
            distro_id="d1", queue=items, generated_at=time.time(),
        ))
        w0 = time.monotonic()
        hub.notify("d1", n_hint=wave_size)
        fed += wave_size
        deadline = time.monotonic() + wave_timeout_s
        while time.monotonic() < deadline:
            with merge_lock:
                if outstanding[0] <= 0:
                    break
            time.sleep(0.005)
        else:
            stalled = True
            break
        if os.environ.get("EVERGREEN_TPU_SOAK_DEBUG"):
            print(
                f"# soak wave {w}: drain "
                f"{(time.monotonic() - w0) * 1e3:.0f}ms "
                f"pending {hub.pending('d1')} waiters {hub.waiters}",
                file=sys.stderr, flush=True,
            )
        # let the fleet park between waves: arrivals are bursty in
        # production (a tick lands a plan every cadence), and
        # back-to-back waves would measure a permanent convoy instead
        time.sleep(0.1)
    wall_s = time.perf_counter() - wall0

    stop.set()
    # release loop, not a single wake: an agent that sampled its
    # generation just before this notify parks anyway and would sit out
    # its full long-poll timeout — keep waking until the hub is empty
    join_deadline = time.monotonic() + 90.0
    while hub.waiters and time.monotonic() < join_deadline:
        hub.notify("d1")
        time.sleep(0.05)
    for t in threads:
        t.join(timeout=max(0.1, join_deadline - time.monotonic()))

    latencies.sort()
    if len(latencies) >= 100:
        qs = statistics.quantiles(latencies, n=100)
        p50, p90, p99 = qs[49], qs[89], qs[98]
    else:
        p50 = p90 = p99 = latencies[-1] if latencies else 0.0
    dupes = len(taken) - len(set(taken))
    return {
        "n_agents": n_agents,
        "waves": waves,
        "wave_size": wave_size,
        "fed": fed,
        "assigned": len(taken),
        "duplicates": dupes,
        "stalled": stalled,
        "pulls": len(latencies),
        "p50_ms": round(p50, 2),
        "p90_ms": round(p90, 2),
        "p99_ms": round(p99, 2),
        "max_ms": round(latencies[-1], 2) if latencies else 0.0,
        "spawn_s": round(spawn_s, 2),
        "wall_s": round(wall_s, 2),
        "budget_ms": 100.0,
    }


def read_path_dispatch_section(
    quick: bool = False,
) -> dict:
    """The ``read_path`` bench payload's dispatch half: the long-poll
    soak at 1k and (unless ``quick``) 10k agents. Shared by bench.py,
    tools/perf_guard.py and tools/read_parity.py so every consumer
    reports the same shape. Wave sizing matches the herd a 1-core CI
    box can serialize inside the 100ms pull budget — the arrival BURST
    bounds the woken cohort, the parked fleet size does not."""
    out = {}
    soak_1k = run_soak(n_agents=1_000, waves=8, wave_size=250)
    out["soak_1k"] = soak_1k
    out["dispatch_p99_1k_ms"] = soak_1k["p99_ms"]
    if not quick:
        soak_10k = run_soak(n_agents=10_000, waves=8, wave_size=100)
        out["soak_10k"] = soak_10k
        out["dispatch_p99_10k_ms"] = soak_10k["p99_ms"]
        out["dispatch_duplicates"] = (
            soak_1k["duplicates"] + soak_10k["duplicates"]
        )
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    if len(sys.argv) > 1 and sys.argv[1] == "--soak":
        n = int(sys.argv[2]) if len(sys.argv) > 2 else 10_000
        print(json.dumps(run_soak(n_agents=n)))
        sys.exit(0)
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    q = int(sys.argv[2]) if len(sys.argv) > 2 else 50_000
    p = int(sys.argv[3]) if len(sys.argv) > 3 else 250
    print(json.dumps(run_bench(n, q, p)))
