#!/usr/bin/env python
"""Trace-driven scenario engine CLI: replay every weather, emit the
scorecard, diff it against the last green run.

    python tools/scenario_engine.py                 # full suite
    python tools/scenario_engine.py --scenario seasonality
    python tools/scenario_engine.py --check-determinism
    python tools/scenario_engine.py --diff          # vs SCORECARD_GREEN
    python tools/scenario_engine.py --write-green   # refresh baseline
    python tools/scenario_engine.py --sabotage      # self-test: expect RED

The suite lives in evergreen_tpu/scenarios/ (library.py — six weathers —
plus the migrated fault/overload matrix cases). Each run writes
``SCORECARD.json`` (per-scenario pass/fail, invariant verdicts, SLO
margins, degradation-level dwell times, shed/retry/fallback counters).

``--diff`` compares against ``SCORECARD_GREEN.json`` (the tracked
last-green baseline) and fails on *graceful-degradation regressions*,
not on any change:

  * a scenario, invariant, check, or SLO that was green going red
  * an SLO margin collapsing below half its green headroom (and under
    0.25 absolute)
  * RED+BLACK dwell growing beyond 1.5x + 2 ticks of the baseline
  * total sheds growing beyond 2x + 10 of the baseline
  * a previously-scored scenario disappearing

``tools/gate.py --scenarios`` runs this with --check-determinism and
--diff, and refreshes SCORECARD_GREEN.json after a green run — so a
regression in how the system degrades fails CI the same way a perf
regression does.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

SCORECARD_PATH = os.path.join(_REPO_ROOT, "SCORECARD.json")
GREEN_PATH = os.path.join(_REPO_ROOT, "SCORECARD_GREEN.json")

#: diff tolerances (see module docstring) — deliberate constants, not
#: knobs: loosening them is a reviewed change
MARGIN_COLLAPSE_RATIO = 0.5
MARGIN_FLOOR = 0.25
DWELL_RATIO, DWELL_SLACK = 1.5, 2
SHED_RATIO, SHED_SLACK = 2.0, 10


def _force_cpu() -> None:
    from evergreen_tpu.utils.jaxenv import force_cpu

    force_cpu(n_devices=1)


def run_suite(
    names: Optional[List[str]] = None,
    include_matrix: bool = True,
    check_determinism: bool = False,
) -> Dict:
    """Run the scenario suite; returns the scorecard document."""
    from evergreen_tpu.scenarios import (
        FAULT_SCENARIO_CASES,
        OVERLOAD_SCENARIO_CASES,
        SCENARIOS,
        run_matrix_case,
        run_scenario,
    )
    from evergreen_tpu.scenarios.trace import load_regression_specs

    # fuzz-found minimal timelines checked in under
    # scenarios/regressions/ replay alongside the shipped weathers —
    # once a bug is found and fixed, its timeline stays in the suite
    suite = dict(SCENARIOS)
    suite.update(load_regression_specs())

    entries: Dict[str, dict] = {}
    for name, factory in suite.items():
        if names and name not in names:
            continue
        entry = run_scenario(factory())
        if check_determinism and entry["deterministic"]:
            replay = run_scenario(factory())
            if replay["fingerprint"] != entry["fingerprint"]:
                entry["ok"] = False
                entry.setdefault("invariants", {})["same_seed_same_scorecard"] = {
                    "ok": False,
                    "detail": (
                        f"replay fingerprint {replay['fingerprint']} != "
                        f"{entry['fingerprint']}"
                    ),
                }
            else:
                entry.setdefault("invariants", {})["same_seed_same_scorecard"] = {
                    "ok": True, "detail": "",
                }
        entries[name] = entry
        print(json.dumps({
            "scenario": name, "ok": entry["ok"],
            "dwell": entry["dwell_ticks"],
            "wall_ms": entry["timing"]["wall_ms"],
        }))
    if include_matrix and not names:
        for name in sorted(FAULT_SCENARIO_CASES):
            out = run_matrix_case("fault", name, 0)
            entries[out["entry"]["name"]] = out["entry"]
            print(json.dumps(
                {"scenario": out["entry"]["name"], "ok": out["ok"]}
            ))
        for name in sorted(OVERLOAD_SCENARIO_CASES):
            out = run_matrix_case("overload", name, 0)
            entries[out["entry"]["name"]] = out["entry"]
            print(json.dumps(
                {"scenario": out["entry"]["name"], "ok": out["ok"]}
            ))
    return {
        "schema": 1,
        # an empty run is NOT green: all() over nothing would pass a
        # suite that never executed
        "ok": bool(entries) and all(e["ok"] for e in entries.values()),
        "scenarios": entries,
    }


def _dwell_hot(entry: dict) -> int:
    dwell = entry.get("dwell_ticks", {})
    return int(dwell.get("red", 0)) + int(dwell.get("black", 0))


def _sheds(entry: dict) -> int:
    return int(entry.get("stats", {}).get("sheds_total", 0))


def diff_scorecards(new: Dict, green: Dict) -> List[str]:
    """Regressions of NEW relative to GREEN (empty = clean)."""
    regressions: List[str] = []
    green_scen = green.get("scenarios", {})
    new_scen = new.get("scenarios", {})
    for name, g in green_scen.items():
        n = new_scen.get(name)
        if n is None:
            regressions.append(f"{name}: scenario disappeared")
            continue
        if g.get("ok") and not n.get("ok"):
            regressions.append(f"{name}: was green, now red")
        for section in ("invariants", "checks"):
            for key, gv in g.get(section, {}).items():
                nv = n.get(section, {}).get(key)
                if gv.get("ok") and nv is not None and not nv.get("ok"):
                    regressions.append(
                        f"{name}: {section[:-1]} {key} regressed "
                        f"({nv.get('detail', '')})"
                    )
        for key, gv in g.get("slos", {}).items():
            nv = n.get("slos", {}).get(key)
            if nv is None:
                continue
            if gv.get("ok") and not nv.get("ok"):
                regressions.append(f"{name}: SLO {key} regressed")
                continue
            gm, nm = gv.get("margin", 0.0), nv.get("margin", 0.0)
            if (
                gm > 0
                and nm < gm * MARGIN_COLLAPSE_RATIO
                and nm < MARGIN_FLOOR
            ):
                regressions.append(
                    f"{name}: SLO {key} margin collapsed "
                    f"{gm:.3f} -> {nm:.3f}"
                )
        g_hot, n_hot = _dwell_hot(g), _dwell_hot(n)
        if n_hot > g_hot * DWELL_RATIO + DWELL_SLACK:
            regressions.append(
                f"{name}: RED+BLACK dwell grew {g_hot} -> {n_hot} ticks"
            )
        g_shed, n_shed = _sheds(g), _sheds(n)
        if n_shed > g_shed * SHED_RATIO + SHED_SLACK:
            regressions.append(
                f"{name}: sheds grew {g_shed} -> {n_shed}"
            )
    return regressions


def run_sabotage() -> int:
    """Self-test: the deliberately-broken specs must score RED — proving
    an invariant violation fails the gate rather than sliding through."""
    from evergreen_tpu.scenarios import SABOTAGE_SCENARIOS, run_scenario

    rc = 0
    for name, factory in SABOTAGE_SCENARIOS.items():
        entry = run_scenario(factory())
        caught = not entry["ok"]
        print(json.dumps({"sabotage": name, "caught": caught}))
        if not caught:
            print(
                f"sabotage case {name} was NOT caught — the invariant "
                "layer is broken", file=sys.stderr,
            )
            rc = 1
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="",
                   help="run one scenario only (skips the matrix cases)")
    p.add_argument("--check-determinism", action="store_true",
                   help="replay each deterministic scenario and require "
                        "an identical scorecard fingerprint")
    p.add_argument("--diff", action="store_true",
                   help="fail on regressions vs SCORECARD_GREEN.json")
    p.add_argument("--write-green", action="store_true",
                   help="refresh SCORECARD_GREEN.json from this run "
                        "(only when the run itself is green)")
    p.add_argument("--no-matrix", action="store_true",
                   help="skip the migrated fault/overload matrix cases")
    p.add_argument("--sabotage", action="store_true",
                   help="run the deliberately-red self-test specs and "
                        "require they are caught")
    p.add_argument("--scorecard", default=SCORECARD_PATH)
    args = p.parse_args(argv)

    _force_cpu()
    if args.sabotage:
        return run_sabotage()

    names = [args.scenario] if args.scenario else None
    if names:
        from evergreen_tpu.scenarios import SCENARIOS
        from evergreen_tpu.scenarios.trace import load_regression_specs

        known = set(SCENARIOS) | set(load_regression_specs())
        unknown = [n for n in names if n not in known]
        if unknown:
            # a typo must never read as "scenario passed" (or worse,
            # --write-green an empty baseline that defuses every diff)
            print(
                f"unknown scenario(s) {unknown}; known: "
                f"{sorted(known)}", file=sys.stderr,
            )
            return 2
    scorecard = run_suite(
        names=names,
        include_matrix=not args.no_matrix,
        check_determinism=args.check_determinism,
    )
    with open(args.scorecard, "w") as f:
        json.dump(scorecard, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    rc = 0 if scorecard["ok"] else 1
    if rc:
        failed = [
            n for n, e in scorecard["scenarios"].items() if not e["ok"]
        ]
        print(f"scenarios RED: {failed}", file=sys.stderr)
    if args.diff and os.path.exists(GREEN_PATH):
        with open(GREEN_PATH) as f:
            green = json.load(f)
        regressions = diff_scorecards(scorecard, green)
        for r in regressions:
            print(f"scorecard regression: {r}", file=sys.stderr)
        if regressions:
            rc = rc or 2
    if args.write_green and rc == 0:
        with open(GREEN_PATH, "w") as f:
            json.dump(scorecard, f, indent=1, sort_keys=True, default=str)
            f.write("\n")
        print(f"refreshed {os.path.basename(GREEN_PATH)}")
    print(json.dumps({"scenarios_ok": rc == 0}))
    return rc


if __name__ == "__main__":
    sys.exit(main())
