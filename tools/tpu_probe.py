#!/usr/bin/env python
"""Background TPU-tunnel prober (VERDICT r3 ask #3a).

Probes the axon TPU tunnel on an interval, appends every attempt to
TPU_PROBE_LOG.jsonl at the repo root, and on the FIRST healthy window
runs the real benchmark on the TPU and snapshots the proof to
TPU_EVIDENCE.json (via bench.py's own evidence writer).

    python tools/tpu_probe.py                # daemon, probe every 180s
    python tools/tpu_probe.py --once         # single probe, exit 0/1
    python tools/tpu_probe.py --interval 60  # custom cadence
    python tools/tpu_probe.py --once --backend gpu   # probe CUDA instead

``--once`` prints the failure cause (the bounded taxonomy from
jaxenv.probe_tpu_detail: cpu-pinned / no-pool-ips / timeout /
backend-error / spawn-error) plus a cause histogram + failure streak
over the log tail, so one invocation answers both "is it down" and
"what has it been dying of". ``--backend gpu`` is the escape hatch for
boxes whose accelerator is NOT behind the axon tunnel: it probes the
CUDA backend directly with the same taxonomy.

The service entry point (`cli.py service`) starts this loop in a daemon
thread so a long-running deployment captures evidence whenever the
tunnel first comes up — no operator action needed.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(ROOT, "TPU_PROBE_LOG.jsonl")
EVIDENCE = os.path.join(ROOT, "TPU_EVIDENCE.json")

if ROOT not in sys.path:
    sys.path.append(ROOT)


def _log(record: dict) -> None:
    record["t"] = round(time.time(), 1)
    with open(LOG, "a") as f:
        f.write(json.dumps(record) + "\n")


def _probe_env() -> dict:
    """Env for probe/capture subprocesses: undo a force_cpu scrub (it
    blanks PALLAS_AXON_POOL_IPS in THIS process but stashes the original
    in EVG_AXON_POOL_IPS_ORIG) so the prober keeps testing the tunnel
    even after the service fell back to CPU at boot."""
    env = dict(os.environ)
    if not env.get("PALLAS_AXON_POOL_IPS") and env.get(
        "EVG_AXON_POOL_IPS_ORIG"
    ):
        env["PALLAS_AXON_POOL_IPS"] = env["EVG_AXON_POOL_IPS_ORIG"]
    env.pop("JAX_PLATFORMS", None)  # let the axon backend win
    return env


def probe_once(timeout_s: float = 45.0, backend: str = "axon") -> bool:
    return probe_once_detail(timeout_s, backend)[0]


def probe_once_detail(
    timeout_s: float = 45.0, backend: str = "axon"
) -> tuple:
    from evergreen_tpu.utils.jaxenv import (
        probe_backend_detail,
        probe_tpu_detail,
    )

    if backend == "axon":
        ok, reason = probe_tpu_detail(timeout_s, env=_probe_env())
    else:
        ok, reason = probe_backend_detail(
            backend, timeout_s, env=_probe_env()
        )
    rec = {"event": "probe", "ok": ok, "reason": reason}
    if backend != "axon":
        rec["backend"] = backend
    _log(rec)
    return ok, reason


def probe_log_summary(tail_records: int = 200) -> dict:
    """Cause histogram + failure streak over the log tail: the
    cross-run answer to "what has the tunnel been dying of". Same
    bounded-tail discipline as jaxenv.refresh_probe_metrics_from_log."""
    from evergreen_tpu.utils.jaxenv import probe_cause

    try:
        with open(LOG, "rb") as fh:
            fh.seek(0, os.SEEK_END)
            size = fh.tell()
            fh.seek(max(0, size - 64 * 1024))
            lines = fh.read().decode("utf-8", errors="replace").splitlines()
        if size > 64 * 1024 and lines:
            lines = lines[1:]  # drop the possibly-torn partial
    except OSError:
        return {"attempts": 0, "causes": {}, "failure_streak": 0}
    records = []
    for line in lines[-tail_records:]:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("event") == "probe":
            records.append(rec)
    causes: dict = {}
    for rec in records:
        cause = "ok" if rec.get("ok") else probe_cause(
            rec.get("reason", "")
        )
        causes[cause] = causes.get(cause, 0) + 1
    streak = 0
    for rec in reversed(records):
        if rec.get("ok"):
            break
        streak += 1
    return {
        "attempts": len(records),
        "causes": causes,
        "failure_streak": streak,
    }


def capture_evidence(timeout_s: float = 1800.0) -> bool:
    """Run the full benchmark in a fresh process on the live tunnel;
    bench.py writes TPU_EVIDENCE.json itself when the backend is axon.
    A healthy window also runs the single-chip dryrun compile check on
    the REAL device (VERDICT r4 ask #9: the first window must yield
    both a perf number and an on-device compile proof)."""
    _log({"event": "capture_start"})
    env = _probe_env()
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            timeout=timeout_s, capture_output=True, env=env, text=True,
        )
    except (subprocess.TimeoutExpired, OSError) as e:
        _log({"event": "capture_failed", "error": str(e)[:200]})
        return False
    ok = r.returncode == 0 and os.path.exists(EVIDENCE)
    _log({
        "event": "capture_done", "ok": ok, "rc": r.returncode,
        "stdout": r.stdout.strip()[-500:],
        "stderr": r.stderr.strip()[-500:],
    })
    # on-device compile proof: jit the flagship solve via entry() on the
    # tunnel backend (separate process; bounded tighter than the bench —
    # capture must not block the daemon for 2x the nominal timeout)
    dryrun_ok = False
    try:
        r2 = subprocess.run(
            [
                sys.executable, "-c",
                "import __graft_entry__ as g, jax; "
                "fn, args = g.entry(); "
                "out = jax.jit(fn)(*args); jax.block_until_ready(out); "
                "print('devices:', jax.devices())",
            ],
            timeout=min(600.0, timeout_s), capture_output=True, env=env,
            text=True, cwd=ROOT,
        )
        dryrun_ok = r2.returncode == 0
        _log({
            "event": "tpu_dryrun_done", "ok": dryrun_ok,
            "rc": r2.returncode,
            "stdout": r2.stdout.strip()[-300:],
            "stderr": r2.stderr.strip()[-300:],
        })
    except (subprocess.TimeoutExpired, OSError) as e:
        _log({"event": "tpu_dryrun_failed", "error": str(e)[:200]})
    # a window only counts as fully captured when BOTH artifacts exist;
    # a failed compile proof retries on the shorter backoff
    return ok and dryrun_ok


def daemon_loop(interval_s: float = 180.0) -> None:
    """Probe forever; capture bench evidence on the first healthy window
    (re-capture at most once per day after a success, and back off an
    hour after a failed capture — a flappy tunnel must not relaunch the
    full benchmark every probe interval)."""
    next_capture_after = 0.0
    while True:
        try:
            if probe_once() and time.time() >= next_capture_after:
                ok = capture_evidence()
                next_capture_after = time.time() + (86_400 if ok else 3_600)
        except Exception as e:  # noqa: BLE001 — the prober must survive
            _log({"event": "probe_error", "error": repr(e)[:200]})
        time.sleep(interval_s)


def main() -> int:
    backend = "axon"
    if "--backend" in sys.argv:
        backend = sys.argv[sys.argv.index("--backend") + 1]
    if "--once" in sys.argv:
        ok, reason = probe_once_detail(backend=backend)
        label = backend if backend != "axon" else "tpu"
        state = "healthy" if ok else f"unreachable ({reason})"
        print(f"{label} probe: {state}")
        summary = probe_log_summary()
        print(
            f"{label} probe log: {summary['attempts']} attempts, "
            f"failure streak {summary['failure_streak']}, causes "
            f"{json.dumps(summary['causes'], sort_keys=True)}"
        )
        if ok and backend == "axon" and not os.path.exists(EVIDENCE):
            capture_evidence()
        return 0 if ok else 1
    interval = 180.0
    if "--interval" in sys.argv:
        interval = float(sys.argv[sys.argv.index("--interval") + 1])
    print(f"tpu prober: every {interval:.0f}s -> {LOG}", flush=True)
    daemon_loop(interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
