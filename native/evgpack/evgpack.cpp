// evgpack — native snapshot packer for the scheduling tick.
//
// The per-task column extraction is the hottest host-side loop of a tick
// (~12 Python-level passes over 50k Task objects). This CPython extension
// makes ONE pass, reading attributes through the C API and writing the
// snapshot arena views directly. Semantics mirror
// evergreen_tpu/scheduler/snapshot.py's fill block exactly; the Python
// implementation remains as the fallback and the behavioral reference.
//
// Built with g++ at first use (see evergreen_tpu/utils/native.py); no
// build-system dependency.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// cached attribute-name objects (created once at module init)
PyObject* s_priority;
PyObject* s_requester;
PyObject* s_activated_by;
PyObject* s_generate_task;
PyObject* s_task_group;
PyObject* s_task_group_order;
PyObject* s_activated_time;
PyObject* s_ingest_time;
PyObject* s_scheduled_time;
PyObject* s_dependencies_met_time;
PyObject* s_expected_duration_s;
PyObject* s_num_dependents;

bool StrEquals(PyObject* obj, const char* want) {
  if (!PyUnicode_Check(obj)) return false;
  const char* got = PyUnicode_AsUTF8(obj);
  return got != nullptr && strcmp(got, want) == 0;
}

double AsDouble(PyObject* obj, bool* ok) {
  double v = PyFloat_AsDouble(obj);
  if (v == -1.0 && PyErr_Occurred()) {
    *ok = false;
    return 0.0;
  }
  return v;
}

// pack_task_static_columns(tasks, default_duration_s, out) -> None
//
// The time-INdependent subset of pack_task_columns, plus the f64 time
// bases (t_basis = activated-or-ingest, t_start = max(scheduled,
// deps-met)) from which the per-tick dynamic columns (time-in-queue,
// wait-since-deps-met) are one vectorized numpy expression. Outputs are
// cacheable per unchanged task list (snapshot.py static-column memo):
//   uint8:  t_is_merge, t_is_patch, t_stepback, t_generate, t_in_group
//   int32:  t_priority, t_group_order, t_num_dependents
//   float32: t_expected_s, t_expected_floor_s
//   float64: t_basis, t_start
PyObject* PackTaskStaticColumns(PyObject*, PyObject* args) {
  PyObject* tasks;
  double default_dur;
  PyObject* out;
  if (!PyArg_ParseTuple(args, "OdO", &tasks, &default_dur, &out)) {
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(tasks, "tasks must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

  auto view = [&](const char* name, Py_ssize_t itemsize,
                  Py_buffer* buf) -> bool {
    PyObject* arr = PyDict_GetItemString(out, name);  // borrowed
    if (arr == nullptr) {
      PyErr_Format(PyExc_KeyError, "missing output column %s", name);
      return false;
    }
    if (PyObject_GetBuffer(arr, buf, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) !=
        0) {
      return false;
    }
    if (buf->itemsize != itemsize || buf->len < n * itemsize) {
      PyBuffer_Release(buf);
      PyErr_Format(PyExc_ValueError, "column %s has wrong shape/dtype", name);
      return false;
    }
    return true;
  };

  Py_buffer b_merge{}, b_patch{}, b_stepback{}, b_generate{}, b_in_group{};
  Py_buffer b_priority{}, b_group_order{}, b_numdep{};
  Py_buffer b_expected{}, b_expected_floor{}, b_basis{}, b_start{};
  Py_buffer* all[] = {&b_merge,       &b_patch,   &b_stepback,
                      &b_generate,    &b_in_group, &b_priority,
                      &b_group_order, &b_numdep,  &b_expected,
                      &b_expected_floor, &b_basis, &b_start};
  int acquired = 0;
  bool ok = view("t_is_merge", 1, &b_merge) && ++acquired &&
            view("t_is_patch", 1, &b_patch) && ++acquired &&
            view("t_stepback", 1, &b_stepback) && ++acquired &&
            view("t_generate", 1, &b_generate) && ++acquired &&
            view("t_in_group", 1, &b_in_group) && ++acquired &&
            view("t_priority", 4, &b_priority) && ++acquired &&
            view("t_group_order", 4, &b_group_order) && ++acquired &&
            view("t_num_dependents", 4, &b_numdep) && ++acquired &&
            view("t_expected_s", 4, &b_expected) && ++acquired &&
            view("t_expected_floor_s", 4, &b_expected_floor) && ++acquired &&
            view("t_basis", 8, &b_basis) && ++acquired &&
            view("t_start", 8, &b_start) && ++acquired;
  if (!ok) {
    for (int i = 0; i < acquired; ++i) PyBuffer_Release(all[i]);
    Py_DECREF(seq);
    return nullptr;
  }

  auto* merge = static_cast<uint8_t*>(b_merge.buf);
  auto* patch = static_cast<uint8_t*>(b_patch.buf);
  auto* stepback = static_cast<uint8_t*>(b_stepback.buf);
  auto* generate = static_cast<uint8_t*>(b_generate.buf);
  auto* in_group = static_cast<uint8_t*>(b_in_group.buf);
  auto* priority = static_cast<int32_t*>(b_priority.buf);
  auto* group_order = static_cast<int32_t*>(b_group_order.buf);
  auto* numdep = static_cast<int32_t*>(b_numdep.buf);
  auto* expected = static_cast<float*>(b_expected.buf);
  auto* expected_floor = static_cast<float*>(b_expected_floor.buf);
  auto* basis_out = static_cast<double*>(b_basis.buf);
  auto* start_out = static_cast<double*>(b_start.buf);

  bool good = true;
  for (Py_ssize_t i = 0; good && i < n; ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);  // borrowed

    PyObject* req = PyObject_GetAttr(t, s_requester);
    PyObject* act_by = PyObject_GetAttr(t, s_activated_by);
    PyObject* gen = PyObject_GetAttr(t, s_generate_task);
    PyObject* tg = PyObject_GetAttr(t, s_task_group);
    PyObject* prio = PyObject_GetAttr(t, s_priority);
    PyObject* tgo = PyObject_GetAttr(t, s_task_group_order);
    PyObject* nd = PyObject_GetAttr(t, s_num_dependents);
    PyObject* at = PyObject_GetAttr(t, s_activated_time);
    PyObject* it = PyObject_GetAttr(t, s_ingest_time);
    PyObject* st = PyObject_GetAttr(t, s_scheduled_time);
    PyObject* dmt = PyObject_GetAttr(t, s_dependencies_met_time);
    PyObject* dur = PyObject_GetAttr(t, s_expected_duration_s);

    if (!req || !act_by || !gen || !tg || !prio || !tgo || !nd || !at || !it ||
        !st || !dmt || !dur) {
      good = false;
    } else {
      const bool is_merge = StrEquals(req, "github_merge_request");
      merge[i] = is_merge ? 1 : 0;
      patch[i] = (!is_merge && (StrEquals(req, "patch_request") ||
                                StrEquals(req, "github_pull_request")))
                     ? 1
                     : 0;
      stepback[i] = StrEquals(act_by, "stepback-activator") ? 1 : 0;
      generate[i] = PyObject_IsTrue(gen) ? 1 : 0;
      const bool grouped = PyUnicode_Check(tg) && PyUnicode_GetLength(tg) > 0;
      in_group[i] = grouped ? 1 : 0;

      priority[i] = static_cast<int32_t>(PyLong_AsLong(prio));
      group_order[i] = static_cast<int32_t>(PyLong_AsLong(tgo));
      numdep[i] = static_cast<int32_t>(PyLong_AsLong(nd));

      const double activated = AsDouble(at, &good);
      const double ingest = AsDouble(it, &good);
      const double sched = AsDouble(st, &good);
      const double deps_met_t = AsDouble(dmt, &good);
      const double duration = AsDouble(dur, &good);
      if (good) {
        basis_out[i] = activated > 0.0 ? activated : ingest;
        start_out[i] = sched > deps_met_t ? sched : deps_met_t;
        const double exp_dur = duration > 0.0 ? duration : default_dur;
        expected[i] = static_cast<float>(exp_dur);
        expected_floor[i] = static_cast<float>(std::floor(exp_dur));
      }
      if (PyErr_Occurred()) good = false;
    }
    Py_XDECREF(req);
    Py_XDECREF(act_by);
    Py_XDECREF(gen);
    Py_XDECREF(tg);
    Py_XDECREF(prio);
    Py_XDECREF(tgo);
    Py_XDECREF(nd);
    Py_XDECREF(at);
    Py_XDECREF(it);
    Py_XDECREF(st);
    Py_XDECREF(dmt);
    Py_XDECREF(dur);
  }

  for (auto* b : all) PyBuffer_Release(b);
  Py_DECREF(seq);
  if (!good) return nullptr;
  Py_RETURN_NONE;
}

// pack_host_columns(hosts, estimates, out) -> [(index, group_string)...]
//
// One native pass over the host fleet: emits h_free / h_running /
// h_elapsed_s / h_expected_s / h_std_s directly into arena views and
// returns the (rare) hosts that are running a task-group task, as
// (flat index, task_group_string) pairs for the caller's segment
// mapping. ``estimates`` maps host id -> RunningTaskEstimate.
PyObject* PackHostColumns(PyObject*, PyObject* args) {
  static PyObject* s_running_task = PyUnicode_InternFromString("running_task");
  static PyObject* s_running_group =
      PyUnicode_InternFromString("running_task_group");
  static PyObject* s_teardown =
      PyUnicode_InternFromString("task_group_teardown_start_time");
  static PyObject* s_host_id = PyUnicode_InternFromString("id");
  static PyObject* s_elapsed = PyUnicode_InternFromString("elapsed_s");
  static PyObject* s_expected = PyUnicode_InternFromString("expected_s");
  static PyObject* s_std = PyUnicode_InternFromString("std_dev_s");
  static PyObject* s_tgs = PyUnicode_InternFromString("task_group_string");

  PyObject* hosts;
  PyObject* estimates;
  PyObject* out;
  if (!PyArg_ParseTuple(args, "OOO", &hosts, &estimates, &out)) {
    return nullptr;
  }
  if (!PyDict_Check(estimates)) {
    PyErr_SetString(PyExc_TypeError, "estimates must be a dict");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(hosts, "hosts must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

  auto view = [&](const char* name, Py_ssize_t itemsize,
                  Py_buffer* buf) -> bool {
    PyObject* arr = PyDict_GetItemString(out, name);  // borrowed
    if (arr == nullptr) {
      PyErr_Format(PyExc_KeyError, "missing output column %s", name);
      return false;
    }
    if (PyObject_GetBuffer(arr, buf, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) !=
        0) {
      return false;
    }
    if (buf->itemsize != itemsize || buf->len < n * itemsize) {
      PyBuffer_Release(buf);
      PyErr_Format(PyExc_ValueError, "column %s has wrong shape/dtype", name);
      return false;
    }
    return true;
  };

  Py_buffer b_free{}, b_running{}, b_elapsed{}, b_expected{}, b_std{};
  Py_buffer* all[] = {&b_free, &b_running, &b_elapsed, &b_expected, &b_std};
  int acquired = 0;
  bool ok = view("h_free", 1, &b_free) && ++acquired &&
            view("h_running", 1, &b_running) && ++acquired &&
            view("h_elapsed_s", 4, &b_elapsed) && ++acquired &&
            view("h_expected_s", 4, &b_expected) && ++acquired &&
            view("h_std_s", 4, &b_std) && ++acquired;
  if (!ok) {
    for (int i = 0; i < acquired; ++i) PyBuffer_Release(all[i]);
    Py_DECREF(seq);
    return nullptr;
  }
  auto* hfree = static_cast<uint8_t*>(b_free.buf);
  auto* hrun = static_cast<uint8_t*>(b_running.buf);
  auto* helap = static_cast<float*>(b_elapsed.buf);
  auto* hexp = static_cast<float*>(b_expected.buf);
  auto* hstd = static_cast<float*>(b_std.buf);

  PyObject* named = PyList_New(0);
  if (named == nullptr) {
    for (auto* b : all) PyBuffer_Release(b);
    Py_DECREF(seq);
    return nullptr;
  }

  bool good = true;
  for (Py_ssize_t i = 0; good && i < n; ++i) {
    PyObject* h = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    PyObject* rt = PyObject_GetAttr(h, s_running_task);
    PyObject* rg = PyObject_GetAttr(h, s_running_group);
    PyObject* td = PyObject_GetAttr(h, s_teardown);
    PyObject* hid = PyObject_GetAttr(h, s_host_id);
    if (!rt || !rg || !td || !hid) {
      good = false;
    } else {
      const bool has_task =
          PyUnicode_Check(rt) && PyUnicode_GetLength(rt) > 0;
      const double teardown = AsDouble(td, &good);
      // Host.is_free: no running task and not tearing down
      hfree[i] = (!has_task && teardown <= 0.0) ? 1 : 0;
      PyObject* est =
          has_task ? PyDict_GetItem(estimates, hid) : nullptr;  // borrowed
      if (est != nullptr && est != Py_None) {
        hrun[i] = 1;
        PyObject* e = PyObject_GetAttr(est, s_elapsed);
        PyObject* x = PyObject_GetAttr(est, s_expected);
        PyObject* sd = PyObject_GetAttr(est, s_std);
        if (!e || !x || !sd) {
          good = false;
        } else {
          helap[i] = static_cast<float>(AsDouble(e, &good));
          hexp[i] = static_cast<float>(AsDouble(x, &good));
          hstd[i] = static_cast<float>(AsDouble(sd, &good));
        }
        Py_XDECREF(e);
        Py_XDECREF(x);
        Py_XDECREF(sd);
      } else {
        hrun[i] = 0;
        helap[i] = 0.0f;
        hexp[i] = 0.0f;
        hstd[i] = 0.0f;
      }
      if (has_task && PyUnicode_Check(rg) && PyUnicode_GetLength(rg) > 0) {
        PyObject* gs = PyObject_CallMethodNoArgs(h, s_tgs);
        if (gs == nullptr) {
          good = false;
        } else {
          PyObject* pair = Py_BuildValue("(nO)", i, gs);
          Py_DECREF(gs);
          if (pair == nullptr || PyList_Append(named, pair) != 0) {
            Py_XDECREF(pair);
            good = false;
          } else {
            Py_DECREF(pair);
          }
        }
      }
      if (PyErr_Occurred()) good = false;
    }
    Py_XDECREF(rt);
    Py_XDECREF(rg);
    Py_XDECREF(td);
    Py_XDECREF(hid);
  }

  for (auto* b : all) PyBuffer_Release(b);
  Py_DECREF(seq);
  if (!good) {
    Py_DECREF(named);
    return nullptr;
  }
  return named;
}

// build_memberships(tasks, group_versions, base) ->
//   (n_units, m_task list, m_unit list, group_keys list)
//
// Mirrors evergreen_tpu/scheduler/snapshot.py::build_memberships exactly,
// including unit creation ORDER (the planner's deterministic tie-break)
// and tolerance for depends_on=None. Task indices in m_task are offset by
// ``base`` (the caller's global flat-task position).
PyObject* BuildMemberships(PyObject*, PyObject* args) {
  PyObject* tasks;
  int group_versions;
  Py_ssize_t base = 0;
  Py_ssize_t unit_base = 0;
  Py_ssize_t di = 0;
  Py_ssize_t named_base = 0;
  PyObject* t_seg_out = nullptr;
  PyObject* deps_met = nullptr;
  PyObject* t_dm_out = nullptr;
  int want_group_keys = 1;
  if (!PyArg_ParseTuple(args, "Op|nnnnOOOp", &tasks, &group_versions, &base,
                        &unit_base, &di, &named_base, &t_seg_out, &deps_met,
                        &t_dm_out, &want_group_keys)) {
    return nullptr;
  }
  if (deps_met == Py_None) deps_met = nullptr;
  if (deps_met != nullptr && !PyDict_Check(deps_met)) {
    // a silent all-met fallback here would schedule blocked tasks;
    // non-dict mappings must go through the Python path instead
    PyErr_SetString(PyExc_TypeError, "deps_met must be a dict or None");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(tasks, "tasks must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

  static PyObject* s_id = PyUnicode_InternFromString("id");
  static PyObject* s_version = PyUnicode_InternFromString("version");
  static PyObject* s_build_variant = PyUnicode_InternFromString("build_variant");
  static PyObject* s_project = PyUnicode_InternFromString("project");
  static PyObject* s_depends_on = PyUnicode_InternFromString("depends_on");
  static PyObject* s_task_id = PyUnicode_InternFromString("task_id");
  static PyObject* s_tg_max_hosts =
      PyUnicode_InternFromString("task_group_max_hosts");
  static PyObject* s_empty = PyUnicode_InternFromString("");

  struct Scope {
    PyObject* seq;
    ~Scope() { Py_DECREF(seq); }
  } scope{seq};

  // checked str -> utf8: raises a Python TypeError/UnicodeError instead of
  // crashing on non-str attributes or non-encodable surrogates
  auto as_utf8 = [](PyObject* obj, const char* what,
                    const char** out) -> bool {
    if (obj == nullptr) return false;
    if (!PyUnicode_Check(obj)) {
      PyErr_Format(PyExc_TypeError, "task attribute %s must be str", what);
      return false;
    }
    const char* c = PyUnicode_AsUTF8(obj);
    if (c == nullptr) return false;  // encoding error already set
    *out = c;
    return true;
  };

  std::unordered_map<std::string, int32_t> key_to_unit;
  std::unordered_map<std::string, int32_t> task_unit;
  std::vector<std::vector<int32_t>> mem_by_task(n);
  std::vector<std::string> task_ids(n);
  int32_t n_units = 0;

  // allocator segments: ordinal per distinct group string (first-seen
  // order), final global seg id = named_base + ordinal for grouped tasks
  // and di (the distro's "" segment) for ungrouped ones — the same
  // assignment the snapshot's seg_for loop produced in Python
  std::unordered_map<std::string, int32_t> seg_ord;
  std::vector<PyObject*> seg_name_objs;  // owns one ref each until output
  std::vector<long> seg_max;
  std::vector<int32_t> seg_vec(n);

  // optional dependency-met column: deps_met.get(task.id, True) written
  // straight into the caller's uint8 buffer (folds the snapshot's 50k-item
  // dict-lookup comprehension into this pass)
  uint8_t* dm_buf = nullptr;
  Py_buffer dm_view{};
  if (t_dm_out != nullptr && t_dm_out != Py_None && n > 0) {
    if (PyObject_GetBuffer(t_dm_out, &dm_view,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0) {
      return nullptr;
    }
    if (dm_view.itemsize != 1 || dm_view.len < n) {
      PyBuffer_Release(&dm_view);
      PyErr_SetString(PyExc_ValueError,
                      "t_dm_out must be a writable uint8 buffer of >= n");
      return nullptr;
    }
    dm_buf = static_cast<uint8_t*>(dm_view.buf);
  }
  struct DmScope {
    Py_buffer* view;
    uint8_t* buf;
    ~DmScope() {
      if (buf != nullptr) PyBuffer_Release(view);
    }
  } dm_scope{&dm_view, dm_buf};

  // group_keys is optional output: the snapshot's production path discards
  // it (segments carry the same information), so skip the n-element list
  // and its per-task increfs unless asked for
  PyObject* group_keys = want_group_keys ? PyList_New(n) : Py_None;
  if (group_keys == nullptr) return nullptr;
  if (!want_group_keys) Py_INCREF(group_keys);

  bool good = true;
  for (Py_ssize_t i = 0; good && i < n; ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* tg = PyObject_GetAttr(t, s_task_group);
    PyObject* tid = PyObject_GetAttr(t, s_id);
    if (dm_buf != nullptr && tid != nullptr) {
      if (deps_met != nullptr) {
        PyObject* got = PyDict_GetItemWithError(deps_met, tid);  // borrowed
        if (got == nullptr && PyErr_Occurred()) {
          Py_XDECREF(tg);
          Py_DECREF(tid);
          good = false;
          break;
        }
        int truth = 1;
        if (got != nullptr) {
          truth = PyObject_IsTrue(got);
          if (truth < 0) {  // __bool__ raised
            Py_XDECREF(tg);
            Py_DECREF(tid);
            good = false;
            break;
          }
        }
        dm_buf[i] = truth ? 1 : 0;
      } else {
        dm_buf[i] = 1;
      }
    }
    const char* tg_c = nullptr;
    const char* tid_c = nullptr;
    if (!as_utf8(tg, "task_group", &tg_c) || !as_utf8(tid, "id", &tid_c)) {
      Py_XDECREF(tg);
      Py_XDECREF(tid);
      good = false;
      break;
    }
    task_ids[i] = tid_c;
    auto& units_of_t = mem_by_task[i];
    const bool grouped = tg_c[0] != '\0';
    PyObject* group_key_obj = nullptr;
    if (grouped) {
      PyObject* bv = PyObject_GetAttr(t, s_build_variant);
      PyObject* proj = PyObject_GetAttr(t, s_project);
      PyObject* ver = PyObject_GetAttr(t, s_version);
      const char* bv_c = nullptr;
      const char* proj_c = nullptr;
      const char* ver_c = nullptr;
      const bool attrs_ok = as_utf8(bv, "build_variant", &bv_c) &&
                            as_utf8(proj, "project", &proj_c) &&
                            as_utf8(ver, "version", &ver_c);
      if (attrs_ok) {
        // Task.task_group_string(): group _ variant _ project _ version
        std::string key;
        key.reserve(strlen(tg_c) + strlen(bv_c) + strlen(proj_c) +
                    strlen(ver_c) + 3);
        key.append(tg_c).append("_").append(bv_c).append("_")
            .append(proj_c).append("_").append(ver_c);
        auto it = key_to_unit.find(key);
        int32_t u;
        if (it == key_to_unit.end()) {
          u = n_units++;
          key_to_unit.emplace(key, u);
        } else {
          u = it->second;
        }
        units_of_t.push_back(u);
        task_unit.emplace(task_ids[i], u);
        if (group_versions) {
          auto vit = key_to_unit.find(ver_c);
          int32_t v;
          if (vit == key_to_unit.end()) {
            v = n_units++;
            key_to_unit.emplace(ver_c, v);
          } else {
            v = vit->second;
          }
          if (v != u) units_of_t.push_back(v);
        }
        auto sit = seg_ord.find(key);
        int32_t so;
        if (sit == seg_ord.end()) {
          so = static_cast<int32_t>(seg_name_objs.size());
          seg_ord.emplace(key, so);
          PyObject* name_obj = PyUnicode_FromString(key.c_str());
          if (name_obj == nullptr) {
            good = false;
          } else {
            seg_name_objs.push_back(name_obj);
            seg_max.push_back(0);
          }
        } else {
          so = sit->second;
        }
        if (good) {
          seg_vec[i] = static_cast<int32_t>(named_base) + so;
          // first task with a nonzero group max-hosts wins (seg_for)
          if (seg_max[so] == 0) {
            PyObject* mh = PyObject_GetAttr(t, s_tg_max_hosts);
            if (mh == nullptr) {
              good = false;
            } else {
              const long v = PyLong_AsLong(mh);
              if (v == -1 && PyErr_Occurred()) good = false;
              else if (v > 0) seg_max[so] = v;
              Py_DECREF(mh);
            }
          }
        }
        if (good && want_group_keys) {
          group_key_obj = seg_name_objs[so];
          Py_INCREF(group_key_obj);
        }
      } else {
        good = false;
      }
      Py_XDECREF(bv);
      Py_XDECREF(proj);
      Py_XDECREF(ver);
    } else if (group_versions) {
      PyObject* ver = PyObject_GetAttr(t, s_version);
      const char* ver_c = nullptr;
      if (!as_utf8(ver, "version", &ver_c)) {
        Py_XDECREF(ver);
        Py_DECREF(tg);
        Py_DECREF(tid);
        good = false;
        break;
      }
      auto vit = key_to_unit.find(ver_c);
      int32_t v;
      if (vit == key_to_unit.end()) {
        v = n_units++;
        key_to_unit.emplace(ver_c, v);
      } else {
        v = vit->second;
      }
      units_of_t.push_back(v);
      task_unit.emplace(task_ids[i], v);
      Py_DECREF(ver);
    } else {
      const int32_t u = n_units++;
      units_of_t.push_back(u);
      task_unit.emplace(task_ids[i], u);
    }
    if (good && !grouped) {
      seg_vec[i] = static_cast<int32_t>(di);  // the distro's "" segment
      if (want_group_keys) {
        group_key_obj = s_empty;
        Py_INCREF(group_key_obj);
      }
    }
    if (good && want_group_keys) {
      PyList_SET_ITEM(group_keys, i, group_key_obj);  // steals
    } else {
      Py_XDECREF(group_key_obj);
    }
    Py_DECREF(tg);
    Py_DECREF(tid);
  }

  // dependency-closure pass (depends_on may be None: treated as empty,
  // matching the Python fallback's `if t.depends_on:` guard)
  for (Py_ssize_t i = 0; good && i < n; ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
    PyObject* deps = PyObject_GetAttr(t, s_depends_on);
    if (deps == nullptr) {
      good = false;
      break;
    }
    if (deps == Py_None) {
      Py_DECREF(deps);
      continue;
    }
    PyObject* dep_seq = PySequence_Fast(deps, "depends_on must be a sequence");
    Py_DECREF(deps);
    if (dep_seq == nullptr) {
      good = false;
      break;
    }
    const Py_ssize_t nd = PySequence_Fast_GET_SIZE(dep_seq);
    auto& lst = mem_by_task[i];
    for (Py_ssize_t j = 0; good && j < nd; ++j) {
      PyObject* dep = PySequence_Fast_GET_ITEM(dep_seq, j);
      PyObject* dep_id = PyObject_GetAttr(dep, s_task_id);
      const char* dep_c = nullptr;
      if (!as_utf8(dep_id, "task_id", &dep_c)) {
        Py_XDECREF(dep_id);
        good = false;
        break;
      }
      auto it = task_unit.find(dep_c);
      Py_DECREF(dep_id);
      if (it != task_unit.end()) {
        const int32_t u = it->second;
        bool present = false;
        for (int32_t x : lst) {
          if (x == u) {
            present = true;
            break;
          }
        }
        if (!present) lst.push_back(u);
      }
    }
    Py_DECREF(dep_seq);
  }

  if (!good) {
    Py_DECREF(group_keys);
    for (PyObject* o : seg_name_objs) Py_DECREF(o);
    if (!PyErr_Occurred()) {
      PyErr_SetString(PyExc_TypeError, "malformed task objects");
    }
    return nullptr;
  }

  // final per-task segment ids straight into the caller's int32 buffer
  if (t_seg_out != nullptr && t_seg_out != Py_None && n > 0) {
    Py_buffer buf{};
    if (PyObject_GetBuffer(t_seg_out, &buf,
                           PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS) != 0) {
      Py_DECREF(group_keys);
      for (PyObject* o : seg_name_objs) Py_DECREF(o);
      return nullptr;
    }
    if (buf.itemsize != 4 ||
        buf.len < n * static_cast<Py_ssize_t>(sizeof(int32_t))) {
      PyBuffer_Release(&buf);
      Py_DECREF(group_keys);
      for (PyObject* o : seg_name_objs) Py_DECREF(o);
      PyErr_SetString(PyExc_ValueError,
                      "t_seg_out must be a writable int32 buffer of >= n");
      return nullptr;
    }
    memcpy(buf.buf, seg_vec.data(), n * sizeof(int32_t));
    PyBuffer_Release(&buf);
  }

  // memberships as raw int32 little-endian bytes: np.frombuffer on the
  // Python side — no 2×M PyLong allocations crossing the boundary
  size_t total = 0;
  for (auto& lst : mem_by_task) total += lst.size();
  std::vector<int32_t> mt_vec(total);
  std::vector<int32_t> mu_vec(total);
  size_t k = 0;
  for (Py_ssize_t i = 0; i < n; ++i) {
    for (int32_t u : mem_by_task[i]) {
      mt_vec[k] = static_cast<int32_t>(base + i);
      mu_vec[k] = static_cast<int32_t>(unit_base) + u;
      ++k;
    }
  }
  PyObject* m_task = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(mt_vec.data()),
      static_cast<Py_ssize_t>(total * sizeof(int32_t)));
  PyObject* m_unit = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(mu_vec.data()),
      static_cast<Py_ssize_t>(total * sizeof(int32_t)));
  if (m_task == nullptr || m_unit == nullptr) {
    Py_XDECREF(m_task);
    Py_XDECREF(m_unit);
    Py_DECREF(group_keys);
    for (PyObject* o : seg_name_objs) Py_DECREF(o);
    return nullptr;
  }
  const Py_ssize_t n_segs = static_cast<Py_ssize_t>(seg_name_objs.size());
  PyObject* seg_names = PyList_New(n_segs);
  PyObject* seg_max_out = PyList_New(n_segs);
  if (seg_names == nullptr || seg_max_out == nullptr) {
    Py_XDECREF(seg_names);
    Py_XDECREF(seg_max_out);
    Py_DECREF(m_task);
    Py_DECREF(m_unit);
    Py_DECREF(group_keys);
    for (PyObject* o : seg_name_objs) Py_DECREF(o);
    return nullptr;
  }
  for (Py_ssize_t s = 0; s < n_segs; ++s) {
    PyList_SET_ITEM(seg_names, s, seg_name_objs[s]);  // steals creation ref
  }
  for (Py_ssize_t s = 0; s < n_segs; ++s) {
    PyObject* mh = PyLong_FromLong(seg_max[s]);
    if (mh == nullptr) {
      Py_DECREF(seg_names);  // owns every name ref now
      Py_DECREF(seg_max_out);
      Py_DECREF(m_task);
      Py_DECREF(m_unit);
      Py_DECREF(group_keys);
      return nullptr;
    }
    PyList_SET_ITEM(seg_max_out, s, mh);
  }
  return Py_BuildValue("iNNNNN", n_units, m_task, m_unit, group_keys,
                       seg_names, seg_max_out);
}

// fill_deps_met(tasks, deps_met, out) -> None
//
// out[i] = bool(deps_met.get(tasks[i].id, True)) into a writable uint8
// buffer.  Used by the snapshot's membership-memo hit path, where the
// cached unit grouping is reused but the deps-met column (the only
// dynamic input) must be refreshed each tick.
PyObject* FillDepsMet(PyObject*, PyObject* args) {
  PyObject* tasks;
  PyObject* deps_met;
  PyObject* out;
  if (!PyArg_ParseTuple(args, "OOO", &tasks, &deps_met, &out)) {
    return nullptr;
  }
  if (deps_met == Py_None) deps_met = nullptr;
  if (deps_met != nullptr && !PyDict_Check(deps_met)) {
    PyErr_SetString(PyExc_TypeError, "deps_met must be a dict or None");
    return nullptr;
  }
  PyObject* seq = PySequence_Fast(tasks, "tasks must be a sequence");
  if (seq == nullptr) return nullptr;
  const Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  static PyObject* s_id_attr = PyUnicode_InternFromString("id");
  Py_buffer view{};
  if (PyObject_GetBuffer(out, &view, PyBUF_WRITABLE | PyBUF_C_CONTIGUOUS)
      != 0) {
    Py_DECREF(seq);
    return nullptr;
  }
  if (view.itemsize != 1 || view.len < n) {
    PyBuffer_Release(&view);
    Py_DECREF(seq);
    PyErr_SetString(PyExc_ValueError,
                    "out must be a writable uint8 buffer of >= n");
    return nullptr;
  }
  auto* buf = static_cast<uint8_t*>(view.buf);
  bool good = true;
  for (Py_ssize_t i = 0; good && i < n; ++i) {
    PyObject* t = PySequence_Fast_GET_ITEM(seq, i);
    if (deps_met == nullptr) {
      buf[i] = 1;
      continue;
    }
    PyObject* tid = PyObject_GetAttr(t, s_id_attr);
    if (tid == nullptr) {
      good = false;
      break;
    }
    PyObject* got = PyDict_GetItemWithError(deps_met, tid);  // borrowed
    Py_DECREF(tid);
    if (got == nullptr) {
      if (PyErr_Occurred()) {
        good = false;
        break;
      }
      buf[i] = 1;
    } else {
      int truth = PyObject_IsTrue(got);
      if (truth < 0) {
        good = false;
        break;
      }
      buf[i] = truth ? 1 : 0;
    }
  }
  PyBuffer_Release(&view);
  Py_DECREF(seq);
  if (!good) return nullptr;
  Py_RETURN_NONE;
}

PyMethodDef kMethods[] = {
    {"pack_task_static_columns", PackTaskStaticColumns, METH_VARARGS,
     "Time-independent task columns + f64 time bases (cacheable)."},
    {"pack_host_columns", PackHostColumns, METH_VARARGS,
     "Host fleet columns in one pass; returns named-group (i, key) pairs."},
    {"build_memberships", BuildMemberships, METH_VARARGS,
     "Planner unit grouping: (n_units, m_task, m_unit, group_keys)."},
    {"fill_deps_met", FillDepsMet, METH_VARARGS,
     "out[i] = deps_met.get(tasks[i].id, True) as uint8."},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef kModule = {
    PyModuleDef_HEAD_INIT, "evgpack",
    "Native snapshot packer for evergreen_tpu.", -1, kMethods,
};

}  // namespace

PyMODINIT_FUNC PyInit_evgpack(void) {
  s_priority = PyUnicode_InternFromString("priority");
  s_requester = PyUnicode_InternFromString("requester");
  s_activated_by = PyUnicode_InternFromString("activated_by");
  s_generate_task = PyUnicode_InternFromString("generate_task");
  s_task_group = PyUnicode_InternFromString("task_group");
  s_task_group_order = PyUnicode_InternFromString("task_group_order");
  s_activated_time = PyUnicode_InternFromString("activated_time");
  s_ingest_time = PyUnicode_InternFromString("ingest_time");
  s_scheduled_time = PyUnicode_InternFromString("scheduled_time");
  s_dependencies_met_time = PyUnicode_InternFromString("dependencies_met_time");
  s_expected_duration_s = PyUnicode_InternFromString("expected_duration_s");
  s_num_dependents = PyUnicode_InternFromString("num_dependents");
  return PyModule_Create(&kModule);
}
