#include "evgsolve.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

namespace evgsolve {

namespace {
constexpr char kMagic[4] = {'E', 'V', 'G', 'S'};
constexpr uint32_t kVersion = 2;
}  // namespace

Client::Client(const std::string& host, uint16_t port)
    : host_(host), port_(port) {}

Client::~Client() { Close(); }

bool Client::Connect() {
  if (fd_ >= 0) return true;
  struct addrinfo hints;
  memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port_);
  int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    error_ = std::string("getaddrinfo: ") + gai_strerror(rc);
    return false;
  }
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd_ < 0) continue;
    if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd_);
    fd_ = -1;
  }
  freeaddrinfo(res);
  if (fd_ < 0) {
    error_ = std::string("connect failed: ") + strerror(errno);
    return false;
  }
  int one = 1;
  setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return true;
}

void Client::Close() {
  if (fd_ >= 0) {
    close(fd_);
    fd_ = -1;
  }
}

bool Client::WriteAll(const void* data, size_t len) {
  const char* p = static_cast<const char*>(data);
  while (len > 0) {
    ssize_t n = send(fd_, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      error_ = std::string("send: ") + strerror(errno);
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool Client::ReadAll(void* data, size_t len) {
  char* p = static_cast<char*>(data);
  while (len > 0) {
    ssize_t n = recv(fd_, p, len, 0);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) continue;
      error_ = n == 0 ? "server closed connection"
                      : std::string("recv: ") + strerror(errno);
      return false;
    }
    p += n;
    len -= static_cast<size_t>(n);
  }
  return true;
}

bool Client::Solve(const Snapshot& snapshot, SolveResult* result) {
  if (!Connect()) return false;

  // request
  if (!WriteAll(kMagic, 4)) return false;
  if (!WriteAll(&kVersion, 4)) return false;
  if (!WriteAll(&snapshot.shape, sizeof(ShapeKey))) return false;

  uint64_t n = snapshot.f32.size();
  if (!WriteAll(&n, 8)) return false;
  if (n && !WriteAll(snapshot.f32.data(), n * sizeof(float))) return false;
  n = snapshot.i32.size();
  if (!WriteAll(&n, 8)) return false;
  if (n && !WriteAll(snapshot.i32.data(), n * sizeof(int32_t))) return false;
  n = snapshot.u8.size();
  if (!WriteAll(&n, 8)) return false;
  if (n && !WriteAll(snapshot.u8.data(), n)) return false;

  // response
  uint32_t status = 0;
  if (!ReadAll(&status, 4)) return false;
  if (status != 0) {
    uint32_t mlen = 0;
    if (!ReadAll(&mlen, 4)) return false;
    std::string msg(mlen, '\0');
    if (mlen && !ReadAll(&msg[0], mlen)) return false;
    error_ = "sidecar error: " + msg;
    return false;
  }
  uint64_t n_i32 = 0;
  if (!ReadAll(&n_i32, 8)) return false;
  result->i32.resize(n_i32);
  if (n_i32 && !ReadAll(result->i32.data(), n_i32 * sizeof(int32_t)))
    return false;
  uint64_t n_f32 = 0;
  if (!ReadAll(&n_f32, 8)) return false;
  result->f32.resize(n_f32);
  if (n_f32 && !ReadAll(result->f32.data(), n_f32 * sizeof(float)))
    return false;
  return true;
}

}  // namespace evgsolve
