// evgsolve_cli — demo control-plane client: load a snapshot dump, call the
// solver sidecar, print per-distro spawn counts + the queue head.
//
// Snapshot dump format (written by tests / tools via
// evergreen_tpu.api.sidecar dump helpers): the wire request payload without
// magic/version — 8x u32 shape key, then u64-count-prefixed f32/i32/u8
// arenas.
//
// Usage: evgsolve_cli <host> <port> <snapshot.bin> [repeats]
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "evgsolve.h"

static bool LoadDump(const char* path, evgsolve::Snapshot* snap) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    perror("open snapshot");
    return false;
  }
  bool ok = fread(&snap->shape, sizeof(snap->shape), 1, f) == 1;
  uint64_t n = 0;
  if (ok) ok = fread(&n, 8, 1, f) == 1;
  if (ok) {
    snap->f32.resize(n);
    ok = n == 0 || fread(snap->f32.data(), sizeof(float), n, f) == n;
  }
  if (ok) ok = fread(&n, 8, 1, f) == 1;
  if (ok) {
    snap->i32.resize(n);
    ok = n == 0 || fread(snap->i32.data(), sizeof(int32_t), n, f) == n;
  }
  if (ok) ok = fread(&n, 8, 1, f) == 1;
  if (ok) {
    snap->u8.resize(n);
    ok = n == 0 || fread(snap->u8.data(), 1, n, f) == n;
  }
  fclose(f);
  if (!ok) fprintf(stderr, "malformed snapshot dump: %s\n", path);
  return ok;
}

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <host> <port> <snapshot.bin> [repeats]\n",
            argv[0]);
    return 2;
  }
  evgsolve::Snapshot snap;
  if (!LoadDump(argv[3], &snap)) return 1;
  const int repeats = argc > 4 ? atoi(argv[4]) : 1;

  evgsolve::Client client(argv[1], static_cast<uint16_t>(atoi(argv[2])));
  evgsolve::SolveResult result;
  for (int i = 0; i < repeats; ++i) {
    if (!client.Solve(snap, &result)) {
      fprintf(stderr, "solve failed: %s\n", client.last_error().c_str());
      return 1;
    }
  }

  const evgsolve::ShapeKey& s = snap.shape;
  const uint64_t want_i32 = 3ull * s.n_tasks + 7ull * s.n_distros +
                            6ull * s.n_segments;
  const uint64_t want_f32 =
      4ull * s.n_tasks + 3ull * s.n_distros + 2ull * s.n_segments +
      1ull * s.n_units * s.n_pools;
  if (result.i32.size() != want_i32 || result.f32.size() != want_f32) {
    fprintf(stderr, "unexpected result sizes: i32=%zu (want %llu) f32=%zu (want %llu)\n",
            result.i32.size(), (unsigned long long)want_i32,
            result.f32.size(), (unsigned long long)want_f32);
    return 1;
  }

  const int32_t* order = result.order(s);
  const int32_t* new_hosts = result.new_hosts(s);
  long long total_spawns = 0;
  for (uint32_t d = 0; d < s.n_distros; ++d) total_spawns += new_hosts[d];

  printf("solve ok: N=%u D=%u G=%u\n", s.n_tasks, s.n_distros, s.n_segments);
  printf("queue head:");
  for (uint32_t i = 0; i < s.n_tasks && i < 8; ++i) printf(" %d", order[i]);
  printf("\ntotal spawns: %lld\n", total_spawns);
  return 0;
}
