// evgsolve — C++ client for the TPU scheduling-solver sidecar.
//
// The sidecar (evergreen_tpu/api/sidecar.py) hosts the batched JAX solve;
// this library lets a non-Python control plane ship snapshot arenas and
// receive queue orderings + spawn counts, matching the north-star
// architecture (SURVEY §7 step 5: Solve(SnapshotTensor) -> queues, spawns).
//
// Wire protocol (little-endian), version 2:
//   request:  "EVGS" | u32 version | 8x u32 shape key (N,M,U,G,H,D,P,C)
//             | u64 n_f32 | f32[] | u64 n_i32 | i32[] | u64 n_u8 | u8[]
//   response: u32 status | ok: u64 n_i32, i32[], u64 n_f32, f32[]
//                        | err: u32 len, msg
// Version 2 widened the shape key for the fused capacity page: P pool
// rows (prices/quotas) and C config slots in the f32 arena; the solve
// additionally returns cap_x[D] + aff_pool[U*P] in the f32 half.
#ifndef EVGSOLVE_H
#define EVGSOLVE_H

#include <cstdint>
#include <string>
#include <vector>

namespace evgsolve {

struct ShapeKey {
  uint32_t n_tasks;        // N: padded task count
  uint32_t n_memberships;  // M: task->unit edges
  uint32_t n_units;        // U: planner units
  uint32_t n_segments;     // G: distro x task-group segments
  uint32_t n_hosts;        // H
  uint32_t n_distros;      // D
  uint32_t n_pools;        // P: capacity pool rows (fixed P_BUCKET=8)
  uint32_t n_cfg;          // C: capacity config slots (fixed C_BUCKET=8)
};

// Snapshot transfer arenas. Field layout within each arena is the canonical
// order defined by evergreen_tpu/scheduler/snapshot.py FIELD_KINDS and is
// fully determined by the shape key.
struct Snapshot {
  ShapeKey shape;
  std::vector<float> f32;
  std::vector<int32_t> i32;
  std::vector<uint8_t> u8;
};

// Solve outputs, packed per evergreen_tpu/ops/solve.py OUTPUT_SPEC:
// i32: order[N], t_unit[N], t_stepback[N], d_new_hosts[D],
//      d_free_approx[D], d_length[D], d_deps_met[D], d_over_count[D],
//      d_wait_over[D], d_merge[D], g_count[G], g_count_free[G],
//      g_count_required[G], g_over_count[G], g_wait_over[G], g_merge[G]
// f32: t_value[N], t_prio[N], t_rank[N], t_tiq[N], d_expected_dur_s[D],
//      d_over_dur_s[D], g_expected_dur_s[G], g_over_dur_s[G],
//      cap_x[D], aff_pool[U*P]
struct SolveResult {
  std::vector<int32_t> i32;
  std::vector<float> f32;

  // convenience accessors into the packed buffers
  const int32_t* order(const ShapeKey& s) const { return i32.data(); }
  const int32_t* new_hosts(const ShapeKey& s) const {
    // after order + t_unit + t_stepback
    return i32.data() + 3ull * s.n_tasks;
  }
};

class Client {
 public:
  Client(const std::string& host, uint16_t port);
  ~Client();

  Client(const Client&) = delete;
  Client& operator=(const Client&) = delete;

  // Connects (idempotent). Returns false and sets last_error() on failure.
  bool Connect();
  void Close();

  // Ships the snapshot, blocks for the solve result.
  // Returns false and sets last_error() on transport or server error.
  bool Solve(const Snapshot& snapshot, SolveResult* result);

  const std::string& last_error() const { return error_; }

 private:
  bool WriteAll(const void* data, size_t len);
  bool ReadAll(void* data, size_t len);

  std::string host_;
  uint16_t port_;
  int fd_ = -1;
  std::string error_;
};

}  // namespace evgsolve

#endif  // EVGSOLVE_H
