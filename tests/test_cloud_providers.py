"""Cloud providers: ec2-fleet-shaped, docker/container pools, static,
spawn hosts (reference analog: cloud package tests against mocks)."""
import time

from evergreen_tpu.cloud import docker as docker_mod
from evergreen_tpu.cloud import ec2_fleet, spawnhost
from evergreen_tpu.cloud.docker import (
    ContainerPool,
    ensure_parent_capacity,
    set_container_pools,
)
from evergreen_tpu.cloud.manager import CloudHostStatus, get_manager
from evergreen_tpu.cloud.mock import MockCloudManager
from evergreen_tpu.cloud.provisioning import (
    create_hosts_from_intents,
    provision_ready_hosts,
)
from evergreen_tpu.cloud.static import update_static_distro
from evergreen_tpu.globals import HostStatus, Provider
from evergreen_tpu.models import distro as distro_mod
from evergreen_tpu.models import host as host_mod
from evergreen_tpu.models.distro import Distro, HostAllocatorSettings
from evergreen_tpu.models.host import Host, new_intent

NOW = 1_700_000_000.0


def test_ec2_fleet_lifecycle(store):
    ec2_fleet.reset_default_client()
    distro_mod.insert(
        store,
        Distro(
            id="d-ec2", provider=Provider.EC2_FLEET.value,
            provider_settings={"instance_type": "c5.xlarge",
                               "fleet_use_spot": True, "az": "us-west-2b"},
        ),
    )
    intent = new_intent("d-ec2", Provider.EC2_FLEET.value)
    host_mod.insert(store, intent)
    mgr = get_manager(Provider.EC2_FLEET.value)
    mgr.spawn_host(store, intent)
    h = host_mod.get(store, intent.id)
    assert h.external_id.startswith("i-")
    assert h.instance_type == "c5.xlarge"
    assert h.status == HostStatus.STARTING.value
    # instance observed running → provisioning promotes
    assert mgr.get_instance_status(store, h) == CloudHostStatus.RUNNING
    ready = provision_ready_hosts(store, NOW)
    assert ready == [h.id]
    # stop/start/terminate path
    mgr.stop_instance(store, host_mod.get(store, h.id))
    assert mgr.get_instance_status(store, host_mod.get(store, h.id)) == (
        CloudHostStatus.STOPPED
    )
    mgr.start_instance(store, host_mod.get(store, h.id))
    mgr.terminate_instance(store, host_mod.get(store, h.id), "test")
    assert host_mod.get(store, h.id).status == HostStatus.TERMINATED.value


def test_container_pool_parent_capacity_and_spawn(store):
    docker_mod.reset_default_client()
    MockCloudManager.reset()
    set_container_pools(
        store, [ContainerPool(id="pool1", distro="d-parent", max_containers=2)]
    )
    distro_mod.insert(
        store,
        Distro(
            id="d-parent", provider=Provider.MOCK.value,
            host_allocator_settings=HostAllocatorSettings(maximum_hosts=2),
        ),
    )
    distro_mod.insert(
        store,
        Distro(
            id="d-containers", provider=Provider.DOCKER.value,
            container_pool="pool1",
            provider_settings={"image_url": "ci-image:1"},
        ),
    )
    # three container intents, no parents yet
    intents = [new_intent("d-containers", Provider.DOCKER.value) for _ in range(3)]
    for i in intents:
        host_mod.insert(store, i)

    created_parents = ensure_parent_capacity(store, NOW)
    assert created_parents, "parent intents should be created for demand"
    # bring parents up via the normal provisioning pipeline (mock provider)
    create_hosts_from_intents(store, NOW)
    provision_ready_hosts(store, NOW)
    parents_up = host_mod.find(
        store,
        lambda d: d["distro_id"] == "d-parent"
        and d["status"] == HostStatus.RUNNING.value,
    )
    assert parents_up

    # now docker containers can spawn onto parents (capacity 2 per parent)
    create_hosts_from_intents(store, NOW)
    provision_ready_hosts(store, NOW)
    containers = host_mod.find(
        store,
        lambda d: d["distro_id"] == "d-containers"
        and d["status"] == HostStatus.RUNNING.value,
    )
    assert len(containers) >= 2
    assert all(c.parent_id for c in containers)
    per_parent = {}
    for c in containers:
        per_parent[c.parent_id] = per_parent.get(c.parent_id, 0) + 1
    assert all(n <= 2 for n in per_parent.values())


def test_static_distro_upsert_and_decommission(store):
    d = Distro(
        id="d-static", provider=Provider.STATIC.value,
        provider_settings={"hosts": [{"name": "10.0.0.1"}, {"name": "10.0.0.2"}]},
    )
    distro_mod.insert(store, d)
    created = update_static_distro(store, d, NOW)
    assert len(created) == 2
    # drop one machine from settings → decommissioned
    d.provider_settings = {"hosts": [{"name": "10.0.0.1"}]}
    update_static_distro(store, d, NOW)
    statuses = {
        h.id: h.status
        for h in host_mod.find(store, lambda x: x["distro_id"] == "d-static")
    }
    assert statuses["static-d-static-10.0.0.1"] == HostStatus.RUNNING.value
    assert statuses["static-d-static-10.0.0.2"] == HostStatus.DECOMMISSIONED.value


def test_spawn_host_lifecycle_and_expiration(store):
    MockCloudManager.reset()
    distro_mod.insert(store, Distro(id="ws", provider=Provider.MOCK.value))
    h = spawnhost.create_spawn_host(store, "alice", "ws", now=NOW)
    assert h.user_host and h.started_by == "alice"
    assert h.expiration_time == NOW + spawnhost.DEFAULT_EXPIRATION_S
    # spawn-host hosts are NOT part of the task-host capacity pool
    assert host_mod.all_active_hosts(store, "ws") == []
    new_exp = spawnhost.extend_expiration(store, h.id, 2.0, now=NOW)
    assert new_exp == h.expiration_time + 7200
    # not yet expired
    assert spawnhost.expire_spawn_hosts(store, NOW + 3600) == []
    # past expiration → terminated
    expired = spawnhost.expire_spawn_hosts(store, new_exp + 1)
    assert expired == [h.id]
    assert host_mod.get(store, h.id).status == HostStatus.TERMINATED.value


def test_container_distro_planned_end_to_end(store, tmp_path):
    """Container distros must flow through the normal tick (they were the
    reference's ByNeedsPlanning inclusion; only pool PARENTS are excluded)."""
    from evergreen_tpu.agent.agent import Agent, AgentOptions
    from evergreen_tpu.agent.comm import LocalCommunicator
    from evergreen_tpu.dispatch.dag_dispatcher import DispatcherService
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick

    docker_mod.reset_default_client()
    MockCloudManager.reset()
    set_container_pools(
        store, [ContainerPool(id="pool1", distro="d-parent", max_containers=2)]
    )
    distro_mod.insert(
        store,
        Distro(
            id="d-parent", provider=Provider.MOCK.value,
            host_allocator_settings=HostAllocatorSettings(maximum_hosts=2),
        ),
    )
    distro_mod.insert(
        store,
        Distro(
            id="d-containers", provider=Provider.DOCKER.value,
            container_pool="pool1",
            host_allocator_settings=HostAllocatorSettings(maximum_hosts=4),
        ),
    )
    store.collection("parser_projects").upsert(
        {"_id": "v1", "tasks": {"job": {"commands": [
            {"command": "shell.exec", "params": {"script": "echo in-container"}}
        ]}}}
    )
    task_mod.insert(
        store,
        Task(id="ct1", display_name="job", version="v1",
             distro_id="d-containers", status="undispatched", activated=True,
             activated_time=NOW - 60, create_time=NOW - 100,
             expected_duration_s=60),
    )

    res = run_tick(store, TickOptions(), now=NOW)
    # the container distro was planned and allocated
    assert res.new_hosts.get("d-containers", 0) >= 1
    # parent distro is NOT part of the allocator fan-out
    assert "d-parent" not in res.new_hosts

    # pool capacity job creates parents; provisioning brings everything up
    ensure_parent_capacity(store, NOW)
    create_hosts_from_intents(store, NOW)
    provision_ready_hosts(store, NOW)
    create_hosts_from_intents(store, NOW)  # containers onto live parents
    provision_ready_hosts(store, NOW)
    container_hosts = host_mod.find(
        store,
        lambda d: d["distro_id"] == "d-containers"
        and d["status"] == HostStatus.RUNNING.value,
    )
    assert container_hosts

    agent = Agent(
        LocalCommunicator(store, DispatcherService(store)),
        AgentOptions(host_id=container_hosts[0].id, work_dir=str(tmp_path)),
    )
    assert agent.run_until_idle() == ["ct1"]
    assert task_mod.get(store, "ct1").status == "success"
