"""Split-brain-safe failover: lease epochs + CAS steal/renewal, fenced
WAL commits, superseded-frame replay drops, the startup reconciliation
pass, and (slow-marked) the real-process crash/failover matrix
(tools/crash_matrix.py).

Acceptance contract (ISSUE 4): exactly one holder owns each lease epoch;
a holder whose epoch was superseded mid-tick sheds the tick with
EpochFencedError and nothing from it reaches the WAL; recovery replays
drop stale-epoch frames that interleave past the fence point; the
recovery pass heals half-dispatched assignments, stranded tasks, and
phantom building hosts before the first tick plans.
"""
import json
import os
import threading
import time

import pytest

from evergreen_tpu.globals import HostStatus, Provider, TaskStatus
from evergreen_tpu.models import distro as distro_mod
from evergreen_tpu.models import host as host_mod
from evergreen_tpu.models import task as task_mod
from evergreen_tpu.models.distro import Distro
from evergreen_tpu.models.host import Host
from evergreen_tpu.models.task import Task
from evergreen_tpu.scheduler.recovery import run_recovery_pass
from evergreen_tpu.storage.durable import DurableStore
from evergreen_tpu.storage.lease import EpochFencedError, FileLease

NOW = 1_700_000_000.0


# --------------------------------------------------------------------------- #
# lease epochs + CAS
# --------------------------------------------------------------------------- #


def test_epoch_monotone_across_steals_and_releases(tmp_path):
    """Epochs increase on every steal AND survive a clean release+unlink
    cycle (the sidecar floor file carries the high-water mark)."""
    path = str(tmp_path / "writer.lease")
    a = FileLease(path, ttl_s=0.4)
    assert a.try_acquire() and a.epoch == 1
    # stale steal bumps
    time.sleep(0.5)
    b = FileLease(path, ttl_s=0.4)
    assert b.try_acquire() and b.epoch == 2
    # clean release + fresh acquire still advances past the floor
    b.release()
    c = FileLease(path, ttl_s=0.4)
    assert c.try_acquire()
    assert c.epoch == 3


def test_steal_is_cas_exactly_one_winner(tmp_path):
    """N concurrent stealers of one stale lease: exactly one wins, and
    the winner owns a strictly higher epoch (claim-by-rename is the
    atomic primitive)."""
    path = str(tmp_path / "writer.lease")
    holder = FileLease(path, ttl_s=0.2)
    assert holder.try_acquire()
    time.sleep(0.3)  # go stale
    thieves = [FileLease(path, ttl_s=0.2) for _ in range(8)]
    results = [None] * len(thieves)
    barrier = threading.Barrier(len(thieves))

    def steal(i):
        barrier.wait()
        results[i] = thieves[i].try_acquire()

    threads = [
        threading.Thread(target=steal, args=(i,))
        for i in range(len(thieves))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [t for t, ok in zip(thieves, results) if ok]
    assert len(winners) == 1
    assert winners[0].epoch == 2
    assert not holder.renew()  # the old holder observes the loss


def test_renew_is_cas_detects_steal(tmp_path):
    path = str(tmp_path / "writer.lease")
    a = FileLease(path, ttl_s=0.3)
    assert a.try_acquire()
    time.sleep(0.4)
    b = FileLease(path, ttl_s=0.3)
    assert b.try_acquire()
    # a's renew must fail BOTH on owner and on epoch mismatch — even if
    # the file somehow carried a's owner id at a different epoch
    assert not a.renew()
    assert b.renew()


def test_release_only_unlinks_own_lease(tmp_path):
    """The release read-then-unlink race fix: releasing after a standby
    stole must NOT delete the standby's lease."""
    path = str(tmp_path / "writer.lease")
    a = FileLease(path, ttl_s=0.3)
    assert a.try_acquire()
    time.sleep(0.4)
    b = FileLease(path, ttl_s=0.3)
    assert b.try_acquire()
    a.release()  # stale holder releases AFTER the steal
    assert os.path.exists(path), "release deleted the standby's lease"
    assert b.renew()
    b.release()
    assert not os.path.exists(path)  # the rightful owner's unlink works


def test_renewal_clobber_cannot_win(tmp_path):
    """The stalled-renewal race: A passes its read-verify, stalls, B
    completes a steal, A's replace clobbers B's lease file and A reads
    its own payload back. The monotone epoch-floor file outlives the
    clobber, so A's renewal still observes the loss."""
    path = str(tmp_path / "writer.lease")
    a = FileLease(path, ttl_s=0.2)
    assert a.try_acquire()
    time.sleep(0.3)
    b = FileLease(path, ttl_s=0.2)
    assert b.try_acquire() and b.epoch == 2
    # simulate the stalled half of a.renew(): the read-verify happened
    # BEFORE b's steal, so only the blind replace remains
    a._write()
    assert not a.renew(), "clobbering renewal must not win"
    assert a.superseded()


def test_stand_down_fires_on_lost_once(tmp_path):
    path = str(tmp_path / "writer.lease")
    a = FileLease(path, ttl_s=5.0)
    assert a.try_acquire()
    calls = []
    a._on_lost = lambda: calls.append(1)
    a.stand_down("test")
    a.stand_down("test again")
    assert a.lost and calls == [1]


# --------------------------------------------------------------------------- #
# fenced WAL writes
# --------------------------------------------------------------------------- #


def _steal_from(tmp_path) -> FileLease:
    thief = FileLease(str(tmp_path / "data" / "writer.lease"), ttl_s=60.0)
    thief.ttl_s = -1.0  # anything is stale: steal immediately
    assert thief.try_acquire()
    return thief


def _holder_store(tmp_path, **kw):
    lease = FileLease(str(tmp_path / "data" / "writer.lease"), ttl_s=60.0)
    assert lease.try_acquire()
    store = DurableStore(str(tmp_path / "data"), lease=lease, **kw)
    return lease, store


def test_group_frames_stamped_with_epoch(tmp_path):
    lease, store = _holder_store(tmp_path)
    store.begin_tick()
    store.collection("k").upsert({"_id": "x", "v": 1})
    store.end_tick()
    frames = [
        json.loads(line)
        for line in open(str(tmp_path / "data" / "wal.log"))
        if line.startswith('{"o":"g"')
    ]
    assert frames and all(f["e"] == lease.epoch for f in frames)


def test_fenced_commit_sheds_group_and_stands_down(tmp_path):
    lease, store = _holder_store(tmp_path)
    store.collection("k").upsert({"_id": "pre", "v": 0})
    store.begin_tick()
    store.collection("k").upsert({"_id": "mid-tick", "v": 1})
    _steal_from(tmp_path)  # the steal lands before the flush
    with pytest.raises(EpochFencedError):
        store.end_tick()
    assert store.fenced and lease.lost
    # every further write refuses
    with pytest.raises(EpochFencedError):
        store.collection("k").upsert({"_id": "after", "v": 2})
    with pytest.raises(EpochFencedError):
        store.checkpoint()
    # recovery sees the pre-tick write and nothing from the shed group
    recovered = DurableStore(str(tmp_path / "data"))
    assert recovered.collection("k").get("pre") is not None
    assert recovered.collection("k").get("mid-tick") is None
    assert recovered.collection("k").get("after") is None


def test_fenced_close_writes_nothing(tmp_path):
    lease, store = _holder_store(tmp_path)
    store.collection("k").upsert({"_id": "pre", "v": 0})
    _steal_from(tmp_path)
    try:
        store.collection("k").upsert({"_id": "post-steal", "v": 1})
    except EpochFencedError:
        pass
    snap = str(tmp_path / "data" / "snapshot.json")
    store.close()  # must not checkpoint a dir a newer epoch owns
    assert not os.path.exists(snap)


def test_replay_drops_superseded_epoch_frames(tmp_path):
    """Frames from a superseded epoch that interleave PAST the fence
    point are dropped; frames before it (and the newer epoch's own)
    replay normally."""
    d = tmp_path / "data"
    d.mkdir()
    frame = (
        '{"o":"g","n":1,"e":%d,"rs":['
        '{"c":"k","o":"p","d":{"_id":"%s","by":%d}}]}\n'
    )
    with open(d / "wal.log", "w") as fh:
        fh.write(frame % (1, "a", 1))   # old holder, pre-fence: applies
        fh.write(frame % (2, "b", 2))   # new holder: the fence point
        fh.write(frame % (1, "c", 1))   # stale interleave: DROPPED
        fh.write(frame % (2, "d", 2))   # new holder continues
    store = DurableStore(str(d))
    assert store.collection("k").get("a") is not None
    assert store.collection("k").get("b") is not None
    assert store.collection("k").get("c") is None
    assert store.collection("k").get("d") is not None
    assert store.replay_report["stale_frames_dropped"] == 1
    assert store.replay_report["wal_max_epoch"] == 2


def test_replay_drops_superseded_per_op_records(tmp_path):
    """Per-op lines carry the writer's epoch too: a stale holder's
    between-ticks write (REST mutation, event log) landing past the
    fence point is erased at replay just like a stale group frame."""
    d = tmp_path / "data"
    d.mkdir()
    with open(d / "wal.log", "w") as fh:
        fh.write('{"c":"k","o":"p","d":{"_id":"pre"},"e":1}\n')
        fh.write('{"o":"f","e":2}\n')  # new holder's open-time marker
        fh.write('{"c":"k","o":"p","d":{"_id":"stale"},"e":1}\n')
        fh.write('{"c":"k","o":"p","d":{"_id":"new"},"e":2}\n')
    store = DurableStore(str(d))
    assert store.collection("k").get("pre") is not None
    assert store.collection("k").get("stale") is None
    assert store.collection("k").get("new") is not None
    assert store.replay_report["stale_frames_dropped"] == 1


def test_per_op_records_stamped_with_epoch(tmp_path):
    lease, store = _holder_store(tmp_path)
    store.collection("k").upsert({"_id": "x"})
    lines = [
        json.loads(line)
        for line in open(str(tmp_path / "data" / "wal.log"))
    ]
    put = next(line for line in lines if line.get("o") == "p")
    assert put["e"] == lease.epoch


def test_stale_write_to_same_doc_cannot_clobber(tmp_path):
    """The doc-level consequence of frame fencing: a stale holder's
    version of a doc the new holder rewrote does not survive replay."""
    d = tmp_path / "data"
    d.mkdir()
    rec = '{"o":"g","n":1,"e":%d,"rs":[{"c":"k","o":"p","d":{"_id":"x","owner":%d}}]}\n'
    with open(d / "wal.log", "w") as fh:
        fh.write(rec % (2, 2))
        fh.write(rec % (1, 1))  # stale holder's racing write
    store = DurableStore(str(d))
    assert store.collection("k").get("x")["owner"] == 2


def test_fence_marker_drops_late_frame_before_first_commit(tmp_path):
    """A deposed holder's async flusher can land its frame AFTER the new
    holder opened but BEFORE the new holder's first commit. The new
    holder's open-time fence marker makes replay drop it anyway."""
    lease, store = _holder_store(tmp_path)  # epoch 1, no commits yet
    thief = _steal_from(tmp_path)           # epoch 2
    new_store = DurableStore(str(tmp_path / "data"), lease=thief)
    assert new_store.epoch == 2
    # the stale holder's late frame races in (same inode, append mode)
    with open(str(tmp_path / "data" / "wal.log"), "a") as fh:
        fh.write(
            '{"o":"g","n":1,"e":1,"rs":['
            '{"c":"k","o":"p","d":{"_id":"late"}}]}\n'
        )
    recovered = DurableStore(str(tmp_path / "data"))
    assert recovered.collection("k").get("late") is None
    assert recovered.replay_report["stale_frames_dropped"] == 1


def test_snapshot_watermark_survives_compaction(tmp_path):
    """Compaction truncates the WAL; the fence point must survive in the
    snapshot so a stale frame appended to the fresh log still ranks
    below it."""
    lease, store = _holder_store(tmp_path)
    thief = _steal_from(tmp_path)
    new_store = DurableStore(str(tmp_path / "data"), lease=thief)
    new_store.collection("k").upsert({"_id": "mine"})
    new_store.checkpoint()  # WAL truncated; watermark lives in snapshot
    with open(str(tmp_path / "data" / "wal.log"), "a") as fh:
        fh.write(
            '{"o":"g","n":1,"e":1,"rs":['
            '{"c":"k","o":"p","d":{"_id":"stale"}}]}\n'
        )
    recovered = DurableStore(str(tmp_path / "data"))
    assert recovered.collection("k").get("mine") is not None
    assert recovered.collection("k").get("stale") is None


def test_event_id_reseed_never_moves_backward(store):
    """Two recovered stores with different id floors share one process
    counter; a reseed against the LOW store (the concurrent-collision
    interleave) must not drag the counter back under ids already issued
    — the high-water mark wins."""
    from evergreen_tpu.models import event as event_mod
    from evergreen_tpu.storage.store import Store as _Store

    high, low = _Store(), _Store()
    base = {"resource_type": "TASK", "event_type": "X", "resource_id": "r",
            "timestamp": NOW, "processed_at": 0.0, "data": {}}
    for _ in range(5):
        e1 = event_mod.log(high, "TASK", "A", "r")
    hwm = int(e1.id.split("-")[1])
    low.collection("events").insert({"_id": "evt-3", **base})
    # the interleaved half of a concurrent collision: a reseed computed
    # from the low store landing after higher ids were already issued
    event_mod._reseed_past(low.collection("events"))
    e2 = event_mod.log(high, "TASK", "B", "r")
    assert int(e2.id.split("-")[1]) > hwm


def test_standby_epoch_outranks_orphaned_wal_frames(tmp_path):
    """If the lease file vanished but the WAL kept high-epoch frames, a
    fresh holder is advanced past them at open so its frames can never
    be dropped as stale."""
    d = tmp_path / "data"
    d.mkdir()
    with open(d / "wal.log", "w") as fh:
        fh.write(
            '{"o":"g","n":1,"e":7,"rs":[{"c":"k","o":"p","d":{"_id":"x"}}]}\n'
        )
    lease = FileLease(str(d / "writer.lease"), ttl_s=60.0)
    assert lease.try_acquire()
    assert lease.epoch == 1  # no floor file: fresh epoch
    store = DurableStore(str(d), lease=lease)
    assert lease.epoch == 8 and store.epoch == 8


def test_run_tick_refuses_when_fenced(tmp_path):
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick

    lease, store = _holder_store(tmp_path)
    distro_mod.insert(store, Distro(id="d1"))
    _steal_from(tmp_path)
    res = run_tick(
        store, TickOptions(create_intent_hosts=False), now=NOW
    )
    assert res.degraded == "fenced"
    assert res.queues == {}


def test_fenced_store_skips_scheduler_tick_population(tmp_path):
    """The on_lost path at the populator level: once the renewer observed
    the loss, the cron plane stops enqueueing ticks and per-op writes
    refuse."""
    from evergreen_tpu.units.crons import scheduler_tick_jobs

    lease, store = _holder_store(tmp_path)
    _steal_from(tmp_path)
    lease.stand_down("renewal failed")  # what the renewer thread does
    assert store.fenced
    assert scheduler_tick_jobs(store, NOW) == []
    with pytest.raises(EpochFencedError):
        store.collection("poke").upsert({"_id": "x"})


# --------------------------------------------------------------------------- #
# startup reconciliation
# --------------------------------------------------------------------------- #


def test_recovery_releases_half_dispatched_claim(store):
    """A crash between the dispatch CAS pair leaves a host claiming a
    task that never transitioned: recovery releases the claim."""
    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status=TaskStatus.UNDISPATCHED.value,
             activated=True),
    )
    host_mod.insert(
        store,
        Host(id="h1", distro_id="d1", status=HostStatus.RUNNING.value,
             running_task="t1"),
    )
    report = run_recovery_pass(store, now=NOW)
    assert report.released_claims == ["h1"]
    assert host_mod.get(store, "h1").running_task == ""
    t = task_mod.get(store, "t1")
    assert t.status == TaskStatus.UNDISPATCHED.value


def test_recovery_counts_provider_probe_failures(store, monkeypatch):
    """evglint shedcheck regression: a building host whose provider
    probe raises is SKIPPED by recovery (the periodic monitor retries),
    but the skip must be counted — an unreachable provider during
    recovery can no longer hide."""
    from evergreen_tpu.cloud.mock import MockCloudManager
    from evergreen_tpu.utils.log import get_counter

    host_mod.insert(
        store,
        Host(id="hb1", distro_id="d1", provider=Provider.MOCK.value,
             status=HostStatus.BUILDING.value, external_id="mock-hb1"),
    )

    def boom(self, store_, h):
        raise RuntimeError("provider API down")

    monkeypatch.setattr(MockCloudManager, "get_instance_status", boom)
    before = get_counter("recovery.provider_errors")
    run_recovery_pass(store, now=NOW)
    assert get_counter("recovery.provider_errors") == before + 1
    # the host is left for the periodic monitor, not terminated
    assert host_mod.get(store, "hb1").status == HostStatus.BUILDING.value


def test_recovery_keeps_coherent_assignment(store):
    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status=TaskStatus.STARTED.value,
             activated=True, host_id="h1", last_heartbeat=NOW - 5),
    )
    host_mod.insert(
        store,
        Host(id="h1", distro_id="d1", status=HostStatus.RUNNING.value,
             running_task="t1"),
    )
    report = run_recovery_pass(store, now=NOW)
    assert report.released_claims == []
    assert report.reconciled_tasks == 0
    assert host_mod.get(store, "h1").running_task == "t1"


def test_recovery_resets_stranded_task_with_attempt_accounting(store):
    """In-flight task on a dead host: archived as a system failure, then
    reset to run again; num_automatic_restarts carries the accounting."""
    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status=TaskStatus.STARTED.value,
             activated=True, host_id="gone", start_time=NOW - 100,
             last_heartbeat=NOW - 50),
    )
    report = run_recovery_pass(store, now=NOW)
    assert report.stranded_reset == ["t1"]
    t = task_mod.get(store, "t1")
    assert t.status == TaskStatus.UNDISPATCHED.value
    assert t.execution == 1
    assert t.num_automatic_restarts == 1
    archived = store.collection("task_archives").get("t1:0")
    assert archived["status"] == TaskStatus.FAILED.value
    assert archived["details_type"] == "system"


def test_recovery_stale_heartbeat_reset_and_max_restarts(store):
    """Heartbeat-stale in-flight task on a live host is reset; past the
    restart cap it STAYS system-failed."""
    from evergreen_tpu.units.host_jobs import MAX_STRANDED_TASK_RESTARTS

    host_mod.insert(
        store,
        Host(id="h1", distro_id="d1", status=HostStatus.RUNNING.value,
             running_task="t1"),
    )
    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status=TaskStatus.STARTED.value,
             activated=True, host_id="h1", last_heartbeat=NOW - 3600,
             num_automatic_restarts=0),
    )
    report = run_recovery_pass(store, now=NOW)
    assert report.stranded_reset == ["t1"]

    # exhaust the attempts: the task stays failed
    task_mod.coll(store).update(
        "t1",
        {"status": TaskStatus.STARTED.value, "host_id": "h1",
         "last_heartbeat": NOW - 3600,
         "num_automatic_restarts": MAX_STRANDED_TASK_RESTARTS},
    )
    host_mod.coll(store).update("h1", {"running_task": "t1"})
    report2 = run_recovery_pass(store, now=NOW)
    assert report2.stranded_failed == ["t1"]
    t = task_mod.get(store, "t1")
    assert t.status == TaskStatus.FAILED.value
    assert t.details_type == "system"


def test_recovery_reverifies_building_hosts(store):
    from evergreen_tpu.cloud.mock import MockCloudManager

    MockCloudManager.reset()
    distro_mod.insert(store, Distro(id="d1", provider=Provider.MOCK.value))
    host_mod.insert(
        store,
        Host(id="alive", distro_id="d1", provider=Provider.MOCK.value,
             status=HostStatus.PROVISIONING.value, external_id="mock-a"),
    )
    host_mod.insert(
        store,
        Host(id="ghost", distro_id="d1", provider=Provider.MOCK.value,
             status=HostStatus.BUILDING.value, external_id="mock-g"),
    )
    MockCloudManager.instances["mock-a"] = "running"
    # mock-g never registered → the provider reports it nonexistent
    report = run_recovery_pass(store, now=NOW)
    assert report.hosts_terminated == ["ghost"]
    assert host_mod.get(store, "ghost").status == HostStatus.TERMINATED.value
    assert host_mod.get(store, "alive").status == HostStatus.PROVISIONING.value


def test_recovery_invalidates_persister_state(store):
    from evergreen_tpu.scheduler.persister import persister_state_for

    pstate = persister_state_for(store)
    pstate._fps[("d1", False)] = object()
    pstate.infos_static = True
    run_recovery_pass(store, now=NOW)
    assert pstate._fps == {}
    assert pstate.infos_static is False


def test_recovery_counts_via_structured_log(store):
    from evergreen_tpu.utils import log as log_mod

    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status=TaskStatus.DISPATCHED.value,
             activated=True, host_id="gone", last_heartbeat=NOW - 10),
    )
    log_mod.reset_counters()
    got = []
    log_mod.add_sink(got.append)
    try:
        run_recovery_pass(store, now=NOW)
    finally:
        log_mod.remove_sink(got.append)
    assert log_mod.get_counter("recovery.reconciled_tasks") == 1
    recs = [r for r in got if r.get("message") == "recovery-pass"]
    assert recs and recs[0]["reconciled_tasks"] == 1


def test_environment_build_runs_recovery_pass(tmp_path):
    """A durable-writer Environment heals the data dir before the job
    plane starts (the standby-takeover entry point)."""
    from evergreen_tpu.env import Environment

    d = str(tmp_path / "data")
    seed = DurableStore(d)
    task_mod.insert(
        seed,
        Task(id="t1", distro_id="d1", status=TaskStatus.STARTED.value,
             activated=True, host_id="gone", last_heartbeat=1.0),
    )
    seed.close()
    env = Environment.build(data_dir=d, with_job_plane=False)
    try:
        assert env.recovery_report is not None
        assert env.recovery_report.reconciled_tasks == 1
        assert env.store.epoch == env.lease.epoch > 0
    finally:
        env.close()


# --------------------------------------------------------------------------- #
# satellite fixes
# --------------------------------------------------------------------------- #


def test_reap_stale_building_missing_timestamps(store):
    """A building host doc missing BOTH start_time and creation_time must
    not be reaped instantly: its clock starts at first observation."""
    from evergreen_tpu.units import host_jobs
    from evergreen_tpu.utils import log as log_mod

    store.collection("hosts").upsert(
        {"_id": "h-bare", "distro_id": "d1", "provider": "mock",
         "status": HostStatus.BUILDING.value, "started_by": "mci",
         "start_time": 0.0, "creation_time": 0.0, "running_task": ""},
    )
    log_mod.reset_counters()
    reaped = host_jobs.reap_stale_building_hosts(store, NOW)
    assert reaped == []
    assert log_mod.get_counter("hosts.reap_missing_timestamps") == 1
    # the clock started: stamped with the observation time …
    doc = store.collection("hosts").get("h-bare")
    assert doc["creation_time"] == NOW
    # … so once the window genuinely elapses it IS reaped
    reaped = host_jobs.reap_stale_building_hosts(store, NOW + 16 * 60)
    assert reaped == ["h-bare"]


# --------------------------------------------------------------------------- #
# crash matrix (subprocess; reduced sample in tier-1, full set slow)
# --------------------------------------------------------------------------- #


def test_crash_point_dispatch_assign_recovers(tmp_path):
    """The tier-1 reduced sample: one real SIGKILL-shaped death between
    the dispatch CAS pair; the restarted process reconciles and converges
    to the uninterrupted run's state."""
    from tools.crash_matrix import reference_state, run_point

    out = run_point("dispatch.assign", 0, reference=reference_state())
    assert out["ok"], out


@pytest.mark.slow
@pytest.mark.parametrize(
    "seam,idx",
    [p for p in __import__("tools.crash_matrix",
                           fromlist=["KILL_POINTS"]).KILL_POINTS],
)
def test_crash_matrix_full(seam, idx):
    from tools.crash_matrix import reference_state, run_point

    out = run_point(seam, idx, reference=reference_state())
    assert out["ok"], out


@pytest.mark.slow
def test_two_process_failover():
    """Holder SIGSTOPped mid-commit, standby steals + reconciles, holder
    SIGCONTed: the resumed holder's commit is rejected (EpochFencedError
    → FENCED/exit 75, or the renewer's stand-down), and zero stale-epoch
    frames survive past the fence point."""
    from tools.crash_matrix import failover_case

    out = failover_case()
    assert out["ok"], out
    assert out["standby_epoch"] > out["holder_epoch"]
