"""The generated GraphQL type system (api/schema.py): registry/resolver
agreement, typed introspection, unknown-field validation, __typename.

Reference parity: gqlgen's generated schema + introspection
(/root/reference/graphql/generated.go, graphql/schema/*.graphql).
"""
import pytest

from evergreen_tpu.api import schema as schema_mod
from evergreen_tpu.api.graphql import GraphQLApi
from evergreen_tpu.models.distro import Distro
from evergreen_tpu.models import distro as distro_mod
from evergreen_tpu.models import task as task_mod
from evergreen_tpu.models.task import Dependency, Task
from evergreen_tpu.storage.store import Store


@pytest.fixture()
def store():
    return Store()


@pytest.fixture()
def gql(store):
    return GraphQLApi(store)


def test_schema_and_resolver_registries_agree(gql):
    """Every resolver has a schema field and vice versa — the analog of
    gqlgen failing the build when schema and resolvers drift."""
    reg = schema_mod.schema()
    assert set(reg["Query"]["fields"]) == set(gql.queries)
    assert set(reg["Mutation"]["fields"]) == set(gql.mutations)


def test_schema_types_are_well_formed():
    reg = schema_mod.schema()
    for name, tdef in reg.items():
        assert tdef is not None, f"unresolved type {name}"
        assert tdef["name"] == name
        if tdef["kind"] == "OBJECT":
            for fname, fdef in tdef["fields"].items():
                inner = schema_mod.named_type(fdef["type"])
                assert inner in reg, (
                    f"{name}.{fname} references undeclared type {inner!r}"
                )
                for aname, adef in fdef["args"].items():
                    ainner = schema_mod.named_type(adef["type"])
                    assert ainner in reg, (
                        f"{name}.{fname}({aname}) references {ainner!r}"
                    )


def test_generated_task_type_matches_dataclass():
    reg = schema_mod.schema()
    fields = reg["Task"]["fields"]
    assert "display_name" in fields and "depends_on" in fields
    # private packing cache never leaks into the schema
    assert "_qrow" not in fields
    # list-of-dataclass maps to [Dependency!]!
    dep = fields["depends_on"]["type"]
    assert dep["kind"] == "NON_NULL"
    assert dep["ofType"]["kind"] == "LIST"
    assert schema_mod.named_type(dep) == "Dependency"
    assert reg["Dependency"]["fields"]["unattainable"]["type"] == (
        schema_mod.nn(schema_mod.BOOLEAN)
    )


def test_sensitive_fields_excluded():
    reg = schema_mod.schema()
    assert "secret" not in reg["Host"]["fields"]
    assert "api_key" not in reg["User"]["fields"]


def test_unknown_field_on_typed_object_errors(gql, store):
    task_mod.insert(store, Task(id="t1", display_name="compile"))
    out = gql.execute('{ task(taskId: "t1") { id displayNameTypo } }')
    assert "displayNameTypo" in out["errors"][0]["message"]
    assert "Task" in out["errors"][0]["message"]


def test_nested_typed_selection_and_typename(gql, store):
    task_mod.insert(
        store,
        Task(id="t1", display_name="compile",
             depends_on=[Dependency(task_id="t0", status="success")]),
    )
    out = gql.execute(
        '{ task(taskId: "t1") { __typename display_name '
        "depends_on { __typename task_id } } }"
    )
    t = out["data"]["task"]
    assert t["__typename"] == "Task"
    assert t["depends_on"][0] == {"__typename": "Dependency",
                                  "task_id": "t0"}


def test_nested_unknown_field_errors(gql, store):
    task_mod.insert(
        store,
        Task(id="t1", depends_on=[Dependency(task_id="t0")]),
    )
    out = gql.execute(
        '{ task(taskId: "t1") { depends_on { task_id nope } } }'
    )
    assert "nope" in out["errors"][0]["message"]
    assert "Dependency" in out["errors"][0]["message"]


def test_json_scalar_fields_stay_permissive(gql, store):
    """Raw store documents declared as JSON project any key."""
    store.collection("project_refs").insert(
        {"_id": "p1", "enabled": True, "branch": "main"}
    )
    out = gql.execute("{ projects { _id branch anything } }")
    assert out["data"]["projects"][0]["branch"] == "main"
    assert out["data"]["projects"][0]["anything"] is None


def test_full_introspection_query(gql):
    """The graphiql-style introspection query executes and returns typed
    fields with ofType chains."""
    out = gql.execute(
        """
        { __schema {
            queryType { name }
            mutationType { name }
            types {
              kind name
              fields { name args { name type { kind name ofType { kind name } } defaultValue }
                       type { kind name ofType { kind name ofType { kind name } } } }
              inputFields { name type { kind name } }
              enumValues { name }
            }
            directives { name locations }
        } }
        """
    )
    assert "errors" not in out, out.get("errors")
    s = out["data"]["__schema"]
    assert s["queryType"]["name"] == "Query"
    by_name = {t["name"]: t for t in s["types"]}
    task_fields = {f["name"]: f for f in by_name["Task"]["fields"]}
    # Task.priority: Int!
    pr = task_fields["priority"]["type"]
    assert pr["kind"] == "NON_NULL" and pr["ofType"]["name"] == "Int"
    # input object introspects its fields
    vt = by_name["VariantTasksInput"]
    assert vt["kind"] == "INPUT_OBJECT"
    assert {f["name"] for f in vt["inputFields"]} == {"variant", "tasks"}
    # enum meta-type
    assert {v["name"] for v in by_name["__TypeKind"]["enumValues"]} >= {
        "OBJECT", "SCALAR", "NON_NULL"
    }
    # query field args carry rendered defaults
    q_fields = {f["name"]: f for f in by_name["Query"]["fields"]}
    wf_args = {a["name"]: a for a in q_fields["waterfall"]["args"]}
    assert wf_args["limit"]["defaultValue"] == "10"
    assert wf_args["projectId"]["type"]["kind"] == "NON_NULL"


def test_type_introspection_by_name(gql):
    out = gql.execute(
        '{ __type(name: "Host") { name kind fields { name } } }'
    )
    fields = {f["name"] for f in out["data"]["__type"]["fields"]}
    assert "distro_id" in fields and "secret" not in fields
    # unknown type -> null, not an error
    out2 = gql.execute('{ __type(name: "Nope") { name } }')
    assert out2["data"]["__type"] is None


def test_distro_nested_settings_typed(gql, store):
    distro_mod.insert(store, Distro(id="d1"))
    out = gql.execute(
        "{ distros { id planner_settings { version target_time_s } "
        "host_allocator_settings { maximum_hosts } } }"
    )
    d = out["data"]["distros"][0]
    assert d["planner_settings"]["version"] == "tpu"
    assert isinstance(
        d["host_allocator_settings"]["maximum_hosts"], int
    )
    bad = gql.execute("{ distros { planner_settings { nope } } }")
    assert "PlannerSettings" in bad["errors"][0]["message"]
