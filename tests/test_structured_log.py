"""Structured logging plane (utils/log.py): leveled field records,
buffered sinks, in-store ring, config-section wiring, and the
runtime-stats line the scheduler tick emits. Reference analog: grip
message.Fields logging with buffered senders (scheduler/wrapper.go:93-128
runtime-stats; config_logger.go knobs).
"""
import pytest

from evergreen_tpu.api.rest import RestApi
from evergreen_tpu.settings import LoggerConfig
from evergreen_tpu.utils import log as log_mod
from evergreen_tpu.utils.log import (
    BufferedSink,
    Logger,
    StoreSink,
    add_sink,
    configure,
    reset_sinks,
    set_level,
)


@pytest.fixture(autouse=True)
def _clean_log_state():
    yield
    reset_sinks()
    set_level("info")


def test_logger_emits_field_records():
    got = []
    reset_sinks(got.append)
    log = Logger("scheduler")
    log.info("runtime-stats", operation="tick", n_tasks=5)
    (rec,) = got
    assert rec["component"] == "scheduler"
    assert rec["level"] == "info"
    assert rec["message"] == "runtime-stats"
    assert rec["operation"] == "tick" and rec["n_tasks"] == 5
    assert rec["ts"] > 0


def test_level_threshold_and_config(store):
    got = []
    reset_sinks(got.append)
    log = Logger("c")
    log.debug("hidden")
    assert got == []
    cfg = LoggerConfig.get(store)
    cfg.default_level = "debug"
    cfg.set(store)
    configure(store)
    log.debug("visible")
    assert [r["message"] for r in got] == ["visible"]
    cfg.default_level = "error"
    cfg.set(store)
    configure(store)
    log.warning("suppressed")
    log.error("boom")
    assert [r["message"] for r in got] == ["visible", "boom"]


def test_broken_sink_never_breaks_caller():
    got = []

    def bad(rec):
        raise RuntimeError("sink down")

    reset_sinks(bad, got.append)
    from evergreen_tpu.utils.log import get_counter

    before = get_counter("log.sink_errors")
    Logger("c").info("still delivered")
    assert [r["message"] for r in got] == ["still delivered"]
    # evglint shedcheck regression: the swallowed sink failure must
    # reconcile somewhere — the loss is counted, never silent
    assert get_counter("log.sink_errors") == before + 1


def test_buffered_sink_flushes_on_count_and_age():
    batches = []
    sink = BufferedSink(batches.append, count=3, interval_s=9999)
    log = Logger("c")
    reset_sinks(sink)
    log.info("a")
    log.info("b")
    assert batches == []
    log.info("c")
    assert len(batches) == 1 and len(batches[0]) == 3
    # age-based flush
    sink2 = BufferedSink(batches.append, count=1000, interval_s=0.0)
    reset_sinks(sink2)
    log.info("d")
    assert len(batches) == 2
    # manual flush drains the remainder
    sink3 = BufferedSink(batches.append, count=1000, interval_s=9999)
    reset_sinks(sink3)
    log.info("e")
    sink3.flush()
    assert [r["message"] for r in batches[-1]] == ["e"]


def test_store_sink_ring_and_admin_route(store):
    sink = StoreSink(store, cap=50)
    reset_sinks(sink)
    log = Logger("scheduler")
    for i in range(300):
        log.info("line", n=i)
    coll = store.collection(StoreSink.COLLECTION)
    assert len(coll) <= 50 + 256  # cap plus one amortized-trim window
    api = RestApi(store)
    st, out = api.handle("GET", "/rest/v2/admin/log_lines", {"limit": 10})
    assert st == 200 and len(out) == 10
    assert out[-1]["n"] == 299  # newest last
    st, out = api.handle("GET", "/rest/v2/admin/log_lines",
                         {"level": "error"})
    assert st == 200 and out == []


def test_store_sink_resumes_seq_after_restart(tmp_path):
    """With a durable store, a fresh process's sink must continue after
    the surviving ids, never overwrite or reorder them."""
    from evergreen_tpu.storage.durable import DurableStore

    store = DurableStore(str(tmp_path))
    sink = StoreSink(store, cap=100)
    reset_sinks(sink)
    log = Logger("c")
    log.info("before-restart")
    store.close()
    store2 = DurableStore(str(tmp_path))
    sink2 = StoreSink(store2, cap=100)
    reset_sinks(sink2)
    log.info("after-restart")
    docs = store2.collection(StoreSink.COLLECTION).find()
    docs.sort(key=lambda d: d["_id"])
    assert [d["message"] for d in docs] == ["before-restart",
                                           "after-restart"]


def test_tick_emits_runtime_stats_line(store):
    from evergreen_tpu.models import distro as distro_mod
    from evergreen_tpu.models import task as task_mod
    from evergreen_tpu.models.distro import Distro
    from evergreen_tpu.models.task import Task
    from evergreen_tpu.scheduler.wrapper import TickOptions, run_tick

    got = []
    reset_sinks(got.append)
    distro_mod.insert(store, Distro(id="d1"))
    task_mod.insert(
        store,
        Task(id="t1", distro_id="d1", status="undispatched", activated=True,
             expected_duration_s=60),
    )
    run_tick(store, TickOptions(create_intent_hosts=False))
    stats = [r for r in got if r["message"] == "runtime-stats"]
    assert stats, got
    rec = stats[-1]
    assert rec["component"] == "scheduler"
    assert rec["n_tasks"] == 1 and rec["n_distros"] == 1
    assert rec["total_ms"] > 0


def test_sampled_request_logger(store):
    """HTTP access sampling (reference service/sampled_request_logger.go):
    off by default; at ratio 1.0 every request logs; 5xx always logs
    while sampling is on."""
    import threading
    import urllib.request

    got = []
    reset_sinks(got.append)
    api = RestApi(store)
    srv = api.serve(port=0)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        urllib.request.urlopen(f"{base}/rest/v2/status").read()
        assert got == []  # ratio defaults to 0 → no access records
        cfg = LoggerConfig.get(store)
        cfg.request_sample_ratio = 1.0
        cfg.set(store)
        api._sample_ratio_cache = None  # expire the 5s TTL cache
        urllib.request.urlopen(f"{base}/rest/v2/status").read()
        reqs = [r for r in got if r["message"] == "request"]
        assert reqs and reqs[0]["path"] == "/rest/v2/status"
        assert reqs[0]["status"] == 200 and reqs[0]["duration_ms"] >= 0
    finally:
        srv.shutdown()


def test_job_failure_logs_error_line(store):
    from evergreen_tpu.queue.jobs import FnJob, JobQueue

    got = []
    reset_sinks(got.append)

    def boom(s):
        raise ValueError("job exploded")

    q = JobQueue(store, workers=1)
    q.put(FnJob("j1", boom, job_type="test-job"))
    q.wait_idle()
    q.close()
    errs = [r for r in got if r["level"] == "error"]
    assert errs and errs[0]["job_type"] == "test-job"
    assert "job exploded" in errs[0]["error"]
