"""WAL-tailing read replicas (storage/replica.py): the multi-process
read-scaling story. Reference analog: any app-server replica serves reads
because state lives in shared Mongo (environment.go:431-486); here a
replica tails the writer's WAL and serves the same read surface.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from evergreen_tpu.api.rest import RestApi
from evergreen_tpu.storage.durable import DurableStore
from evergreen_tpu.storage.replica import ReplicaReadOnly, ReplicaStore


def test_replica_sees_primary_writes(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1", "status": "undispatched"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1")["status"] == "undispatched"
    # subsequent writes arrive on poll
    primary.collection("tasks").update("t1", {"status": "success"})
    primary.collection("hosts").insert({"_id": "h1", "status": "running"})
    assert replica.poll() >= 2
    assert replica.collection("tasks").get("t1")["status"] == "success"
    assert replica.collection("hosts").get("h1") is not None
    # removes replicate too
    primary.collection("hosts").remove("h1")
    replica.poll()
    assert replica.collection("hosts").get("h1") is None


def test_replica_survives_primary_checkpoint(tmp_path):
    primary = DurableStore(str(tmp_path))
    for i in range(20):
        primary.collection("tasks").insert({"_id": f"t{i}", "n": i})
    replica = ReplicaStore(str(tmp_path))
    assert len(replica.collection("tasks")) == 20
    # checkpoint rewrites the snapshot and truncates the WAL in place
    primary.collection("tasks").update("t0", {"n": 99})
    primary.checkpoint()
    primary.collection("tasks").insert({"_id": "after", "n": -1})
    replica.poll()
    assert replica.collection("tasks").get("t0")["n"] == 99
    assert replica.collection("tasks").get("after") is not None
    assert len(replica.collection("tasks")) == 21


def test_replica_rejects_writes_with_primary_hint(tmp_path):
    DurableStore(str(tmp_path)).collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://primary:9090")
    with pytest.raises(ReplicaReadOnly) as e:
        replica.collection("tasks").update("t1", {"x": 1})
    assert e.value.primary_url == "http://primary:9090"
    for call in (
        lambda c: c.insert({"_id": "z"}),
        lambda c: c.upsert({"_id": "z"}),
        lambda c: c.remove("t1"),
        lambda c: c.clear(),
        lambda c: c.mutate("t1", lambda d: d),
        lambda c: c.compare_and_set("t1", expect={}, update={}),
    ):
        with pytest.raises(ReplicaReadOnly):
            call(replica.collection("tasks"))
    # reads still work
    assert replica.collection("tasks").get("t1") is not None


def test_replica_rest_api_reads_200_writes_503(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("distros").insert({"_id": "d1", "provider": "mock"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://primary:9090")
    api = RestApi(replica)
    st, out = api.handle("GET", "/rest/v2/distros", {})
    assert st == 200 and out[0]["_id"] == "d1"
    st, out = api.handle(
        "PUT", "/rest/v2/distros/d2", {"provider": "mock"}
    )
    assert st == 503
    assert out["primary"] == "http://primary:9090"


def test_replica_tails_a_real_writer_process(tmp_path):
    """Cross-process: a subprocess writer appends while this process's
    replica tails — the two-replica deployment shape."""
    data_dir = str(tmp_path)
    script = f"""
import time
from evergreen_tpu.utils.jaxenv import force_cpu
from evergreen_tpu.storage.durable import DurableStore
store = DurableStore({data_dir!r})
for i in range(50):
    store.collection("events").insert({{"_id": f"e{{i}}", "n": i}})
    if i == 25:
        store.checkpoint()
store.close()
print("WRITER DONE", flush=True)
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    proc = subprocess.Popen(
        [sys.executable, "-c", script], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    replica = ReplicaStore(data_dir, poll_interval_s=0.05)
    replica.start()
    try:
        out, err = proc.communicate(timeout=120)
        assert "WRITER DONE" in out, err
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(replica.collection("events")) == 50:
                break
            time.sleep(0.05)
        assert len(replica.collection("events")) == 50
        assert replica.collection("events").get("e49")["n"] == 49
    finally:
        replica.close()
        proc.kill()


def test_write_guard_is_thread_local_during_apply(tmp_path):
    """While the tail thread is mid-apply, a REST thread's write must
    still raise — the permission is per-thread, not a shared flag."""
    import threading

    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path))
    entered = threading.Event()
    release = threading.Event()
    orig_apply = replica._apply

    def slow_apply(rec):
        entered.set()
        release.wait(5)
        orig_apply(rec)

    replica._apply = slow_apply
    primary.collection("tasks").insert({"_id": "t2"})
    poller = threading.Thread(target=replica.poll)
    poller.start()
    assert entered.wait(5)
    # tail thread holds _applying for ITS thread only
    with pytest.raises(ReplicaReadOnly):
        replica.collection("tasks").insert({"_id": "smuggled"})
    release.set()
    poller.join()
    assert replica.collection("tasks").get("t2") is not None
    assert replica.collection("tasks").get("smuggled") is None


def test_snapshot_reload_never_shows_empty_state(tmp_path):
    """Readers during a checkpoint reload see old or new state, never an
    empty collection."""
    import threading

    primary = DurableStore(str(tmp_path))
    for i in range(200):
        primary.collection("tasks").insert({"_id": f"t{i}"})
    replica = ReplicaStore(str(tmp_path))
    primary.collection("tasks").update("t0", {"marked": True})
    primary.checkpoint()
    stop = threading.Event()
    failures = []

    def reader():
        while not stop.is_set():
            n = len(replica.collection("tasks"))
            if n not in (200,):
                failures.append(n)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(10):
        replica._load_snapshot()
    stop.set()
    for t in threads:
        t.join()
    assert failures == []


def test_rate_limited_replica_serves_reads(tmp_path):
    """Rate limiting keeps per-server scratch writable on a replica —
    a limited replica must keep serving reads, not 500."""
    primary = DurableStore(str(tmp_path))
    primary.collection("distros").insert({"_id": "d1", "provider": "mock"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://p:9090")
    api = RestApi(replica, rate_limit_per_min=100)
    for _ in range(3):
        st, out = api.handle("GET", "/rest/v2/distros", {},
                             headers={"x-peer-addr": "10.0.0.9"})
        assert st == 200
    # and the limit actually enforces locally
    api2 = RestApi(replica, rate_limit_per_min=1)
    api2.handle("GET", "/rest/v2/distros", {},
                headers={"x-peer-addr": "10.0.0.9"})
    sts = [api2.handle("GET", "/rest/v2/distros", {},
                       headers={"x-peer-addr": "10.0.0.9"})[0]
           for _ in range(8)]
    assert 429 in sts


def test_replica_skips_primary_rate_limit_records(tmp_path):
    """The primary journals its rate_limits writes; a replica must not
    let them clobber its own per-server windows."""
    primary = DurableStore(str(tmp_path))
    replica = ReplicaStore(str(tmp_path))
    replica.collection("rate_limits").upsert({"_id": "u:1", "n": 7})
    primary.collection("rate_limits").upsert({"_id": "u:1", "n": 1})
    primary.collection("tasks").insert({"_id": "t1"})
    replica.poll()
    assert replica.collection("rate_limits").get("u:1")["n"] == 7
    assert replica.collection("tasks").get("t1") is not None


def test_corrupt_terminated_wal_line_does_not_stall_replication(tmp_path):
    """A terminated-but-unparseable line (merged torn append) loses that
    one record, never everything after it — on the replica AND on
    primary recovery."""
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "GARBAGE\n')
    primary.collection("tasks").insert({"_id": "t2"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1") is not None
    assert replica.collection("tasks").get("t2") is not None
    # primary recovery tolerates it the same way
    recovered = DurableStore(str(tmp_path))
    assert recovered.collection("tasks").get("t2") is not None


def test_journal_repairs_torn_tail_on_open(tmp_path):
    """A crash mid-append leaves an unterminated line; the next writer
    terminates it before appending so records never merge."""
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    primary._journal.close()
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "half')  # no \n
    # a fresh writer repairs the tail, then appends cleanly
    writer2 = DurableStore(str(tmp_path))
    writer2.collection("tasks").insert({"_id": "t2"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1") is not None
    assert replica.collection("tasks").get("t2") is not None
    assert replica.collection("tasks").get("half") is None


def test_task_log_appends_reach_replicas(tmp_path):
    """Log appends must be journaled writes (the in-place extend bug made
    them invisible to replicas and lost on restart)."""
    from evergreen_tpu.api.rest import RestApi as _Api

    primary = DurableStore(str(tmp_path))
    api = _Api(primary)
    primary.collection("tasks").insert(
        {"_id": "t1", "status": "started", "execution": 0}
    )
    api.handle("POST", "/rest/v2/tasks/t1/agent/logs", {"lines": ["one"]})
    api.handle("POST", "/rest/v2/tasks/t1/agent/logs", {"lines": ["two"]})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("task_logs").get("t1")["lines"] == [
        "one", "two"]
    # and a primary restart keeps them
    recovered = DurableStore(str(tmp_path))
    assert recovered.collection("task_logs").get("t1")["lines"] == [
        "one", "two"]


def test_replica_tolerates_torn_tail(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path))
    # simulate the writer mid-append: a partial line at the WAL tail
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "t2"')
    assert replica.poll() == 0
    assert replica.collection("tasks").get("t2") is None
    # the writer finishes the line: the next poll applies it
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write(', "x": 1}}\n')
    assert replica.poll() == 1
    assert replica.collection("tasks").get("t2") is not None
