"""WAL-tailing read replicas (storage/replica.py): the multi-process
read-scaling story. Reference analog: any app-server replica serves reads
because state lives in shared Mongo (environment.go:431-486); here a
replica tails the writer's WAL and serves the same read surface.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from evergreen_tpu.api.rest import RestApi
from evergreen_tpu.storage.durable import DurableStore
from evergreen_tpu.storage.replica import ReplicaReadOnly, ReplicaStore


def test_replica_sees_primary_writes(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1", "status": "undispatched"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1")["status"] == "undispatched"
    # subsequent writes arrive on poll
    primary.collection("tasks").update("t1", {"status": "success"})
    primary.collection("hosts").insert({"_id": "h1", "status": "running"})
    assert replica.poll() >= 2
    assert replica.collection("tasks").get("t1")["status"] == "success"
    assert replica.collection("hosts").get("h1") is not None
    # removes replicate too
    primary.collection("hosts").remove("h1")
    replica.poll()
    assert replica.collection("hosts").get("h1") is None


def test_replica_survives_primary_checkpoint(tmp_path):
    primary = DurableStore(str(tmp_path))
    for i in range(20):
        primary.collection("tasks").insert({"_id": f"t{i}", "n": i})
    replica = ReplicaStore(str(tmp_path))
    assert len(replica.collection("tasks")) == 20
    # checkpoint rewrites the snapshot and truncates the WAL in place
    primary.collection("tasks").update("t0", {"n": 99})
    primary.checkpoint()
    primary.collection("tasks").insert({"_id": "after", "n": -1})
    replica.poll()
    assert replica.collection("tasks").get("t0")["n"] == 99
    assert replica.collection("tasks").get("after") is not None
    assert len(replica.collection("tasks")) == 21


def test_replica_rejects_writes_with_primary_hint(tmp_path):
    DurableStore(str(tmp_path)).collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://primary:9090")
    with pytest.raises(ReplicaReadOnly) as e:
        replica.collection("tasks").update("t1", {"x": 1})
    assert e.value.primary_url == "http://primary:9090"
    for call in (
        lambda c: c.insert({"_id": "z"}),
        lambda c: c.upsert({"_id": "z"}),
        lambda c: c.remove("t1"),
        lambda c: c.clear(),
        lambda c: c.mutate("t1", lambda d: d),
        lambda c: c.compare_and_set("t1", expect={}, update={}),
    ):
        with pytest.raises(ReplicaReadOnly):
            call(replica.collection("tasks"))
    # reads still work
    assert replica.collection("tasks").get("t1") is not None


def test_replica_rest_api_reads_200_writes_503(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("distros").insert({"_id": "d1", "provider": "mock"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://primary:9090")
    api = RestApi(replica)
    st, out = api.handle("GET", "/rest/v2/distros", {})
    assert st == 200 and out[0]["_id"] == "d1"
    st, out = api.handle(
        "PUT", "/rest/v2/distros/d2", {"provider": "mock"}
    )
    assert st == 503
    assert out["primary"] == "http://primary:9090"


def test_replica_tails_a_real_writer_process(tmp_path):
    """Cross-process: a subprocess writer appends while this process's
    replica tails — the two-replica deployment shape."""
    data_dir = str(tmp_path)
    script = f"""
import time
from evergreen_tpu.utils.jaxenv import force_cpu
from evergreen_tpu.storage.durable import DurableStore
store = DurableStore({data_dir!r})
for i in range(50):
    store.collection("events").insert({{"_id": f"e{{i}}", "n": i}})
    if i == 25:
        store.checkpoint()
store.close()
print("WRITER DONE", flush=True)
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": ""}
    proc = subprocess.Popen(
        [sys.executable, "-c", script], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    replica = ReplicaStore(data_dir, poll_interval_s=0.05)
    replica.start()
    try:
        out, err = proc.communicate(timeout=120)
        assert "WRITER DONE" in out, err
        deadline = time.time() + 10
        while time.time() < deadline:
            if len(replica.collection("events")) == 50:
                break
            time.sleep(0.05)
        assert len(replica.collection("events")) == 50
        assert replica.collection("events").get("e49")["n"] == 49
    finally:
        replica.close()
        proc.kill()


def test_write_guard_is_thread_local_during_apply(tmp_path):
    """While the tail thread is mid-apply, a REST thread's write must
    still raise — the permission is per-thread, not a shared flag."""
    import threading

    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path))
    entered = threading.Event()
    release = threading.Event()
    orig_apply = replica._apply

    def slow_apply(rec):
        entered.set()
        release.wait(5)
        orig_apply(rec)

    replica._apply = slow_apply
    primary.collection("tasks").insert({"_id": "t2"})
    poller = threading.Thread(target=replica.poll)
    poller.start()
    assert entered.wait(5)
    # tail thread holds _applying for ITS thread only
    with pytest.raises(ReplicaReadOnly):
        replica.collection("tasks").insert({"_id": "smuggled"})
    release.set()
    poller.join()
    assert replica.collection("tasks").get("t2") is not None
    assert replica.collection("tasks").get("smuggled") is None


def test_snapshot_reload_never_shows_empty_state(tmp_path):
    """Readers during a checkpoint reload see old or new state, never an
    empty collection."""
    import threading

    primary = DurableStore(str(tmp_path))
    for i in range(200):
        primary.collection("tasks").insert({"_id": f"t{i}"})
    replica = ReplicaStore(str(tmp_path))
    primary.collection("tasks").update("t0", {"marked": True})
    primary.checkpoint()
    stop = threading.Event()
    failures = []

    def reader():
        while not stop.is_set():
            n = len(replica.collection("tasks"))
            if n not in (200,):
                failures.append(n)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(10):
        replica._load_snapshot()
    stop.set()
    for t in threads:
        t.join()
    assert failures == []


def test_rate_limited_replica_serves_reads(tmp_path):
    """Rate limiting keeps per-server scratch writable on a replica —
    a limited replica must keep serving reads, not 500."""
    primary = DurableStore(str(tmp_path))
    primary.collection("distros").insert({"_id": "d1", "provider": "mock"})
    replica = ReplicaStore(str(tmp_path), primary_url="http://p:9090")
    api = RestApi(replica, rate_limit_per_min=100)
    for _ in range(3):
        st, out = api.handle("GET", "/rest/v2/distros", {},
                             headers={"x-peer-addr": "10.0.0.9"})
        assert st == 200
    # and the limit actually enforces locally
    api2 = RestApi(replica, rate_limit_per_min=1)
    api2.handle("GET", "/rest/v2/distros", {},
                headers={"x-peer-addr": "10.0.0.9"})
    sts = [api2.handle("GET", "/rest/v2/distros", {},
                       headers={"x-peer-addr": "10.0.0.9"})[0]
           for _ in range(8)]
    assert 429 in sts


def test_replica_skips_primary_rate_limit_records(tmp_path):
    """The primary journals its rate_limits writes; a replica must not
    let them clobber its own per-server windows."""
    primary = DurableStore(str(tmp_path))
    replica = ReplicaStore(str(tmp_path))
    replica.collection("rate_limits").upsert({"_id": "u:1", "n": 7})
    primary.collection("rate_limits").upsert({"_id": "u:1", "n": 1})
    primary.collection("tasks").insert({"_id": "t1"})
    replica.poll()
    assert replica.collection("rate_limits").get("u:1")["n"] == 7
    assert replica.collection("tasks").get("t1") is not None


def test_corrupt_terminated_wal_line_does_not_stall_replication(tmp_path):
    """A terminated-but-unparseable line (merged torn append) loses that
    one record, never everything after it — on the replica AND on
    primary recovery."""
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "GARBAGE\n')
    primary.collection("tasks").insert({"_id": "t2"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1") is not None
    assert replica.collection("tasks").get("t2") is not None
    # primary recovery tolerates it the same way
    recovered = DurableStore(str(tmp_path))
    assert recovered.collection("tasks").get("t2") is not None


def test_journal_repairs_torn_tail_on_open(tmp_path):
    """A crash mid-append leaves an unterminated line; the next writer
    terminates it before appending so records never merge."""
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    primary._journal.close()
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "half')  # no \n
    # a fresh writer repairs the tail, then appends cleanly
    writer2 = DurableStore(str(tmp_path))
    writer2.collection("tasks").insert({"_id": "t2"})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("tasks").get("t1") is not None
    assert replica.collection("tasks").get("t2") is not None
    assert replica.collection("tasks").get("half") is None


def test_task_log_appends_reach_replicas(tmp_path):
    """Log appends must be journaled writes (the in-place extend bug made
    them invisible to replicas and lost on restart)."""
    from evergreen_tpu.api.rest import RestApi as _Api

    primary = DurableStore(str(tmp_path))
    api = _Api(primary)
    primary.collection("tasks").insert(
        {"_id": "t1", "status": "started", "execution": 0}
    )
    api.handle("POST", "/rest/v2/tasks/t1/agent/logs", {"lines": ["one"]})
    api.handle("POST", "/rest/v2/tasks/t1/agent/logs", {"lines": ["two"]})
    replica = ReplicaStore(str(tmp_path))
    assert replica.collection("task_logs").get("t1")["lines"] == [
        "one", "two"]
    # and a primary restart keeps them
    recovered = DurableStore(str(tmp_path))
    assert recovered.collection("task_logs").get("t1")["lines"] == [
        "one", "two"]


def test_replica_tolerates_torn_tail(tmp_path):
    primary = DurableStore(str(tmp_path))
    primary.collection("tasks").insert({"_id": "t1"})
    replica = ReplicaStore(str(tmp_path))
    # simulate the writer mid-append: a partial line at the WAL tail
    wal = os.path.join(str(tmp_path), "wal.log")
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write('{"c": "tasks", "o": "p", "d": {"_id": "t2"')
    assert replica.poll() == 0
    assert replica.collection("tasks").get("t2") is None
    # the writer finishes the line: the next poll applies it
    with open(wal, "a", encoding="utf-8") as fh:
        fh.write(', "x": 1}}\n')
    assert replica.poll() == 1
    assert replica.collection("tasks").get("t2") is not None


# --------------------------------------------------------------------------- #
# Write forwarding: replicas proxy mutations to the primary (reference:
# any app server writes to shared Mongo; here writes serialize at the
# WAL writer and replicate back through the tail).
# --------------------------------------------------------------------------- #


@pytest.fixture()
def primary_server(tmp_path):
    import threading

    store = DurableStore(str(tmp_path))
    api = RestApi(store)
    srv = api.serve("127.0.0.1", 0)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    yield store, f"http://127.0.0.1:{port}", tmp_path
    srv.shutdown()


def test_replica_forwards_rest_writes(primary_server):
    pstore, purl, data_dir = primary_server
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    rapi = RestApi(replica)

    st, out = rapi.handle("PUT", "/rest/v2/distros/d-fwd",
                          {"provider": "mock"})
    assert st in (200, 201), out
    # the primary applied it...
    assert pstore.collection("distros").get("d-fwd") is not None
    # ...and the replica already serves its own write back (poll ran
    # inside the forward path: read-your-writes)
    st, docs = rapi.handle("GET", "/rest/v2/distros", {})
    assert st == 200 and any(d["_id"] == "d-fwd" for d in docs)


def test_replica_forwards_graphql_mutations_serves_queries_locally(
    primary_server,
):
    pstore, purl, data_dir = primary_server
    pstore.collection("tasks").upsert(
        {"_id": "t-fwd", "status": "undispatched", "priority": 0,
         "display_name": "t", "activated": False}
    )
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    replica.poll()
    rapi = RestApi(replica)

    # mutation → forwarded to the primary
    st, out = rapi.handle(
        "POST", "/graphql",
        {"query": 'mutation { setTaskPriority(taskId: "t-fwd", '
                  "priority: 42) { id priority } }"},
    )
    assert st == 200 and "errors" not in out, out
    assert pstore.collection("tasks").get("t-fwd")["priority"] == 42

    # query → served locally (kill the primary's reachability by using a
    # fresh replica pointed at a dead port; reads must still work)
    dead = ReplicaStore(str(data_dir), primary_url="http://127.0.0.1:9")
    dead_api = RestApi(dead)
    st, out = dead_api.handle(
        "POST", "/graphql",
        {"query": '{ task(taskId: "t-fwd") { id priority } }'},
    )
    assert st == 200 and out["data"]["task"]["priority"] == 42


def test_forward_failure_degrades_to_503(tmp_path):
    DurableStore(str(tmp_path))  # create the data dir files
    replica = ReplicaStore(str(tmp_path),
                           primary_url="http://127.0.0.1:9")
    rapi = RestApi(replica)
    st, out = rapi.handle("PUT", "/rest/v2/distros/d1",
                          {"provider": "mock"})
    assert st == 503
    assert out["primary"] == "http://127.0.0.1:9"


def test_forwarded_requests_never_hop_again(primary_server):
    """A request already marked forwarded executes locally — on a
    replica that means ReplicaReadOnly → 503, not an infinite loop."""
    pstore, purl, data_dir = primary_server
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    rapi = RestApi(replica)
    st, out = rapi.handle(
        "PUT", "/rest/v2/distros/d-loop", {"provider": "mock"},
        {"x-evg-forwarded": "1"},
    )
    assert st == 503
    assert pstore.collection("distros").get("d-loop") is None


def _wsgi_post(api, path, raw, extra_headers=None):
    """Drive wsgi_app directly (the webhook branch lives there, outside
    handle())."""
    import io

    captured = {}

    def start_response(status, headers):
        captured["status"] = int(status.split()[0])

    environ = {
        "REQUEST_METHOD": "POST",
        "PATH_INFO": path,
        "CONTENT_LENGTH": str(len(raw)),
        "wsgi.input": io.BytesIO(raw),
        "REMOTE_ADDR": "127.0.0.1",
    }
    for k, v in (extra_headers or {}).items():
        environ["HTTP_" + k.upper().replace("-", "_")] = v
    body = b"".join(api.wsgi_app(environ, start_response))
    return captured["status"], json.loads(body or b"{}")


def test_replica_forwards_github_webhooks_raw(primary_server):
    """A webhook delivered to a replica forwards as RAW bytes (the HMAC
    covers the exact body) and the primary ingests it."""
    from evergreen_tpu.ingestion.repotracker import (
        ProjectRef,
        upsert_project_ref,
    )

    pstore, purl, data_dir = primary_server
    # the primary's hook handler parses a fixed config (network-free)
    upsert_project_ref(
        pstore,
        ProjectRef(id="proj", owner="acme", repo="widgets", branch="main"),
    )
    # reach into the served api: it shares pstore via the fixture's RestApi
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    rapi = RestApi(replica)
    payload = {
        "ref": "refs/heads/main",
        "repository": {"name": "widgets", "owner": {"login": "acme"}},
        "commits": [{"id": "d4d4d4d4d4", "message": "fix",
                     "author": {"name": "a"}}],
    }
    raw = json.dumps(payload).encode()
    st, out = _wsgi_post(
        rapi, "/hooks/github", raw,
        {"x-github-event": "push", "x-github-delivery": "dl-1",
         "content-type": "application/json"},
    )
    assert st == 200, out
    # the primary ingested the push (stub version on config fetch
    # failure still records the revision)
    assert any(
        v.get("revision", "").startswith("d4d4")
        for v in pstore.collection("versions").find()
    ), pstore.collection("versions").find()
    # and the replica already sees it (read-your-writes)
    assert any(
        v.get("revision", "").startswith("d4d4")
        for v in replica.collection("versions").find()
    )


def test_concurrent_polls_never_regress(primary_server):
    """REST post-forward polls race the background tail thread; the poll
    lock must keep document versions monotonic."""
    import threading

    pstore, purl, data_dir = primary_server
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    stop = threading.Event()
    errors = []

    def writer():
        for n in range(300):
            pstore.collection("counters").upsert({"_id": "c", "n": n})

    def poller():
        last = -1
        while not stop.is_set():
            try:
                replica.poll()
            except OSError:
                continue
            doc = replica.collection("counters").get("c")
            n = doc["n"] if doc else -1
            if n < last:
                errors.append((last, n))
            last = n

    pollers = [threading.Thread(target=poller) for _ in range(4)]
    for t in pollers:
        t.start()
    wt = threading.Thread(target=writer)
    wt.start()
    wt.join()
    time.sleep(0.2)
    stop.set()
    for t in pollers:
        t.join(timeout=5)
    assert not errors, f"document version regressed: {errors[:5]}"
    assert replica.collection("counters").get("c")["n"] == 299


def test_forward_routing_decisions(tmp_path):
    """Route-aware classification: mutating GETs forward, read-only
    POSTs stay local."""
    DurableStore(str(tmp_path))
    replica = ReplicaStore(str(tmp_path), primary_url="http://127.0.0.1:9")
    rapi = RestApi(replica)

    # mutating GETs forward (unreachable primary → 503 with hint)
    for path in ("/login/redirect",
                 "/rest/v2/hosts/h1/agent/next_task"):
        got = rapi._maybe_forward("GET", path, {}, {})
        assert got is not None and got[0] == 503, path

    # plain GETs and read-only POSTs stay local (None = run the handler)
    assert rapi._maybe_forward("GET", "/rest/v2/distros", {}, {}) is None
    for path in ("/rest/v2/projects/p/validate",
                 "/rest/v2/artifacts/sign",
                 "/rest/v2/tasks/t/select_tests"):
        assert rapi._maybe_forward("POST", path, {}, {}) is None, path

    # read-only POST actually works with the primary DOWN
    st, out = rapi.handle("POST", "/rest/v2/projects/p/validate",
                          {"config_yaml": "tasks: []"})
    assert st == 200 and "issues" in out


def test_agent_credentials_relay_through_replica(primary_server):
    """An authenticated agent can drive the protocol via a replica: the
    host-id/host-secret headers survive the forward hop."""
    from evergreen_tpu.models.host import Host
    from evergreen_tpu.models import host as host_mod

    pstore, purl, data_dir = primary_server
    host_mod.insert(
        pstore,
        Host(id="h-agent", distro_id="d1", status="running",
             secret="s3cr3t"),
    )
    replica = ReplicaStore(str(data_dir), primary_url=purl)
    replica.poll()
    rapi = RestApi(replica, require_auth=True)
    creds = {"host-id": "h-agent", "host-secret": "s3cr3t"}

    # mutating GET: next_task forwards WITH credentials → 200 (empty
    # queue, but authenticated)
    st, out = rapi.handle(
        "GET", "/rest/v2/hosts/h-agent/agent/next_task", {}, creds
    )
    assert st == 200, out

    # bad secret still dies (at the replica's own auth, before any hop)
    st, out = rapi.handle(
        "GET", "/rest/v2/hosts/h-agent/agent/next_task", {},
        {"host-id": "h-agent", "host-secret": "wrong"},
    )
    assert st == 401
