"""Process-per-shard fleet runtime (evergreen_tpu/runtime/).

Covers the ISSUE-13 contracts: control-protocol framing (torn/garbage
lines), supervisor spawn/heartbeat/restart-with-backoff, SIGKILL a
worker mid-round → fenced takeover at a strictly higher lease epoch
with zero duplicate dispatch, cross-process fenced handoffs, graceful
drain releasing every shard lease (including the classic service's
SIGTERM path), and the admin fleet endpoint shape.

Plus the ISSUE-14 survivability contracts: a supervisor crash puts
workers in ORPHAN mode (shard lease kept + renewed, autonomous local
ticks, bounded grace, clean drain at expiry), a restarted supervisor
ADOPTS live workers over the fleet-manifest control sockets (same
pids, zero shard-lease epoch bumps, zero recovery passes), the
supervisor fleet lease fences the control plane (a second supervisor
cannot acquire it; its stale-epoch commands are rejected with
``stale_sup``), and adoption-within-grace converges with the usual
no-duplicate-dispatch / exactly-one-owner invariants.

Process-spawning tests keep the workload tiny (a couple of distros,
a couple dozen tasks) and lease TTLs short so a fenced takeover lands
in ~2s; the full weathers + crash-point sample run under
``tools/fleet_runtime.py`` (gate --fleet-runtime).
"""
from __future__ import annotations

import io
import os
import signal
import subprocess
import sys
import time

import pytest

from evergreen_tpu.runtime.protocol import parse_line, send_msg
from evergreen_tpu.runtime.supervisor import (
    FleetSupervisor,
    attach_fleet_supervisor,
    peek_fleet_supervisor,
)
from evergreen_tpu.scenarios.procs import _seed_fleet
from evergreen_tpu.utils.benchgen import NOW
from evergreen_tpu.utils.retry import RetryPolicy

TICK_S = 15.0


def _policy(base: float = 0.2, cap: float = 2.0) -> RetryPolicy:
    return RetryPolicy(
        attempts=1_000_000, base_backoff_s=base, max_backoff_s=cap,
        jitter=0.0,
    )


def _fleet(data_dir, n_shards: int, workload=None, seed: bool = True,
           **kw) -> FleetSupervisor:
    if seed:
        _seed_fleet(
            str(data_dir), n_shards,
            workload or {"distros": 2, "tasks": 16, "seed": 11},
        )
    kw.setdefault("ttl_s", 1.0)
    kw.setdefault("hb_interval_s", 0.2)
    kw.setdefault("hb_deadline_s", 1.2)
    kw.setdefault("harness", True)
    kw.setdefault("recovery_anchor", NOW)
    kw.setdefault("restart_policy", _policy())
    kw.setdefault("orphan_grace_s", 30.0)
    kw.setdefault("orphan_tick_s", 0.5)
    kw.setdefault("supervisor_lease_ttl_s", 1.0)
    return FleetSupervisor(str(data_dir), n_shards, **kw)


def _reap(sup: FleetSupervisor) -> None:
    """Wait out a crashed-then-superseded supervisor's Popen handles
    so adopted workers never linger as zombies of the test process."""
    for h in sup.handles.values():
        if h.proc is None:
            continue
        if h.proc.poll() is None:
            try:
                h.proc.kill()
            except OSError:
                pass
        try:
            h.proc.wait(timeout=10.0)
        except Exception:  # noqa: BLE001 — best effort
            pass


def _drive_to_convergence(sup: FleetSupervisor, max_rounds: int = 24,
                          start: int = 0) -> int:
    """Round + agent step until the workload drains; returns the
    number of rounds driven."""
    for i in range(start, start + max_rounds):
        now = NOW + (i + 1) * TICK_S
        sup.round(now=now)
        done = sup.agent_sim(now=now)
        if (
            len(done) == sup.n_shards
            and sum(r.get("unfinished", 0) for r in done.values()) == 0
        ):
            return i + 1 - start
        # let a fenced takeover land before the next virtual tick
        deadline = time.time() + 30.0
        while time.time() < deadline and not all(
            h.state == "ready" for h in sup.handles.values()
        ):
            time.sleep(0.05)
    raise AssertionError("fleet did not converge")


# --------------------------------------------------------------------------- #
# control-protocol framing
# --------------------------------------------------------------------------- #


def test_parse_line_accepts_only_protocol_messages():
    assert parse_line('{"op":"round","ms":1.5}\n') == {
        "op": "round", "ms": 1.5,
    }
    # torn line (a killed writer's partial flush)
    assert parse_line('{"op":"round","ms"') is None
    # garbage: a stray library print on the channel
    assert parse_line("some warning text\n") is None
    assert parse_line("") is None
    assert parse_line("   \n") is None
    # JSON but not a protocol message
    assert parse_line("[1,2,3]\n") is None
    assert parse_line('{"no_op_field":1}\n') is None
    assert parse_line('{"op":7}\n') is None


def test_send_msg_survives_closed_pipe():
    buf = io.StringIO()
    assert send_msg(buf, op="tick", now=1.0)
    assert parse_line(buf.getvalue()) == {"op": "tick", "now": 1.0}
    buf.close()
    assert send_msg(buf, op="tick") is False  # dead peer: no raise


def test_worker_skips_garbage_command_lines(tmp_path):
    """Torn/garbage lines on a live worker's stdin must be skipped —
    the next well-formed command still executes."""
    sup = _fleet(tmp_path, 1)
    try:
        sup.start(monitor=False)
        h = sup.handles[0]
        assert h.state == "ready"
        h.proc.stdin.write("NOT JSON AT ALL\n")
        h.proc.stdin.write('{"op":"status"\n')  # torn
        h.proc.stdin.write('{"no_op": true}\n')
        h.proc.stdin.flush()
        h.send(op="status")
        reply = h.wait_reply("status", 15.0)
        assert reply is not None and reply["shard"] == 0
        # unknown ops answer an error instead of dying
        h.send(op="definitely-not-an-op")
        err = h.wait_reply("status", 5.0)  # error ends the wait → None
        assert err is None
        assert h.alive()
    finally:
        sup.stop()


# --------------------------------------------------------------------------- #
# spawn / heartbeat / rounds
# --------------------------------------------------------------------------- #


def test_spawn_heartbeat_and_rounds(tmp_path):
    sup = _fleet(tmp_path, 1)
    try:
        sup.start(monitor=False)
        h = sup.handles[0]
        assert h.state == "ready"
        assert h.epochs == [1]  # first lease acquisition
        time.sleep(0.6)  # a few beats
        assert not h.hb_deadline.exceeded()
        r = sup.round(now=NOW + TICK_S)
        assert 0 in r and r[0]["epoch"] == 1
        assert r[0]["n_tasks"] == 16
        rounds = _drive_to_convergence(sup, start=1)
        assert rounds >= 1
        assert sup.rounds_done >= 2
    finally:
        sup.stop()


def test_restart_backoff_grows_exponentially(tmp_path):
    """PR-1 RetryPolicy shape: consecutive failures widen the respawn
    pause. A quick hello does NOT reset the streak (boot-then-crash
    loops must keep widening); only a sustained healthy period does."""
    sup = FleetSupervisor(
        str(tmp_path), 1, restart_policy=_policy(base=0.1, cap=10.0),
    )
    h = sup.handles[0]
    sup._schedule_restart(h, 86)
    h.state = "new"
    # a hello that is immediately followed by another crash: the
    # streak keeps growing (ready_since too recent to count as healthy)
    h.ready_since = time.monotonic()
    sup._schedule_restart(h, 86)
    h.state = "new"
    sup._schedule_restart(h, 86)
    assert h.backoffs == [
        pytest.approx(0.1), pytest.approx(0.2), pytest.approx(0.4),
    ]
    # a SUSTAINED healthy period before the next death restarts the
    # ladder from base
    h.ready_since = time.monotonic() - (
        FleetSupervisor.BACKOFF_RESET_AFTER_S + 1.0
    )
    sup._schedule_restart(h, 86)
    assert h.backoffs[-1] == pytest.approx(0.1)


def test_sigkill_mid_round_fenced_takeover(tmp_path):
    """The acceptance centerpiece: kill a worker AT the wal.commit seam
    mid-round; the supervisor restarts it; the replacement steals the
    shard lease at a STRICTLY higher epoch; the fleet converges with
    zero duplicate dispatch and exactly-one-owner."""
    from evergreen_tpu.scenarios.invariants import (
        check_duplicate_dispatch,
        check_store_consistent,
    )
    from evergreen_tpu.scenarios.procs import _open_fleet_stores
    from evergreen_tpu.scheduler.sharded_plane import (
        fleet_owner_violations,
        merge_fleet_state,
    )

    sup = _fleet(
        tmp_path, 2,
        workload={"distros": 4, "tasks": 24, "seed": 11},
    )
    try:
        sup.start()
        sup.round(now=NOW + TICK_S)
        sup.agent_sim(now=NOW + TICK_S)
        h = sup.handles[0]
        h.send(op="arm_fault", seam="wal.commit", kind="crash")
        assert h.wait_reply("armed", 10.0) is not None
        _drive_to_convergence(sup, start=1)
        assert h.exits == [86], "the armed crash must have fired"
        assert h.restarts == 1
        assert len(h.epochs) == 2 and h.epochs[1] > h.epochs[0], (
            f"takeover must steal at a higher epoch: {h.epochs}"
        )
        assert sup.handles[1].restarts == 0
    finally:
        sup.stop()
    stores = _open_fleet_stores(str(tmp_path), 2)
    try:
        assert fleet_owner_violations(stores) == []
        merged = merge_fleet_state(stores)
        assert check_duplicate_dispatch(merged) == []
        assert check_store_consistent(merged) == []
    finally:
        for s in stores:
            s.close()


def test_hang_detection_kills_and_restarts(tmp_path):
    """A SIGSTOPped worker stops heartbeating; the supervisor's
    missed-heartbeat deadline kills it and the restart comes back
    fenced at a higher epoch."""
    sup = _fleet(tmp_path, 1)
    try:
        sup.start()
        h = sup.handles[0]
        os.kill(h.pid, signal.SIGSTOP)
        deadline = time.time() + 30.0
        while time.time() < deadline and h.restarts == 0:
            time.sleep(0.05)
        while time.time() < deadline and h.state != "ready":
            time.sleep(0.05)
        assert h.restarts == 1
        assert h.exits and h.exits[0] < 0  # killed, not exited
        assert len(h.epochs) == 2 and h.epochs[1] > h.epochs[0]
        _drive_to_convergence(sup)
    finally:
        sup.stop()


# --------------------------------------------------------------------------- #
# cross-process fenced handoff
# --------------------------------------------------------------------------- #


def test_migrate_over_control_protocol(tmp_path):
    from evergreen_tpu.scenarios.procs import _open_fleet_stores
    from evergreen_tpu.scheduler.sharded_plane import (
        HANDOFFS_COLLECTION,
        fleet_owner_violations,
    )

    sup = _fleet(
        tmp_path, 2,
        workload={"distros": 4, "tasks": 24, "seed": 11},
    )
    try:
        sup.start()
        sup.round(now=NOW + TICK_S)
        # find a distro and move it off its owner
        st = sup.broadcast("load", "load")
        src, reps = next(
            (k, v["reps"]) for k, v in sorted(st.items())
            if v["reps"]
        )
        distro = sorted(reps.values())[0]
        dst = (src + 1) % 2
        rec = sup.migrate(distro, src, dst, now=NOW + 16.0)
        assert rec is not None and rec["state"] == "released"
        assert distro in rec["group"]
        sup.drain()
    finally:
        sup.stop()
    stores = _open_fleet_stores(str(tmp_path), 2)
    try:
        assert fleet_owner_violations(stores) == []
        # the moved distro's documents now live on the target
        assert stores[dst].collection("distros").get(distro) is not None
        assert stores[src].collection("distros").get(distro) is None
        src_rec = stores[src].collection(HANDOFFS_COLLECTION).get(
            rec["_id"]
        )
        tgt_rec = stores[dst].collection(HANDOFFS_COLLECTION).get(
            rec["_id"]
        )
        assert src_rec["state"] == "done"
        assert tgt_rec["state"] == "primed"
    finally:
        for s in stores:
            s.close()


# --------------------------------------------------------------------------- #
# graceful shutdown
# --------------------------------------------------------------------------- #


def test_graceful_stop_releases_all_shard_leases(tmp_path):
    from evergreen_tpu.storage.lease import shard_lease_path

    sup = _fleet(tmp_path, 2,
                 workload={"distros": 4, "tasks": 24, "seed": 11})
    sup.start()
    for k in range(2):
        assert os.path.exists(shard_lease_path(str(tmp_path), k))
    sup.round(now=NOW + TICK_S)
    sup.stop(graceful=True)
    for k in range(2):
        assert not os.path.exists(shard_lease_path(str(tmp_path), k)), (
            f"shard {k}'s lease must be RELEASED on graceful stop, "
            "not left to time out"
        )
    for h in sup.handles.values():
        assert h.proc.poll() == 0, "workers must exit cleanly"


@pytest.mark.slow
def test_service_sigterm_releases_writer_lease(tmp_path):
    """The classic (unsharded) service path: a SIGTERM'd writer must
    drain and RELEASE its lease before exit — previously only
    KeyboardInterrupt was handled and the lease was left to TTL out."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    data_dir = str(tmp_path / "svc")
    proc = subprocess.Popen(
        [sys.executable, "-m", "evergreen_tpu", "service",
         "--data-dir", data_dir, "--port", str(port)],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""},
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    try:
        deadline = time.time() + 120.0
        for line in proc.stdout:
            if "listening" in line:
                break
            if time.time() > deadline:
                raise AssertionError("service never came up")
        lease_path = os.path.join(data_dir, "writer.lease")
        assert os.path.exists(lease_path)
        proc.send_signal(signal.SIGTERM)
        proc.wait(timeout=60)
        assert proc.returncode == 0
        assert not os.path.exists(lease_path), (
            "SIGTERM must release the writer lease (graceful drain), "
            "not abandon it to the TTL"
        )
    finally:
        if proc.poll() is None:
            proc.kill()


# --------------------------------------------------------------------------- #
# supervisor survivability (ISSUE 14): orphan mode, adoption, fencing
# --------------------------------------------------------------------------- #


def _read_json(path):
    import json

    with open(path, encoding="utf-8") as fh:
        return json.load(fh)


def test_orphan_keeps_renewing_then_drains_at_grace_expiry(tmp_path):
    """A supervisor crash puts the worker in orphan mode: it KEEPS its
    shard lease (renewals keep landing), keeps ticking locally, and at
    grace expiry drains, RELEASES the lease and removes its manifest
    entry — the bounded worst case of an unrecovered supervisor."""
    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.storage.lease import shard_lease_path

    sup = _fleet(tmp_path, 1, orphan_grace_s=4.0, orphan_tick_s=0.5)
    sup.start()
    sup.round(now=NOW + TICK_S)
    h = sup.handles[0]
    lease_path = shard_lease_path(str(tmp_path), 0)
    entry = manifest.read_entry(str(tmp_path), 0)
    assert entry is not None and entry["pid"] == h.pid
    assert os.path.exists(entry["sock"])
    sup.simulate_crash()
    # mid-grace: lease still held by the SAME epoch and still renewing
    time.sleep(1.0)
    assert h.proc.poll() is None, "worker must outlive the supervisor"
    doc1 = _read_json(lease_path)
    assert doc1["epoch"] == 1
    time.sleep(0.8)
    doc2 = _read_json(lease_path)
    assert doc2["at"] > doc1["at"], "orphan must keep renewing"
    # grace expiry: clean exit, lease released, manifest entry gone
    assert h.proc.wait(timeout=30.0) == 0
    assert not os.path.exists(lease_path), (
        "an expired orphan must RELEASE its lease, not abandon it"
    )
    assert manifest.read_entry(str(tmp_path), 0) is None
    assert not os.path.exists(entry["sock"])


def test_adoption_within_grace_no_epoch_bump_no_recovery(tmp_path):
    """The acceptance centerpiece, in-process: kill the supervisor,
    restart it, and both live workers are ADOPTED — same pids, same
    shard-lease epochs (zero bumps), no recovery pass, autonomous
    orphan ticks recorded — then the fleet converges with zero
    duplicate dispatch and exactly-one-owner."""
    from evergreen_tpu.scenarios.invariants import (
        check_duplicate_dispatch,
        check_store_consistent,
    )
    from evergreen_tpu.scenarios.procs import _open_fleet_stores
    from evergreen_tpu.scheduler.sharded_plane import (
        fleet_owner_violations,
        merge_fleet_state,
    )

    sup = _fleet(
        tmp_path, 2,
        workload={"distros": 4, "tasks": 24, "seed": 11},
    )
    sup2 = None
    try:
        sup.start()
        sup.round(now=NOW + TICK_S)
        sup.agent_sim(now=NOW + TICK_S)
        pre = {k: (h.pid, h.epoch) for k, h in sup.handles.items()}
        assert sup.sup_epoch == 1
        sup.simulate_crash()
        time.sleep(1.6)  # orphan + at least one autonomous tick
        sup2 = _fleet(tmp_path, 2, seed=False)
        sup2.start()
        assert sup2.sup_epoch > sup.sup_epoch, (
            "the successor must steal the fleet lease at a higher epoch"
        )
        for k, h in sup2.handles.items():
            assert h.adopted, f"shard {k} was not adopted"
            assert h.pid == pre[k][0], "adoption must keep the process"
            assert h.epochs == [pre[k][1]], (
                f"adoption must not bump the shard lease: {h.epochs}"
            )
            assert h.adopt_hello.get("recovery_passes") == 1, (
                "an adopted worker must still be at its single "
                "boot-time recovery pass"
            )
            assert h.adopt_hello.get("orphaned") is True
            assert h.adopt_hello.get("tick", 0) >= 1, (
                "tick continuity proves the plane stayed warm"
            )
            assert h.restarts == 0
        _drive_to_convergence(sup2, start=1)
    finally:
        if sup2 is not None:
            sup2.stop()
        _reap(sup)
    stores = _open_fleet_stores(str(tmp_path), 2)
    try:
        assert fleet_owner_violations(stores) == []
        merged = merge_fleet_state(stores)
        assert check_duplicate_dispatch(merged) == []
        assert check_store_consistent(merged) == []
    finally:
        for s in stores:
            s.close()


def test_stale_supervisor_commands_rejected(tmp_path):
    """The split-brain guard at the worker: commands carrying a
    superseded supervisor epoch come back ``stale_sup`` and do NOT
    execute; the live fleet keeps working and learns the reject count
    through heartbeats."""
    import threading

    from evergreen_tpu.runtime import manifest
    from evergreen_tpu.runtime.protocol import parse_line, send_msg

    sup = _fleet(tmp_path, 1)
    try:
        sup.start()
        sup.round(now=NOW + TICK_S)
        pre_tick = sup.statuses()[0]["tick"]
        entry = manifest.read_entry(str(tmp_path), 0)
        conn = manifest.connect(entry["sock"], timeout_s=5.0)
        rf = conn.makefile("r", encoding="utf-8")
        wf = conn.makefile("w", encoding="utf-8")
        lock = threading.Lock()
        try:
            # the current-epoch adopt is the replay attack: the rogue
            # read the CURRENT fleet-lease epoch; only a strictly
            # higher one (an actual steal) may adopt a foreign channel
            for op, sup_e in (("adopt", sup.sup_epoch), ("adopt", 0),
                              ("tick", 0), ("shutdown", 0)):
                req = f"rogue-{op}-{sup_e}"
                send_msg(wf, lock, op=op, sup=sup_e, req=req,
                         now=NOW + 30.0)
                reply = None
                while reply is None:
                    msg = parse_line(rf.readline())
                    if msg is not None and msg.get("req") == req:
                        reply = msg
                assert reply["op"] == "stale_sup", (
                    f"rogue {op!r} must be rejected, got {reply}"
                )
        finally:
            for f in (rf, wf, conn):
                f.close()
        # nothing executed: same tick index, same process, and the
        # live supervisor still commands the fleet
        st = sup.statuses()[0]
        assert st["tick"] == pre_tick
        assert sup.round(now=NOW + 2 * TICK_S)
        time.sleep(0.6)  # a heartbeat carries the reject count
        assert sup.handles[0].stale_rejects >= 4
        assert sup.fleet_state()["workers"]["0"]["stale_rejects"] >= 4
    finally:
        sup.stop()


def test_second_supervisor_cannot_acquire_held_fleet_lease(tmp_path):
    """Supervisor fencing half one: while a live supervisor renews the
    fleet lease, a second one's start() must refuse to run rather than
    split-brain the fleet."""
    sup = _fleet(tmp_path, 1)
    try:
        sup.start()
        rogue = _fleet(tmp_path, 1, seed=False)
        rogue.fleet_acquire_timeout_s = 1.5
        rogue.adopt_enabled = False
        with pytest.raises(RuntimeError, match="fleet lease"):
            rogue.start()
        # the live fleet is untouched
        assert sup.round(now=NOW + TICK_S)
    finally:
        sup.stop()


def test_deposed_supervisor_stands_down_without_killing_workers(
    tmp_path,
):
    """Supervisor fencing half two: a supervisor whose fleet lease is
    gone stops commanding (rounds return empty) and its stop() leaves
    the workers RUNNING — they belong to the successor."""
    sup = _fleet(tmp_path, 1)
    try:
        sup.start()
        h = sup.handles[0]
        sup._fleet_deposed("test: simulated loss")
        assert sup.round(now=NOW + TICK_S) == {}
        assert sup.broadcast("status", "status") == {}
        sup.stop()
        assert h.proc.poll() is None, (
            "a deposed supervisor must NOT kill its successor's workers"
        )
    finally:
        sup.deposed = False
        sup.crashed = True  # detach cleanly
        _reap(sup)


# --------------------------------------------------------------------------- #
# admin surface
# --------------------------------------------------------------------------- #


def test_admin_fleet_endpoint_shape(tmp_path):
    from evergreen_tpu.api.rest import ApiError, RestApi
    from evergreen_tpu.storage.store import Store

    store = Store()
    api = RestApi(store)
    with pytest.raises(ApiError) as exc:
        api.get_fleet("GET", {}, {})
    assert exc.value.status == 404

    sup = FleetSupervisor(str(tmp_path), 2)
    attach_fleet_supervisor(store, sup)
    assert peek_fleet_supervisor(store) is sup
    status, doc = api.get_fleet("GET", {}, {})
    assert status == 200
    assert doc["n_shards"] == 2
    assert set(doc) >= {
        "workers", "rounds", "restarts_total", "migrations",
        "reconciled_handoffs", "data_dir", "supervisor_epoch",
        "adoptions_total", "orphaned_total", "deposed",
    }
    assert doc["supervisor_epoch"] == 0  # never started → no lease
    for k in ("0", "1"):
        w = doc["workers"][k]
        assert set(w) >= {
            "state", "epoch", "epochs", "restarts", "level",
            "last_round_ms", "exits", "heartbeat_overdue",
            "adopted", "orphan", "orphan_ticks", "stale_rejects",
        }


def test_fleet_state_tracks_rounds_and_levels(tmp_path):
    sup = _fleet(tmp_path, 1)
    try:
        sup.start(monitor=False)
        sup.round(now=NOW + TICK_S)
        st = sup.fleet_state()
        w = st["workers"]["0"]
        assert st["rounds"] == 1
        assert w["state"] == "ready"
        assert w["level"] in ("green", "yellow", "red", "black")
        assert w["last_round_ms"] > 0
    finally:
        sup.stop()


# --------------------------------------------------------------------------- #
# bench mode (tools/bench_sharded_plane.py dedupe)
# --------------------------------------------------------------------------- #


def test_bench_mode_speaks_the_protocol(tmp_path):
    """The bench spawns the production worker entrypoint: ready → go →
    report with the original report fields (methodology unchanged)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-m", "evergreen_tpu.runtime.worker",
         "--bench", "--shard", "0", "--shards", "1",
         "--bench-distros", "2", "--bench-tasks", "40",
         "--bench-ticks", "2", "--bench-warmup", "1"],
        cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""},
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True,
    )
    try:
        ready = None
        deadline = time.time() + 180.0
        while time.time() < deadline:
            msg = parse_line(proc.stdout.readline())
            if msg and msg["op"] == "ready":
                ready = msg
                break
        assert ready is not None and ready["n_tasks"] == 40
        proc.stdin.write('{"op":"go"}\n')
        proc.stdin.flush()
        report = None
        while time.time() < deadline:
            msg = parse_line(proc.stdout.readline())
            if msg and msg["op"] == "report":
                report = msg
                break
        assert report is not None
        assert len(report["tick_ms"]) == 2
        assert report["median_ms"] > 0
        assert proc.wait(timeout=30) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
